"""Numerical properties of the arena rating math (arena/ratings.py).

What must hold for the bench's speedup claim to mean anything:

- the scatter-free sorted segment sum IS a segment sum (pinned against
  `jax.ops.segment_sum` on random data);
- jitting changes nothing but speed (jit-vs-eager equivalence);
- batched updates are order-free within a batch (permutation
  invariance — the property that makes the batch semantics coherent);
- both Elo and Bradley–Terry recover the true total order on synthetic
  transitive data (the engine actually *rates*);
- the optimized path agrees with the deliberately naive baseline it is
  benchmarked against.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from arena import baseline, engine
from arena import ratings as R

N_PLAYERS = 50


def make_matches(num_matches, num_players=N_PLAYERS, seed=0):
    """Stochastic outcomes from linearly spaced true log-strengths."""
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, num_matches)
    b = (a + 1 + rng.integers(0, num_players - 1, num_matches)) % num_players
    strength = np.linspace(2.5, -2.5, num_players)
    p_a = 1.0 / (1.0 + np.exp(strength[b] - strength[a]))
    a_wins = rng.random(num_matches) < p_a
    return (
        np.where(a_wins, a, b).astype(np.int32),
        np.where(a_wins, b, a).astype(np.int32),
    )


def test_sorted_segment_sum_equals_segment_sum():
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 37, 500).astype(np.int32)
    vals = rng.normal(size=500).astype(np.float32)
    perm, bounds = engine._group_by_player(ids, 37)
    got = R.sorted_segment_sum(jnp.asarray(vals), jnp.asarray(perm), jnp.asarray(bounds))
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(ids), num_segments=37)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-4)


def test_sorted_segment_sum_empty_segments_are_zero():
    """Players with no matches must get exactly 0, not garbage from
    neighboring boundary offsets."""
    ids = np.array([3, 3, 7], np.int32)
    vals = np.array([1.0, 2.0, 4.0], np.float32)
    perm, bounds = engine._group_by_player(ids, 10)
    got = np.asarray(
        R.sorted_segment_sum(jnp.asarray(vals), jnp.asarray(perm), jnp.asarray(bounds))
    )
    want = np.zeros(10, np.float32)
    want[3], want[7] = 3.0, 4.0
    np.testing.assert_array_equal(got, want)


def test_elo_batch_update_jit_vs_eager():
    w, l = make_matches(300)
    r = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    wj, lj = jnp.asarray(w), jnp.asarray(l)
    eager = R.elo_batch_update(r, wj, lj)
    jitted = jax.jit(R.elo_batch_update)(r, wj, lj)
    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted), atol=1e-4)


def test_sorted_path_matches_scatter_path():
    """The hot path (sorted cumsum) and the plain segment_sum scatter
    formulation are the same update."""
    w, l = make_matches(512)
    packed = engine.pack_batch(N_PLAYERS, w, l, min_bucket=512)
    r = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    scatter = R.elo_batch_update(r, packed.winners, packed.losers, packed.valid)
    sorted_ = R.elo_batch_update_sorted(
        r, packed.winners, packed.losers, packed.valid, packed.perm, packed.bounds
    )
    np.testing.assert_allclose(np.asarray(scatter), np.asarray(sorted_), atol=1e-3)


def test_elo_epoch_jit_vs_eager():
    w, l = make_matches(600)
    packed = engine.pack_epoch(N_PLAYERS, w, l, batch_size=256)
    r = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    args = (packed.winners, packed.losers, packed.valid, packed.perms, packed.bounds)
    eager = R.elo_epoch(r, *args)
    jitted = R.jit_elo_epoch(N_PLAYERS, donate=False)(r, *args)
    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted), atol=1e-3)


def test_elo_batch_permutation_invariance():
    """Shuffling the matches WITHIN a batch must not change the
    ratings: every expected score reads the ratings at batch start."""
    w, l = make_matches(400)
    r = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    out1 = R.elo_batch_update(r, jnp.asarray(w), jnp.asarray(l))
    shuffle = np.random.default_rng(7).permutation(len(w))
    out2 = R.elo_batch_update(r, jnp.asarray(w[shuffle]), jnp.asarray(l[shuffle]))
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-3)
    # Same through the sorted hot path (fresh ingest of the shuffled batch).
    p1 = engine.pack_batch(N_PLAYERS, w, l)
    p2 = engine.pack_batch(N_PLAYERS, w[shuffle], l[shuffle])
    s1 = R.elo_batch_update_sorted(r, p1.winners, p1.losers, p1.valid, p1.perm, p1.bounds)
    s2 = R.elo_batch_update_sorted(r, p2.winners, p2.losers, p2.valid, p2.perm, p2.bounds)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), atol=1e-3)


def test_optimized_elo_agrees_with_naive_baseline():
    """The pair the bench compares must compute the same thing."""
    w, l = make_matches(2000)
    batch = 256
    naive = baseline.elo_epoch_naive(N_PLAYERS, w, l, batch)
    packed = engine.pack_epoch(N_PLAYERS, w, l, batch)
    r = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    jitted = R.jit_elo_epoch(N_PLAYERS, donate=False)(
        r, packed.winners, packed.losers, packed.valid, packed.perms, packed.bounds
    )
    assert float(np.abs(np.asarray(jitted) - naive).max()) < 0.05


def test_elo_recovers_total_order_on_transitive_data():
    """On strongly separated strengths, a few epochs of batched Elo
    must rank every player correctly (true order is 0 > 1 > ... > n-1)."""
    num_players = 12
    rng = np.random.default_rng(3)
    a = rng.integers(0, num_players, 3000)
    b = (a + 1 + rng.integers(0, num_players - 1, 3000)) % num_players
    # Deterministically transitive: the lower index always wins.
    w = np.minimum(a, b).astype(np.int32)
    l = np.maximum(a, b).astype(np.int32)
    packed = engine.pack_epoch(num_players, w, l, batch_size=256)
    r = jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32)
    epoch = R.jit_elo_epoch(num_players, donate=False)
    for _ in range(3):
        r = epoch(r, packed.winners, packed.losers, packed.valid, packed.perms, packed.bounds)
    assert list(np.argsort(-np.asarray(r))) == list(range(num_players))


def test_bt_recovers_total_order_and_matches_naive():
    w, l = make_matches(4000, seed=11)
    packed = engine.pack_batch(N_PLAYERS, w, l, min_bucket=4096)
    win_counts = jnp.asarray(
        np.bincount(w, minlength=N_PLAYERS).astype(np.float32)
    )
    fit = R.jit_bt_fit(N_PLAYERS, num_iters=60)
    strengths = np.asarray(
        fit(packed.winners, packed.losers, packed.valid, packed.perm, packed.bounds, win_counts)
    )
    # Spearman-style check: the fitted ranking must essentially match
    # the true one (strengths are linspace-separated; a tiny number of
    # adjacent swaps from sampling noise is tolerable).
    true_rank = np.arange(N_PLAYERS)
    fitted_rank = np.empty(N_PLAYERS)
    fitted_rank[np.argsort(-strengths)] = np.arange(N_PLAYERS)
    corr = np.corrcoef(true_rank, fitted_rank)[0, 1]
    assert corr > 0.98, f"rank correlation {corr}"
    # Naive MM agrees with the vectorized MM.
    naive = baseline.bt_fit_naive(N_PLAYERS, w, l, num_iters=60)
    np.testing.assert_allclose(
        strengths, naive, rtol=5e-2, atol=1e-3
    )


def test_bt_mm_step_does_not_decrease_likelihood():
    """MM is monotone in the (regularized) likelihood; check the plain
    data likelihood over a few steps from a cold start."""
    w, l = make_matches(1500, seed=5)
    packed = engine.pack_batch(N_PLAYERS, w, l, min_bucket=2048)
    win_counts = jnp.asarray(np.bincount(w, minlength=N_PLAYERS).astype(np.float32))
    p = jnp.ones((N_PLAYERS,), jnp.float32)
    prev = float(
        R.bt_log_likelihood(p, packed.winners, packed.losers, packed.valid)
    )
    step = jax.jit(R.bt_mm_step)
    for _ in range(5):
        p = step(p, packed.winners, packed.losers, packed.valid, packed.perm,
                 packed.bounds, win_counts, 0.1)
        cur = float(
            R.bt_log_likelihood(p, packed.winners, packed.losers, packed.valid)
        )
        assert cur >= prev - 1e-3
        prev = cur


def test_elo_expected_is_the_classic_formula():
    """The sigmoid rewrite must be the textbook 10** curve."""
    for rw, rl in [(1500.0, 1500.0), (1700.0, 1400.0), (1200.0, 1900.0)]:
        got = float(R.elo_expected(jnp.float32(rw), jnp.float32(rl)))
        want = baseline.elo_expected_naive(rw, rl)
        assert got == pytest.approx(want, abs=1e-5)


# --- bootstrap confidence intervals (PR 5 satellite) -----------------------


def test_elo_bootstrap_is_deterministic_under_a_fixed_seed():
    w, l = make_matches(800, seed=6)
    packed = engine.pack_epoch(N_PLAYERS, w, l, batch_size=256)
    args = (packed.winners, packed.losers, packed.valid, packed.perms,
            packed.bounds)
    r0 = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(42), 6)
    fn = R.jit_elo_bootstrap()
    a = np.asarray(fn(r0, *args, keys))
    b = np.asarray(fn(r0, *args, keys))
    assert a.shape == (6, N_PLAYERS)
    np.testing.assert_array_equal(a, b)
    other = np.asarray(fn(r0, *args, jax.random.split(jax.random.PRNGKey(43), 6)))
    assert not np.array_equal(a, other)


def test_elo_bootstrap_round_is_a_poisson_weighted_epoch():
    """Pin the resample semantics: each vmapped round is EXACTLY the
    plain epoch with that key's Poisson(1) weights folded into the
    valid mask — the padded-slot mask and the resample weights ride
    the same multiply."""
    w, l = make_matches(400, seed=7)
    packed = engine.pack_epoch(N_PLAYERS, w, l, batch_size=256)
    r0 = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    samples = np.asarray(
        R.elo_bootstrap(
            r0, packed.winners, packed.losers, packed.valid, packed.perms,
            packed.bounds, keys,
        )
    )
    for i in range(3):
        weights = jax.random.poisson(keys[i], 1.0, shape=packed.valid.shape)
        manual = R.elo_epoch(
            r0, packed.winners, packed.losers,
            packed.valid * weights.astype(packed.valid.dtype),
            packed.perms, packed.bounds,
        )
        np.testing.assert_array_equal(samples[i], np.asarray(manual))


def test_bootstrap_intervals_are_ordered_and_bracket_the_estimate():
    w, l = make_matches(1200, seed=8)
    packed = engine.pack_epoch(N_PLAYERS, w, l, batch_size=256)
    args = (packed.winners, packed.losers, packed.valid, packed.perms,
            packed.bounds)
    r0 = jnp.full((N_PLAYERS,), R.DEFAULT_BASE, jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(0), 16)
    samples = R.jit_elo_bootstrap()(r0, *args, keys)
    lo, hi = R.bootstrap_intervals(samples, alpha=0.05)
    lo, hi = np.asarray(lo), np.asarray(hi)
    assert (lo <= hi).all()
    # Real spread for active players, and the percentile interval
    # brackets the per-player sample median by construction.
    med = np.median(np.asarray(samples), axis=0)
    assert ((lo <= med) & (med <= hi)).all()
    assert (hi - lo).max() > 1.0
