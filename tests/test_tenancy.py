"""Multi-tenant arena contracts (arena/tenancy.py).

The load-bearing claim is BIT-exactness: every tenant's ratings row out
of the fused ``(tenant_bucket, players)`` update must equal — by
`np.array_equal`, not a tolerance — a dedicated single-tenant
`ArenaEngine` fed the same per-round batches with the same row bucket.
The property test here drives that across random tenant splits, three
seeds, a permanently empty tenant, and a tenant-bucket boundary
crossing mid-stream.

Two mutation-audit kills are named here:
- `test_store_groups_tenant_major` kills tenant-key-dropped-from-
  segment-sort (compose_ids without the tenant offset collapses every
  tenant onto tenant 0's id range).
- `test_tenant_growth_within_bucket_zero_recompiles` kills
  tenant-bucket-never-padded (an unpadded tenant axis recompiles on
  every tenant added — the sentinel turns red).
"""

import jax
import numpy as np
import pytest

from arena import engine, serving, tenancy
from arena.analysis import sanitize
from arena.engine import ArenaEngine, _validate_tenant
from arena.obs import Observability
from arena.tenancy import (
    CategoryRegistry,
    MIN_TENANT_BUCKET,
    MultiTenantEngine,
    compose_ids,
    pack_tenant_batch,
    tenant_bucket,
)

P = 16  # players per tenant, small: compiles stay cheap
ROW_BUCKET = 16  # min_bucket both sides — the bit-exactness precondition


def _matches(n, players, rng):
    w = rng.integers(0, players, n).astype(np.int32)
    l = ((w + 1 + rng.integers(0, players - 1, n)) % players).astype(np.int32)
    return w, l


# --- geometry ---------------------------------------------------------------


def test_tenant_bucket_is_pow2_with_floor():
    assert tenant_bucket(1) == MIN_TENANT_BUCKET
    assert tenant_bucket(MIN_TENANT_BUCKET) == MIN_TENANT_BUCKET
    assert tenant_bucket(MIN_TENANT_BUCKET + 1) == 2 * MIN_TENANT_BUCKET
    assert tenant_bucket(3, min_bucket=4) == 4
    assert tenant_bucket(5, min_bucket=4) == 8
    assert tenant_bucket(200) == 256


def test_compose_ids_is_tenant_major():
    ids = np.array([0, 3, 15], np.int32)
    out = compose_ids(ids, 2, P)
    assert list(out) == [32, 35, 47]
    assert out.dtype == np.int32
    # Tenant-major: every tenant-2 composite sorts after every tenant-1.
    assert compose_ids(np.int32(P - 1), 1, P) < compose_ids(np.int32(0), 2, P)


def test_pack_tenant_batch_rejects_cross_tenant():
    w = compose_ids(np.array([1], np.int32), 0, P)
    l = compose_ids(np.array([2], np.int32), 1, P)
    with pytest.raises(ValueError, match="cross-tenant"):
        pack_tenant_batch(4, P, w, l, min_bucket=ROW_BUCKET)


def test_validate_tenant_rejects_garbage():
    assert _validate_tenant(4, 3) == 3
    assert _validate_tenant(4, np.int64(0)) == 0
    for bad in (-1, 4, 99):
        with pytest.raises(ValueError, match="unknown tenant"):
            _validate_tenant(4, bad)
    for bad in ("x", 1.5, None, True):
        with pytest.raises(ValueError, match="tenant must be an integer"):
            _validate_tenant(4, bad)


# --- the bit-exactness property ---------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_batched_bit_exact_vs_dedicated_engines(seed):
    """Random matches split across T tenants through ONE fused engine
    land bit-identically on T dedicated engines fed the same per-round
    batches — including a tenant that never gets a match and a tenant-
    bucket boundary crossing mid-stream (both sides keep the same row
    bucket, the documented precondition)."""
    rng = np.random.default_rng(seed)
    eng = MultiTenantEngine(
        P, num_tenants=3, min_bucket=ROW_BUCKET, min_tenant_bucket=4
    )
    assert eng.tenant_bucket == 4
    dedicated = {}

    def dedicated_for(t):
        if t not in dedicated:
            dedicated[t] = ArenaEngine(P, min_bucket=ROW_BUCKET)
        return dedicated[t]

    def play_round(active):
        ws, ls = [], []
        for t in range(active):
            if t in (2, 5):
                continue  # the permanently empty tenants
            n = int(rng.integers(0, ROW_BUCKET + 1))  # 0..row bucket
            if n == 0:
                continue
            w, l = _matches(n, P, rng)
            dedicated_for(t).ingest(w, l)
            ws.append(compose_ids(w, t, P))
            ls.append(compose_ids(l, t, P))
        if ws:
            eng.ingest(np.concatenate(ws), np.concatenate(ls))

    for _ in range(3):
        play_round(3)
    before_growth = np.asarray(eng.ratings).copy()
    eng.ensure_tenants(6)  # bucket 4 -> 8: the boundary crossing
    assert eng.tenant_bucket == 8
    assert eng.num_players == 8 * P
    # Crossing pads with base rows and bit-preserves existing tenants.
    assert np.array_equal(np.asarray(eng.ratings)[:4], before_growth)
    for _ in range(3):
        play_round(6)

    got = np.asarray(eng.ratings)
    assert got.dtype == np.float32
    base = np.full(P, engine.R.DEFAULT_BASE, np.float32)
    for t in range(6):
        want = (
            np.asarray(dedicated[t].ratings) if t in dedicated else base
        )
        assert np.array_equal(got[t], want), f"tenant {t} diverged (seed {seed})"
    # The empty tenants stayed bit-identical to base (the +-0.0 property).
    assert np.array_equal(got[2], base)
    assert np.array_equal(got[5], base)


def test_async_ingest_bit_exact_to_sync():
    rng = np.random.default_rng(7)
    sync_eng = MultiTenantEngine(P, num_tenants=3, min_bucket=ROW_BUCKET)
    async_eng = MultiTenantEngine(P, num_tenants=3, min_bucket=ROW_BUCKET)
    async_eng.start_pipeline()
    for _ in range(4):
        for t in range(3):
            w, l = _matches(8, P, rng)
            sync_eng.ingest(w, l, tenant=t)
            async_eng.ingest_async(w, l, tenant=t)
    async_eng.flush()
    assert np.array_equal(
        np.asarray(sync_eng.ratings), np.asarray(async_eng.ratings)
    )
    assert async_eng.matches_applied == sync_eng.matches_applied


# --- the mutation-audit kills -----------------------------------------------


def test_store_groups_tenant_major():
    """Named kill for tenant-key-dropped-from-segment-sort: the store
    must hold COMPOSITE ids (tenant the leading sort key), so each
    tenant's matches live in its own id range and its ratings row moves
    alone. Drop the tenant term from `compose_ids` and every tenant
    collapses onto tenant 0's range — both assertions go red."""
    eng = MultiTenantEngine(P, num_tenants=4, min_bucket=ROW_BUCKET)
    rng = np.random.default_rng(3)
    w0, l0 = _matches(8, P, rng)
    w2, l2 = _matches(8, P, rng)
    eng.ingest(w0, l0, tenant=0)
    eng.ingest(w2, l2, tenant=2)
    state = eng._store.export_state()
    stored_w = np.asarray(state["winners"])
    stored_tenants = np.sort(np.unique(stored_w // P))
    assert list(stored_tenants) == [0, 2], (
        f"store holds tenant ranges {stored_tenants}, expected [0, 2] — "
        "composite ids must carry the tenant offset"
    )
    ratings = np.asarray(eng.ratings)
    base = np.full(P, engine.R.DEFAULT_BASE, np.float32)
    assert not np.array_equal(ratings[0], base)
    assert not np.array_equal(ratings[2], base)
    assert np.array_equal(ratings[1], base)
    assert np.array_equal(ratings[3], base)
    # And the BT refit consumes the same composite grouping: strengths
    # come back over the whole composite space.
    strengths = eng.bt_strengths(num_iters=3)
    assert np.asarray(strengths).shape == (eng.num_players,)


def test_tenant_growth_within_bucket_zero_recompiles():
    """Named kill for tenant-bucket-never-padded: adding tenants inside
    one pow2 tenant bucket is bookkeeping — no shape change, no new jit
    compiles. Without the pow2 pad, every added tenant changes the
    (tenant, players) dispatch shape and the sentinel turns red."""
    eng = MultiTenantEngine(P, num_tenants=5, min_bucket=ROW_BUCKET)
    assert eng.tenant_bucket == MIN_TENANT_BUCKET  # 5 padded up to 8
    rng = np.random.default_rng(11)

    def round_for(active):
        for t in range(active):
            w, l = _matches(8, P, rng)
            eng.ingest(w, l, tenant=t)

    round_for(5)  # warmup: compiles the (bucket, P) fused update once
    jax.block_until_ready(eng.ratings)
    sentinel = sanitize.RecompileSentinel(update=eng.num_compiles)
    for want in (6, 7, 8):
        assert eng.ensure_tenants(want) == want
        round_for(want)
    jax.block_until_ready(eng.ratings)
    sentinel.assert_no_new_compiles()
    assert eng.tenant_bucket == MIN_TENANT_BUCKET
    assert eng.num_players == MIN_TENANT_BUCKET * P


# --- reads / registry -------------------------------------------------------


def test_tenant_leaderboard_is_local_ids():
    eng = MultiTenantEngine(P, num_tenants=3, min_bucket=ROW_BUCKET)
    eng.ingest([1], [2], tenant=1)
    board = eng.leaderboard(top_k=3, tenant=1)
    assert board[0][0] == 1 and board[0][1] > engine.R.DEFAULT_BASE
    assert all(0 <= p < P for p, _r in board)
    with pytest.raises(ValueError, match="unknown tenant"):
        eng.leaderboard(tenant=7)
    # The admin view ranks the whole composite space.
    admin = eng.leaderboard(top_k=1)
    assert admin[0][0] == compose_ids(np.int32(1), 1, P)


def test_category_registry_maps_names_to_slots():
    eng = MultiTenantEngine(P, num_tenants=1, min_bucket=ROW_BUCKET)
    reg = CategoryRegistry(eng, categories=("chat", "code"))
    assert reg.resolve("chat") == 0
    assert reg.resolve("code") == 1
    assert reg.register("chat") == 0  # idempotent
    assert eng.num_tenants >= 2  # registration grew the roster
    with pytest.raises(ValueError, match="unknown category 'vision'"):
        reg.resolve("vision")
    with pytest.raises(ValueError, match="non-empty string"):
        reg.register("")
    assert reg.categories() == [("chat", 0), ("code", 1)]
    auto = CategoryRegistry(eng, auto_register=True)
    slot = auto.resolve("fresh")
    assert auto.resolve("fresh") == slot


# --- snapshots (arena-snapshot@v3) ------------------------------------------


def test_snapshot_v3_roundtrip_rebuilds_multitenant(tmp_path):
    eng = MultiTenantEngine(P, num_tenants=3, min_bucket=ROW_BUCKET)
    srv = serving.ArenaServer(engine=eng, obs=Observability())
    rng = np.random.default_rng(5)
    for t in (0, 2):
        w, l = _matches(8, P, rng)
        eng.ingest(w, l, tenant=t)
    snap = tmp_path / "snap"
    manifest = srv.snapshot(snap)
    assert manifest["version"] == serving.SNAPSHOT_VERSION == 3
    assert manifest["num_tenants"] == 3
    assert manifest["players_per_tenant"] == P
    assert manifest["num_players"] == eng.num_players  # composite bound
    _m, arrays = serving.read_snapshot(snap)
    counts = arrays["tenant_counts"]
    assert counts.dtype == np.int32 and counts.shape == (3,)
    assert list(counts) == [8, 0, 8]

    srv2 = serving.ArenaServer(num_players=2)
    srv2.restore(snap)
    eng2 = srv2.engine
    assert isinstance(eng2, MultiTenantEngine)
    assert eng2.players_per_tenant == P
    assert eng2.num_tenants == 3
    assert eng2.tenant_bucket == eng.tenant_bucket
    assert np.array_equal(np.asarray(eng2.ratings), np.asarray(eng.ratings))
    # Tenant reads answer from the restored slices.
    out = srv2.query(leaderboard=(0, 3), tenant=0)
    assert out["tenant"] == 0
    assert all(0 <= row["player"] < P for row in out["leaderboard"])
    srv.close()
    srv2.close()


def test_snapshot_single_tenant_defaults_restore_plain_engine(tmp_path):
    srv = serving.ArenaServer(num_players=P)
    srv.engine.ingest([1, 2], [3, 4])
    snap = tmp_path / "snap"
    manifest = srv.snapshot(snap)
    assert manifest["num_tenants"] == 1
    assert manifest["players_per_tenant"] == P
    srv2 = serving.ArenaServer(num_players=P)
    srv2.restore(snap)
    assert type(srv2.engine) is ArenaEngine  # no tenancy layer imposed
    assert np.array_equal(
        np.asarray(srv2.engine.ratings), np.asarray(srv.engine.ratings)
    )
    srv.close()
    srv2.close()


def test_incremental_chain_allows_tenant_growth(tmp_path):
    """A base snapshot at 3 tenants chains with an increment cut after
    within-bucket growth to 5 — tenants never shrink, and the restored
    engine carries the grown roster."""
    eng = MultiTenantEngine(P, num_tenants=3, min_bucket=ROW_BUCKET)
    srv = serving.ArenaServer(engine=eng, obs=Observability())
    rng = np.random.default_rng(9)
    w, l = _matches(8, P, rng)
    eng.ingest(w, l, tenant=1)
    base_dir = tmp_path / "base"
    srv.snapshot(base_dir)
    eng.ensure_tenants(5)
    w, l = _matches(8, P, rng)
    eng.ingest(w, l, tenant=4)
    inc_dir = tmp_path / "inc"
    inc_manifest = srv.snapshot(inc_dir, base=base_dir)
    assert inc_manifest["num_tenants"] == 5
    srv2 = serving.ArenaServer(num_players=2)
    srv2.restore(inc_dir)
    assert srv2.engine.num_tenants == 5
    assert np.array_equal(
        np.asarray(srv2.engine.ratings), np.asarray(eng.ratings)
    )
    srv.close()
    srv2.close()


def test_query_parts_tenant_slices_one_view():
    eng = MultiTenantEngine(P, num_tenants=3, min_bucket=ROW_BUCKET)
    srv = serving.ArenaServer(engine=eng, obs=Observability())
    eng.ingest([1], [2], tenant=1)
    srv.refresh_view()
    out = srv.query(leaderboard=(0, 2), players=[1], pairs=[(1, 2)], tenant=1)
    assert out["tenant"] == 1
    assert out["leaderboard"][0]["player"] == 1
    assert out["players"][0]["rating"] > engine.R.DEFAULT_BASE
    assert out["pairs"][0]["p_a_beats_b"] > 0.5
    # Tenant 0 saw nothing: same view, different slice.
    quiet = srv.query(players=[1], tenant=0)
    assert quiet["players"][0]["rating"] == engine.R.DEFAULT_BASE
    with pytest.raises(ValueError, match="unknown tenant"):
        srv.query(leaderboard=(0, 2), tenant=9)
    batch = srv.query_batch([
        {"players": [1], "tenant": 1},
        {"players": [1]},
    ])
    assert batch["results"][0]["tenant"] == 1
    assert "tenant" not in batch["results"][1]
    srv.close()
