"""Runtime-sanitizer contracts (arena/analysis/sanitize.py).

Three sanitizers, each tested in both directions — it passes on the
engine's sanctioned patterns AND it catches the exact failure it
exists for:

- recompile sentinel: zero new compiles over `ArenaEngine` across
  varying batch sizes (the acceptance criterion), and a loud
  `RecompileError` on an unbucketed jit fed varying shapes;
- donation guard: `jit_elo_epoch(donate=True)` under the guard makes a
  deliberate reuse-after-donate raise instead of silently reading a
  stale buffer — and the guard deletes the buffer ITSELF when the
  wrapped function does not donate (the silent-skip case it exists for);
- checked(): a NaN raises FloatingPointError inside the block, flags
  restored after.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from arena import engine
from arena import ratings as R
from arena.analysis import sanitize
from arena.engine import ArenaEngine


def feed(eng, n, seed):
    rng = np.random.default_rng(seed)
    w = rng.integers(0, eng.num_players, n).astype(np.int32)
    l = ((w + 1 + rng.integers(0, eng.num_players - 1, n)) % eng.num_players).astype(
        np.int32
    )
    eng.update(w, l)


def test_recompile_sentinel_passes_over_bucketed_engine():
    """The acceptance criterion: after warmup, arbitrary batch sizes
    within the touched buckets add ZERO jit-cache entries — asserted
    through the sanitizer, not the raw cache stats."""
    eng = ArenaEngine(48)
    feed(eng, 10, seed=0)  # warmup: compiles the floor bucket
    sentinel = sanitize.RecompileSentinel(update=eng.num_compiles)
    for i, n in enumerate((1, 7, 100, 255, engine.MIN_BUCKET)):
        feed(eng, n, seed=i + 1)
    sentinel.assert_no_new_compiles()  # must not raise
    assert sentinel.new_compiles() == {}


def test_recompile_sentinel_catches_unbucketed_jit():
    """The failure the bucketing contract forbids: raw varying shapes
    into a jit make the cache grow per size; the sentinel names the
    function and the growth."""
    f = jax.jit(lambda x: x * 2.0)
    f(jnp.zeros(3))  # warmup
    sentinel = sanitize.RecompileSentinel(unbucketed=f)
    f(jnp.zeros(5))  # new shape -> new compile
    with pytest.raises(sanitize.RecompileError, match="unbucketed: 1 -> 2"):
        sentinel.assert_no_new_compiles()
    assert sentinel.new_compiles() == {"unbucketed": (1, 2)}


def test_recompile_sentinel_context_manager_form():
    f = jax.jit(lambda x: x + 1.0)
    f(jnp.zeros(4))
    with sanitize.RecompileSentinel(f=f):
        f(jnp.ones(4))  # same shape: cached
    with pytest.raises(sanitize.RecompileError):
        with sanitize.RecompileSentinel(f=f):
            f(jnp.zeros(6))


def test_recompile_sentinel_rejects_unwatchable_and_empty():
    with pytest.raises(ValueError):
        sanitize.RecompileSentinel()
    with pytest.raises(TypeError):
        sanitize.RecompileSentinel(x=object())


def test_donation_guard_catches_reuse_after_donate():
    """The satellite: the real donating epoch under the sanitizer. The
    deliberate reuse below is exactly what jaxlint's use-after-donate
    rule forbids, hence the inline suppressions — the lint rule and the
    runtime guard are two halves of one invariant."""
    num_players = 16
    rng = np.random.default_rng(3)
    w = rng.integers(0, num_players, 500).astype(np.int32)
    l = ((w + 1 + rng.integers(0, num_players - 1, 500)) % num_players).astype(
        np.int32
    )
    packed = engine.pack_epoch(num_players, w, l, batch_size=256)
    with sanitize.checked():
        epoch = sanitize.donation_guard(
            R.jit_elo_epoch(num_players, donate=True), donate_argnums=(0,)
        )
        r = jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32)
        out = epoch(
            r, packed.winners, packed.losers, packed.valid, packed.perms,
            packed.bounds,
        )
        assert not out.is_deleted()
        assert r.is_deleted()  # jaxlint: disable=use-after-donate
        with pytest.raises(RuntimeError, match="deleted"):
            _ = r + 1.0  # jaxlint: disable=use-after-donate


def test_donation_guard_deletes_when_wrapped_fn_does_not_donate():
    """The silent-skip case the guard exists for: the wrapped function
    did NOT donate (donate=False stands in for XLA skipping donation
    with only a warning), so the stale input would survive as a
    readable alias — the guard kills it anyway."""
    num_players = 8
    packed = engine.pack_epoch(
        num_players, [1, 2, 3], [4, 5, 6], batch_size=256
    )
    epoch = sanitize.donation_guard(
        R.jit_elo_epoch(num_players, donate=False), donate_argnums=(0,)
    )
    r = jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32)
    epoch(
        r, packed.winners, packed.losers, packed.valid, packed.perms,
        packed.bounds,
    )
    assert r.is_deleted()  # jaxlint: disable=use-after-donate


def test_donation_guard_preserves_output_and_semantics():
    """Guarded and unguarded calls compute the same ratings."""
    num_players = 12
    packed = engine.pack_epoch(
        num_players, [0, 1, 2, 3], [4, 5, 6, 7], batch_size=256
    )
    args = (packed.winners, packed.losers, packed.valid, packed.perms, packed.bounds)
    r0 = jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32)
    want = R.jit_elo_epoch(num_players, donate=False)(r0, *args)
    guarded = sanitize.donation_guard(R.jit_elo_epoch(num_players, donate=True))
    got = guarded(jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32), *args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_checked_raises_on_nan_and_restores_flags():
    assert not jax.config.jax_debug_nans
    with sanitize.checked():
        assert jax.config.jax_debug_nans and jax.config.jax_debug_infs
        with pytest.raises(FloatingPointError):
            jnp.log(jnp.float32(-1.0))
    assert not jax.config.jax_debug_nans
    assert not jax.config.jax_debug_infs
    # Outside the block the same op is NaN-silent again.
    assert np.isnan(float(jnp.log(jnp.float32(-1.0))))


def test_checked_restores_flags_even_when_body_raises():
    with pytest.raises(RuntimeError, match="boom"):
        with sanitize.checked():
            raise RuntimeError("boom")
    assert not jax.config.jax_debug_nans
    assert not jax.config.jax_debug_infs


def test_checked_engine_epoch_is_nan_free():
    """The sanitizer in its intended posture: a healthy engine pass
    runs clean under full NaN/Inf checking."""
    eng = ArenaEngine(10)
    with sanitize.checked():
        feed(eng, 64, seed=7)
    assert np.isfinite(np.asarray(eng.ratings)).all()


# --- production (metrics) mode (PR 5 satellite) ----------------------------


def test_recompile_sentinel_count_mode_counts_instead_of_raising():
    """Serving posture: cache growth lands in `recompile_events`,
    assert_no_new_compiles never raises, and observe() re-baselines so
    one compile is never double-counted."""
    f = jax.jit(lambda x: x * 4.0)
    f(jnp.zeros(3))
    sentinel = sanitize.RecompileSentinel(mode="count", unbucketed=f)
    f(jnp.zeros(5))  # new shape -> new compile
    sentinel.assert_no_new_compiles()  # must NOT raise
    assert sentinel.recompile_events == 1
    assert sentinel.observe() == {}  # already folded in
    assert sentinel.recompile_events == 1
    f(jnp.zeros(7))
    assert sentinel.observe() == {"unbucketed": (2, 3)}
    assert sentinel.recompile_events == 2


def test_recompile_sentinel_raise_mode_unchanged_and_modes_validated():
    """The test posture is untouched by the metrics mode: the default
    still raises, and an unknown mode is rejected."""
    f = jax.jit(lambda x: x - 1.0)
    f(jnp.zeros(2))
    sentinel = sanitize.RecompileSentinel(f=f)
    assert sentinel.mode == "raise"
    f(jnp.zeros(9))
    with pytest.raises(sanitize.RecompileError):
        sentinel.assert_no_new_compiles()
    with pytest.raises(ValueError, match="mode"):
        sanitize.RecompileSentinel(mode="log", f=f)


def test_donation_guard_count_mode_counts_skip_without_deleting():
    """Production posture: a silently-skipped donation (donate=False
    stands in for XLA skipping with a warning) is COUNTED, the stale
    buffer survives, and the server keeps serving."""
    num_players = 8
    packed = engine.pack_epoch(num_players, [1, 2, 3], [4, 5, 6], batch_size=256)
    args = (packed.winners, packed.losers, packed.valid, packed.perms,
            packed.bounds)
    guarded = sanitize.donation_guard(
        R.jit_elo_epoch(num_players, donate=False), mode="count"
    )
    r = jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32)
    guarded(r, *args)
    assert guarded.donation_skipped == 1 and guarded.sampled == 1
    # Deliberate: count mode must LEAVE the stale alias alive (observe,
    # never mutate) — the exact read raise-mode forbids.
    assert not r.is_deleted()  # jaxlint: disable=use-after-donate
    # Healthy donation counts nothing.
    healthy = sanitize.donation_guard(
        R.jit_elo_epoch(num_players, donate=True), mode="count"
    )
    healthy(jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32), *args)
    assert healthy.donation_skipped == 0 and healthy.sampled == 1


def test_donation_guard_count_mode_samples_every_nth_call():
    num_players = 8
    packed = engine.pack_epoch(num_players, [1, 2], [4, 5], batch_size=256)
    args = (packed.winners, packed.losers, packed.valid, packed.perms,
            packed.bounds)
    guarded = sanitize.donation_guard(
        R.jit_elo_epoch(num_players, donate=False), mode="count", sample_every=3
    )
    for _ in range(9):
        guarded(jnp.full((num_players,), R.DEFAULT_BASE, jnp.float32), *args)
    assert guarded.calls == 9
    assert guarded.sampled == 3  # calls 3, 6, 9
    assert guarded.donation_skipped == 3


def test_donation_guard_passes_through_cache_size_and_validates():
    jitted = jax.jit(lambda x: x + 2.0)
    jitted(jnp.zeros(4))
    guarded = sanitize.donation_guard(jitted, mode="count")
    assert guarded._cache_size() == 1  # RecompileSentinel keeps working
    sanitize.RecompileSentinel(update=guarded).assert_no_new_compiles()
    with pytest.raises(ValueError, match="mode"):
        sanitize.donation_guard(jitted, mode="metrics")
    with pytest.raises(ValueError, match="sample_every"):
        sanitize.donation_guard(jitted, mode="count", sample_every=0)
