"""Mechanical round-start verification that the reference is (still) empty.

The single load-bearing fact of this repository is that the upstream
`mark1222/arena` tree mounted at /root/reference contains zero files
(SURVEY.md), which makes the repo non-graftable (NON_GRAFTABLE.md,
BASELINE.json north star). This script makes the round-start gate
mechanical: it re-runs the SURVEY.md verification checks and compares
the results against the committed fingerprint
(reference_fingerprint.json):

- recursive entry count under the reference mount (guarded against the
  mount going stale mid-walk);
- mount stat facts (mode, link count, timestamps) — recorded as
  evidence only, NOT compared: the mount is recreated every round, so
  timestamps legitimately differ while content facts must not;
- sha256 of the driver sidecars BASELINE.json and PAPERS.md, and the
  presence/absence of SNIPPETS.md — retrieved public content appearing
  mid-project is the most likely vector for accidentally "discovering"
  capabilities the reference never had, so sidecar drift is surfaced
  explicitly (it does NOT by itself change what there is to build:
  only the mounted tree defines capabilities).

Output: exactly ONE JSON line on stdout with the evidence and a `drift`
list. Exit codes (each failure mode distinct, so exit-code-only
consumers — a `set -e` round-start script, a driver hook — can never
misread one as another):

- 0  everything matches the fingerprint: reference still empty,
     sidecars unchanged; the non-graftable verdict stands.
- 1  genuine drift: the reference tree is non-empty or the sidecars
     changed. If the tree is non-empty, SURVEY.md is obsolete —
     rewrite it from the real tree before writing any code.
- 2  could not gather evidence: fingerprint missing or corrupt
     (repo bug, fix the fingerprint).
- 3  transient environment failure: the mount is absent, unreadable,
     or went stale mid-walk. This is NOT evidence the reference
     changed — there is no tree to re-survey; investigate the mount
     and re-run.

When a non-empty tree is observed, a per-file manifest (relative path,
type, size, sha256) is additionally written to
`reference_manifest_observed.json` in the repo directory — evidence to
bootstrap the mandated SURVEY.md rewrite, so the obsolescence path
starts from facts instead of a blank page. stdout stays one JSON line.

The core comparison lives in `verify(reference, repo)` so bench.py can
embed the same evidence in the driver's mandatory bench line every
round (sidecar drift must never depend on a human remembering to run
this script).

Paths are overridable for tests: GRAFT_REFERENCE_PATH (mount) and
GRAFT_REPO_PATH (directory holding the fingerprint and sidecars).
"""

import hashlib
import json
import os
import pathlib
import stat as stat_module
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
import bench  # the accessibility check + guarded walk live in ONE place

DEFAULT_REFERENCE = "/root/reference"
FINGERPRINT_NAME = "reference_fingerprint.json"
MANIFEST_NAME = "reference_manifest_observed.json"
COMPARED_KEYS = (
    "reference_entry_count",
    "baseline_json_sha256",
    "papers_md_sha256",
    "snippets_md_present",
)

EXIT_MATCH = 0
EXIT_DRIFT = 1
EXIT_FINGERPRINT_CORRUPT = 2
EXIT_TRANSIENT = 3


def sha256_of(path: pathlib.Path):
    try:
        return hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError:
        return None


def count_entries(reference: pathlib.Path, scan_result: dict = None):
    """Recursive entry count, or an error-string sentinel on failure.

    Delegates to bench.scan() so the mount-accessibility check and the
    OSError-guarded walk exist in exactly one place; bench and this gate
    can never disagree about whether the same mount is empty. A caller
    that already ran bench.scan() (bench.main embedding verification)
    passes its result via scan_result so the counting walk is not
    repeated. (A non-empty observation still triggers a separate
    traversal for the manifest — see write_manifest, which derives its
    entry_count from its own walk for exactly that reason.)
    """
    result = scan_result if scan_result is not None else bench.scan(reference)
    metric = result["metric"]
    if metric in ("non_graftable_reference_is_empty", "reference_tree_non_empty"):
        return result["value"]
    if metric == "reference_scan_error":
        return "scan_error"
    return "mount_missing_or_unreadable"


def mount_stat(reference: pathlib.Path):
    """Informational stat facts (not compared — mount is recreated per round)."""
    try:
        st = reference.stat()
        return {
            "mode": oct(st.st_mode),
            "nlink": st.st_nlink,
            "size": st.st_size,
            "mtime": st.st_mtime,
        }
    except OSError as exc:
        return {"error": exc.__class__.__name__}


def gather(reference: pathlib.Path, repo: pathlib.Path, scan_result: dict = None) -> dict:
    return {
        "reference_entry_count": count_entries(reference, scan_result),
        "baseline_json_sha256": sha256_of(repo / "BASELINE.json"),
        "papers_md_sha256": sha256_of(repo / "PAPERS.md"),
        "snippets_md_present": (repo / "SNIPPETS.md").exists(),
    }


def _manifest_entry(path: pathlib.Path, root: pathlib.Path) -> dict:
    rel = path.relative_to(root).as_posix()
    try:
        st = path.lstat()
    except OSError as exc:
        return {"path": rel, "type": "error", "error": exc.__class__.__name__}
    if stat_module.S_ISLNK(st.st_mode):
        entry = {"path": rel, "type": "symlink", "size": st.st_size, "sha256": None}
        try:
            entry["target"] = os.readlink(path)
        except OSError as exc:
            # Unreadable must be visibly unreadable, same as the file branch.
            entry["target"] = None
            entry["error"] = exc.__class__.__name__
        return entry
    if stat_module.S_ISDIR(st.st_mode):
        return {"path": rel, "type": "dir", "size": None, "sha256": None}
    try:
        digest = hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError as exc:
        # An unreadable file must be visibly unreadable in the evidence,
        # not shaped like a dir/symlink's benign sha256:null.
        return {
            "path": rel,
            "type": "file",
            "size": st.st_size,
            "sha256": None,
            "error": exc.__class__.__name__,
        }
    return {"path": rel, "type": "file", "size": st.st_size, "sha256": digest}


def build_manifest(reference: pathlib.Path) -> list:
    """Per-entry facts for an observed non-empty tree, sorted by path.

    Iterates bench.guarded_walk, so it shares the count's exact
    traversal semantics: directory symlinks are not followed (a
    symlinked subtree is recorded as one symlink entry) and scandir
    failures raise rather than silently truncating the evidence.
    """
    entries = []
    for dirpath, dirnames, filenames in bench.guarded_walk(reference):
        base = pathlib.Path(dirpath)
        for name in dirnames + filenames:
            entries.append(_manifest_entry(base / name, reference))
    entries.sort(key=lambda entry: entry["path"])
    return entries


def write_manifest(reference: pathlib.Path, repo: pathlib.Path) -> str:
    """Write the manifest; its entry_count is derived from its own walk
    (the mount may have changed between the counting walk and this one,
    so the evidence file must be internally consistent).

    Written atomically (per-process unique temp file + os.replace):
    concurrent gate runs (e.g. bench and verify_reference in the same
    round) or a crash mid-write must never leave truncated JSON in the
    evidence file.
    """
    manifest_path = repo / MANIFEST_NAME
    entries = build_manifest(reference)
    payload = {
        "comment": (
            "A NON-EMPTY reference tree was observed. SURVEY.md (which "
            "surveyed an empty tree) is obsolete and must be rewritten "
            "from this real tree before any build work; this manifest is "
            "the evidence to start that rewrite from. Only the mounted "
            "tree defines capabilities."
        ),
        "reference_path": str(reference),
        "entry_count": len(entries),
        "entries": entries,
    }
    fd, tmp_name = tempfile.mkstemp(
        dir=repo, prefix=MANIFEST_NAME + ".", suffix=".tmp"
    )
    os.fchmod(fd, 0o644)  # mkstemp's 0600 would survive os.replace
    os.close(fd)
    tmp_path = pathlib.Path(tmp_name)
    try:
        tmp_path.write_text(json.dumps(payload, indent=2) + "\n")
        os.replace(tmp_path, manifest_path)
    except OSError:
        try:
            tmp_path.unlink()
        except OSError:
            pass
        raise
    return str(manifest_path)


def verify(reference: pathlib.Path, repo: pathlib.Path, scan_result: dict = None):
    """Compare the live mount + sidecars to the committed fingerprint.

    Returns (result_dict, exit_code) — the JSON-serializable evidence
    and the exit code documented in the module docstring. Used by
    main() and embedded by bench.main() into the driver's bench line;
    scan_result lets bench pass its own scan() so the mount is walked
    once per invocation.
    """
    fingerprint_path = repo / FINGERPRINT_NAME
    try:
        fingerprint = json.loads(fingerprint_path.read_text())
        if not isinstance(fingerprint, dict):
            raise ValueError("fingerprint must be a JSON object")
        fingerprint_count = fingerprint.get("reference_entry_count")
        # A non-int count (e.g. an error sentinel pasted from an observed
        # block during a mount outage) would make every future transient
        # failure "match" with rc 0 — treat it as a corrupt fingerprint.
        if (
            not isinstance(fingerprint_count, int)
            or isinstance(fingerprint_count, bool)
            or fingerprint_count < 0
        ):
            raise ValueError("reference_entry_count must be a non-negative int")
        # Same defense for the sidecar facts: a missing/null/mistyped key
        # is a corrupt fingerprint (rc 2, fix the repo), not "the sidecars
        # drifted" (rc 1, a verdict-affecting workflow).
        for key in ("baseline_json_sha256", "papers_md_sha256"):
            if not isinstance(fingerprint.get(key), str):
                raise ValueError(f"{key} must be a string")
        if not isinstance(fingerprint.get("snippets_md_present"), bool):
            raise ValueError("snippets_md_present must be a bool")
    except (OSError, ValueError):
        return (
            {
                "check": "reference_verification",
                "error": "fingerprint_missing_or_corrupt",
                "fingerprint_path": str(fingerprint_path),
            },
            EXIT_FINGERPRINT_CORRUPT,
        )

    observed = gather(reference, repo, scan_result)
    drift = [
        {"fact": key, "fingerprint": fingerprint.get(key), "observed": observed[key]}
        for key in COMPARED_KEYS
        if observed[key] != fingerprint.get(key)
    ]
    count = observed["reference_entry_count"]
    transient = count in ("mount_missing_or_unreadable", "scan_error")

    manifest = None
    manifest_error = None
    if isinstance(count, int) and count > 0:
        try:
            manifest = write_manifest(reference, repo)
        except OSError as exc:
            manifest_error = exc.__class__.__name__

    non_count_drift = [d for d in drift if d["fact"] != "reference_entry_count"]

    if not drift:
        exit_code = EXIT_MATCH
        if count == 0:
            note = "reference still empty; non-graftable verdict stands"
        else:
            # Reachable only after a deliberate fingerprint update to a
            # re-populated reference: a match must not keep endorsing the
            # old emptiness claim.
            note = (
                f"matches fingerprint, which records a NON-EMPTY tree "
                f"({count} entries): the non-graftable verdict no longer "
                "applies — build against the surveyed tree."
                + (" See the manifest." if manifest is not None else "")
            )
    elif transient and not non_count_drift:
        exit_code = EXIT_TRANSIENT
        note = (
            "TRANSIENT ENVIRONMENT FAILURE: the mount could not be scanned "
            "(absent, unreadable, or going stale mid-walk). This is NOT "
            "evidence the reference changed — there is no tree to re-survey. "
            "Investigate the mount / re-run; do not touch SURVEY.md."
        )
    else:
        # Sidecar drift is genuine drift even when the mount is also
        # unscannable this run — rc 3 must never mask it from
        # exit-code-only consumers.
        exit_code = EXIT_DRIFT
        note = (
            "DRIFT: the surveyed state changed. If the reference tree is "
            "non-empty, SURVEY.md is obsolete — rewrite it from the real tree "
            "before writing any code"
            + (
                " (see the manifest for the observed entries)"
                if manifest is not None
                else ""
            )
            + ". Sidecar-only drift (PAPERS/SNIPPETS) does not add "
            "capabilities: only the mounted tree defines what to build."
        )
        if transient:
            note += (
                " NOTE: the mount itself could not be scanned this run "
                "(transient environment failure), so only the sidecar drift "
                "is confirmed; re-run once the mount is back."
            )

    result = {
        "check": "reference_verification",
        "reference_path": str(reference),
        "reference_empty": count == 0,
        "matches_fingerprint": not drift,
        "transient_environment_failure": transient,
        "drift": drift,
        "observed": observed,
        "mount_stat": mount_stat(reference),
        "manifest": manifest,
        "note": note,
    }
    if manifest_error is not None:
        result["manifest_error"] = manifest_error
    return result, exit_code


def main() -> int:
    reference = pathlib.Path(os.environ.get("GRAFT_REFERENCE_PATH", DEFAULT_REFERENCE))
    repo = pathlib.Path(
        os.environ.get("GRAFT_REPO_PATH", pathlib.Path(__file__).resolve().parent)
    )
    result, exit_code = verify(reference, repo)
    print(json.dumps(result))
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
