"""jaxlint corpus: device jnp compute in a host-side NumPy ingest path.

This is `engine.pack_batch`'s counting-sort territory: the arrays are
host NumPy, the result feeds a host layout, and every jnp op here pays
a device dispatch plus transfers for work np does in-place.
Rule: jnp-on-host-path."""

import jax.numpy as jnp
import numpy as np


def pack_ids(ids, num_players):
    ids = np.asarray(ids, np.int32)
    order = jnp.argsort(ids)
    bounds = jnp.searchsorted(ids[np.asarray(order)], np.arange(num_players + 1))
    return order, bounds
