"""Keep tests/mutation_audit.py from rotting.

The audit's value rests on each mutation's `old` pattern matching the
live source: a refactor that renames a constant or reflows a line would
otherwise silently turn that mutation into a no-op and the audit into a
false "all killed". These checks run in the regular suite (milliseconds,
no subprocesses) so pattern drift turns the suite red in the same
commit that caused it.

Deliberately NOT copied into the audit's mutated runs (mutation_audit
passes --ignore for this file): under any source mutation the pattern
assertion below fails by construction, which would count as a free
"kill" for every mutant and void the audit. See the audit's module
docstring.
"""

import mutation_audit


def test_every_mutation_pattern_matches_live_source_exactly_once():
    for name, relpath, old, new, _property in mutation_audit.MUTATIONS:
        source = (mutation_audit.REPO / relpath).read_text()
        occurrences = source.count(old)
        assert occurrences == 1, (
            f"mutation {name!r}: pattern occurs {occurrences}x in {relpath} "
            "(must be exactly 1 — update tests/mutation_audit.py in the "
            "same commit as the source refactor)"
        )
        assert old != new, f"mutation {name!r} is a no-op"


def test_mutations_cover_both_runtime_surfaces():
    files = {relpath for _n, relpath, _o, _nw, _p in mutation_audit.MUTATIONS}
    assert files == {"bench.py", "verify_reference.py"}


def test_copied_set_exists_and_excludes_git():
    for name in mutation_audit.COPIED:
        assert (mutation_audit.REPO / name).exists(), name
    assert ".git" not in mutation_audit.COPIED
