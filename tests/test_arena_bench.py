"""Contract + acceptance tests for arena/bench_arena.py.

The smoke test (tier-1) runs the REAL subprocess entrypoint at a small
size: one JSON line, rc 0, schema intact, vectorized path faster than
naive, numerics verified. The full acceptance run — 100k matches /
1k players, >= 50x — is `slow` (it is exactly what
`python arena/bench_arena.py` measures; run it on demand or via
`-m slow`).
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "arena" / "bench_arena.py"

CONTRACT_KEYS = {
    "metric", "value", "unit", "vs_baseline", "params", "elo", "bt",
    "equivalence_ok", "max_rating_diff", "sharded",
}


def run_bench(env_overrides, timeout=240, expect_rc=0):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update(env_overrides)
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)],
        capture_output=True,
        text=True,
        env=env,
        cwd="/tmp",  # must work from any cwd
        timeout=timeout,
    )
    assert proc.returncode == expect_rc, (proc.returncode, proc.stderr)
    lines = proc.stdout.splitlines()
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    return json.loads(lines[0])


def assert_contract(result):
    assert set(result) == CONTRACT_KEYS
    assert result["metric"] == "arena_elo_update_speedup"
    assert result["unit"] == "x_vs_naive_baseline"
    assert result["vs_baseline"] is None
    assert result["equivalence_ok"] is True, (
        "speedup reported over non-equivalent computations: "
        f"max_rating_diff={result['max_rating_diff']}"
    )


def test_bench_smoke_contract_and_speedup(tmp_path):
    """Fast-path version of the acceptance comparison (tier-1) — also
    pins the watchdog history contract: with ARENA_BENCH_HISTORY set,
    the emitted line is APPENDED verbatim to the JSON Lines file
    `python -m arena.obs.regress` reads."""
    history = tmp_path / "hist.jsonl"
    result = run_bench(
        {
            "ARENA_BENCH_MATCHES": "2000",
            "ARENA_BENCH_PLAYERS": "64",
            "ARENA_BENCH_BATCH": "512",
            "ARENA_BENCH_REPEATS": "3",
            "ARENA_BENCH_BT_ITERS": "5",
            "ARENA_BENCH_HISTORY": str(history),
        }
    )
    assert_contract(result)
    lines = history.read_text().splitlines()
    assert len(lines) == 1 and json.loads(lines[0]) == result
    assert result["params"]["num_matches"] == 2000
    # Even at smoke size (where fixed dispatch overhead is at its most
    # punishing relative to work), vectorized must beat the loop.
    assert result["value"] > 1.0
    assert result["elo"]["jit_matches_per_s"] > result["elo"]["naive_matches_per_s"]
    assert result["bt"]["iter_speedup"] > 0
    assert result["sharded"] is None  # XLA_FLAGS stripped: single device


@pytest.mark.slow
def test_bench_full_size_hits_50x_with_sharded_path():
    """The PR's acceptance number, at the acceptance size, through the
    real entrypoint — plus the sharded path on a forced 2-device mesh."""
    result = run_bench({"ARENA_BENCH_DEVICES": "2"}, timeout=600)
    if result["value"] < 50.0:
        # One retry: a single sub-50 reading on this shared 1-core box
        # is timing noise (typical readings are 55-70x); a real
        # regression fails twice.
        result = run_bench({"ARENA_BENCH_DEVICES": "2"}, timeout=600)
    assert_contract(result)
    assert result["params"]["num_matches"] == 100_000
    assert result["params"]["num_players"] == 1_000
    assert result["value"] >= 50.0, f"speedup regressed: {result['value']}x"
    assert result["sharded"]["devices"] == 2
    assert result["sharded"]["matches_per_s"] > 0


INGEST_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "ingest",
    "ARENA_BENCH_MATCHES": "20000",
    "ARENA_BENCH_DELTA": "2000",
    "ARENA_BENCH_PLAYERS": "64",
    "ARENA_BENCH_BATCH": "2048",
    "ARENA_BENCH_REPEATS": "2",
    "ARENA_BENCH_BT_ITERS": "5",
    "ARENA_BENCH_CHUNK_ENTRIES": "4096",
}


def test_ingest_bench_smoke_contract():
    """ARENA_BENCH_MODE=ingest through the real entrypoint: one JSON
    line, rc 0, the arena_ingest metric with the incremental merge
    beating the cold re-pack, zero steady-state compiles, and the
    chunked BT peak bucket strictly under the single pow2 pad."""
    result = run_bench(INGEST_SMOKE_ENV)
    assert result["metric"] == "arena_ingest"
    assert result["unit"] == "x_vs_cold_repack"
    assert result["equivalence_ok"] is True
    # Even at smoke size the delta merge must beat repacking the world.
    assert result["value"] > 1.0
    assert result["ingest"]["steady_state_new_compiles"] == 0
    assert result["ingest"]["incremental_merge_s"] < result["ingest"]["cold_pack_s"]
    assert result["bt"]["chunked_peak_entries"] < result["bt"]["single_bucket_entries"]
    assert result["max_rating_diff"] < 0.5
    assert result["params"]["delta_matches"] == 2000
    # The instrumentation-overhead gate ran (rc 0 means it passed) and
    # the live registry actually recorded every instrumented build:
    # one whole-set (base + delta) live build per repeat.
    assert result["obs"]["tolerance"] > 0
    assert result["obs"]["csr_merges_counted"] == 22000 * 2
    assert result["obs"]["spans_recorded"] > 0


def test_ingest_bench_equivalence_gate_extends_to_incremental_path(tmp_path):
    """The hard gate on the INCREMENTAL path: forcing the chunked-vs-
    single BT tolerance to 0 must emit the distinct equivalence-failure
    line (ingest-mode unit, no speedup fields) and exit rc 2 — and,
    since PR 7, ship a flight-recorder bundle path next to the verdict
    (registry dump + Chrome trace, the postmortem evidence)."""
    result = run_bench(
        {
            **INGEST_SMOKE_ENV,
            "ARENA_BENCH_BT_TOL": "0",
            "ARENA_DEBUG_DIR": str(tmp_path),
        },
        expect_rc=2,
    )
    assert result["metric"] == "arena_bench_equivalence_failure"
    assert result["value"] == -1
    assert result["unit"] == "x_vs_cold_repack"
    assert result["tolerance"] == 0.0
    assert "exceeds tolerance" in result["error"]
    assert "ingest" not in result and "bt" not in result
    bundle = pathlib.Path(result["debug_bundle"])
    assert bundle.parent == tmp_path
    assert (bundle / "metrics.json").exists()
    assert (bundle / "trace.json").exists()
    metrics = json.loads((bundle / "metrics.json").read_text())
    assert metrics["counters"], "bundle registry dump must carry counters"


@pytest.mark.slow
def test_ingest_bench_full_size_hits_5x():
    """The acceptance number: a 10k delta merged into a 100k base at
    least 5x faster than the cold re-pack of the combined set, through
    the real entrypoint at the default sizes."""
    result = run_bench({"ARENA_BENCH_MODE": "ingest"}, timeout=600)
    if result["value"] < 5.0:
        result = run_bench({"ARENA_BENCH_MODE": "ingest"}, timeout=600)
    assert result["metric"] == "arena_ingest"
    assert result["params"]["base_matches"] == 100_000
    assert result["params"]["delta_matches"] == 10_000
    assert result["value"] >= 5.0, f"incremental merge regressed: {result['value']}x"
    assert result["ingest"]["steady_state_new_compiles"] == 0
    assert result["bt"]["chunked_peak_entries"] < result["bt"]["single_bucket_entries"]


PIPELINE_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "pipeline",
    "ARENA_BENCH_MATCHES": "20000",
    "ARENA_BENCH_DELTA": "2000",
    "ARENA_BENCH_STREAM_BATCHES": "4",
    "ARENA_BENCH_PLAYERS": "64",
    "ARENA_BENCH_BATCH": "2048",
    "ARENA_BENCH_REPEATS": "2",
}


def test_pipeline_bench_smoke_contract():
    """ARENA_BENCH_MODE=pipeline through the real entrypoint: one JSON
    line, rc 0, the arena_pipeline metric with the async ratings
    BIT-EXACT to sync (max_rating_diff 0.0 — same slots, same jitted
    update, same order), zero steady-state compiles with the packer
    thread running, nothing dropped under the block policy, and the
    host-pack vs device-dispatch breakdown populated."""
    result = run_bench(PIPELINE_SMOKE_ENV)
    assert result["metric"] == "arena_pipeline"
    assert result["unit"] == "x_vs_sync_ingest"
    assert result["equivalence_ok"] is True
    assert result["value"] > 0
    assert result["max_rating_diff"] == 0.0
    assert result["max_rating_diff_vs_cold"] < 0.5
    assert result["pipeline"]["steady_state_new_compiles"] == 0
    assert result["pipeline"]["dropped_batches"] == 0
    assert result["pipeline"]["host_pack_s"] > 0
    assert result["pipeline"]["dispatch_s"] > 0
    assert result["params"]["host_cores"] >= 1
    assert result["params"]["policy"] == "block"
    # The instrumented twin streamed the same batches within budget
    # (rc 0 means the overhead hard gate passed) and recorded spans.
    assert result["obs"]["null_s"] > 0 and result["obs"]["live_s"] > 0
    assert result["obs"]["spans_recorded"] > 0


def test_pipeline_bench_equivalence_gate_extends_to_async_path():
    """The hard gate covers the ASYNC path: with the tolerance forced
    to 0 even a bit-exact run trips it (no diff is < 0), emitting the
    distinct equivalence-failure line (pipeline-mode unit, no speedup
    fields) and rc 2 — so the gate being skipped in pipeline mode is
    loudly visible (the mutation audit carries exactly that mutant)."""
    result = run_bench(
        {**PIPELINE_SMOKE_ENV, "ARENA_BENCH_TOL": "0"}, expect_rc=2
    )
    assert result["metric"] == "arena_bench_equivalence_failure"
    assert result["value"] == -1
    assert result["unit"] == "x_vs_sync_ingest"
    assert result["tolerance"] == 0.0
    assert "exceeds tolerance" in result["error"]
    assert "pipeline" not in result and "bt" not in result
    # The rc-2 line ships a flight-recorder bundle (instrumented mode).
    assert result["debug_bundle"] is not None


@pytest.mark.slow
def test_pipeline_bench_full_size_streams_clean():
    """The full-size overlapped run through the real entrypoint: the
    equivalence gate, the recompile sentinel, and lossless streaming
    all hold at 100k base / 10k streamed batches. Deliberately NO
    speedup floor: on this 1-core image the packer and dispatcher
    share one CPU, so the overlap cannot beat sync wall-clock (the
    line's `note` and `host_cores` record that); the measured value is
    reported, not asserted against hardware that cannot show it."""
    result = run_bench({"ARENA_BENCH_MODE": "pipeline"}, timeout=600)
    assert result["metric"] == "arena_pipeline"
    assert result["params"]["base_matches"] == 100_000
    assert result["params"]["stream_batch"] == 10_000
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["pipeline"]["steady_state_new_compiles"] == 0
    assert result["pipeline"]["dropped_batches"] == 0
    assert result["value"] > 0.5


SERVE_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "serve",
    "ARENA_BENCH_MATCHES": "20000",
    "ARENA_BENCH_DELTA": "2000",
    "ARENA_BENCH_STREAM_BATCHES": "4",
    "ARENA_BENCH_PLAYERS": "64",
    "ARENA_BENCH_BATCH": "2048",
    "ARENA_BENCH_REPEATS": "2",
    "ARENA_BENCH_BOOTSTRAP_ROUNDS": "4",
}


def test_serve_bench_smoke_contract():
    """ARENA_BENCH_MODE=serve through the real entrypoint: one JSON
    line, rc 0, the arena_serve metric with a BIT-EXACT snapshot/
    restore round-trip (max_rating_diff and max_resume_diff both 0.0 —
    ratings reload raw, the grouping reloads without re-sorting), a
    positive query throughput under concurrent ingest, no torn views
    (mass deviation inside the gate), zero steady-state compiles
    across serve + ingest threads, and the production-mode sanitizer
    counters in the line."""
    result = run_bench(SERVE_SMOKE_ENV)
    assert result["metric"] == "arena_serve"
    assert result["unit"] == "queries_per_s"
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["max_resume_diff"] == 0.0
    assert result["value"] > 0
    assert result["serve"]["queries_under_ingest"] > 0
    assert result["serve"]["snapshot_s"] > 0
    assert result["serve"]["restore_s"] > 0
    assert result["serve"]["snapshot_matches"] == 20000
    assert result["serve"]["steady_state_new_compiles"] == 0
    assert result["serve"]["max_view_mass_dev"] < 0.5
    assert result["serve"]["donation_skipped"] == 0
    assert result["params"]["max_staleness_matches"] == 2000


def test_serve_bench_equivalence_gate_is_hard():
    """The hard gate covers the serve path: with the tolerance forced
    to 0 even the bit-exact round-trip trips it (no diff is < 0) —
    the distinct equivalence-failure line (serve-mode unit, no
    throughput fields) and rc 2, so a silently skipped gate is loudly
    visible."""
    result = run_bench(
        {**SERVE_SMOKE_ENV, "ARENA_BENCH_TOL": "0"}, expect_rc=2
    )
    assert result["metric"] == "arena_bench_equivalence_failure"
    assert result["value"] == -1
    assert result["unit"] == "queries_per_s"
    assert result["tolerance"] == 0.0
    assert "exceeds tolerance" in result["error"]
    assert "serve" not in result and "bt" not in result
    assert result["debug_bundle"] is not None


@pytest.mark.slow
def test_serve_bench_full_size_round_trips_100k_bit_exact():
    """The acceptance criterion at the acceptance size: the 100k-match
    base round-trips bit-exact, queries never observe a torn view, and
    the steady state stays compile-free with both threads running."""
    result = run_bench({"ARENA_BENCH_MODE": "serve"}, timeout=600)
    assert result["metric"] == "arena_serve"
    assert result["params"]["base_matches"] == 100_000
    assert result["serve"]["snapshot_matches"] == 100_000
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["max_resume_diff"] == 0.0
    assert result["serve"]["steady_state_new_compiles"] == 0
    assert result["value"] > 0


SOAK_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "soak",
    "ARENA_BENCH_MATCHES": "20000",
    "ARENA_BENCH_DELTA": "2000",
    "ARENA_BENCH_SOAK_BATCHES": "8",
    "ARENA_BENCH_PLAYERS": "64",
    "ARENA_BENCH_BATCH": "2048",
    "ARENA_BENCH_BOOTSTRAP_ROUNDS": "4",
}


def test_soak_bench_smoke_contract():
    """ARENA_BENCH_MODE=soak through the real entrypoint: one JSON
    line, rc 0, the arena_soak metric with p50/p99 query latency,
    ingest throughput, queue-depth and staleness distributions,
    interval refreshes AND snapshots inside the measured window, ZERO
    recompile events across the whole mixed workload (the hard gate),
    and the final ratings bit-exact to the sync replay."""
    result = run_bench(SOAK_SMOKE_ENV)
    assert result["metric"] == "arena_soak"
    assert result["unit"] == "p99_query_latency_ms"
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["value"] > 0
    soak = result["soak"]
    assert soak["queries"] > 0
    assert soak["query_latency_ms"]["p50"] > 0
    assert soak["query_latency_ms"]["p99"] >= soak["query_latency_ms"]["p50"]
    assert soak["stream_matches_per_s"] > 0
    assert soak["queue_depth"]["count"] == 8  # one sample per batch
    assert soak["staleness_matches"]["count"] == soak["queries"] + 1
    assert soak["interval_refreshes"] == 2 and soak["snapshots"] == 2
    # The soak's reason to exist: the production counters stayed flat.
    assert soak["recompile_events"] == 0
    assert soak["donation_skipped"] == 0
    assert soak["dropped_batches"] == 0
    assert soak["trace_spans_recorded"] > 0
    # Causal diagnosis held through the soak: every span chains to a
    # root (zero DANGLING orphans), and the p99 query-latency bucket
    # carries a resolvable exemplar trace id.
    assert soak["trace_dangling_orphans"] == 0
    assert soak["p99_exemplar"]["trace_id"] > 0
    assert soak["max_view_mass_dev"] < 0.5
    assert result["params"]["max_staleness_matches"] == 2000


def test_soak_bench_gate_is_hard():
    """The soak gate covers equivalence AND the recompile counter:
    with the tolerance forced to 0 even a bit-exact run trips it (no
    diff is < 0) — the distinct equivalence-failure line and rc 2, so
    a silently skipped soak gate is loudly visible (the mutation audit
    carries exactly that mutant; this is its named kill)."""
    result = run_bench(
        {**SOAK_SMOKE_ENV, "ARENA_BENCH_TOL": "0"}, expect_rc=2
    )
    assert result["metric"] == "arena_bench_equivalence_failure"
    assert result["value"] == -1
    assert result["unit"] == "p99_query_latency_ms"
    assert result["tolerance"] == 0.0
    assert "exceeds tolerance" in result["error"]
    assert "soak" not in result
    assert result["debug_bundle"] is not None


@pytest.mark.slow
def test_soak_bench_full_size_stays_compile_free():
    """The acceptance run: the full-size mixed workload (100k base,
    16 streamed 10k batches with periodic snapshots and interval
    refreshes under concurrent queries) holds recompile_events == 0
    and sync-replay equivalence end to end."""
    result = run_bench({"ARENA_BENCH_MODE": "soak"}, timeout=600)
    assert result["metric"] == "arena_soak"
    assert result["params"]["base_matches"] == 100_000
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["soak"]["recompile_events"] == 0
    assert result["soak"]["queries"] > 0
    assert result["soak"]["snapshots"] == 4
    assert result["soak"]["interval_refreshes"] == 4


FRONTEND_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "frontend",
    "ARENA_BENCH_MATCHES": "20000",
    "ARENA_BENCH_DELTA": "500",
    "ARENA_BENCH_PLAYERS": "64",
    "ARENA_BENCH_BATCH": "2048",
    "ARENA_BENCH_FRONTEND_BATCHES": "4",
    "ARENA_BENCH_OVERLOAD_BATCHES": "6",
}


def test_frontend_bench_smoke_contract():
    """ARENA_BENCH_MODE=frontend through the real entrypoint: one JSON
    line, rc 0, the arena_frontend metric with N=4 producers + M=2
    readers over REAL localhost HTTP — ratings bit-exact to the sync
    sequence-order replay of the applied log (max_rating_diff 0.0),
    zero steady-state compiles across all threads, and the forced-
    overload phase actually shedding: coalesced batches counted under
    policy="coalesce", staleness held within the configured bound,
    every shed trace ended with its dropped marker, zero dangling
    orphans at quiescence."""
    result = run_bench(FRONTEND_SMOKE_ENV, timeout=300)
    assert result["metric"] == "arena_frontend"
    assert result["unit"] == "wire_queries_per_s"
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["value"] > 0
    assert result["params"]["producers"] == 4
    assert result["params"]["readers"] == 2
    fe = result["frontend"]
    assert fe["wire_queries"] > 0
    assert fe["ingest_matches_per_s"] > 0
    assert fe["steady_state_new_compiles"] == 0
    # The wire really carried the traffic: per-endpoint counters from
    # the ONE registry (submits = warmup + phase-1 + overload).
    assert fe["requests_by_endpoint"]["submit"] == 1 + 4 * 4 + 4 * 6
    assert fe["requests_by_status"]["202"] == fe["requests_by_endpoint"]["submit"]
    assert fe["requests_by_endpoint"]["leaderboard"] > 0
    # The overload phase exercised the shedding policy, boundedly.
    assert fe["shed_batches"] > 0
    assert fe["shed_by_policy"]["coalesce"] == fe["shed_batches"]
    assert fe["max_staleness_matches_seen"] <= fe["staleness_bound"]
    assert fe["dropped_marker_spans"] >= fe["shed_batches"]
    assert fe["trace_dangling_orphans"] == 0
    assert fe["max_view_mass_dev"] < 0.5


def test_frontend_bench_equivalence_gate_is_hard(tmp_path):
    """The hard gate covers the wire path: with the tolerance forced
    to 0 even a bit-exact run trips it (no diff is < 0) — the distinct
    equivalence-failure line (frontend-mode unit, no throughput
    fields), rc 2, and a flight-recorder bundle next to the verdict."""
    result = run_bench(
        {
            **FRONTEND_SMOKE_ENV,
            "ARENA_BENCH_TOL": "0",
            "ARENA_DEBUG_DIR": str(tmp_path),
        },
        timeout=300,
        expect_rc=2,
    )
    assert result["metric"] == "arena_bench_equivalence_failure"
    assert result["value"] == -1
    assert result["unit"] == "wire_queries_per_s"
    assert result["tolerance"] == 0.0
    assert "exceeds tolerance" in result["error"]
    assert "frontend" not in result
    bundle = pathlib.Path(result["debug_bundle"])
    assert bundle.parent == tmp_path
    assert (bundle / "metrics.json").exists()


@pytest.mark.slow
def test_frontend_bench_full_size_over_real_http():
    """The acceptance run at the acceptance size: 4 producers x 6 x
    10k-match batches + 2 readers over real HTTP against the 100k
    base — bit-exact sequence-order replay, zero steady-state
    compiles, bounded shedding under forced overload."""
    result = run_bench({"ARENA_BENCH_MODE": "frontend"}, timeout=600)
    assert result["metric"] == "arena_frontend"
    assert result["params"]["base_matches"] == 100_000
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["value"] > 0
    fe = result["frontend"]
    assert fe["steady_state_new_compiles"] == 0
    assert fe["shed_batches"] > 0
    assert fe["max_staleness_matches_seen"] <= fe["staleness_bound"]
    assert fe["trace_dangling_orphans"] == 0


REPLICA_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "replica",
    "ARENA_BENCH_MATCHES": "20000",
    "ARENA_BENCH_DELTA": "500",
    "ARENA_BENCH_PLAYERS": "64",
    "ARENA_BENCH_BATCH": "2048",
    "ARENA_BENCH_CATCHUP_BATCHES": "2",
    "ARENA_BENCH_READ_WINDOW_S": "0.3",
}


def test_replica_bench_smoke_contract():
    """ARENA_BENCH_MODE=replica through the real entrypoint: one JSON
    line, rc 0, the arena_replica metric with 2 replicas restoring the
    incremental chain and tailing GET /log over REAL localhost HTTP —
    the incremental cut >= 5x smaller than a full cut at the same
    watermark, replica ratings bit-exact to the writer's at equal
    watermark, catch-up inside its bound under concurrent wire ingest,
    zero steady-state compiles across writer and replay threads."""
    result = run_bench(REPLICA_SMOKE_ENV, timeout=300)
    assert result["metric"] == "arena_replica"
    assert result["unit"] == "replica_queries_per_s"
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["value"] > 0
    assert result["params"]["replicas"] == 2
    rep = result["replica"]
    snap = rep["snapshot"]
    assert snap["bytes_ratio"] >= 5.0
    assert snap["incremental_bytes"] < snap["full_bytes"]
    assert snap["chain_depth"] == 1
    assert snap["reuses_base_runs"] is True
    assert snap["delta_matches"] == snap["churn_matches"] == 2000
    # The fleet really read and really caught up over the wire.
    assert rep["aggregate_queries_per_s"] > 0
    assert rep["single_server_queries_per_s"] > 0
    assert rep["scaleout_ratio"] >= 0.75
    assert len(rep["per_replica_queries"]) == 2
    assert all(q > 0 for q in rep["per_replica_queries"])
    cu = rep["catchup"]
    assert cu["streamed_matches"] == 2 * 2 * 500
    assert cu["catchup_s"] <= cu["catchup_bound_s"]
    # Warmup batch + every streamed batch reached BOTH replicas.
    assert cu["records_shipped"] == 2 * (1 + cu["streamed_batches"])
    assert cu["segments_fetched"] >= 2
    assert rep["steady_state_new_compiles"] == 0
    assert rep["staleness_slo_registered"] is True


def test_replica_bench_equivalence_gate_is_hard(tmp_path):
    """The bit-exactness gate covers the replica fleet: with the
    tolerance forced below zero even a bit-exact run trips it — the
    distinct equivalence-failure line (replica-mode unit, no
    throughput fields), rc 2, and a flight-recorder bundle next to
    the verdict."""
    result = run_bench(
        {
            **REPLICA_SMOKE_ENV,
            "ARENA_BENCH_TOL": "-1",
            "ARENA_DEBUG_DIR": str(tmp_path),
        },
        timeout=300,
        expect_rc=2,
    )
    assert result["metric"] == "arena_bench_equivalence_failure"
    assert result["value"] == -1
    assert result["unit"] == "replica_queries_per_s"
    assert result["tolerance"] == -1.0
    assert "exceeds tolerance" in result["error"]
    assert "replica" not in result
    bundle = pathlib.Path(result["debug_bundle"])
    assert bundle.parent == tmp_path
    assert (bundle / "metrics.json").exists()


def test_replica_bench_snapshot_size_gate_is_hard():
    """The incremental-size gate is a verdict of its own: an impossible
    ratio floor turns the (really ~10x smaller) delta cut into a
    measured arena_bench_replica_gate_failure at rc 2 — never a
    throughput line."""
    result = run_bench(
        {**REPLICA_SMOKE_ENV, "ARENA_BENCH_INC_RATIO_MIN": "1000"},
        timeout=300,
        expect_rc=2,
    )
    assert result["metric"] == "arena_bench_replica_gate_failure"
    assert result["value"] == -1
    assert result["unit"] == "replica_queries_per_s"
    assert "smaller than a full cut" in result["error"]
    assert "replica" not in result


@pytest.mark.slow
def test_replica_bench_full_size_over_real_http():
    """The acceptance run at the acceptance size: 2 replicas against
    the 100k base with 10k-match stream batches — incremental chain
    >= 5x smaller, bit-exact catch-up under concurrent ingest, zero
    steady-state compiles."""
    result = run_bench({"ARENA_BENCH_MODE": "replica"}, timeout=600)
    assert result["metric"] == "arena_replica"
    assert result["params"]["base_matches"] == 100_000
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["value"] > 0
    rep = result["replica"]
    assert rep["snapshot"]["bytes_ratio"] >= 5.0
    assert rep["steady_state_new_compiles"] == 0
    assert rep["catchup"]["catchup_s"] <= rep["catchup"]["catchup_bound_s"]


TENANT_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "tenant",
    "ARENA_BENCH_TENANTS": "6",
    "ARENA_BENCH_TENANT_PLAYERS": "32",
    "ARENA_BENCH_TENANT_ROUND": "32",
    "ARENA_BENCH_TENANT_ROUNDS": "2",
    # At toy sizes per-call overhead dominates both sides; the speedup
    # FLOOR is a full-size property, so the smoke only checks the
    # machinery (growth sentinel, bit-exactness, ops plane) end to end.
    "ARENA_BENCH_TENANT_MIN_SPEEDUP": "0",
}


def test_tenant_bench_smoke_contract():
    """ARENA_BENCH_MODE=tenant through the real entrypoint: one JSON
    line, rc 0, the arena_tenant metric with 6 tenants fused through
    one engine — tenants grown 5 -> 6 inside the pow2 bucket under the
    recompile sentinel, every tenant bit-exact vs its own dedicated
    engine (the permanently-empty last tenant included), and the
    tenant-labeled counters reconciling on the one live registry."""
    result = run_bench(TENANT_SMOKE_ENV, timeout=300)
    assert result["metric"] == "arena_tenant"
    assert result["unit"] == "x_vs_dedicated_engines"
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["value"] > 0
    assert result["params"]["tenants"] == 6
    assert result["params"]["tenant_bucket"] == 8
    assert result["params"]["grow_from"] == 5
    ten = result["tenant"]
    assert ten["steady_state_new_compiles"] == 0
    assert ten["bit_exact_tenants"] == 6
    assert ten["zero_match_tenant"] == 5
    # Every tenant that received matches is labeled on the ops plane.
    assert ten["ops_plane_tenants_labeled"] == 5
    assert ten["batched_s"] > 0 and ten["dedicated_s"] > 0
    assert ten["timed_matches"] == 2 * 5 * 32  # rounds x active x round


def test_tenant_bench_speedup_gate_is_hard(tmp_path):
    """The fusion floor is a verdict, not a log line: an impossible
    MIN_SPEEDUP turns the run into arena_bench_tenant_gate_failure at
    rc 2 with a flight-recorder bundle — never an arena_tenant line."""
    result = run_bench(
        {
            **TENANT_SMOKE_ENV,
            "ARENA_BENCH_TENANT_MIN_SPEEDUP": "1e9",
            "ARENA_DEBUG_DIR": str(tmp_path),
        },
        timeout=300,
        expect_rc=2,
    )
    assert result["metric"] == "arena_bench_tenant_gate_failure"
    assert result["value"] == -1
    assert result["unit"] == "x_vs_dedicated_engines"
    assert "tenant" not in result
    assert "dedicated loop" in result["error"]
    bundle = pathlib.Path(result["debug_bundle"])
    assert bundle.parent == tmp_path
    assert (bundle / "metrics.json").exists()


@pytest.mark.slow
def test_tenant_bench_full_size_hits_5x():
    """The acceptance run at the acceptance size: 256 tenants x 1k
    players, batched >= 5x the dedicated-engine loop, bit-exact
    per-tenant, zero recompiles across within-bucket growth."""
    result = run_bench({"ARENA_BENCH_MODE": "tenant"}, timeout=600)
    assert result["metric"] == "arena_tenant"
    assert result["params"]["tenants"] == 256
    assert result["value"] >= 5.0
    assert result["equivalence_ok"] is True
    assert result["tenant"]["steady_state_new_compiles"] == 0
    assert result["tenant"]["bit_exact_tenants"] == 256


MATCHLOOP_SMOKE_ENV = {
    "ARENA_BENCH_MODE": "matchloop",
    "ARENA_BENCH_MATCHLOOP_PLAYERS": "16",
    "ARENA_BENCH_MATCHLOOP_PROPOSALS": "8",
    "ARENA_BENCH_MATCHLOOP_BUDGET": "2000",
    "ARENA_BENCH_MATCHLOOP_CORR": "0.9",
    "ARENA_BENCH_MATCHLOOP_SUSTAIN": "3",
    "ARENA_BENCH_MATCHLOOP_REFRESH_EVERY": "4",
    "ARENA_BENCH_BOOTSTRAP_ROUNDS": "4",
    # The advantage FLOOR is a full-size property (the toy ladder
    # converges in a handful of rounds either way): the smoke checks
    # the machinery — three closed HTTP loops, bit-equal replay,
    # recompile sentinel, SLO silence — not the race margin.
    "ARENA_BENCH_MATCHLOOP_MIN_ADVANTAGE": "0",
}


def test_matchloop_bench_smoke_contract():
    """ARENA_BENCH_MODE=matchloop through the real entrypoint: one
    JSON line, rc 0, the arena_matchloop metric with both arms
    converged over real localhost HTTP, the replay arm bit-equal, zero
    steady-state recompiles, and the SLO engine silent."""
    result = run_bench(MATCHLOOP_SMOKE_ENV, timeout=300)
    assert result["metric"] == "arena_matchloop"
    assert result["unit"] == "x_fewer_matches_vs_random"
    assert result["equivalence_ok"] is True
    assert result["max_rating_diff"] == 0.0
    assert result["value"] > 0
    assert result["params"]["players"] == 16
    assert result["params"]["sustain_checks"] == 3
    loop = result["matchloop"]
    assert loop["deterministic_replay_ok"] is True
    assert loop["steady_state_new_compiles"] == 0
    assert loop["slo_alerts_fired"] == 0
    for arm in (loop["active"], loop["random"]):
        assert arm["matches_to_corr"] is not None
        assert arm["final_corr"] >= 0.9
        assert arm["slo_alerts_fired"] == 0
        # Every submitted match came from a served proposal.
        assert arm["proposals_served"] == arm["submitted"]
    assert loop["advantage"] == pytest.approx(
        loop["random"]["matches_to_corr"] / loop["active"]["matches_to_corr"],
        rel=1e-3,
    )


def test_matchloop_convergence_gate_is_hard(tmp_path):
    """The named kill for closed-loop-gate-skipped: an impossible
    MIN_ADVANTAGE must turn the run into
    arena_bench_matchloop_gate_failure at rc 2 with a flight-recorder
    bundle — never an arena_matchloop line. Skip the advantage
    comparison and this becomes a green run."""
    result = run_bench(
        {
            **MATCHLOOP_SMOKE_ENV,
            "ARENA_BENCH_MATCHLOOP_MIN_ADVANTAGE": "1e9",
            "ARENA_DEBUG_DIR": str(tmp_path),
        },
        timeout=300,
        expect_rc=2,
    )
    assert result["metric"] == "arena_bench_matchloop_gate_failure"
    assert result["value"] == -1
    assert result["unit"] == "x_fewer_matches_vs_random"
    assert "matchloop" not in result
    assert "measurably faster" in result["error"]
    bundle = pathlib.Path(result["debug_bundle"])
    assert bundle.parent == tmp_path
    assert (bundle / "metrics.json").exists()


@pytest.mark.slow
def test_matchloop_bench_full_size_beats_random():
    """The acceptance run at the acceptance size: 64 players in four
    hard tiers, active sampling reaching sustained 0.95 rank
    correlation >= 1.1x fewer matches than random pairing at the same
    20k budget, replay bit-equal, zero recompiles, SLOs silent."""
    result = run_bench({"ARENA_BENCH_MODE": "matchloop"}, timeout=600)
    assert result["metric"] == "arena_matchloop"
    assert result["params"]["players"] == 64
    assert result["params"]["budget_matches"] == 20_000
    assert result["value"] >= 1.1
    loop = result["matchloop"]
    assert loop["random_converged"] in (True, False)
    assert loop["active"]["matches_to_corr"] is not None
    assert loop["deterministic_replay_ok"] is True
    assert loop["steady_state_new_compiles"] == 0


def test_bench_equivalence_failure_exits_nonzero_before_any_speedup():
    """The hard gate: with the tolerance forced to 0 the (real, tiny)
    float32-vs-float64 divergence trips it — one JSON line carrying the
    distinct equivalence metric, NO speedup fields, and rc 2 (a
    measured-divergence verdict, not a crash and not rc 0)."""
    result = run_bench(
        {
            "ARENA_BENCH_MATCHES": "2000",
            "ARENA_BENCH_PLAYERS": "64",
            "ARENA_BENCH_BATCH": "512",
            "ARENA_BENCH_REPEATS": "1",
            "ARENA_BENCH_TOL": "0",
        },
        expect_rc=2,
    )
    assert result["metric"] == "arena_bench_equivalence_failure"
    assert result["value"] == -1
    assert result["tolerance"] == 0.0
    assert result["max_rating_diff"] >= 0.0
    assert "exceeds tolerance" in result["error"]
    # The line must not smuggle a speedup or per-path timings along.
    assert "elo" not in result and "bt" not in result and "sharded" not in result
    # elo mode runs uninstrumented: no flight to record, honest null.
    assert result["debug_bundle"] is None


def test_bench_internal_error_degrades_to_error_line():
    """A crashed benchmark must still emit one JSON line and exit 0,
    like bench.py (the driver contract outranks the measurement)."""
    result = run_bench(
        {
            "ARENA_BENCH_MATCHES": "not-a-number",  # int() raises inside the guard
        }
    )
    assert result["metric"] == "arena_bench_internal_error"
    assert result["value"] == -1
    assert result["error"].startswith("ValueError")
