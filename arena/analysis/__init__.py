"""arena.analysis — static analysis + runtime sanitizers for the hot path.

Two halves, deliberately decoupled:

- `arena.analysis.jaxlint` — AST-based lint rules (stdlib only, never
  imports jax) enforcing the engine's performance invariants at source
  level. Since v2 it is a TWO-PASS engine: `arena.analysis.project`
  builds a project-wide symbol table (modules, classes, functions,
  meshes, locks, `guarded_by` contracts, imports resolved), then the
  rules — including the concurrency lock-discipline analyzer in
  `arena.analysis.concurrency` — run with it in scope, so
  cross-module facts (a mesh imported from another file, opposite
  lock-nesting orders in different modules) are lintable. CLI:
  `python -m arena.analysis [--format=human|json] [paths...]`;
  rc 0 = clean, rc 1 = findings, rc 2 = bad path. Findings are
  suppressible inline with `# jaxlint: disable=<rule>` (honored across
  the enclosing statement for multi-line expressions).
- `arena.analysis.sanitize` — opt-in RUNTIME checks (imports jax, and
  deliberately NOT re-exported here): `checked()` wires
  jax_debug_nans/jax_debug_infs, `RecompileSentinel` pins
  zero-new-compiles after warmup, and `donation_guard` poisons donated
  buffers so reuse fails loudly.

The embedded bad-example corpus lives in `arena/analysis/badcorpus/`
(one file per rule, each tripping exactly its rule). Default directory
walks skip it; lint it explicitly to see every rule fire:

    python -m arena.analysis arena/analysis/badcorpus
"""

from arena.analysis.jaxlint import (
    RULES,
    Finding,
    lint_paths,
    lint_source,
    main,
)

__all__ = ["RULES", "Finding", "lint_paths", "lint_source", "main"]
