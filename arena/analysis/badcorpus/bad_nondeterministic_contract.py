"""jaxlint corpus: a `# deterministic` contract broken two hops down.

`stamped_score` promises bit-identical outputs for identical inputs —
the property a log-shipping replica needs to replay the applied_log
bit-exactly. But its helper's helper reads the wall clock and the
value flows into the returned score: two runs of the "same" replay
now disagree. The one-hop analyzers would have missed this; the
call-graph fixpoint does not. Rule: nondeterminism-in-deterministic-fn.
"""

import time


def _jitter():
    return time.time() % 1.0


def _adjusted(base):
    return base + _jitter()


def stamped_score(base):  # deterministic
    return _adjusted(base) * 2.0
