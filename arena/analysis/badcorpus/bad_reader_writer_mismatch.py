"""jaxlint corpus: a reader depends on a field no writer produces.

`parse_rows` is contracted to `corpus-wire@v1` (sidecar fields
{status, rows}) but requires `row_count` from the payload — a field
outside the recorded shape, so no contracted writer is obligated to
send it. The reader works against today's writer by luck and breaks
the day the writer is regenerated from the contract.
Rule: reader-writer-schema-mismatch.
"""


def parse_rows(payload):  # schema: corpus-wire@v1
    if payload.get("status") != "ok":
        raise ValueError("bad payload status")
    expected = payload.get("row_count")
    rows = payload.get("rows")
    if rows is None or len(rows) != expected:
        raise ValueError("row count mismatch")
    return rows
