"""arena.obs — zero-dependency observability: metrics, tracing, diagnosis.

The measurement substrate every subsystem reports through (and every
later PR — network tier, replicas, multi-host — will report through):

- `arena.obs.metrics`  — thread-safe registry of counters, gauges, and
  fixed-bucket log2 histograms over preallocated numpy arrays, with a
  Prometheus-style text `render()`, a one-JSON-line `dump()`, and
  per-bucket `(trace_id, value)` latency exemplars.
- `arena.obs.tracing`  — monotonic-clock stage spans in a bounded
  overwrite-oldest ring buffer with MONOTONIC span ids and
  parent/trace links, exportable as Chrome trace-event JSON with
  cross-thread flow events.
- `arena.obs.context`  — the thread-local / cross-thread trace-context
  carrier (`TraceContext`, `attach`) that turns isolated spans into
  one causal tree per request.
- `arena.obs.debug`    — the flight recorder: `dump_debug_bundle()`
  atomically writes one postmortem directory (Chrome trace, registry
  dump, config, recent events + queue-depth timeline).
- `arena.obs.regress`  — the perf-regression watchdog CLI
  (`python -m arena.obs.regress`) comparing the newest bench-history
  line against a pinned baseline.
- `arena.obs.windows`  — the live half of the registry: a ring of
  cumulative boundary snapshots merged on read into rolling rates and
  windowed log2 quantiles (record stays free; windowed counts stay
  exact).
- `arena.obs.slo`      — declarative SLOs with fast/slow multi-window
  burn-rate alerting over the windowed views; alert transitions land
  in the event log with the offending bucket's trace-id exemplar.
- `arena.obs.profile`  — a continuous sampling profiler folding
  per-thread stacks under stable thread ROLES (packer, dispatcher,
  HTTP workers) into collapsed-stack output.

`Observability.enable_ops()` constructs the three over the same
registry (`start_ops()`/`stop_ops()` manage their two daemon
threads); `ArenaServer` enables them by default and serves them at
`/debug/window`, `/debug/slo`, `/debug/profile`.

`Observability` bundles one registry + one tracer (+ a bounded recent-
event log for the flight recorder) behind the small surface the
instrumented modules call (`span`/`counter`/`gauge`/`histogram`/
`event`/`dump`/`render`), and `NULL` is the shared no-op instance:
every call is a constant-time no-op, nothing allocates, nothing is
recorded. `ArenaEngine` defaults to `NULL` (a library user who never
asked for metrics pays a method call, not a measurement — and the
bench hard-gates that the LIVE registry costs < 3% on the ingest and
pipeline paths, so turning it on is cheap too). `ArenaServer` defaults
to a live instance: a serving surface without latency percentiles and
drop counters cannot stand behind any load-shedding policy.

Nothing in this package imports jax — it must load (and its tests must
run) on boxes with no accelerator stack, the same rule as the linter
half of `arena/analysis`.
"""

import time
from collections import deque

from arena.obs.context import TraceContext, attach, current as current_context
from arena.obs.metrics import (
    DEFAULT_LATENCY_BASE,
    DEFAULT_NUM_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    NullRegistry,
    Registry,
)
from arena.obs.profile import (
    NullProfiler,
    ProfilerError,
    SamplingProfiler,
    thread_role,
)
from arena.obs.slo import (
    SLO,
    NullSLOEngine,
    Selector,
    SLOEngine,
    default_slos,
)
from arena.obs.tracing import NullTracer, SpanRecord, Tracer
from arena.obs.windows import NullWindow, SlidingWindow, WindowError

# Recent structured events kept for the flight recorder (drops, spills,
# queue-depth samples). Bounded: a long soak keeps the newest.
DEFAULT_EVENT_CAPACITY = 1024


class Observability:  # protocol: start_ops->stop_ops
    """One registry + one tracer + one bounded recent-event log, behind
    the instrumentation surface."""

    enabled = True

    def __init__(self, registry=None, tracer=None, trace_capacity=4096,
                 event_capacity=DEFAULT_EVENT_CAPACITY):
        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else Tracer(trace_capacity)
        self.events = deque(maxlen=event_capacity)
        # The live ops plane (PR 13): None until enable_ops() — a plain
        # Observability stays exactly the PR 6 cumulative registry.
        self.windows = None
        self.slo = None
        self.profiler = None

    # --- live ops plane (windows + SLO + profiler) -------------------

    def enable_ops(self, intervals=None, interval_s=None, hz=None,
                   slos=None, clock=None):
        """Construct the sliding window, SLO engine, and profiler over
        this registry (no threads yet — `start_ops()` spawns those).
        Idempotent: the FIRST call's configuration wins, so a bench
        that configures short intervals before handing the obs to
        `ArenaServer` (which calls this with defaults) keeps its
        configuration."""
        from arena.obs import profile as _profile
        from arena.obs import windows as _windows

        if self.windows is None:
            kwargs = {}
            if clock is not None:
                kwargs["clock"] = clock
            self.windows = SlidingWindow(
                self.registry,
                intervals=(
                    intervals if intervals is not None
                    else _windows.DEFAULT_INTERVALS
                ),
                interval_s=(
                    interval_s if interval_s is not None
                    else _windows.DEFAULT_INTERVAL_S
                ),
                **kwargs,
            )
        if self.slo is None:
            self.slo = SLOEngine(self.windows, slos=slos, obs=self)
        if self.profiler is None:
            self.profiler = SamplingProfiler(
                hz=hz if hz is not None else _profile.DEFAULT_HZ
            )
        return self

    def start_ops(self):
        """Start the window-rotation and profiler-sampling threads
        (enables the ops plane first if nobody did)."""
        self.enable_ops()
        self.windows.start()
        self.profiler.start()
        return self

    def stop_ops(self):
        """Stop the ops threads; windowed reads keep working in
        on-read mode and accumulated profiles stay readable."""
        if self.windows is not None:
            self.windows.close()
        if self.profiler is not None:
            self.profiler.close()

    # --- delegation (the only calls instrumented modules make) -------

    def span(self, name):
        return self.tracer.span(name)

    def counter(self, name, **labels):
        return self.registry.counter(name, **labels)

    def gauge(self, name, **labels):
        return self.registry.gauge(name, **labels)

    def histogram(self, name, base=DEFAULT_LATENCY_BASE,
                  num_buckets=DEFAULT_NUM_BUCKETS, **labels):
        return self.registry.histogram(
            name, base=base, num_buckets=num_buckets, **labels
        )

    def event(self, kind, **fields):
        """Append one structured event (monotonic timestamp + kind +
        fields) to the bounded recent-event log — the drop/spill/
        queue-depth record the flight recorder bundles. Cheap (one
        dict + deque append per EVENT, not per match) and fixed
        memory; never read on the hot path."""
        self.events.append({"t": time.perf_counter(), "kind": kind, **fields})

    def render(self):
        """Prometheus text exposition of the registry."""
        return self.registry.render()

    def dump(self):
        """One JSON-able dict: metrics + trace/event accounting."""
        out = self.registry.dump()
        out["trace"] = {
            "spans_recorded": self.tracer.recorded,
            "trace_dropped": self.tracer.dropped,
            "capacity": self.tracer.capacity,
            "events_recorded": len(self.events),
        }
        if self.windows is not None:
            out["ops"] = {
                "window": self.windows.health(),
                "profiler": (
                    self.profiler.health()
                    if self.profiler is not None else None
                ),
                "slo_alerts_fired": (
                    self.slo.alerts_fired() if self.slo is not None else 0
                ),
            }
        return out


class _NullObservability(Observability):
    """The shared no-op instance behind `NULL` (not for direct
    construction — use `NULL`)."""

    enabled = False

    def __init__(self):
        super().__init__(registry=NullRegistry(), tracer=NullTracer(),
                         event_capacity=1)
        self.windows = NullWindow()
        self.slo = NullSLOEngine()
        self.profiler = NullProfiler()

    def event(self, kind, **fields):
        return None

    def enable_ops(self, intervals=None, interval_s=None, hz=None,
                   slos=None, clock=None):
        return self

    def start_ops(self):
        return self

    def stop_ops(self):
        return None

    def dump(self):
        out = super(_NullObservability, self).dump()
        out.pop("ops", None)
        return out


NULL = _NullObservability()

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "NULL",
    "NullProfiler",
    "NullRegistry",
    "NullSLOEngine",
    "NullTracer",
    "NullWindow",
    "Observability",
    "ProfilerError",
    "Registry",
    "SLO",
    "SLOEngine",
    "SamplingProfiler",
    "Selector",
    "SlidingWindow",
    "SpanRecord",
    "TraceContext",
    "Tracer",
    "WindowError",
    "attach",
    "current_context",
    "default_slos",
    "thread_role",
    "DEFAULT_EVENT_CAPACITY",
    "DEFAULT_LATENCY_BASE",
    "DEFAULT_NUM_BUCKETS",
]
