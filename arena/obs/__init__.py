"""arena.obs — zero-dependency observability: metrics + stage tracing.

The measurement substrate every subsystem reports through (and every
later PR — network tier, replicas, multi-host — will report through):

- `arena.obs.metrics`  — thread-safe registry of counters, gauges, and
  fixed-bucket log2 histograms over preallocated numpy arrays, with a
  Prometheus-style text `render()` and a one-JSON-line `dump()`.
- `arena.obs.tracing`  — monotonic-clock stage spans in a bounded
  overwrite-oldest ring buffer, exportable as Chrome trace-event JSON.

`Observability` bundles one registry + one tracer behind the small
surface the instrumented modules call (`span`/`counter`/`gauge`/
`histogram`/`dump`/`render`), and `NULL` is the shared no-op instance:
every call is a constant-time no-op, nothing allocates, nothing is
recorded. `ArenaEngine` defaults to `NULL` (a library user who never
asked for metrics pays a method call, not a measurement — and the
bench hard-gates that the LIVE registry costs < 3% on the ingest and
pipeline paths, so turning it on is cheap too). `ArenaServer` defaults
to a live instance: a serving surface without latency percentiles and
drop counters cannot stand behind any load-shedding policy.

Nothing in this package imports jax — it must load (and its tests must
run) on boxes with no accelerator stack, the same rule as the linter
half of `arena/analysis`.
"""

from arena.obs.metrics import (
    DEFAULT_LATENCY_BASE,
    DEFAULT_NUM_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    NullRegistry,
    Registry,
)
from arena.obs.tracing import NullTracer, Tracer


class Observability:
    """One registry + one tracer, behind the instrumentation surface."""

    enabled = True

    def __init__(self, registry=None, tracer=None, trace_capacity=4096):
        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else Tracer(trace_capacity)

    # --- delegation (the only calls instrumented modules make) -------

    def span(self, name):
        return self.tracer.span(name)

    def counter(self, name, **labels):
        return self.registry.counter(name, **labels)

    def gauge(self, name, **labels):
        return self.registry.gauge(name, **labels)

    def histogram(self, name, base=DEFAULT_LATENCY_BASE,
                  num_buckets=DEFAULT_NUM_BUCKETS, **labels):
        return self.registry.histogram(
            name, base=base, num_buckets=num_buckets, **labels
        )

    def render(self):
        """Prometheus text exposition of the registry."""
        return self.registry.render()

    def dump(self):
        """One JSON-able dict: metrics + trace accounting."""
        out = self.registry.dump()
        out["trace"] = {
            "spans_recorded": self.tracer.recorded,
            "trace_dropped": self.tracer.dropped,
            "capacity": self.tracer.capacity,
        }
        return out


class _NullObservability(Observability):
    """The shared no-op instance behind `NULL` (not for direct
    construction — use `NULL`)."""

    enabled = False

    def __init__(self):
        super().__init__(registry=NullRegistry(), tracer=NullTracer())


NULL = _NullObservability()

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "NULL",
    "NullRegistry",
    "NullTracer",
    "Observability",
    "Registry",
    "Tracer",
    "DEFAULT_LATENCY_BASE",
    "DEFAULT_NUM_BUCKETS",
]
