"""Mechanical round-start verification that the reference is (still) empty.

The single load-bearing fact of this repository is that the upstream
`mark1222/arena` tree mounted at /root/reference contains zero files
(SURVEY.md), which makes the repo non-graftable (NON_GRAFTABLE.md,
BASELINE.json north star). This script makes the round-start gate
mechanical: it re-runs the SURVEY.md verification checks and compares
the results against the committed fingerprint
(reference_fingerprint.json):

- recursive entry count under the reference mount (guarded against the
  mount going stale mid-walk);
- mount stat facts (mode, link count, timestamps) — recorded as
  evidence only, NOT compared: the mount is recreated every round, so
  timestamps legitimately differ while content facts must not;
- sha256 of the driver sidecars BASELINE.json, PAPERS.md and
  SNIPPETS.md — retrieved public content appearing mid-project is the
  most likely vector for accidentally "discovering" capabilities the
  reference never had, so sidecar drift is surfaced explicitly (it
  does NOT by itself change what there is to build: only the mounted
  tree defines capabilities). Each sidecar observation is four-state:
  a sha256 hex digest; "absent" (the file does not exist — a real
  content fact, compared against the fingerprint); "not-a-regular-file"
  (a directory in place of the sidecar — a persistent state change,
  so genuine drift); or "unreadable" (any other OSError — a transient
  read failure that must classify as rc 3, never as drift and never
  as a match).

The JSON line also carries `uncommitted_round_artifacts` — a
best-effort `git status` over the round evidence files (the
driver-written BENCH_r*.json, MULTICHIP_r*.json, VERDICT.md,
ADVICE.md, the fingerprinted sidecars
BASELINE.json/PAPERS.md/SNIPPETS.md, and the gate-written remount
manifest reference_manifest_observed.json), so the round-start rule
"commit the previous round's artifacts first" — and the remount
playbook's commit-the-manifest-first rule — are checked mechanically
instead of relying on a session reading prose. Null when the repo dir
is not a git work tree; never affects the exit code.

Output: exactly ONE JSON line on stdout with the evidence and a `drift`
list. Exit codes (each failure mode distinct, so exit-code-only
consumers — a `set -e` round-start script, a driver hook — can never
misread one as another):

- 0  everything matches the fingerprint: reference still empty,
     sidecars unchanged; the non-graftable verdict stands.
- 1  genuine drift: the reference tree is non-empty, a readable
     sidecar's content changed (including a sidecar appearing,
     disappearing, or being replaced by a directory), or the mount
     path itself exists but is not a directory (a file/FIFO/socket/
     symlink loop in its place — a persistent state change, named in
     the note and in `mount_type_error`). If the tree is non-empty,
     SURVEY.md is obsolete — rewrite it from the real tree before
     writing any code (see SURVEY_REWRITE.md for the mandated
     procedure).
- 2  could not gather evidence: fingerprint missing or corrupt
     (repo bug, fix the fingerprint).
- 3  transient environment failure: the mount is absent (including a
     dangling symlink — the mount is recreated every round, so
     absence means the environment is not ready), unreadable, or went
     stale mid-walk, or a sidecar exists but could not be read. This
     is NOT evidence the surveyed state changed; investigate the
     environment and re-run.
- 4  the gate itself crashed (unhandled exception anywhere, including
     a failure to import its own bench module at load time). Printed
     as a one-line JSON error; a repo bug to fix, carrying no evidence
     about the reference either way. Distinct from rc 1 so a crash
     can never read as "genuine drift".

When a non-empty tree is observed, a per-entry manifest is additionally
written to `reference_manifest_observed.json` in the repo directory —
relative path, type, size, sha256 per entry; types are file / dir /
symlink (with target) / special (FIFO/socket/device, carrying a `mode`
field and never opened, so they cannot hang the walk) / error. This is
evidence to bootstrap the mandated SURVEY.md rewrite, so the
obsolescence path starts from facts instead of a blank page. stdout
stays one JSON line. The manifest (and the gate line's
`manifest_shape`) also classifies the tree's shape: "working-tree";
"vcs-metadata-only" when every entry is git metadata (a bare or hidden
.git tree — the upstream shape BASELINE.json predicts), in which case
the note directs the reader to materialize the committed tree before
surveying, because the absence of working files says nothing about
capabilities; or "vcs-metadata-gitlink" when the sole entry is a .git
FILE (a `gitdir: ...` pointer), in which case the note says to read
the pointer before attempting any `git clone` — the real git dir
lives outside the mount, so the vcs-only clone prescription cannot
work.

The core comparison lives in `verify(reference, repo)` so bench.py can
embed the same evidence in the driver's mandatory bench line every
round (sidecar drift must never depend on a human remembering to run
this script).

Paths are overridable for tests: GRAFT_REFERENCE_PATH (mount) and
GRAFT_REPO_PATH (directory holding the fingerprint and sidecars).
"""

import errno
import hashlib
import json
import os
import pathlib
import re
import stat as stat_module
import subprocess
import sys
import tempfile
import time

EXIT_MATCH = 0
EXIT_DRIFT = 1
EXIT_FINGERPRINT_CORRUPT = 2
EXIT_TRANSIENT = 3
EXIT_INTERNAL_ERROR = 4

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
try:
    import bench  # the accessibility check + guarded walk live in ONE place
except Exception as exc:  # noqa: BLE001 — rc must stay meaningful
    # main()'s rc-4 catch-all cannot see this: the import runs at module
    # load, before main() exists. Without a guard, a missing or broken
    # bench.py would exit with Python's default status 1 — the one
    # remaining path by which a gate crash could read as "genuine drift"
    # (EXIT_DRIFT) to an exit-code-only consumer. bench.exc_detail is
    # unavailable here by definition, so the detail is formatted inline.
    if __name__ != "__main__":
        raise  # importers (tests, bench's lazy embed) need the real error
    print(
        json.dumps(
            {
                "check": "reference_verification",
                "error": "internal_error",
                "detail": f"{exc.__class__.__name__}: {exc}"[:200],
                "note": (
                    "the gate could not import its bench module — a repo "
                    "bug, not evidence about the reference; fix the repo "
                    "and re-run"
                ),
            }
        )
    )
    sys.exit(EXIT_INTERNAL_ERROR)

DEFAULT_REFERENCE = "/root/reference"
FINGERPRINT_NAME = "reference_fingerprint.json"
MANIFEST_NAME = "reference_manifest_observed.json"
# Sidecar fact name -> file the observation reads. The fact names double
# as fingerprint keys; each value is a sha256 hex digest or "absent".
SIDECAR_FILES = {
    "baseline_json_sha256": "BASELINE.json",
    "papers_md_sha256": "PAPERS.md",
    "snippets_md_sha256": "SNIPPETS.md",
}
COMPARED_KEYS = ("reference_entry_count",) + tuple(SIDECAR_FILES)
SIDECAR_ABSENT = "absent"
SIDECAR_UNREADABLE = "unreadable"
SIDECAR_NOT_A_FILE = "not-a-regular-file"
# Mount-type observation states (observe_mount_type). The first is the
# only healthy one; NOT_A_DIR is the persistent wrong-type state that
# must classify as genuine drift, the other two stay transient.
MOUNT_DIR = "dir"
MOUNT_ABSENT = "absent"
MOUNT_NOT_A_DIR = "not-a-directory"
MOUNT_UNREADABLE = "unreadable"
# Observed-count sentinel for the wrong-type state, so the drift entry
# itself names what was found instead of the generic accessibility
# sentinel (which remains for the genuinely transient states).
COUNT_NOT_A_DIRECTORY = "mount_not_a_directory"
# Manifest shapes (classify_manifest_shape). BASELINE.json predicts the
# upstream is "only a bare .git directory": if the driver ever mounts
# that tree as-is, every observed entry is VCS metadata and the real
# source (if any) lives in the git object store — a survey of the
# working files would wrongly conclude "still nothing here".
MANIFEST_SHAPE_VCS_ONLY = "vcs-metadata-only"
MANIFEST_SHAPE_WORKING_TREE = "working-tree"
# A `.git` that is a FILE, not a directory: a gitlink — a one-line
# `gitdir: <path>` pointer to a git dir living OUTSIDE the mount
# (worktree/submodule packaging). Distinct from vcs-metadata-only
# because the playbook's `git clone <mount>` prescription FAILS on it;
# the pointer must be read first.
MANIFEST_SHAPE_VCS_GITLINK = "vcs-metadata-gitlink"
# The manifest walk runs AFTER the counting walk; if the mount empties
# in between, the entries list is empty and neither non-empty shape is
# true. A distinct shape keeps the manifest from ever claiming "a
# NON-EMPTY tree was observed" with entry_count 0 — internally
# contradictory evidence.
MANIFEST_SHAPE_EMPTIED = "emptied-between-walks"
# Top-level names that together are the anatomy of a bare git
# repository directory (objects/refs/HEAD are the load-bearing trio;
# the rest are common companions). Used only as a *subset* test — a
# tree with any non-git top-level entry classifies as a working tree.
BARE_GIT_DIR_NAMES = frozenset((
    "HEAD", "FETCH_HEAD", "ORIG_HEAD", "MERGE_HEAD", "MERGE_MSG",
    "COMMIT_EDITMSG", "config", "description", "hooks", "info",
    "objects", "refs", "packed-refs", "branches", "logs", "index",
    "shallow", "worktrees", "modules",
    # git-generated residue commonly left at a repo dir's top level —
    # without these a single stray gc.log would flip a bare repo to
    # "working-tree" and suppress the materialize warning.
    "gc.log", "gc.pid", "lfs", "sequencer", "rebase-merge",
    "rebase-apply", "CHERRY_PICK_HEAD", "REVERT_HEAD", "BISECT_LOG",
    "BISECT_START", "BISECT_EXPECTED_REV", "AUTO_MERGE",
))
# Orphaned manifest temp files older than this are swept; younger ones
# may belong to a concurrent run mid-write and must be left alone.
STALE_TMP_AGE_S = 3600
_SHA256_HEX = re.compile(r"[0-9a-f]{64}")
# Evidence files the round-start rule says to commit before any other
# work; uncommitted_round_artifacts() reports them mechanically. Mostly
# driver-written (BENCH/MULTICHIP/VERDICT/ADVICE and the fingerprinted
# sidecars: round 4 began with a driver-populated SNIPPETS.md sitting
# untracked — exactly what this check exists to surface), plus the one
# GATE-written evidence file, the remount manifest: on remount day the
# playbook's step 0.4 mandates committing it before reading the tree
# further, and that is the day the hygiene backstop matters most.
# PROGRESS.jsonl is deliberately excluded: the driver rewrites it
# mid-round, so it is expected to be dirty.
ROUND_ARTIFACT_PATTERNS = (
    "BENCH_r*.json",
    "MULTICHIP_r*.json",
    "VERDICT.md",
    "ADVICE.md",
    "BASELINE.json",
    "PAPERS.md",
    "SNIPPETS.md",
    MANIFEST_NAME,
)


def _sha256_of_fd(fd: int) -> str:
    digest = hashlib.sha256()
    while True:
        chunk = os.read(fd, 1 << 20)
        if not chunk:
            break
        digest.update(chunk)
    return digest.hexdigest()


def observe_sidecar(path: pathlib.Path):
    """Four-state sidecar observation; returns (observation, error_detail).

    - sha256 hex digest: present and readable (error_detail None);
    - "absent": the file does not exist (including a dangling symlink).
      A real content fact — a sidecar appearing or disappearing
      relative to the fingerprint is genuine drift, exactly like a
      content change;
    - "not-a-regular-file": the path exists but is not a regular file
      (directory, FIFO, socket, device, symlink loop). Also a real,
      persistent state change — not a read hiccup a re-run could
      clear — so it classifies as genuine drift, and it can never be
      pinned in the fingerprint, so it always drifts. Detected
      race-free by opening with O_NONBLOCK and fstat-ing the open
      descriptor: a blocking open/read of a FIFO would hang the gate
      forever, breaking both scripts' output contracts, and a
      stat-then-open pair would leave a TOCTOU window for the same
      hang;
    - "unreadable": the file may exist but could not be examined or
      read (any other OSError: permissions hiccup, flaky disk, stale
      handle). The true state is unknown, so verify() classifies it
      as transient (rc 3) — never as drift (rc 1), and never as a
      match (rc 0). error_detail carries the class+message for the
      evidence line.

    Note Path.exists() is deliberately NOT used anywhere here: it
    swallows OSErrors into False, which would make a present-but-
    unreadable sidecar indistinguishable from an absent one.
    """
    try:
        # O_NONBLOCK: opening a writer-less FIFO read-only succeeds
        # immediately instead of blocking; regular files ignore the
        # flag. The open itself follows symlinks (a symlink to a
        # regular file is legitimate readable content; a loop raises
        # ELOOP; a socket raises ENXIO — both persistent states).
        fd = os.open(path, os.O_RDONLY | os.O_NONBLOCK)
    except FileNotFoundError:
        return SIDECAR_ABSENT, None
    except IsADirectoryError as exc:
        return SIDECAR_NOT_A_FILE, bench.exc_detail(exc)
    except OSError as exc:
        if exc.errno in (errno.ELOOP, errno.ENXIO):
            return SIDECAR_NOT_A_FILE, bench.exc_detail(exc)
        return SIDECAR_UNREADABLE, bench.exc_detail(exc)
    try:
        # fstat on the OPEN descriptor, so the type check and the read
        # refer to the same filesystem object — no stat-to-open race.
        st = os.fstat(fd)
        if not stat_module.S_ISREG(st.st_mode):
            return (
                SIDECAR_NOT_A_FILE,
                "not a regular file: " + stat_module.filemode(st.st_mode),
            )
        return _sha256_of_fd(fd), None
    except OSError as exc:
        return SIDECAR_UNREADABLE, bench.exc_detail(exc)
    finally:
        os.close(fd)


def observe_mount_type(reference: pathlib.Path):
    """Four-state mount-type observation; returns (state, detail).

    bench.scan deliberately folds every inaccessible-mount state into
    one metric (its job is a state-neutral observation, not a verdict);
    this function supplies the gate's verdict-grade discrimination,
    with the same race-free pattern as observe_sidecar (O_NONBLOCK
    open, then fstat of the OPEN descriptor — a stat-then-open pair
    would leave a TOCTOU window, and a blocking open of a FIFO sitting
    at the mount path would hang the gate forever):

    - "dir": the path opens and fstats as a directory. Reachable only
      in a race (the scan failed moments earlier) — classified
      transient by the caller, a re-run will see the directory.
    - "absent": the path does not exist (FileNotFoundError, including
      a dangling symlink — mirroring observe_sidecar, where a dangling
      symlink is "absent"). For the MOUNT this is transient (rc 3):
      the driver recreates the mount every round, so absence means the
      environment is not ready, unlike a sidecar's absence which is a
      content fact.
    - "not-a-directory": the path EXISTS but is a regular file, FIFO,
      device (fstat), socket (ENXIO), or symlink loop (ELOOP). A
      persistent state change — not a read hiccup a re-run could
      clear — so the caller classifies it as genuine drift (rc 1),
      exactly the doctrine the sidecars got in round 4. detail names
      the type (filemode or errno detail).
    - "unreadable": any other OSError (permissions hiccup, flaky
      disk). True state unknown — transient (rc 3), never drift.
    """
    try:
        fd = os.open(reference, os.O_RDONLY | os.O_NONBLOCK)
    except FileNotFoundError:
        return MOUNT_ABSENT, None
    except OSError as exc:
        if exc.errno in (errno.ELOOP, errno.ENXIO):
            return MOUNT_NOT_A_DIR, bench.exc_detail(exc)
        return MOUNT_UNREADABLE, bench.exc_detail(exc)
    try:
        st = os.fstat(fd)
        if stat_module.S_ISDIR(st.st_mode):
            return MOUNT_DIR, None
        return (
            MOUNT_NOT_A_DIR,
            "not a directory: " + stat_module.filemode(st.st_mode),
        )
    except OSError as exc:
        return MOUNT_UNREADABLE, bench.exc_detail(exc)
    finally:
        os.close(fd)


def count_entries(reference: pathlib.Path, scan_result: dict = None):
    """Recursive entry count, or an error-string sentinel on failure.

    Delegates to bench.scan() so the mount-accessibility check and the
    OSError-guarded walk exist in exactly one place; bench and this gate
    can never disagree about whether the same mount is empty. A caller
    that already ran bench.scan() (bench.main embedding verification)
    passes its result via scan_result so the counting walk is not
    repeated. (A non-empty observation still triggers ONE separate
    traversal: verify() calls build_manifest, classifies the shape from
    those entries, and hands the same entries to write_manifest — so
    the manifest's entry_count reflects that later walk, not this
    count, which may differ if the mount changed in between.)
    """
    result = scan_result if scan_result is not None else bench.scan(reference)
    metric = result["metric"]
    if metric in ("non_graftable_reference_is_empty", "reference_tree_non_empty"):
        return result["value"]
    if metric == "reference_scan_error":
        return "scan_error"
    return "mount_missing_or_unreadable"


def mount_stat(reference: pathlib.Path):
    """Informational stat facts (not compared — mount is recreated per round)."""
    try:
        st = reference.stat()
        return {
            "mode": oct(st.st_mode),
            "nlink": st.st_nlink,
            "size": st.st_size,
            "mtime": st.st_mtime,
        }
    except OSError as exc:
        return {"error": bench.exc_detail(exc)}


def gather(reference: pathlib.Path, repo: pathlib.Path, scan_result: dict = None):
    """Observed facts plus a {fact: error_detail} map for unreadable
    sidecars (empty in the normal case)."""
    observed = {"reference_entry_count": count_entries(reference, scan_result)}
    sidecar_errors = {}
    for key, filename in SIDECAR_FILES.items():
        observed[key], error_detail = observe_sidecar(repo / filename)
        if error_detail is not None:
            sidecar_errors[key] = error_detail
    return observed, sidecar_errors


def uncommitted_round_artifacts(repo: pathlib.Path):
    """Best-effort: driver round artifacts not committed in `repo`'s git
    work tree (untracked or modified), sorted. None when undeterminable
    (not a git repo, git missing/failed) — mechanism for the round-start
    rule "commit the previous round's artifacts first", which recurred
    as a failure in rounds 1-2 while it was prose-only. Never raises and
    never affects the exit code: hygiene reporting must not block the
    drift verdict.
    """
    # Strip inherited GIT_* overrides (GIT_DIR/GIT_WORK_TREE would point
    # `git -C` at a different repo; GIT_INDEX_FILE — exported inside git
    # hooks — would diff against an in-flight index). The deliberate
    # exception is GIT_CEILING_DIRECTORIES, which only bounds upward
    # repo discovery and is how tests pin the "not a git repo" state.
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith("GIT_") or k == "GIT_CEILING_DIRECTORIES"
    }
    try:
        proc = subprocess.run(
            [
                "git",
                "-C",
                str(repo),
                "status",
                "--porcelain",
                "-z",
                "--untracked-files=all",
                "--no-renames",
                "--",
                *ROUND_ARTIFACT_PATTERNS,
            ],
            capture_output=True,
            text=True,
            timeout=10,
            env=env,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        return None
    # Porcelain v1 -z: NUL-separated "XY path" entries, paths verbatim
    # (no C-quoting of spaces/non-ASCII, which line-based parsing would
    # mangle). The pathspec above already restricts output to the
    # artifact patterns.
    return sorted(
        {entry[3:] for entry in proc.stdout.split("\0") if len(entry) > 3}
    )


def _special_entry(rel: str, st: os.stat_result) -> dict:
    """Manifest entry for a FIFO/socket/device: recorded, never opened —
    a blocking read of a writer-less FIFO would hang the gate and break
    the one-line output contract (same hazard observe_sidecar guards)."""
    return {
        "path": rel,
        "type": "special",
        "size": st.st_size,
        "sha256": None,
        "mode": stat_module.filemode(st.st_mode),
    }


def _unreadable_file_entry(rel: str, st: os.stat_result, exc: OSError) -> dict:
    """An unreadable file must be visibly unreadable in the evidence,
    not shaped like a dir/symlink's benign sha256:null."""
    return {
        "path": rel,
        "type": "file",
        "size": st.st_size,
        "sha256": None,
        "error": bench.exc_detail(exc),
    }


def _manifest_entry(path: pathlib.Path, root: pathlib.Path) -> dict:
    rel = path.relative_to(root).as_posix()
    try:
        st = path.lstat()
    except OSError as exc:
        return {"path": rel, "type": "error", "error": bench.exc_detail(exc)}
    if stat_module.S_ISLNK(st.st_mode):
        entry = {"path": rel, "type": "symlink", "size": st.st_size, "sha256": None}
        try:
            entry["target"] = os.readlink(path)
        except OSError as exc:
            # Unreadable must be visibly unreadable, same as the file branch.
            entry["target"] = None
            entry["error"] = bench.exc_detail(exc)
        return entry
    if stat_module.S_ISDIR(st.st_mode):
        return {"path": rel, "type": "dir", "size": None, "sha256": None}
    if not stat_module.S_ISREG(st.st_mode):
        return _special_entry(rel, st)
    try:
        # Same race-free pattern as observe_sidecar: O_NONBLOCK open,
        # then fstat the descriptor so the type check and the read refer
        # to the same object even if the entry changes under us.
        # O_NOFOLLOW because lstat classified this path as a regular
        # file (symlinks got their own branch above): an entry swapped
        # for a symlink mid-walk must surface as an error, not silently
        # hash the link's target under type "file".
        fd = os.open(path, os.O_RDONLY | os.O_NONBLOCK | os.O_NOFOLLOW)
    except OSError as exc:
        return _unreadable_file_entry(rel, st, exc)
    try:
        fst = os.fstat(fd)
        if not stat_module.S_ISREG(fst.st_mode):
            return _special_entry(rel, fst)
        digest = _sha256_of_fd(fd)
    except OSError as exc:
        return _unreadable_file_entry(rel, st, exc)
    finally:
        os.close(fd)
    # Size from the SAME fstat that the digest came from, so the entry's
    # size and sha256 can never describe two different objects.
    return {"path": rel, "type": "file", "size": fst.st_size, "sha256": digest}


def build_manifest(reference: pathlib.Path) -> list:
    """Per-entry facts for an observed non-empty tree, sorted by path.

    Iterates bench.guarded_walk, so it shares the count's exact
    traversal semantics: directory symlinks are not followed (a
    symlinked subtree is recorded as one symlink entry) and scandir
    failures raise rather than silently truncating the evidence.
    """
    entries = []
    for dirpath, dirnames, filenames in bench.guarded_walk(reference):
        base = pathlib.Path(dirpath)
        for name in dirnames + filenames:
            entries.append(_manifest_entry(base / name, reference))
    entries.sort(key=lambda entry: entry["path"])
    return entries


def classify_manifest_shape(entries: list) -> str:
    """"vcs-metadata-only" when EVERY observed entry is git version-
    control metadata; "working-tree" otherwise.

    Two layouts count as VCS-only: a tree whose single top-level entry
    is `.git` (the shape BASELINE.json predicts for the upstream), and
    a tree that IS a bare git directory (top-level names a subset of
    the bare-repo anatomy, with the load-bearing HEAD/objects/refs all
    present). Detection is deliberately strict — any non-git top-level
    entry means working files exist and the normal read order applies.
    The distinction is verdict-critical: in a VCS-only tree the real
    source lives in the object store, so "no README, no entry points"
    is evidence about the PACKAGING, not the capabilities, and the
    playbook must materialize the committed tree before concluding
    anything (SURVEY_REWRITE.md).

    An EMPTY entries list gets its own shape ("emptied-between-walks"):
    this function only runs after the counting walk saw a non-empty
    tree, so no entries means the mount changed underfoot — evidence of
    instability, never of a working tree.

    A `.git` that is a FILE (not a directory) is a GITLINK — a
    `gitdir: <path>` pointer whose target lives outside the mount —
    and gets its own shape ("vcs-metadata-gitlink"): still zero
    working files, but the VCS-only playbook step `git clone <mount>`
    cannot work on it, so the note must say "read the pointer first"
    instead."""
    if not entries:
        return MANIFEST_SHAPE_EMPTIED
    top = {entry["path"].split("/", 1)[0] for entry in entries}
    if top == {".git"}:
        git_entry = next((e for e in entries if e["path"] == ".git"), None)
        if git_entry is not None and git_entry.get("type") == "file":
            return MANIFEST_SHAPE_VCS_GITLINK
        return MANIFEST_SHAPE_VCS_ONLY
    if {"HEAD", "objects", "refs"} <= top and top <= BARE_GIT_DIR_NAMES:
        return MANIFEST_SHAPE_VCS_ONLY
    return MANIFEST_SHAPE_WORKING_TREE


def write_manifest(
    reference: pathlib.Path,
    repo: pathlib.Path,
    entries: list = None,
    shape: str = None,
):
    """Write the manifest; returns its path. The entry_count is derived
    from the entries list actually recorded — by default its own fresh
    walk, or the caller's walk via `entries` (verify() walks once,
    classifies the shape from that walk, then passes the same entries
    AND shape here, so the shape it reports and the manifest it writes
    can never describe two different trees — and the shape survives
    even when the WRITE fails: the classification is evidence from the
    walk, not a property of repo-dir writability). Either way the
    recorded count matches the recorded entries, never the earlier
    counting walk (the mount may have changed in between). The shape
    classification is embedded in the payload so the evidence file
    self-describes.

    Written atomically (per-process unique temp file + os.replace):
    concurrent gate runs (e.g. bench and verify_reference in the same
    round) or a crash mid-write must never leave truncated JSON in the
    evidence file.
    """
    manifest_path = repo / MANIFEST_NAME
    # Sweep temp files orphaned by a crash between mkstemp and os.replace
    # in an earlier run — nothing else ever deletes them. Age-gated so a
    # CONCURRENT run's in-flight temp file (bench and verify_reference
    # can race in the same round) is never unlinked between its
    # write_text and os.replace — only genuinely abandoned ones.
    try:
        for stale in repo.glob(MANIFEST_NAME + ".*.tmp"):
            try:
                if time.time() - stale.stat().st_mtime > STALE_TMP_AGE_S:
                    stale.unlink()
            except OSError:
                pass
    except OSError:
        pass
    if entries is None:
        entries = build_manifest(reference)
    if shape is None:
        shape = classify_manifest_shape(entries)
    if shape == MANIFEST_SHAPE_EMPTIED:
        # The counting walk saw entries; this walk saw none. The
        # comment must describe the race, not assert a non-empty tree
        # the recorded entry_count (0) would contradict.
        comment = (
            "The reference tree EMPTIED BETWEEN WALKS: the counting "
            "walk observed a non-empty tree, but the manifest walk "
            "found no entries. The mount is changing underfoot — this "
            "manifest is evidence of instability, not a survey "
            "baseline; re-run the gate once the mount settles."
        )
    else:
        comment = (
            "A NON-EMPTY reference tree was observed. SURVEY.md (which "
            "surveyed an empty tree) is obsolete and must be rewritten "
            "from this real tree before any build work; this manifest is "
            "the evidence to start that rewrite from. Only the mounted "
            "tree defines capabilities."
            + (
                " SHAPE WARNING: every entry is git version-control "
                "metadata — materialize the committed tree before "
                "surveying (SURVEY_REWRITE.md, 'The bare-git shape')."
                if shape == MANIFEST_SHAPE_VCS_ONLY
                else ""
            )
            + (
                " SHAPE WARNING: the sole entry is a .git GITLINK "
                "FILE (a 'gitdir: ...' pointer) — the real git dir "
                "lives outside the mount; read the pointer before "
                "attempting any git clone (SURVEY_REWRITE.md, 'The "
                "bare-git shape')."
                if shape == MANIFEST_SHAPE_VCS_GITLINK
                else ""
            )
        )
    payload = {
        "comment": comment,
        "reference_path": str(reference),
        "shape": shape,
        "entry_count": len(entries),
        "entries": entries,
    }
    fd, tmp_name = tempfile.mkstemp(
        dir=repo, prefix=MANIFEST_NAME + ".", suffix=".tmp"
    )
    os.fchmod(fd, 0o644)  # mkstemp's 0600 would survive os.replace
    os.close(fd)
    tmp_path = pathlib.Path(tmp_name)
    try:
        tmp_path.write_text(json.dumps(payload, indent=2) + "\n")
        os.replace(tmp_path, manifest_path)
    except OSError:
        try:
            tmp_path.unlink()
        except OSError:
            pass
        raise
    return str(manifest_path)


def verify(reference: pathlib.Path, repo: pathlib.Path, scan_result: dict = None):
    """Compare the live mount + sidecars to the committed fingerprint.

    Returns (result_dict, exit_code) — the JSON-serializable evidence
    and the exit code documented in the module docstring. Used by
    main() and embedded by bench.main() into the driver's bench line;
    scan_result lets bench pass its own scan() so the mount is walked
    once per invocation.
    """
    fingerprint_path = repo / FINGERPRINT_NAME
    try:
        fingerprint = json.loads(fingerprint_path.read_text())
        if not isinstance(fingerprint, dict):
            raise ValueError("fingerprint must be a JSON object")
        fingerprint_count = fingerprint.get("reference_entry_count")
        # A non-int count (e.g. an error sentinel pasted from an observed
        # block during a mount outage) would make every future transient
        # failure "match" with rc 0 — treat it as a corrupt fingerprint.
        if (
            not isinstance(fingerprint_count, int)
            or isinstance(fingerprint_count, bool)
            or fingerprint_count < 0
        ):
            raise ValueError("reference_entry_count must be a non-negative int")
        # Same defense for the sidecar facts: a missing/null/mistyped key
        # is a corrupt fingerprint (rc 2, fix the repo), not "the sidecars
        # drifted" (rc 1, a verdict-affecting workflow). Values must be a
        # sha256 hex digest or the literal "absent" — in particular the
        # transient "unreadable" sentinel must never be pinned, or every
        # future read failure would "match" with rc 0.
        for key in SIDECAR_FILES:
            value = fingerprint.get(key)
            if not isinstance(value, str) or not (
                value == SIDECAR_ABSENT or _SHA256_HEX.fullmatch(value)
            ):
                raise ValueError(f"{key} must be a sha256 hex digest or 'absent'")
    except (OSError, ValueError):
        return (
            {
                "check": "reference_verification",
                "error": "fingerprint_missing_or_corrupt",
                "fingerprint_path": str(fingerprint_path),
                "note": (
                    "the committed fingerprint is missing or corrupt — a repo "
                    "bug to fix; carries no evidence about the reference"
                ),
            },
            EXIT_FINGERPRINT_CORRUPT,
        )

    observed, sidecar_errors = gather(reference, repo, scan_result)
    count = observed["reference_entry_count"]
    mount_type_error = None
    if count in ("mount_missing_or_unreadable", "scan_error"):
        # bench.scan's accessibility boolean folds "absent" and "wrong
        # type" together (deliberately — its metric is state-neutral).
        # The gate must not: a regular file / FIFO / symlink loop
        # sitting AT the mount path is a persistent state change, not a
        # transient failure a re-run could clear. Discriminate here so
        # the drift entry and the exit code tell the truth — for BOTH
        # inaccessible-mount sentinels: a mid-walk OSError
        # ("scan_error") can also mean the directory was swapped for a
        # file while the walk ran, and that swap must escalate to
        # drift in the SAME run, not stay rc 3 until the next one. If
        # the observation now sees a healthy directory (or plain
        # absence), the earlier scan failure stands as transient.
        mount_state, mount_detail = observe_mount_type(reference)
        if mount_state == MOUNT_NOT_A_DIR:
            count = COUNT_NOT_A_DIRECTORY
            observed["reference_entry_count"] = count
            mount_type_error = mount_detail
    drift = [
        {"fact": key, "fingerprint": fingerprint.get(key), "observed": observed[key]}
        for key in COMPARED_KEYS
        if observed[key] != fingerprint.get(key)
    ]
    mount_transient = count in ("mount_missing_or_unreadable", "scan_error")
    unreadable_sidecars = sorted(
        SIDECAR_FILES[key]
        for key in SIDECAR_FILES
        if observed[key] == SIDECAR_UNREADABLE
    )
    transient = mount_transient or bool(unreadable_sidecars)

    manifest = None
    manifest_error = None
    manifest_shape = None
    if isinstance(count, int) and count > 0:
        # Walk and classify FIRST, write second: the shape is evidence
        # from the walk, and the verdict-critical VCS-only warning must
        # survive a read-only repo dir or full disk — only a failure of
        # the walk itself (OSError from build_manifest) leaves the
        # shape genuinely unknowable.
        try:
            entries = build_manifest(reference)
        except OSError as exc:
            manifest_error = bench.exc_detail(exc)
        else:
            manifest_shape = classify_manifest_shape(entries)
            try:
                manifest = write_manifest(
                    reference, repo, entries, manifest_shape
                )
            except OSError as exc:
                manifest_error = bench.exc_detail(exc)

    # Transient observations (unscannable mount, unreadable sidecar)
    # always mismatch the fingerprint — the fingerprint never stores a
    # transient sentinel — so they appear in `drift` as evidence, but
    # they are not *genuine* drift: the true state is unknown, not
    # known-changed. Only genuine drift may produce rc 1.
    genuine_drift = [
        d
        for d in drift
        if not (
            (d["fact"] == "reference_entry_count" and mount_transient)
            or observed[d["fact"]] == SIDECAR_UNREADABLE
        )
    ]

    if not drift:
        exit_code = EXIT_MATCH
        if count == 0:
            note = "reference still empty; non-graftable verdict stands"
        else:
            # Reachable only after a deliberate fingerprint update to a
            # re-populated reference: a match must not keep endorsing the
            # old emptiness claim.
            note = (
                f"matches fingerprint, which records a NON-EMPTY tree "
                f"({count} entries): the non-graftable verdict no longer "
                "applies — build against the surveyed tree."
                + (" See the manifest." if manifest is not None else "")
            )
    elif not genuine_drift:
        exit_code = EXIT_TRANSIENT
        failures = []
        if mount_transient:
            failures.append(
                "the mount could not be scanned (absent, unreadable, or "
                "going stale mid-walk)"
            )
        if unreadable_sidecars:
            failures.append(
                "sidecar(s) could not be read: " + ", ".join(unreadable_sidecars)
            )
        note = (
            "TRANSIENT ENVIRONMENT FAILURE: "
            + "; ".join(failures)
            + ". This is NOT evidence the surveyed state changed. "
            "Investigate the environment / re-run; do not touch SURVEY.md."
        )
    else:
        # Genuine drift outranks any concurrent transient failure —
        # rc 3 must never mask confirmed drift from exit-code-only
        # consumers.
        exit_code = EXIT_DRIFT
        note = (
            "DRIFT: the surveyed state changed. If the reference tree is "
            "non-empty, SURVEY.md is obsolete — rewrite it from the real tree "
            "before writing any code (procedure: SURVEY_REWRITE.md)"
            + (
                " (see the manifest for the observed entries)"
                if manifest is not None
                else ""
            )
            + ". Sidecar-only drift (PAPERS/SNIPPETS) does not add "
            "capabilities: only the mounted tree defines what to build."
        )
        if count == COUNT_NOT_A_DIRECTORY:
            note += (
                " NOTE: the reference mount path exists but is NOT a "
                "directory ("
                + (mount_type_error or "unknown type")
                + ") — a persistent state change, not a transient mount "
                "failure; there is no tree to survey behind a "
                "non-directory, so investigate how the mount was created."
            )
        if mount_transient:
            note += (
                " NOTE: the mount itself could not be scanned this run "
                "(transient environment failure), so only the sidecar drift "
                "is confirmed; re-run once the mount is back."
            )
        if unreadable_sidecars:
            note += (
                " NOTE: unreadable this run (transient, not confirmed drift): "
                + ", ".join(unreadable_sidecars)
                + "."
            )

    if manifest_shape == MANIFEST_SHAPE_VCS_ONLY:
        # Reachable from both non-empty paths (rc 1 drift and rc 0
        # after a deliberate re-pin): whichever way a VCS-only tree was
        # observed, the warning must ride along — the read order for
        # working files finds nothing in such a tree, and "found
        # nothing" must not be mistaken for "no capabilities".
        note += (
            " NOTE: every observed entry is git VERSION-CONTROL METADATA "
            "(a bare or hidden .git tree with no working files). The real "
            "source, if any, lives in the git object store — do NOT "
            "conclude 'no capabilities' from the absence of working "
            "files; materialize the committed tree read-only (git clone "
            "from the mount) and survey THAT (SURVEY_REWRITE.md, 'The "
            "bare-git shape')."
        )
    elif manifest_shape == MANIFEST_SHAPE_VCS_GITLINK:
        note += (
            " NOTE: the tree's sole entry is a `.git` GITLINK FILE — a "
            "one-line `gitdir: <path>` POINTER, not a git directory. "
            "`git clone` from the mount CANNOT work (there is no object "
            "store here); read the pointer first (`cat <mount>/.git`), "
            "record the pointed path, and only then decide whether a "
            "git dir is reachable to materialize from "
            "(SURVEY_REWRITE.md, 'The bare-git shape')."
        )

    result = {
        "check": "reference_verification",
        "reference_path": str(reference),
        "reference_empty": count == 0,
        "matches_fingerprint": not drift,
        "transient_environment_failure": transient,
        "drift": drift,
        "observed": observed,
        "mount_stat": mount_stat(reference),
        "manifest": manifest,
        "uncommitted_round_artifacts": uncommitted_round_artifacts(repo),
        "note": note,
    }
    if sidecar_errors:
        result["sidecar_errors"] = sidecar_errors
    if manifest_error is not None:
        result["manifest_error"] = manifest_error
    if manifest_shape is not None:
        result["manifest_shape"] = manifest_shape
    if mount_type_error is not None:
        result["mount_type_error"] = mount_type_error
    return result, exit_code


def main() -> int:
    try:
        reference = pathlib.Path(
            os.environ.get("GRAFT_REFERENCE_PATH", DEFAULT_REFERENCE)
        )
        repo = pathlib.Path(
            os.environ.get("GRAFT_REPO_PATH", pathlib.Path(__file__).resolve().parent)
        )
        result, exit_code = verify(reference, repo)
        print(json.dumps(result))
        return exit_code
    except Exception as exc:  # noqa: BLE001 — rc must stay meaningful
        # Without this, an unhandled exception exits with Python's
        # default status 1 — colliding with EXIT_DRIFT, so an
        # exit-code-only consumer would read a gate crash as "genuine
        # drift". A crash carries no evidence about the reference.
        print(
            json.dumps(
                {
                    "check": "reference_verification",
                    "error": "internal_error",
                    "detail": bench.exc_detail(exc),
                    "note": (
                        "the gate itself crashed — a repo bug, not evidence "
                        "about the reference; fix the gate and re-run"
                    ),
                }
            )
        )
        return EXIT_INTERNAL_ERROR


if __name__ == "__main__":
    sys.exit(main())
