"""jaxlint v3: the abstract-interpretation layer and its three rules.

Four surfaces under test:

1. The LATTICE — join must be a real semilattice join (commutative,
   idempotent, associative, rank-monotone) over randomized elements;
   a join that quietly collapses (the lattice-join-returns-bottom
   mutant) blinds every rule riding the lattice, and the property
   test is the named kill.
2. The SHAPE contract — the acceptance fixture (a raw `len(...)`-
   shaped array reaching a jitted call) is flagged, the recognized
   bucketing ops (`bucket_size`, `pack_batch`, `pack_epoch` — incl.
   the PR 6 `pad_batches_pow2=True` bootstrap-CI shape —
   `chunk_layout`, staging `stage`) launder dynamism back to safety,
   and the REAL engine/ingest/ratings call sites carry zero v3
   findings. Un-recognizing a bucketing op (the
   bucketing-op-not-recognized mutant) turns the ok-fixtures red.
3. The DTYPE contract — bare 64-bit producers and json numerics are
   flagged at the boundary, `.astype(np.int32)` / explicit dtypes
   are not.
4. The TAINT contract — wire sources reach sinks only through the
   protocol validators, on EVERY path (branch envs join), one hop
   deep through the project table, and the real wire tier is clean.
   The taint-sanitizer-check-skipped mutant is killed by
   `test_protocol_validators_clear_taint`.

Everything here is stdlib + the linter: no jax imports needed (the
fixtures are parsed, never executed).
"""

import pathlib
import random

from arena.analysis import absint, jaxlint
from arena.analysis.absint import (
    AbsValue,
    RULE_DTYPE,
    RULE_TAINT,
    RULE_UNBUCKETED,
    SHAPE_BOTTOM,
    SHAPE_BUCKETED,
    SHAPE_DYNAMIC,
    join,
    join_shape,
    shape_constant,
    shape_padded,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
V3_RULES = [RULE_UNBUCKETED, RULE_DTYPE, RULE_TAINT]


def rules_of(source, rules=None):
    return {
        f.rule for f in jaxlint.lint_source(source, "fixture.py", rules=rules)
    }


# --- 1. the lattice ---------------------------------------------------------


def _random_shape(rng):
    pick = rng.randrange(6)
    if pick == 0:
        return SHAPE_BOTTOM
    if pick == 1:
        return SHAPE_BUCKETED
    if pick == 2:
        return SHAPE_DYNAMIC
    if pick == 3:
        return shape_constant(rng.choice([0, 1, 7, 256, 1024]))
    if pick == 4:
        return shape_padded(rng.choice([None, 8, 4096]))
    return shape_constant(rng.choice([0, 1, 7, 256, 1024]))


def _random_value(rng):
    return AbsValue(
        shape=_random_shape(rng),
        dtype=rng.choice(
            [None, "int32", "float32", "int64", "float64", "py64"]
        ),
        kind=rng.choice([None, "scalar", "array"]),
        tainted=rng.random() < 0.5,
    )


def test_shape_join_commutative_idempotent():
    """The property the whole layer stands on: join is a semilattice
    join over randomized shape elements — commutative, idempotent,
    associative, and rank-monotone (a join never loses badness)."""
    rng = random.Random(1222)
    for _ in range(500):
        a, b, c = (_random_shape(rng) for _ in range(3))
        assert join_shape(a, b) == join_shape(b, a)
        assert join_shape(a, a) == a
        assert join_shape(a, join_shape(b, c)) == join_shape(join_shape(a, b), c)
        assert join_shape(a, b).rank >= max(a.rank, b.rank)


def test_absvalue_join_commutative_idempotent_associative():
    rng = random.Random(2026)
    for _ in range(500):
        a, b, c = (_random_value(rng) for _ in range(3))
        assert join(a, b) == join(b, a)
        assert join(a, a) == a
        assert join(a, join(b, c)) == join(join(a, b), c)
        # Taint joins as OR: a join never launders.
        assert join(a, b).tainted == (a.tainted or b.tainted)


def test_same_rank_distinct_statics_join_to_bucketed():
    """constant(2) vs constant(4) (or constant vs padded) is no longer
    ONE known size but still a finite shape set — the lub is bucketed,
    never dynamic and never a silent pick-one."""
    assert join_shape(shape_constant(2), shape_constant(4)) == SHAPE_BUCKETED
    assert join_shape(shape_constant(8), shape_padded(8)) == SHAPE_BUCKETED
    assert join_shape(shape_padded(4), shape_padded(4)) == shape_padded(4)


# --- 2. the shape contract --------------------------------------------------

SEEDED_LEN_FIXTURE = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "import numpy as np\n"
    "score = jax.jit(lambda x: x.sum())\n"
    "def ingest(matches):\n"
    "    n = len(matches)\n"
    "    arr = np.zeros(n, np.float32)\n"
    "    return score(jnp.asarray(arr))\n"
)


def test_raw_len_shaped_array_at_jit_boundary_is_flagged():
    """The acceptance fixture: a raw `len(...)`-shaped array reaches a
    jitted call — flagged by exactly the v3 shape rule."""
    assert rules_of(SEEDED_LEN_FIXTURE) == {RULE_UNBUCKETED}


def test_shape_rule_fires_through_shape_subscript_too():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "f = jax.jit(lambda x: x * 2.0)\n"
        "def rescale(weights):\n"
        "    out = np.empty(weights.shape[0], np.float32)\n"
        "    return f(jnp.asarray(out))\n"
    )
    assert rules_of(src) == {RULE_UNBUCKETED}


def test_shard_map_wrapped_callee_is_a_boundary():
    src = (
        "from functools import partial\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from jax.experimental.shard_map import shard_map\n"
        "from jax.sharding import Mesh\n"
        "from jax.sharding import PartitionSpec as P\n"
        "mesh = Mesh(np.array(jax.devices()), ('data',))\n"
        "@partial(shard_map, mesh=mesh, in_specs=(P('data'),), out_specs=P())\n"
        "def kernel(x):\n"
        "    return x * 2.0\n"
        "def drive(batch):\n"
        "    arr = np.zeros(len(batch), np.float32)\n"
        "    return kernel(jnp.asarray(arr))\n"
    )
    assert rules_of(src, rules=[RULE_UNBUCKETED]) == {RULE_UNBUCKETED}


def test_pow2_bucketing_ops_are_recognized_sanitizers():
    """The kill test for the bucketing-op-not-recognized mutant: a
    dynamic size routed through `bucket_size` (and a batch routed
    through `pack_batch`) reaches the boundary CLEAN — if the
    recognized-op set is emptied, these fixtures go red."""
    via_bucket_size = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from arena.engine import bucket_size\n"
        "score = jax.jit(lambda x: x.sum())\n"
        "def ok(matches):\n"
        "    b = bucket_size(len(matches))\n"
        "    arr = np.zeros(b, np.float32)\n"
        "    return score(jnp.asarray(arr))\n"
    )
    assert rules_of(via_bucket_size) == set()
    via_pack_batch = (
        "import jax\n"
        "from arena.engine import pack_batch\n"
        "score = jax.jit(lambda x: x.sum())\n"
        "def ok(num_players, winners, losers):\n"
        "    packed = pack_batch(num_players, winners, losers)\n"
        "    return score(packed.valid)\n"
    )
    assert rules_of(via_pack_batch) == set()
    via_chunk_layout = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from arena.ingest import chunk_layout\n"
        "fit = jax.jit(lambda p: p.sum())\n"
        "def ok(perm, bounds):\n"
        "    perms, chunk_bounds = chunk_layout(perm, bounds, 4096)\n"
        "    return fit(jnp.asarray(perms))\n"
    )
    assert rules_of(via_chunk_layout) == set()


def test_pack_epoch_pow2_padded_call_sites_are_recognized():
    """Regression for the PR 6 bootstrap-CI fix: the
    `pack_epoch(pad_batches_pow2=True)` call shape (engine.
    bootstrap_ratings) must read as a bucketing sanitizer — the
    compile-free interval-refresh contract stays statically clean."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from arena.engine import pack_epoch\n"
        "from arena import ratings as R\n"
        "resampler = R.jit_elo_bootstrap()\n"
        "def refresh(num_players, winners, losers, keys, base):\n"
        "    packed = pack_epoch(num_players, winners, losers, 8192,\n"
        "                        pad_batches_pow2=True, min_batches=8)\n"
        "    return resampler(base, packed.winners, packed.losers,\n"
        "                     packed.valid, packed.perms, packed.bounds,\n"
        "                     keys)\n"
    )
    assert rules_of(src) == set()


def test_real_bucketing_call_sites_stay_clean():
    """The other half of the acceptance criterion: the REAL
    pack_batch / pack_epoch / chunk_layout / staging call sites in
    engine.py, ingest.py, ratings.py, and sharding.py carry ZERO v3
    findings (lint them with only the v3 families active, so this
    stays a targeted pin even if other rules grow)."""
    targets = [
        str(REPO / "arena" / name)
        for name in ("engine.py", "ingest.py", "ratings.py", "sharding.py")
    ]
    findings = jaxlint.lint_paths(targets, rules=V3_RULES)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_one_hop_shape_summary_through_the_project_table(tmp_path):
    """Interprocedural, one hop: a helper in ANOTHER MODULE that mints
    a dynamic-shaped array from its argument's length — the caller's
    jit boundary is flagged through the table-resolved return
    summary; the same helper handed a constant stays clean."""
    (tmp_path / "helpers.py").write_text(
        "import numpy as np\n"
        "def expand(batch):\n"
        "    return np.zeros(len(batch), np.float32)\n"
    )
    (tmp_path / "main.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from helpers import expand\n"
        "score = jax.jit(lambda x: x.sum())\n"
        "def ingest(matches):\n"
        "    return score(jnp.asarray(expand(matches)))\n"
    )
    findings = jaxlint.lint_paths([str(tmp_path)], rules=V3_RULES)
    assert {f.rule for f in findings} == {RULE_UNBUCKETED}
    assert all(f.path.endswith("main.py") for f in findings)


# --- 3. the dtype contract --------------------------------------------------


def test_bare_arange_flagged_astype_clean():
    bare = (
        "import jax\n"
        "import numpy as np\n"
        "kernel = jax.jit(lambda idx: idx.sum())\n"
        "def refit(num_players):\n"
        "    return kernel(np.arange(num_players))\n"
    )
    assert rules_of(bare) == {RULE_DTYPE}
    cast = bare.replace(
        "kernel(np.arange(num_players))",
        "kernel(np.arange(num_players).astype(np.int32))",
    )
    assert rules_of(cast) == set()
    pinned = bare.replace(
        "np.arange(num_players)", "np.arange(num_players, dtype=np.int32)"
    )
    assert rules_of(pinned) == set()


def test_json_numbers_need_an_explicit_dtype():
    """json.loads numerics are Python ints/floats — np.asarray widens
    them to 64-bit unless the wire format's int32 is pinned."""
    drift = (
        "import jax\n"
        "import json\n"
        "import numpy as np\n"
        "kernel = jax.jit(lambda w: w.sum())\n"
        "def load(text):\n"
        "    doc = json.loads(text)\n"
        "    return kernel(np.asarray(doc['scores']))\n"
    )
    assert rules_of(drift) == {RULE_DTYPE}
    pinned = drift.replace(
        "np.asarray(doc['scores'])", "np.asarray(doc['scores'], np.float32)"
    )
    assert rules_of(pinned) == set()


def test_jnp_constructors_are_not_64bit_producers():
    """Under the repo's x32 config jnp.zeros/arange default 32-bit —
    the rule must not flag the device-side constructors the tests and
    benches use everywhere."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "f = jax.jit(lambda x: x + 1.0)\n"
        "def ok():\n"
        "    return f(jnp.zeros(16))\n"
    )
    assert rules_of(src) == set()


# --- 4. the taint contract --------------------------------------------------


def test_wire_taint_reaches_sink_without_validator_is_flagged():
    src = (
        "import json\n"
        "from http.server import BaseHTTPRequestHandler\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_POST(self, engine):\n"
        "        raw = self.rfile.read(10)\n"
        "        doc = json.loads(raw)\n"
        "        engine.update(doc['winners'], doc['losers'])\n"
    )
    assert rules_of(src, rules=V3_RULES) == {RULE_TAINT}


def test_protocol_validators_clear_taint():
    """The kill test for the taint-sanitizer-check-skipped mutant:
    the documented flows — parse_submit_body on the result, AND
    _validate_matches validating its argument names in place — both
    read clean; with sanitizer recognition skipped they go red."""
    via_parse = (
        "from http.server import BaseHTTPRequestHandler\n"
        "from arena.net.protocol import parse_submit_body\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_POST(self, frontdoor):\n"
        "        raw = self.rfile.read(10)\n"
        "        winners, losers, producer = parse_submit_body(raw)\n"
        "        frontdoor.submit(winners, losers, producer=producer)\n"
    )
    assert rules_of(via_parse, rules=V3_RULES) == set()
    in_place = (
        "import json\n"
        "from http.server import BaseHTTPRequestHandler\n"
        "from arena.engine import _validate_matches\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def ingest(self, store, num_players):\n"
        "        doc = json.loads(self.rfile.read(10))\n"
        "        w = doc['winners']\n"
        "        l = doc['losers']\n"
        "        _validate_matches(num_players, w, l)\n"
        "        store.add(w, l)\n"
    )
    assert rules_of(in_place, rules=V3_RULES) == set()


def test_taint_requires_sanitizer_on_every_path():
    """Branch envs JOIN: a sanitizer on one arm of an `if` does not
    launder the other arm — only both-arms-validated reads clean."""
    one_arm = (
        "import json\n"
        "from http.server import BaseHTTPRequestHandler\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_POST(self, engine, strict):\n"
        "        raw = self.rfile.read(10)\n"
        "        if strict:\n"
        "            winners, losers, producer = parse_submit_body(raw)\n"
        "        else:\n"
        "            doc = json.loads(raw)\n"
        "            winners, losers = doc['winners'], doc['losers']\n"
        "        engine.update(winners, losers)\n"
    )
    assert rules_of(one_arm, rules=V3_RULES) == {RULE_TAINT}
    both_arms = one_arm.replace(
        "            doc = json.loads(raw)\n"
        "            winners, losers = doc['winners'], doc['losers']\n",
        "            winners, losers, producer = parse_submit_body(raw)\n",
    )
    assert rules_of(both_arms, rules=V3_RULES) == set()


def test_one_hop_taint_into_callee_sink(tmp_path):
    """A helper module that forwards to the sink: the tainted call is
    reported AT THE CALL SITE in the handler module, one hop through
    the table (the helper alone, with untainted params, is clean)."""
    (tmp_path / "sinkmod.py").write_text(
        "def apply_raw(engine, doc):\n"
        "    engine.update(doc['winners'], doc['losers'])\n"
    )
    (tmp_path / "handler.py").write_text(
        "import json\n"
        "from http.server import BaseHTTPRequestHandler\n"
        "from sinkmod import apply_raw\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_POST(self):\n"
        "        raw = self.rfile.read(10)\n"
        "        apply_raw(self.server.engine, json.loads(raw))\n"
    )
    findings = jaxlint.lint_paths([str(tmp_path)], rules=V3_RULES)
    assert {f.rule for f in findings} == {RULE_TAINT}
    assert all(f.path.endswith("handler.py") for f in findings)
    assert any("apply_raw" in f.message for f in findings)
    # The helper on its own makes no claim: its params are untainted.
    alone = jaxlint.lint_paths([str(tmp_path / "sinkmod.py")], rules=V3_RULES)
    assert alone == []


def test_real_wire_tier_stays_taint_clean():
    """The real handlers route every request field through parse_path
    / parse_submit_body before anything mutates — pinned with only
    the v3 families active across the whole net tier + engine."""
    targets = [
        str(REPO / "arena" / "net" / name)
        for name in ("server.py", "protocol.py", "frontdoor.py")
    ] + [str(REPO / "arena" / "engine.py"), str(REPO / "arena" / "serving.py")]
    findings = jaxlint.lint_paths(targets, rules=V3_RULES)
    assert findings == [], "\n".join(f.format() for f in findings)


# --- cross-cutting: suppression + severity + registration ------------------


def test_v3_findings_are_suppressible_inline():
    muted = SEEDED_LEN_FIXTURE.replace(
        "    return score(jnp.asarray(arr))\n",
        "    return score(jnp.asarray(arr))"
        "  # jaxlint: disable=unbucketed-shape-at-jit-boundary\n",
    )
    assert rules_of(muted) == set()


def test_v3_rules_registered_with_severities():
    for name in V3_RULES:
        assert name in jaxlint.RULES
        assert jaxlint.RULES[name].severity in jaxlint.SEVERITIES


def test_analysis_is_cached_per_module_context():
    """The three rules share ONE abstract-interp pass per module (the
    expensive part runs once, not three times)."""
    ctx = jaxlint.ModuleContext("f.py", SEEDED_LEN_FIXTURE)
    first = absint._analysis(ctx)
    assert absint._analysis(ctx) is first
