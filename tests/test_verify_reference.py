"""Tests for verify_reference.py — the mechanical round-start gate.

Contract: exactly one JSON line on stdout; exit 0 when the live state
matches the committed fingerprint, 1 on any drift (reference tree
non-empty, sidecar hashes changed, SNIPPETS.md appearing), 2 when the
fingerprint itself is missing or corrupt.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
import verify_reference  # noqa: E402

BASELINE_CONTENT = '{"north_star": "non-graftable"}\n'
PAPERS_CONTENT = "# PAPERS\n"


def make_repo(tmp_path, with_snippets=False):
    """A fake repo dir whose fingerprint matches its own sidecars."""
    import hashlib

    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "BASELINE.json").write_text(BASELINE_CONTENT)
    (repo / "PAPERS.md").write_text(PAPERS_CONTENT)
    if with_snippets:
        (repo / "SNIPPETS.md").write_text("# SNIPPETS\n")
    fingerprint = {
        "reference_entry_count": 0,
        "baseline_json_sha256": hashlib.sha256(BASELINE_CONTENT.encode()).hexdigest(),
        "papers_md_sha256": hashlib.sha256(PAPERS_CONTENT.encode()).hexdigest(),
        "snippets_md_present": False,
    }
    (repo / "reference_fingerprint.json").write_text(json.dumps(fingerprint))
    return repo


def run_verify(reference_path, repo_path):
    env = dict(os.environ)
    env["GRAFT_REFERENCE_PATH"] = str(reference_path)
    env["GRAFT_REPO_PATH"] = str(repo_path)
    return subprocess.run(
        [sys.executable, str(REPO / "verify_reference.py")],
        capture_output=True,
        text=True,
        env=env,
        cwd="/tmp",
    )


def parse_single_json_line(proc):
    assert proc.stderr == ""
    lines = proc.stdout.splitlines()
    assert len(lines) == 1
    return json.loads(lines[0])


def test_empty_reference_matches_fingerprint(tmp_path):
    ref = tmp_path / "ref"
    ref.mkdir()
    proc = run_verify(ref, make_repo(tmp_path))
    result = parse_single_json_line(proc)
    assert proc.returncode == 0
    assert result["reference_empty"] is True
    assert result["matches_fingerprint"] is True
    assert result["drift"] == []


def test_populated_reference_is_drift(tmp_path):
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// code\n")
    proc = run_verify(ref, make_repo(tmp_path))
    result = parse_single_json_line(proc)
    assert proc.returncode == 1
    assert result["reference_empty"] is False
    assert result["matches_fingerprint"] is False
    assert result["transient_environment_failure"] is False
    assert "DRIFT" in result["note"]
    drifted = {d["fact"] for d in result["drift"]}
    assert drifted == {"reference_entry_count"}
    assert result["observed"]["reference_entry_count"] == 2


def test_missing_reference_is_transient_failure_not_drift(tmp_path):
    proc = run_verify(tmp_path / "gone", make_repo(tmp_path))
    result = parse_single_json_line(proc)
    assert proc.returncode == 1
    assert result["observed"]["reference_entry_count"] == "mount_missing_or_unreadable"
    # The JSON evidence line must self-describe this as environmental,
    # not as the reference having changed (SKILL.md semantics).
    assert result["transient_environment_failure"] is True
    assert "TRANSIENT" in result["note"]


def test_changed_baseline_sidecar_is_drift(tmp_path):
    ref = tmp_path / "ref"
    ref.mkdir()
    repo = make_repo(tmp_path)
    (repo / "BASELINE.json").write_text('{"north_star": "now it has code!"}\n')
    proc = run_verify(ref, repo)
    result = parse_single_json_line(proc)
    assert proc.returncode == 1
    drifted = {d["fact"] for d in result["drift"]}
    assert drifted == {"baseline_json_sha256"}
    # the reference itself is still empty; only the sidecar moved
    assert result["reference_empty"] is True


def test_snippets_appearing_is_drift(tmp_path):
    ref = tmp_path / "ref"
    ref.mkdir()
    repo = make_repo(tmp_path, with_snippets=True)
    proc = run_verify(ref, repo)
    result = parse_single_json_line(proc)
    assert proc.returncode == 1
    drifted = {d["fact"] for d in result["drift"]}
    assert drifted == {"snippets_md_present"}


def test_scan_error_maps_to_sentinel(tmp_path, monkeypatch):
    """A mid-walk OSError (via the shared bench.scan) becomes the
    'scan_error' sentinel, which mismatches the fingerprint's 0 and is
    documented as a transient environment failure, not a changed tree."""

    bad = tmp_path / "bad"
    bad.mkdir()
    real_scandir = os.scandir

    def flaky_scandir(path=".", *args, **kwargs):
        if pathlib.Path(path) == bad:
            raise OSError("mount went stale mid-iteration")
        return real_scandir(path, *args, **kwargs)

    monkeypatch.setattr(os, "scandir", flaky_scandir)
    assert verify_reference.count_entries(tmp_path) == "scan_error"


def test_count_entries_delegates_to_bench(tmp_path):
    """bench.scan and the round-start gate must agree on the same mount."""
    (tmp_path / "a").mkdir()
    (tmp_path / "a" / "b.txt").write_text("x")
    assert verify_reference.count_entries(tmp_path) == 2
    assert verify_reference.count_entries(tmp_path / "gone") == (
        "mount_missing_or_unreadable"
    )


def test_missing_fingerprint_exits_2(tmp_path):
    ref = tmp_path / "ref"
    ref.mkdir()
    repo = tmp_path / "bare"
    repo.mkdir()
    proc = run_verify(ref, repo)
    result = parse_single_json_line(proc)
    assert proc.returncode == 2
    assert result["error"] == "fingerprint_missing_or_corrupt"


def test_corrupt_fingerprint_exits_2(tmp_path):
    ref = tmp_path / "ref"
    ref.mkdir()
    repo = make_repo(tmp_path)
    (repo / "reference_fingerprint.json").write_text("{not json")
    proc = run_verify(ref, repo)
    result = parse_single_json_line(proc)
    assert proc.returncode == 2


def test_non_object_json_fingerprint_exits_2(tmp_path):
    """Valid JSON that is not an object (null, list, scalar) is corrupt,
    not drift: must take the exit-2 path, not crash with rc 1."""
    ref = tmp_path / "ref"
    ref.mkdir()
    repo = make_repo(tmp_path)
    for payload in ("null", "[]", '"x"', "42"):
        (repo / "reference_fingerprint.json").write_text(payload)
        proc = run_verify(ref, repo)
        result = parse_single_json_line(proc)
        assert proc.returncode == 2, payload
        assert result["error"] == "fingerprint_missing_or_corrupt"


def test_real_repo_fingerprint_matches_live_mount():
    """The committed fingerprint must match the real repo sidecars; and
    unless the driver re-mounted a different reference, the live mount
    must still be empty."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "verify_reference.py")],
        capture_output=True,
        text=True,
        cwd="/tmp",
    )
    result = parse_single_json_line(proc)
    # Sidecar hashes are committed alongside the sidecars, so a mismatch
    # here is a repo bug (stale fingerprint), not environment drift.
    sidecar_drift = [
        d for d in result["drift"] if d["fact"] != "reference_entry_count"
    ]
    assert sidecar_drift == [], (
        "reference_fingerprint.json is stale relative to the committed "
        f"sidecars: {sidecar_drift}"
    )
