"""Engine-layer contracts: bucketing, recompile budget, donation, state.

The load-bearing one is the zero-recompile property: shape-bucketed
batching exists so production traffic with arbitrary batch sizes can
NEVER trigger an unbounded stream of XLA compiles. It is asserted via
the jit cache stats, not timing, so it cannot flake.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from arena import engine
from arena import ratings as R
from arena.engine import ArenaEngine, bucket_size


def test_bucket_size_is_pow2_monotone_and_floored():
    assert bucket_size(0) == engine.MIN_BUCKET
    assert bucket_size(1) == engine.MIN_BUCKET
    assert bucket_size(engine.MIN_BUCKET) == engine.MIN_BUCKET
    assert bucket_size(engine.MIN_BUCKET + 1) == engine.MIN_BUCKET * 2
    assert bucket_size(5000) == 8192
    prev = 0
    for n in range(0, 3000, 17):
        b = bucket_size(n)
        assert b >= n and b >= prev  # covers, monotone
        assert b & (b - 1) == 0  # power of two
        prev = b
    with pytest.raises(ValueError):
        bucket_size(-1)


def test_pack_batch_pads_and_masks():
    packed = engine.pack_batch(10, [1, 2, 3], [4, 5, 6])
    b = engine.MIN_BUCKET
    assert packed.winners.shape == (b,)
    assert packed.perm.shape == (2 * b,)
    assert packed.bounds.shape == (11,)
    assert packed.num_real == 3
    assert float(packed.valid.sum()) == 3.0
    assert int(packed.bounds[-1]) == 2 * b  # boundaries cover everything


def test_pack_batch_rejects_ragged_input():
    with pytest.raises(ValueError):
        engine.pack_batch(10, [1, 2], [3])


def test_pack_batch_rejects_out_of_range_ids():
    """An out-of-range id would not crash downstream — the grouping
    would silently scatter the bogus update into padded slots or a
    neighboring player — so ingest must refuse it loudly."""
    with pytest.raises(ValueError, match=r"player ids must be in \[0, 10\)"):
        engine.pack_batch(10, [1, 10], [2, 3])  # == num_players
    with pytest.raises(ValueError, match="player ids"):
        engine.pack_batch(10, [1, 2], [-1, 3])  # negative
    # The boundary ids themselves are fine.
    packed = engine.pack_batch(10, [0, 9], [9, 0])
    assert packed.num_real == 2


def test_pack_batch_rejects_non_1d():
    with pytest.raises(ValueError, match="1-D"):
        engine.pack_batch(10, [[1, 2]], [[3, 4]])


def test_pack_epoch_rejects_empty():
    with pytest.raises(ValueError):
        engine.pack_epoch(10, [], [], batch_size=256)


def test_pack_epoch_rejects_out_of_range_and_ragged():
    """pack_epoch builds its grouping without pack_batch, so it must
    run the same ingest validation."""
    with pytest.raises(ValueError, match="player ids"):
        engine.pack_epoch(10, [1, 99], [2, 3], batch_size=256)
    with pytest.raises(ValueError, match="player ids"):
        engine.pack_epoch(10, [1, 2], [-5, 3], batch_size=256)
    with pytest.raises(ValueError, match="equal length"):
        engine.pack_epoch(10, [1, 2], [3], batch_size=256)


def test_engine_update_rejects_out_of_range_ids_without_state_change():
    """A rejected batch must not half-ingest: ratings, history, and the
    match counter all stay untouched."""
    eng = ArenaEngine(8)
    before = np.asarray(eng.ratings).copy()
    with pytest.raises(ValueError, match="player ids"):
        eng.update([0, 8], [1, 2])
    np.testing.assert_array_equal(np.asarray(eng.ratings), before)
    assert eng.matches_ingested == 0
    with pytest.raises(ValueError, match="no matches ingested"):
        eng.bt_strengths()


def test_padded_update_equals_unpadded():
    """A padded slot must contribute exactly zero: updating through a
    mostly-padding bucket equals the eager unpadded update."""
    rng = np.random.default_rng(2)
    w = rng.integers(0, 20, 37).astype(np.int32)
    l = ((w + 1 + rng.integers(0, 19, 37)) % 20).astype(np.int32)
    r = jnp.full((20,), R.DEFAULT_BASE, jnp.float32)
    want = R.elo_batch_update(r, jnp.asarray(w), jnp.asarray(l))
    eng = ArenaEngine(20)
    got = eng.update(w, l)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)
    assert eng.matches_ingested == 37


def test_variable_batch_sizes_cause_zero_recompiles():
    """The whole point of bucketing: every batch size within one bucket
    hits one cache entry; a new bucket adds exactly one."""
    eng = ArenaEngine(30)
    rng = np.random.default_rng(4)

    def feed(n):
        w = rng.integers(0, 30, n).astype(np.int32)
        l = ((w + 1 + rng.integers(0, 29, n)) % 30).astype(np.int32)
        eng.update(w, l)

    feed(10)
    assert eng.num_compiles() == 1
    for n in (1, 7, 100, 255, engine.MIN_BUCKET):  # all in the floor bucket
        feed(n)
    assert eng.num_compiles() == 1, "a same-bucket batch size recompiled"
    feed(engine.MIN_BUCKET + 5)  # next bucket: exactly one more compile
    assert eng.num_compiles() == 2
    feed(engine.MIN_BUCKET * 2 - 1)  # same (second) bucket again
    assert eng.num_compiles() == 2


def test_update_donates_the_ratings_buffer():
    """donate_argnums on the update: the pre-update ratings buffer is
    consumed by the call (deleted), not left allocated behind the new
    one. Verified effective on this CPU backend."""
    eng = ArenaEngine(16)
    before = eng.ratings
    eng.update([1, 2], [3, 4])
    assert before.is_deleted()


def test_leaderboard_orders_by_rating():
    eng = ArenaEngine(5)
    # Player 0 beats everyone twice; player 4 loses everything extra.
    w = [0, 0, 0, 0, 1, 2, 3]
    l = [1, 2, 3, 4, 4, 4, 4]
    eng.update(w, l)
    board = eng.leaderboard()
    assert [p for p, _ in board][0] == 0
    assert [p for p, _ in board][-1] == 4
    assert len(eng.leaderboard(top_k=2)) == 2
    ratings = [r for _, r in board]
    assert ratings == sorted(ratings, reverse=True)


def test_engine_bt_strengths_rank_ingested_history():
    rng = np.random.default_rng(9)
    eng = ArenaEngine(8)
    # Transitive-ish traffic in several online batches.
    for _ in range(4):
        a = rng.integers(0, 8, 200)
        b = (a + 1 + rng.integers(0, 7, 200)) % 8
        w = np.minimum(a, b).astype(np.int32)
        l = np.maximum(a, b).astype(np.int32)
        eng.update(w, l)
    strengths = np.asarray(eng.bt_strengths(num_iters=40))
    assert list(np.argsort(-strengths)) == list(range(8))


def test_engine_bt_requires_history():
    with pytest.raises(ValueError):
        ArenaEngine(4).bt_strengths()


def test_engine_rejects_degenerate_player_count():
    with pytest.raises(ValueError):
        ArenaEngine(1)
