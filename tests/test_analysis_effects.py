"""jaxlint v5: the interprocedural effect-contract analyzer.

Pins the three properties the mutation audit leans on (each test here
is the NAMED kill for one effects.py mutant) plus the acceptance
criterion's real-code-shaped fixtures:

- summaries propagate to FIXPOINT over call edges — a 2-hop chain
  (contract fn -> helper -> clock) is caught, so a one-hop engine
  (the v3/v4 shape) demonstrably is not enough;
- the check-then-act detector credits the RE-CHECK idiom — a fresh
  read under a re-acquired lock kills the stale fact, so the sanctioned
  fix lints clean;
- `# pure-render(view)` treats reads through the named view (and any
  other parameter) as the contract's declared inputs, never hidden
  state.
"""

import pathlib

from arena.analysis import jaxlint

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "arena" / "analysis" / "badcorpus"


def rules_of(src, name="t.py"):
    return {f.rule for f in jaxlint.lint_source(src, name)}


# --- interprocedural fixpoint (mutant: fixpoint-stops-at-one-hop) ---------


def test_nondeterminism_propagates_over_two_call_hops():
    """The corpus file IS the two-hop chain: `stamped_score` (the
    contract) calls `_adjusted` calls `_jitter` calls `time.time`. A
    summary engine that stops after one propagation pass sees
    `_adjusted` as clean and the contract as satisfied — this test is
    the named kill for the fixpoint-stops-at-one-hop mutant."""
    findings = jaxlint.lint_paths(
        [str(CORPUS / "bad_nondeterministic_contract.py")]
    )
    assert {f.rule for f in findings} == {"nondeterminism-in-deterministic-fn"}
    # ...and the finding names the contract function, not the helper:
    # the blame lands where the promise was made.
    assert any("stamped_score" in f.message for f in findings)


def test_three_hop_chain_through_methods_is_caught():
    """Same property, deeper and through `self.` edges: the fixpoint
    must close over method calls too, not just module functions."""
    src = (
        "import time\n"
        "\n"
        "\n"
        "class Scorer:\n"
        "    def _clock(self):\n"
        "        return time.time()\n"
        "\n"
        "    def _salt(self):\n"
        "        return self._clock() % 1.0\n"
        "\n"
        "    def _shift(self, x):\n"
        "        return x + self._salt()\n"
        "\n"
        "    def score(self, x):  # deterministic\n"
        "        return self._shift(x)\n"
    )
    assert rules_of(src) == {"nondeterminism-in-deterministic-fn"}


def test_deterministic_chain_lints_clean():
    """The same call shape with no nondet source anywhere stays green:
    the rule fires on the CLOSURE's contents, not on call depth."""
    src = (
        "def _base(x):\n"
        "    return x * 2.0\n"
        "\n"
        "\n"
        "def _mid(x):\n"
        "    return _base(x) + 1.0\n"
        "\n"
        "\n"
        "def total(x):  # deterministic\n"
        "    return _mid(x)\n"
    )
    assert rules_of(src) == set()


# --- pure-render (mutant: pure-render-param-reads-flagged-as-hidden) ------


def test_pure_render_reading_only_its_view_lints_clean():
    """Reads through the named view AND other parameters are the
    contract's declared inputs — the named kill for the
    pure-render-param-reads-flagged-as-hidden mutant."""
    src = (
        "class Server:\n"
        "    def row(self, view, p, rank=None):  # pure-render(view)\n"
        "        r = view.ratings[p]\n"
        "        return {'player': p, 'rating': r, 'rank': rank}\n"
    )
    assert rules_of(src) == set()


def test_pure_render_hidden_self_read_fires():
    src = (
        "class Server:\n"
        "    def __init__(self):\n"
        "        self._style = 'wide'\n"
        "\n"
        "    def row(self, view, p):  # pure-render(view)\n"
        "        return (self._style, view.ratings[p])\n"
    )
    assert rules_of(src) == {"hidden-state-read-in-pure-render"}


# --- check-then-act (mutant: check-then-act-ignores-reacquire) ------------

RECHECK_SRC = (
    "import threading\n"
    "\n"
    "\n"
    "class Booker:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._seats = 8  # guarded_by: _lock\n"
    "\n"
    "    def book(self):\n"
    "        with self._lock:\n"
    "            seats = self._seats\n"
    "        if seats == 0:\n"
    "            return False\n"
    "        with self._lock:\n"
    "            seats = self._seats\n"
    "            if seats > 0:\n"
    "                self._seats = seats - 1\n"
    "                return True\n"
    "        return False\n"
)


def test_recheck_under_reacquired_lock_lints_clean():
    """The sanctioned fix for the corpus race — double-checked style:
    the stale copy only gates an early REFUSAL (no state act rides on
    it), and the act path re-reads the field under the re-acquired
    lock and decides on the FRESH copy. The rebind kills the stale
    fact — the named kill for the check-then-act-ignores-reacquire
    mutant."""
    assert rules_of(RECHECK_SRC) == set()
    # ...and dropping the re-read (acting on the escaped copy)
    # resurrects the race, so the clean verdict above is the re-check
    # credit, not blindness.
    broken = RECHECK_SRC.replace(
        "        with self._lock:\n"
        "            seats = self._seats\n"
        "            if seats > 0:\n",
        "        with self._lock:\n"
        "            if seats > 0:\n",
    )
    assert broken != RECHECK_SRC
    assert rules_of(broken) == {"check-then-act-race"}


def test_single_critical_section_lints_clean():
    """Check and act inside ONE lock-held region is the other
    sanctioned shape — no finding."""
    src = (
        "import threading\n"
        "\n"
        "\n"
        "class Booker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._seats = 8  # guarded_by: _lock\n"
        "\n"
        "    def book(self):\n"
        "        with self._lock:\n"
        "            if self._seats > 0:\n"
        "                self._seats -= 1\n"
        "                return True\n"
        "        return False\n"
    )
    assert rules_of(src) == set()


def test_check_then_act_fires_on_frontdoor_shaped_pipeline():
    """The acceptance criterion's real-code-shaped fixture: a FrontDoor
    -like stage with condition-variable-guarded pending state. The
    check (is a slot free?) reads under the cv, the act (claim the
    slot) happens in a LATER critical section against the stale copy —
    two producers that both saw `pending < limit` both enqueue past
    the limit. Rule fires; the rest of the registry stays quiet."""
    src = (
        "import threading\n"
        "\n"
        "\n"
        "class Stage:\n"
        "    def __init__(self, limit):\n"
        "        self._cv = threading.Condition()\n"
        "        self._limit = limit\n"
        "        self._pending = 0  # guarded_by: _cv\n"
        "        self._buffer = []  # guarded_by: _cv\n"
        "\n"
        "    def submit(self, batch):\n"
        "        with self._cv:\n"
        "            pending = self._pending\n"
        "        if pending < self._limit:\n"
        "            with self._cv:\n"
        "                self._pending = pending + 1\n"
        "                self._buffer.append(batch)\n"
        "                self._cv.notify()\n"
        "            return True\n"
        "        return False\n"
    )
    assert rules_of(src) == {"check-then-act-race"}


def test_corpus_race_fixture_fires_only_its_rule():
    """Every access in the corpus file is individually lock-held, so
    the v2 unguarded-shared-write rule has nothing to say — the
    BETWEEN-sections race is exactly the new rule's territory."""
    findings = jaxlint.lint_paths([str(CORPUS / "bad_check_then_act.py")])
    assert {f.rule for f in findings} == {"check-then-act-race"}


# --- undeclared mutation --------------------------------------------------


def test_mutates_allowance_covers_transitive_writes():
    """`# mutates:` is checked against the interprocedural CLOSURE:
    a helper's write counts against the caller's allowance, and
    declaring it makes the contract green."""
    src = (
        "class Rounds:\n"
        "    def __init__(self):\n"
        "        self.ratings = {}\n"
        "        self.rounds_applied = 0\n"
        "\n"
        "    def _bump(self):\n"
        "        self.rounds_applied += 1\n"
        "\n"
        "    def apply_round(self, deltas):"
        "  # deterministic; mutates: ratings, rounds_applied\n"
        "        for player in deltas:\n"
        "            self.ratings[player] = 1.0\n"
        "        self._bump()\n"
    )
    assert rules_of(src) == set()
    undeclared = src.replace("mutates: ratings, rounds_applied",
                             "mutates: ratings")
    assert rules_of(undeclared) == {"undeclared-mutation-in-contract"}
