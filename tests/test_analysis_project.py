"""jaxlint v2 cross-module engine: the symbol table resolves imports,
meshes, locks, and `guarded_by` contracts ACROSS modules, and the
concurrency rules stand on it.

The named kill-tests for the four v2 mutation-audit mutants live here
and in test_analysis_lint.py:

- symbol-table-skips-imports       -> test_symbol_table_resolves_from_imports
                                      (+ the cross-module mesh fixture)
- guarded-write-check-ignores-with-blocks
                                   -> test_guarded_write_inside_with_lock_block_is_clean
- lock-order-graph-edges-dropped   -> test_lock_order_inversion_detected_across_modules
- json-format-omits-rule-name      -> test_json_format_lines_carry_rule
                                      (test_analysis_lint.py)
"""

import ast
import pathlib

from arena.analysis import jaxlint, project

MESH_SRC = (
    "import jax\n"
    "import numpy as np\n"
    "from jax.sharding import Mesh\n"
    "AXIS = 'data'\n"
    "mesh = Mesh(np.array(jax.devices()), (AXIS,))\n"
)

SHARD_SRC = (
    "from functools import partial\n"
    "from jax.experimental.shard_map import shard_map\n"
    "from jax.sharding import PartitionSpec as P\n"
    "from meshes import mesh\n"
    "@partial(shard_map, mesh=mesh, in_specs=(P('model'),), out_specs=P())\n"
    "def f(x):\n"
    "    return x\n"
)


def _symbols(path, src):
    _table, comments = jaxlint._comment_tables(src)
    return project.module_symbols(str(path), ast.parse(src), comments)


# --- the symbol table -------------------------------------------------


def test_symbol_table_resolves_from_imports(tmp_path):
    """The table's import half IS the cross-module capability: a
    `from meshes import mesh` binding in module B resolves to the mesh
    (and its axis names) DEFINED in module A."""
    compute = _symbols(tmp_path / "compute.py", SHARD_SRC)
    assert compute.imports["mesh"] == ("meshes", "mesh")
    meshes = _symbols(tmp_path / "meshes.py", MESH_SRC)
    assert meshes.meshes["mesh"] == (frozenset({"data"}), True)
    table = project.ProjectTable([compute, meshes])
    axes, known = table.resolve_mesh(compute, "mesh")
    assert known and set(axes) == {"data"}


def test_symbol_table_resolves_module_alias_attribute_chains(tmp_path):
    src = "import meshes as m\n"
    mod = _symbols(tmp_path / "user.py", src)
    meshes = _symbols(tmp_path / "meshes.py", MESH_SRC)
    table = project.ProjectTable([mod, meshes])
    axes, known = table.resolve_mesh(mod, "m.mesh")
    assert known and set(axes) == {"data"}


def test_module_names_derive_from_package_layout():
    repo = pathlib.Path(__file__).resolve().parent.parent
    assert project.module_name_for(str(repo / "arena" / "ingest.py")) == (
        "arena.ingest"
    )
    assert project.module_name_for(str(repo / "arena" / "net" / "__init__.py")) == (
        "arena.net"
    )
    assert project.module_name_for("/tmp/somewhere/a.py") == "a"


def test_guarded_by_annotations_collected_from_comments(tmp_path):
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0  # guarded_by: _lock\n"
        "        self.free = 0\n"
    )
    sym = _symbols(tmp_path / "c.py", src)
    cls = sym.classes["C"]
    assert cls.guarded == {"n": "_lock"}
    assert cls.lock_attrs == {"_lock"}
    assert {"n", "free", "_lock"} <= cls.assigned_attrs


def test_symbol_table_sees_the_real_guarded_contracts():
    """The annotations in the four production modules are VISIBLE to
    the engine — the clean-tree pass is a real concurrency contract,
    not a vacuous one (tentpole acceptance)."""
    repo = pathlib.Path(__file__).resolve().parent.parent
    expected = {
        "arena/ingest.py": ("MergeableCSR", "_lock", "num_matches"),
        "arena/pipeline.py": ("IngestPipeline", "_cv", "submitted"),
        "arena/obs/metrics.py": ("Histogram", "_lock", "_counts"),
        "arena/net/frontdoor.py": ("FrontDoor", "_cv", "_buffer"),
    }
    for rel, (cls_name, lock, attr) in expected.items():
        path = repo / rel
        sym = _symbols(path, path.read_text())
        cls = sym.classes[cls_name]
        assert cls.guarded.get(attr) == lock, (rel, cls.guarded)


# --- cross-module mesh resolution (the ROADMAP item 3 gap) ------------


def test_cross_module_mesh_resolution_fires_sharding_rule(tmp_path):
    """Mesh in module A, shard_map in module B: v1 silently passed
    (axis names unknowable per-file); the two-pass engine resolves the
    imported mesh and fires on the inconsistent spec."""
    (tmp_path / "meshes.py").write_text(MESH_SRC)
    (tmp_path / "compute.py").write_text(SHARD_SRC)
    findings = jaxlint.lint_paths([str(tmp_path)])
    assert [(f.rule, pathlib.Path(f.path).name) for f in findings] == [
        ("sharding-spec-arity", "compute.py")
    ]
    assert "'model'" in findings[0].message


def test_cross_module_mesh_resolution_quiet_when_consistent(tmp_path):
    (tmp_path / "meshes.py").write_text(MESH_SRC)
    (tmp_path / "compute.py").write_text(SHARD_SRC.replace("'model'", "'data'"))
    assert jaxlint.lint_paths([str(tmp_path)]) == []


def test_single_file_walk_still_quiet_without_defining_module(tmp_path):
    """Linting B alone cannot know A's axes — the rule must stay
    quiet rather than guess (the documented v1 behavior the project
    pass upgrades on)."""
    (tmp_path / "compute.py").write_text(SHARD_SRC)
    assert jaxlint.lint_paths([str(tmp_path / "compute.py")]) == []


# --- unguarded-shared-write -------------------------------------------


GUARDED_CLASS = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.n = 0  # guarded_by: _lock\n"
    "        self._thread = threading.Thread(target=self._run)\n"
    "    def _run(self):\n"
    "        with self._lock:\n"
    "            self.n += 1\n"
)


def test_guarded_write_inside_with_lock_block_is_clean():
    """Writes lexically inside `with self._lock:` satisfy the
    contract; the SAME write outside it fires. (Kills the
    guarded-write-check-ignores-with-blocks mutant: if held-region
    tracking drops with-blocks, the clean half goes red.)"""
    assert jaxlint.lint_source(GUARDED_CLASS, "c.py") == []
    racy = GUARDED_CLASS + "    def bump(self):\n        self.n += 2\n"
    findings = jaxlint.lint_source(racy, "c.py")
    assert [f.rule for f in findings] == ["unguarded-shared-write"]
    assert "guarded_by: _lock" in findings[0].message


def test_locked_suffix_methods_are_held_regions():
    """The repo's `*_locked` naming convention (called with the lock
    held) is honored — and a non-suffixed helper with the same body is
    not."""
    locked = GUARDED_CLASS + "    def _bump_locked(self):\n        self.n += 2\n"
    assert jaxlint.lint_source(locked, "c.py") == []
    helper = GUARDED_CLASS + "    def bump_helper(self):\n        self.n += 2\n"
    assert jaxlint.lint_source(helper, "c.py") != []


def test_init_writes_are_pre_publication():
    """__init__ writes need no lock (nothing else can hold a reference
    yet) — annotating in __init__ must not flag __init__ itself."""
    assert jaxlint.lint_source(GUARDED_CLASS, "c.py") == []


def test_subscript_and_augmented_writes_count():
    racy = GUARDED_CLASS.replace(
        "        self.n = 0  # guarded_by: _lock\n",
        "        self.n = {}  # guarded_by: _lock\n",
    ) + "    def poke(self, k):\n        self.n[k] = 1\n"
    assert [f.rule for f in jaxlint.lint_source(racy, "c.py")] == [
        "unguarded-shared-write"
    ]


# --- blocking-while-locked --------------------------------------------


def test_blocking_calls_flagged_only_under_held_locks():
    src = (
        "import threading\n"
        "import time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def bad(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.1)\n"
        "    def ok(self):\n"
        "        time.sleep(0.1)\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    findings = jaxlint.lint_source(src, "c.py")
    assert [(f.rule, f.line) for f in findings] == [("blocking-while-locked", 8)]


def test_condition_wait_and_str_join_are_not_blocking_violations():
    """`cond.wait()` RELEASES the lock (the sanctioned shape) and
    `str.join(iterable)` has a positional arg — neither may fire."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "        self.done = False\n"
        "    def wait_done(self):\n"
        "        with self._cv:\n"
        "            while not self.done:\n"
        "                self._cv.wait(0.05)\n"
        "            return ', '.join(['a', 'b'])\n"
    )
    assert jaxlint.lint_source(src, "c.py") == []


# --- lock-order-inversion ---------------------------------------------


LOCKS_SRC = (
    "import threading\n"
    "LOCK_A = threading.Lock()\n"
    "LOCK_B = threading.Lock()\n"
)


def test_lock_order_inversion_detected_across_modules(tmp_path):
    """Module m1 nests A->B, module m2 nests B->A: only the PROJECT
    lock-order graph can see the cycle (neither file is wrong alone).
    Kills the lock-order-graph-edges-dropped mutant."""
    (tmp_path / "locks.py").write_text(LOCKS_SRC)
    (tmp_path / "m1.py").write_text(
        "from locks import LOCK_A, LOCK_B\n"
        "def f():\n"
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            pass\n"
    )
    (tmp_path / "m2.py").write_text(
        "from locks import LOCK_A, LOCK_B\n"
        "def g():\n"
        "    with LOCK_B:\n"
        "        with LOCK_A:\n"
        "            pass\n"
    )
    findings = jaxlint.lint_paths([str(tmp_path)])
    assert {f.rule for f in findings} == {"lock-order-inversion"}
    assert {pathlib.Path(f.path).name for f in findings} == {"m1.py", "m2.py"}


def test_consistent_lock_order_across_modules_is_clean(tmp_path):
    (tmp_path / "locks.py").write_text(LOCKS_SRC)
    for name in ("m1.py", "m2.py"):
        (tmp_path / name).write_text(
            "from locks import LOCK_A, LOCK_B\n"
            f"def f_{name[:2]}():\n"
            "    with LOCK_A:\n"
            "        with LOCK_B:\n"
            "            pass\n"
        )
    assert jaxlint.lint_paths([str(tmp_path)]) == []


def test_lock_order_sees_call_through_acquisitions(tmp_path):
    """A lock held across a call into a function that takes another
    lock contributes an edge (one hop, import-resolved) — the shape a
    purely lexical scan misses."""
    (tmp_path / "locks.py").write_text(LOCKS_SRC)
    (tmp_path / "helper.py").write_text(
        "from locks import LOCK_B\n"
        "def locked_b():\n"
        "    with LOCK_B:\n"
        "        pass\n"
    )
    (tmp_path / "m1.py").write_text(
        "from locks import LOCK_A\n"
        "from helper import locked_b\n"
        "def f():\n"
        "    with LOCK_A:\n"
        "        locked_b()\n"
    )
    (tmp_path / "m2.py").write_text(
        "from locks import LOCK_A, LOCK_B\n"
        "def g():\n"
        "    with LOCK_B:\n"
        "        with LOCK_A:\n"
        "            pass\n"
    )
    findings = jaxlint.lint_paths([str(tmp_path)])
    assert {f.rule for f in findings} == {"lock-order-inversion"}
    assert "m1.py" in {pathlib.Path(f.path).name for f in findings}


def test_rlock_reentry_is_not_an_inversion():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    )
    assert jaxlint.lint_source(src, "c.py") == []


# --- thread-no-liveness-recheck ---------------------------------------


WAITER_SRC = (
    "import threading\n"
    "class W:\n"
    "    def __init__(self):\n"
    "        self._cv = threading.Condition()\n"
    "        self.done = False\n"
    "        self._thread = threading.Thread(target=self._run, daemon=True)\n"
    "        self._thread.start()\n"
    "    def _run(self):\n"
    "        with self._cv:\n"
    "            self.done = True\n"
    "            self._cv.notify_all()\n"
)


def test_wait_loop_without_liveness_recheck_fires():
    src = WAITER_SRC + (
        "    def flush(self):\n"
        "        with self._cv:\n"
        "            while not self.done:\n"
        "                self._cv.wait(0.05)\n"
    )
    assert [f.rule for f in jaxlint.lint_source(src, "w.py")] == [
        "thread-no-liveness-recheck"
    ]


def test_wait_loop_with_helper_liveness_check_is_clean():
    """The `_check_packer_locked` shape: the loop calls a same-class
    helper whose body reads `.is_alive` — one hop resolved, quiet."""
    src = WAITER_SRC + (
        "    def _check_worker(self):\n"
        "        if not self._thread.is_alive():\n"
        "            raise RuntimeError('worker died')\n"
        "    def flush(self):\n"
        "        with self._cv:\n"
        "            while not self.done:\n"
        "                self._check_worker()\n"
        "                self._cv.wait(0.05)\n"
    )
    assert jaxlint.lint_source(src, "w.py") == []


def test_thread_target_wait_loops_are_exempt():
    """The worker waiting for WORK needs no liveness check on itself."""
    src = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "        self.jobs = []\n"
        "        self._thread = threading.Thread(target=self._run, daemon=True)\n"
        "        self._thread.start()\n"
        "    def _run(self):\n"
        "        with self._cv:\n"
        "            while not self.jobs:\n"
        "                self._cv.wait()\n"
    )
    assert jaxlint.lint_source(src, "w.py") == []
