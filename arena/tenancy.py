"""Multi-tenant arenas: thousands of leaderboards through one jitted kernel.

ROADMAP item 4's scale move. Today a second leaderboard means a second
`ArenaEngine` — a second jit cache, a second ops plane, and one Python
dispatch per tenant per round (exactly the naive-loop tax PR 1 measured
at 55–70x). This module makes tenant one more segment key:

- **Composite ids.** A match for tenant ``t`` between local players
  ``(w, l)`` is stored as ``(t * num_players + w, t * num_players + l)``
  — the `MergeableCSR` keeps ONE tenant-major sorted grouping (tenant
  is the leading sort key by construction), and the composite space is
  what the chunked Bradley–Terry refit and the bootstrap resampler
  already consume unchanged.

- **The fused update.** Elo rounds do NOT ride the flat composite
  cumsum (cross-tenant prefix coupling would change each tenant's
  float accumulation order). `MultiTenantEngine` keeps ratings as a
  ``(tenant_bucket, num_players)`` matrix and dispatches
  `ratings.elo_tenant_update_sorted`: per-row grouping, per-row cumsum
  — every tenant's arithmetic is the exact op sequence a dedicated
  single-tenant engine runs, so per-tenant results are bit-identical
  to T dedicated engines fed the same per-round batches (the tenant
  bench hard-gates this at 256 tenants; a property test covers zero-
  match tenants and tenant-bucket growth).

- **Bucketed tenant count.** The tenant axis is padded to a power of
  two (`tenant_bucket`), so adding tenants WITHIN a bucket changes no
  jit-boundary shape — zero steady-state recompiles, the same
  born-shape-bucketed discipline `engine.pack_batch` applies to batch
  sizes (jaxlint's `unbucketed-shape-at-jit-boundary` checks both).

Bit-exactness contract: a tenant's ratings match a dedicated
`ArenaEngine` when both pack each round into the SAME row bucket —
construct the dedicated engine with the same `min_bucket` and keep
per-round per-tenant batch sizes within one bucket (XLA's blocked
cumsum is not padding-invariant past an insertion point, so differing
buckets mean differing — still correct, not bit-equal — floats).

`CategoryRegistry` maps category names ("coding", "creative-writing",
…) onto tenant slots so per-category leaderboards — the LMSYS slice
use-case — ride the same key with no extra kernel code.
"""

import threading
from functools import partial

import jax
import numpy as np
from jax import numpy as jnp

from arena import ratings as R
from arena.engine import (
    ArenaEngine,
    MIN_BUCKET,
    _pow2_ceil,
    _validate_matches,
    _validate_tenant,
    bucket_size,
)

# Tenant-count buckets start here: an arena born with 3 tenants is
# shaped for 8, so early growth never touches a jit boundary.
MIN_TENANT_BUCKET = 8


def tenant_bucket(num_tenants, min_bucket=MIN_TENANT_BUCKET):  # deterministic
    """Pow2 tenant-count bucket (the tenant-axis analogue of
    `engine.bucket_size`): the jitted update's leading dim, so tenant
    growth within a bucket is shape-invisible to XLA."""
    return max(min_bucket, _pow2_ceil(max(int(num_tenants), 1)))


def compose_ids(ids, tenant, players_per_tenant):  # deterministic
    """Tenant-composite segment ids: the store/BT-side key. Tenant is
    the leading sort key because the composite id sorts tenant-major."""
    return ids + np.int32(tenant * players_per_tenant)


class TenantPackedBatch:
    """One round packed tenant-major for the fused 2-D update."""

    __slots__ = ("winners", "losers", "valid", "perm", "bounds",
                 "num_real", "tenant_counts")

    def __init__(self, winners, losers, valid, perm, bounds, num_real,
                 tenant_counts):
        self.winners = winners
        self.losers = losers
        self.valid = valid
        self.perm = perm
        self.bounds = bounds
        self.num_real = num_real
        self.tenant_counts = tenant_counts


def pack_tenant_batch(num_tenants_bucket, players_per_tenant, winners,
                      losers, min_bucket=MIN_BUCKET, dtype=np.float32):  # deterministic
    """Group one composite-id batch into the (T, B) tenant-major layout.

    `winners`/`losers` carry COMPOSITE ids (any tenant mix; a match
    must stay within one tenant — cross-tenant pairs are a reject).
    Each tenant's matches land in its row in arrival order, padded to
    the shared row bucket B exactly as `engine.pack_batch` pads a
    single batch (real entries first, id-0 padding after, valid mask
    0); the per-row grouping is the same stable argsort + boundary
    layout `engine._group_by_player` builds, vectorized across rows —
    no per-tenant Python loop, which is where the >= 5x over the
    dedicated-engine loop comes from.
    """
    w = np.asarray(winners, np.int32)
    l = np.asarray(losers, np.int32)
    ppt = int(players_per_tenant)
    t_w = w // ppt
    if not np.array_equal(t_w, l // ppt):
        raise ValueError(
            "cross-tenant match: winner and loser must belong to the "
            "same tenant"
        )
    n = int(w.shape[0])
    T = int(num_tenants_bucket)
    counts = np.bincount(t_w, minlength=T).astype(np.int64)
    B = bucket_size(max(int(counts.max()) if n else 1, 1), min_bucket)
    # Stable sort by tenant keeps each tenant's matches in batch order;
    # the column index is the within-tenant arrival position.
    order = np.argsort(t_w, kind="stable")
    rows = t_w[order]
    ends = np.cumsum(counts)
    col = np.arange(n, dtype=np.int64) - np.repeat(ends - counts, counts)
    w2 = np.zeros((T, B), np.int32)
    l2 = np.zeros((T, B), np.int32)
    valid = np.zeros((T, B), dtype)
    w2[rows, col] = (w - t_w * ppt)[order]
    l2[rows, col] = (l - t_w * ppt)[order]
    valid[rows, col] = 1
    combined = np.concatenate([w2, l2], axis=1)
    perm = np.argsort(combined, axis=1, kind="stable").astype(np.int32)
    # bounds[t, p] = count of entries with local id < p in row t ==
    # searchsorted(sorted row, p, side="left"), vectorized by counting
    # composite offsets into one flat bincount.
    flat = (combined.astype(np.int64) +
            np.arange(T, dtype=np.int64)[:, None] * ppt).ravel()
    per_id = np.bincount(flat, minlength=T * ppt).reshape(T, ppt)
    bounds = np.zeros((T, ppt + 1), np.int64)
    np.cumsum(per_id, axis=1, out=bounds[:, 1:])
    return TenantPackedBatch(
        w2, l2, valid, perm, bounds.astype(np.int32), n, counts
    )


class MultiTenantEngine(ArenaEngine):
    """N tenants, ONE engine: one jit cache, one store, one ops plane.

    `num_players` is the PER-TENANT roster size; the composite player
    space (`tenant_bucket * num_players` ids) is what the inherited
    store, Bradley–Terry refits (`bt_strengths`, `refit_incremental` —
    composite ids straight through `sorted_segment_sum`/`bt_mm_step`),
    and bootstrap intervals operate on unchanged. Only the Elo update
    is re-routed: batches pack tenant-major (`pack_tenant_batch`) and
    dispatch the fused `elo_tenant_update_sorted`, so `ratings` is a
    ``(tenant_bucket, num_players)`` matrix whose rows are bit-exact
    dedicated-engine results.

    The engine-facing ingest surface speaks composite ids (what the
    front door, the applied log, and snapshot replay carry); pass
    ``tenant=`` to submit tenant-local ids instead.
    """

    def __init__(self, num_players, num_tenants=1, k=R.DEFAULT_K,
                 scale=R.DEFAULT_SCALE, base=R.DEFAULT_BASE,
                 min_bucket=MIN_BUCKET, dtype=jnp.float32, obs=None,
                 min_tenant_bucket=MIN_TENANT_BUCKET):
        if num_tenants < 1:
            raise ValueError(
                f"a multi-tenant arena needs >= 1 tenant, got {num_tenants}"
            )
        bucket = tenant_bucket(num_tenants, min_tenant_bucket)
        super().__init__(
            bucket * num_players, k=k, scale=scale, base=base,
            min_bucket=min_bucket, dtype=dtype, obs=obs,
        )
        self.players_per_tenant = num_players
        self.num_tenants = num_tenants
        self.tenant_bucket = bucket
        self._min_tenant_bucket = min_tenant_bucket
        # Born shape-bucketed: (tenant_bucket, players) from the first
        # dispatch — never (num_tenants, players) reshaped later.
        self.ratings = self.ratings.reshape(bucket, num_players)
        self._update = jax.jit(
            partial(R.elo_tenant_update_sorted, k=k, scale=scale),
            donate_argnums=(0,),
        )

    # --- tenant roster -----------------------------------------------

    def ensure_tenants(self, num_tenants):  # deterministic; mutates: num_tenants, tenant_bucket, num_players, ratings
        """Grow the tenant roster to (at least) `num_tenants`.

        Within the current bucket this is a bookkeeping write — no
        shape changes, no recompiles (the tenant bench's sentinel
        gate). Crossing the bucket pads the ratings matrix with fresh
        base-rating rows and widens the store's composite bound; the
        next dispatch compiles once for the new bucket, and existing
        tenants' rows (and their composite ids, which depend only on
        `players_per_tenant`) are untouched — bit-preserved."""
        want = int(num_tenants)
        if want <= self.num_tenants:
            return self.num_tenants
        new_bucket = tenant_bucket(want, self._min_tenant_bucket)
        if new_bucket != self.tenant_bucket:
            self._drain_pipeline()
            pad = jnp.full(
                (new_bucket - self.tenant_bucket, self.players_per_tenant),
                self.base, self._dtype,
            )
            with self._view_lock:
                self.ratings = jnp.concatenate([self.ratings, pad])
                self.tenant_bucket = new_bucket
                self.num_players = new_bucket * self.players_per_tenant
                # The store's composite bound follows the bucket; every
                # already-stored id stays valid (ids only grow upward).
                self._store.num_players = self.num_players
        self.num_tenants = want
        return self.num_tenants

    def _compose(self, winners, losers, tenant):
        """Map (tenant-local ids, tenant) onto validated composite ids;
        tenant=None passes composite ids through."""
        w = np.asarray(winners, np.int32)
        l = np.asarray(losers, np.int32)
        if tenant is not None:
            t = _validate_tenant(self.num_tenants, tenant)
            _validate_matches(self.players_per_tenant, w, l)
            w = compose_ids(w, t, self.players_per_tenant)
            l = compose_ids(l, t, self.players_per_tenant)
        else:
            _validate_matches(self.num_players, w, l)
        return w, l

    # --- the fused update path ---------------------------------------

    def _apply_tenant(self, packed):
        with self.obs.span("engine.jit_dispatch"), self._view_lock:
            self.ratings = self._update(
                self.ratings,
                packed.winners,
                packed.losers,
                packed.valid.astype(self._dtype),
                packed.perm,
                packed.bounds,
            )
            self.matches_applied += packed.num_real
        if self.obs.enabled:
            for t in np.flatnonzero(packed.tenant_counts):
                self.obs.counter(
                    "arena_tenant_matches_total", tenant=str(int(t))
                ).inc(int(packed.tenant_counts[t]))
        return self.ratings

    def _pack_tenant(self, w, l):
        return pack_tenant_batch(
            self.tenant_bucket, self.players_per_tenant, w, l,
            self.min_bucket, np.float32,
        )

    def ingest(self, winners, losers, tenant=None):  # deterministic; mutates: _store, ratings, matches_applied
        """`ArenaEngine.ingest` re-routed through the fused tenant
        update: merge into the ONE composite store, pack tenant-major,
        dispatch once for every tenant in the batch."""
        self._drain_pipeline()
        w, l = self._compose(winners, losers, tenant)
        with self.obs.span("batch.ingest"):
            self._store.add(w, l)
            if w.shape[0] == 0:
                return self.ratings
            return self._apply_tenant(self._pack_tenant(w, l))

    def update(self, winners, losers, tenant=None):  # deterministic; mutates: _store, ratings, matches_applied
        """Alias of the fused path — a multi-tenant engine has exactly
        one update route, so sync/async/replayed batches all hit the
        same kernel (the replica bit-exactness contract)."""
        return self.ingest(winners, losers, tenant=tenant)

    def ingest_async(self, winners, losers, producer=None, tenant=None):
        """Async ingest through the inherited pipeline; the packer
        thread runs the tenant-major pack (`_pack_for_pipeline`
        override) and the dispatch half applies the fused update."""
        w, l = self._compose(winners, losers, tenant)
        return super().ingest_async(w, l, producer=producer)

    def _pack_for_pipeline(self, w, l):  # deterministic; mutates: _store
        # No staging slots: the tenant-major pack allocates its own
        # arrays (double-buffered 1-D staging doesn't fit a (T, B)
        # layout; the fused dispatch amortizes far more than staging
        # saves).
        self._store.add(w, l)
        if w.shape[0] == 0:
            return None
        return self._pack_tenant(w, l)

    def _dispatch_packed(self, packed):
        with self.obs.span("engine.apply"):
            return self._apply_tenant(packed)

    # --- restore / reads ---------------------------------------------

    def adopt_state(self, ratings, store):  # deterministic; mutates: ratings, _store, matches_applied
        r = np.asarray(ratings, np.float32).reshape(-1)
        super().adopt_state(r, store)
        with self._view_lock:
            self.ratings = self.ratings.reshape(
                self.tenant_bucket, self.players_per_tenant
            )
        return self.ratings

    def leaderboard(self, top_k=None, tenant=None):
        """(player_id, rating) best-first; `tenant=` scopes to one
        tenant's local ids, None ranks the whole composite space (the
        admin view; idle padding rows rank at the base rating)."""
        self._drain_pipeline()
        if tenant is None:
            # The admin view ranks the flat composite space (idle
            # padding rows sit at the base rating).
            flat = np.asarray(self.ratings).reshape(-1)
            order = np.argsort(-flat, kind="stable")
            if top_k is not None:
                order = order[:top_k]
            return [(int(i), float(flat[i])) for i in order]
        t = _validate_tenant(self.num_tenants, tenant)
        row = np.asarray(self.ratings[t])
        order = np.argsort(-row, kind="stable")
        if top_k is not None:
            order = order[:top_k]
        return [(int(i), float(row[i])) for i in order]


class CategoryRegistry:
    """category name -> tenant slot: per-category leaderboards (the
    LMSYS slice use-case) riding the multi-tenant key.

    `resolve` is the wire sanitizer for the submit path's `category=`
    field — an unknown category is a reject unless the registry was
    built with ``auto_register=True`` AND the engine can grow. Slots
    are assigned in registration order and never reused."""

    def __init__(self, engine, categories=(), auto_register=False):
        self._engine = engine
        self._lock = threading.Lock()
        self._slots = {}
        self.auto_register = auto_register
        for name in categories:
            self.register(name)

    def register(self, category):
        """Assign `category` the next tenant slot (idempotent)."""
        if not isinstance(category, str) or not category:
            raise ValueError(
                f"category must be a non-empty string, got {category!r}"
            )
        with self._lock:
            if category in self._slots:
                return self._slots[category]
            slot = len(self._slots)
            self._engine.ensure_tenants(slot + 1)
            self._slots[category] = slot
            return slot

    def resolve(self, category):
        """Map a category onto its tenant slot; unknown categories are
        a reject (or an auto-registration when configured)."""
        with self._lock:
            slot = self._slots.get(category)
        if slot is not None:
            return slot
        if self.auto_register:
            return self.register(category)
        raise ValueError(
            f"unknown category {category!r}: this arena serves "
            f"{sorted(self._slots)}"
        )

    def categories(self):
        """(category, tenant slot) pairs in slot order."""
        with self._lock:
            return sorted(self._slots.items(), key=lambda kv: kv[1])
