"""jaxlint corpus: a contract function with an undeclared write.

`apply_round` declares `# deterministic; mutates: ratings` — callers
(and the replica replay machinery) read that allowance as the COMPLETE
write set. But its helper also bumps `rounds_applied`, so restoring
`ratings` alone does not restore the object: the contract is lying
about the state surface. Rule: undeclared-mutation-in-contract.
"""


class Rounds:
    def __init__(self):
        self.ratings = {}
        self.rounds_applied = 0

    def _bump(self):
        self.rounds_applied += 1

    def apply_round(self, deltas):  # deterministic; mutates: ratings
        for player in deltas:
            self.ratings[player] = self.ratings.get(player, 0.0) + 1.0
        self._bump()
