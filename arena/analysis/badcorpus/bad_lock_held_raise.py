"""jaxlint corpus: a manual lock pairing escaped by a raise.

`update_totals` spells `acquire()`/`release()` by hand — the shape
`with _lock:` would have scoped — and the subscript between them can
raise KeyError. On that path the function unwinds with the lock HELD:
every later caller deadlocks on a lock whose owner is long gone. The
PR 10 lock rules only see with-held locks; this is the manual-pairing
gap they left open. Rule: lock-held-across-raise."""

import threading

_lock = threading.Lock()


def update_totals(totals, key, delta):
    _lock.acquire()
    totals[key] = totals[key] + delta  # KeyError unwinds with the lock held
    _lock.release()
