"""jaxlint corpus: device jnp compute on a request-handler hot path.

The wire tier's handlers (arena/net/server.py) answer from prebuilt
host-side views: pure NumPy + stdlib, ~10k requests/s territory. A
jnp op here pays a device dispatch and a transfer PER REQUEST for
work np does in-place — the exact hazard on the serving path that
`arena/net/` is pinned NOT to contain. Rule: jnp-on-host-path."""

import jax.numpy as jnp
import numpy as np


def handle_leaderboard(ratings, offset, limit):
    """One /leaderboard request: sort the host ratings copy... on the
    device, per request (the bug)."""
    ratings = np.asarray(ratings, np.float32)
    order = jnp.argsort(-ratings)
    page = np.asarray(order)[offset : offset + limit]
    return [(int(p), float(ratings[p])) for p in page]
