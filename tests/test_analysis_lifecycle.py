"""Acceptance for the jaxlint v4 lifecycle/resource typestate analyzer
(arena/analysis/lifecycle.py): each rule fires on its minimal shape,
the sanctioned shapes stay clean, ownership transfer and the one-hop
helper credit are honored, suppression works, and the real resource-
owning modules lint clean under the lifecycle rules alone.

These are also the named mutant killers:

- lifecycle-terminal-state-not-tracked dies in
  `test_use_after_close_fires_and_terminal_state_is_tracked` (no
  terminal tracking -> use-after-close never fires).
- release-in-helper-not-credited dies in
  `test_release_inside_helper_counts` (no helper credit -> the clean
  teardown-helper shape flags).
- exception-edge-dropped-from-cfg dies in
  `test_missing_finally_requires_the_exception_edge` (no exception
  edges -> the happy-path-only release looks total and the rule goes
  quiet).
"""

import pathlib

from arena.analysis import jaxlint

REPO = pathlib.Path(__file__).resolve().parent.parent

LIFECYCLE_RULES = {
    "resource-leaked-on-exception",
    "use-after-close",
    "lock-held-across-raise",
    "missing-finally-for-paired-call",
}

# A minimal protocol-annotated resource, shared by most sources below.
RES = (
    "class Res:  # protocol: stage->release\n"
    "    def stage(self, b):\n"
    "        return b\n"
    "    def release(self):\n"
    "        pass\n"
    "\n"
)


def _rules(src):
    return {f.rule for f in jaxlint.lint_source(src, "t.py")}


def test_lifecycle_rules_are_registered_with_severities():
    assert LIFECYCLE_RULES <= set(jaxlint.RULES)
    for name in LIFECYCLE_RULES:
        assert jaxlint.RULES[name].severity in jaxlint.SEVERITIES


# --- resource-leaked-on-exception -----------------------------------------


def test_leak_fires_when_no_release_exists_on_any_path():
    src = RES + (
        "def pack(b, wire):\n"
        "    r = Res()\n"
        "    r.stage(b)\n"
        "    wire.send(b)\n"
    )
    assert _rules(src) == {"resource-leaked-on-exception"}


def test_paired_release_in_finally_is_clean():
    src = RES + (
        "def pack(b, wire):\n"
        "    r = Res()\n"
        "    r.stage(b)\n"
        "    try:\n"
        "        wire.send(b)\n"
        "    finally:\n"
        "        r.release()\n"
    )
    assert _rules(src) == set()


def test_returning_the_acquired_object_is_ownership_transfer():
    src = RES + (
        "def make(b):\n"
        "    r = Res()\n"
        "    r.stage(b)\n"
        "    return r\n"
    )
    assert _rules(src) == set()


# --- missing-finally-for-paired-call --------------------------------------


def test_missing_finally_requires_the_exception_edge():
    """The release EXISTS but only on fall-through: the finding is
    purely a property of the exceptional paths, so it exists exactly
    because the CFG carries exception edges — drop them and this rule
    goes quiet (the cfg mutant's kill site)."""
    src = RES + (
        "def serve(b, wire):\n"
        "    r = Res()\n"
        "    r.stage(b)\n"
        "    wire.send(b)\n"
        "    r.release()\n"
    )
    assert _rules(src) == {"missing-finally-for-paired-call"}


def test_release_inside_helper_counts():
    """One interprocedural hop: the release lives in a sibling method
    (and, below, in a bare module function) — the analyzer credits it
    instead of flagging the teardown-helper idiom the real engine
    uses."""
    via_method = RES + (
        "class Owner:\n"
        "    def __init__(self):\n"
        "        self._res = Res()\n"
        "    def _teardown(self):\n"
        "        self._res.release()\n"
        "    def run(self, b, wire):\n"
        "        self._res.stage(b)\n"
        "        try:\n"
        "            wire.send(b)\n"
        "        finally:\n"
        "            self._teardown()\n"
    )
    assert _rules(via_method) == set()
    via_function = RES + (
        "def shutdown(res):\n"
        "    res.release()\n"
        "\n"
        "def run(b, wire):\n"
        "    r = Res()\n"
        "    r.stage(b)\n"
        "    try:\n"
        "        wire.send(b)\n"
        "    finally:\n"
        "        shutdown(r)\n"
    )
    assert _rules(via_function) == set()


# --- use-after-close ------------------------------------------------------


def test_use_after_close_fires_and_terminal_state_is_tracked():
    """A method call after the protocol's terminal method flags; the
    same call BEFORE it does not. If the analyzer stopped recording the
    terminal transition (the terminal-state mutant), the first half
    would go quiet."""
    conn = (
        "class Conn:  # protocol: close\n"
        "    def send(self, b):\n"
        "        pass\n"
        "    def close(self):\n"
        "        pass\n"
        "\n"
    )
    after = conn + (
        "def f(b):\n"
        "    c = Conn()\n"
        "    c.close()\n"
        "    c.send(b)\n"
    )
    assert _rules(after) == {"use-after-close"}
    before = conn + (
        "def f(b):\n"
        "    c = Conn()\n"
        "    c.send(b)\n"
        "    c.close()\n"
    )
    assert _rules(before) == set()


# --- lock-held-across-raise -----------------------------------------------


def test_lock_held_across_raise_fires_on_manual_pairing():
    src = (
        "import threading\n"
        "_lk = threading.Lock()\n"
        "def g(d, k):\n"
        "    _lk.acquire()\n"
        "    v = d[k]\n"
        "    _lk.release()\n"
        "    return v\n"
    )
    assert _rules(src) == {"lock-held-across-raise"}


def test_lock_release_in_finally_or_with_is_clean():
    manual = (
        "import threading\n"
        "_lk = threading.Lock()\n"
        "def g(d, k):\n"
        "    _lk.acquire()\n"
        "    try:\n"
        "        return d[k]\n"
        "    finally:\n"
        "        _lk.release()\n"
    )
    assert _rules(manual) == set()
    scoped = (
        "import threading\n"
        "_lk = threading.Lock()\n"
        "def g(d, k):\n"
        "    with _lk:\n"
        "        return d[k]\n"
    )
    assert _rules(scoped) == set()


# --- suppression + real tree ----------------------------------------------


def test_lifecycle_findings_honor_inline_suppression():
    src = RES + (
        "def pack(b, wire):\n"
        "    r = Res()\n"
        "    r.stage(b)  # jaxlint: disable=resource-leaked-on-exception\n"
        "    wire.send(b)\n"
    )
    assert _rules(src) == set()


def test_protocol_methods_themselves_are_exempt():
    """Res.stage's own body necessarily manipulates half-open state —
    the defining class's protocol methods must not self-flag (the
    StagingBuffers.release shape)."""
    assert _rules(RES) == set()


def test_real_resource_owning_modules_lint_clean_under_lifecycle_rules():
    """The modules that actually own stage->release / start->close
    obligations, under the lifecycle rules ALONE (no other family can
    mask a finding by erroring first)."""
    targets = [
        str(REPO / "arena" / "ingest.py"),
        str(REPO / "arena" / "engine.py"),
        str(REPO / "arena" / "pipeline.py"),
        str(REPO / "arena" / "serving.py"),
        str(REPO / "arena" / "net" / "server.py"),
        str(REPO / "arena" / "obs" / "__init__.py"),
    ]
    findings = jaxlint.lint_paths(targets, rules=sorted(LIFECYCLE_RULES))
    assert findings == [], "\n".join(f.format() for f in findings)
