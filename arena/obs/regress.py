"""Perf-regression watchdog: the bench trajectory as a machine-checked gate.

Every bench mode emits one JSON line (`arena/bench_arena.py`), and with
`ARENA_BENCH_HISTORY=<file>` set it also APPENDS that line to a history
file — JSON Lines, one run per line, newest last. Until this module the
trajectory (the BENCH_r*.json records) was checked only by a human
reading JSON; the watchdog makes it a gate:

    python -m arena.obs.regress --history bench_history.jsonl \
        --baseline BENCH_BASELINE.json

compares the NEWEST history run of every baseline-pinned metric against
its pinned value with a noise-aware per-metric tolerance, prints one
JSON verdict line, and exits:

    rc 0  every pinned metric within tolerance (improvements included —
          a speedup is never a failure)
    rc 1  at least one REGRESSION beyond tolerance (a measured verdict)
    rc 2  bad input: unreadable/corrupt history or baseline, empty
          history, a pinned metric with no history run, a malformed pin
          (nothing was measured — never conflated with rc 1, the same
          crash-vs-verdict discipline as the repo's other gates)

**History-file schema**: JSON Lines; each line is a bench_arena.py
output line — the watchdog reads only `metric` (the name) and `value`
(the headline number) and ignores failure lines (their metric names,
e.g. `arena_bench_equivalence_failure`, are simply never pinned).

**Baseline schema** (`BENCH_BASELINE.json` pins this repo's measured
trajectory):

    {"metrics": {
        "arena_ingest": {"value": 15.5, "direction": "higher",
                          "tolerance": 0.30},
        "arena_soak":   {"value": 0.256, "direction": "lower"}}}

`direction` says which way is good: `"higher"` for throughputs and
speedups (regression = value below `pinned * (1 - tol)`), `"lower"`
for latencies (regression = value above `pinned * (1 + tol)`). A value
EXACTLY at the tolerance bound passes — the tolerance is the allowance,
not the tripwire. `tolerance` is optional: when omitted, a NOISE-AWARE
tolerance is derived from the metric's own prior history runs (3x the
relative standard deviation of all runs before the newest, floored at
`--tolerance`, default 0.10) — a metric that historically wobbles 5%
gets a wider band than one that repeats to 0.1%, without hand-tuning
every pin.

No jax imports (the arena/obs rule): the watchdog must run anywhere
the history file can be read.
"""

import argparse
import json
import math
import pathlib
import sys

DEFAULT_TOLERANCE_FLOOR = 0.10
NOISE_MULTIPLIER = 3.0
DIRECTIONS = ("higher", "lower")

RC_OK = 0
RC_REGRESSION = 1
RC_BAD_INPUT = 2

DEFAULT_BASELINE = "BENCH_BASELINE.json"
DEFAULT_HISTORY = "bench_history.jsonl"


class WatchdogInputError(ValueError):
    """History or baseline unusable: nothing measurable (rc 2)."""


def _numeric(x):
    return isinstance(x, (int, float)) and not isinstance(x, bool) and (
        not isinstance(x, float) or math.isfinite(x)
    )


def load_history(path):
    """Parse a JSON Lines history file. Every non-empty line must be a
    JSON object; a corrupt line is BAD INPUT (named with its line
    number), never silently skipped — a half-written history must not
    quietly shrink the evidence."""
    path = pathlib.Path(path)
    try:
        text = path.read_text()
    except OSError as exc:
        raise WatchdogInputError(f"unreadable history {path}: {exc}") from exc
    runs = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        try:
            doc = json.loads(line)
        except ValueError as exc:
            raise WatchdogInputError(
                f"corrupt history line {lineno} in {path}: {exc}"
            ) from exc
        if not isinstance(doc, dict):
            raise WatchdogInputError(
                f"history line {lineno} in {path} is not a JSON object"
            )
        runs.append(doc)
    return runs


def load_baseline(path):
    """Parse and validate the baseline pin file."""
    path = pathlib.Path(path)
    try:
        doc = json.loads(path.read_text())
    except OSError as exc:
        raise WatchdogInputError(f"unreadable baseline {path}: {exc}") from exc
    except ValueError as exc:
        raise WatchdogInputError(f"corrupt baseline {path}: {exc}") from exc
    metrics = doc.get("metrics") if isinstance(doc, dict) else None
    if not isinstance(metrics, dict) or not metrics:
        raise WatchdogInputError(
            f"baseline {path} must carry a non-empty 'metrics' object"
        )
    for name, pin in metrics.items():
        if not isinstance(pin, dict) or not _numeric(pin.get("value")):
            raise WatchdogInputError(
                f"baseline metric {name!r} needs a numeric 'value', "
                f"found {pin!r}"
            )
        if pin.get("direction") not in DIRECTIONS:
            raise WatchdogInputError(
                f"baseline metric {name!r} direction must be one of "
                f"{DIRECTIONS}, found {pin.get('direction')!r}"
            )
        tol = pin.get("tolerance")
        if tol is not None and (not _numeric(tol) or tol < 0):
            raise WatchdogInputError(
                f"baseline metric {name!r} tolerance must be a "
                f"non-negative number, found {tol!r}"
            )
    return doc


def noise_tolerance(prior_values, floor):
    """Noise-aware tolerance: NOISE_MULTIPLIER x the relative standard
    deviation of the metric's prior runs, floored. Fewer than 3 priors
    (or a zero mean) is not enough signal — the floor applies."""
    if len(prior_values) < 3:
        return floor
    mean = sum(prior_values) / len(prior_values)
    if mean == 0:
        return floor
    var = sum((v - mean) ** 2 for v in prior_values) / len(prior_values)
    return max(floor, NOISE_MULTIPLIER * math.sqrt(var) / abs(mean))


def regressed(value, base, tol, direction):
    """True when `value` is beyond the tolerance band on the BAD side.

    Exactly AT the band edge passes; improvements (the good side, any
    size) always pass — the watchdog polices regressions, it never
    punishes a speedup.
    """
    if direction == "higher":
        return value < base * (1.0 - tol)
    return value > base * (1.0 + tol)


def compare(history, baseline, tolerance_floor=DEFAULT_TOLERANCE_FLOOR):
    """Compare the newest history run of every pinned metric against
    its baseline pin. Returns the verdict report; raises
    `WatchdogInputError` when nothing measurable exists (empty history,
    a pinned metric with no run)."""
    if not history:
        raise WatchdogInputError("history is empty: nothing to compare")
    by_metric = {}
    for run in history:
        name = run.get("metric")
        value = run.get("value")
        if isinstance(name, str) and _numeric(value):
            by_metric.setdefault(name, []).append(float(value))
    report = {"metrics": {}, "regressions": []}
    for name, pin in sorted(baseline["metrics"].items()):
        values = by_metric.get(name)
        if not values:
            raise WatchdogInputError(
                f"baseline metric {name!r} has no run in the history"
            )
        newest = values[-1]
        base = float(pin["value"])
        tol = pin.get("tolerance")
        tol_source = "baseline"
        if tol is None:
            tol = noise_tolerance(values[:-1], tolerance_floor)
            tol_source = "history-noise"
        is_reg = regressed(newest, base, float(tol), pin["direction"])
        entry = {
            "value": newest,
            "baseline": base,
            "direction": pin["direction"],
            "tolerance": round(float(tol), 6),
            "tolerance_source": tol_source,
            "delta_frac": round(newest / base - 1.0, 6) if base else None,
            "runs_seen": len(values),
            "regressed": is_reg,
        }
        report["metrics"][name] = entry
        if is_reg:
            report["regressions"].append(name)
    report["unpinned"] = sorted(set(by_metric) - set(baseline["metrics"]))
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m arena.obs.regress",
        description="Compare the newest bench-history run against the "
        "pinned baseline (rc 0 ok / rc 1 regression / rc 2 bad input)",
    )
    parser.add_argument(
        "--history", default=DEFAULT_HISTORY,
        help="JSON Lines bench history (append via ARENA_BENCH_HISTORY)",
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="pinned baseline JSON (see module docstring for the schema)",
    )
    parser.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE_FLOOR,
        help="tolerance floor for metrics without an explicit pin "
        "(noise-aware derivation never goes below this)",
    )
    args = parser.parse_args(argv)
    try:
        if args.tolerance < 0:
            raise WatchdogInputError(
                f"--tolerance must be >= 0, got {args.tolerance}"
            )
        history = load_history(args.history)
        baseline = load_baseline(args.baseline)
        report = compare(history, baseline, tolerance_floor=args.tolerance)
    except WatchdogInputError as exc:
        print(json.dumps({
            "check": "perf_watchdog",
            "verdict": "bad-input",
            "error": str(exc),
        }))
        return RC_BAD_INPUT
    report["check"] = "perf_watchdog"
    report["verdict"] = "regression" if report["regressions"] else "ok"
    print(json.dumps(report))
    return RC_REGRESSION if report["regressions"] else RC_OK


if __name__ == "__main__":
    sys.exit(main())
