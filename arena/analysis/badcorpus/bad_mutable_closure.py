"""jaxlint corpus: a jitted function closing over mutable host state.

Tracing captures `history` once; the append never happens on later
calls (the traced side effect is dropped), and any value read from it
is frozen at trace time. Rule: mutable-closure."""

import jax

history = []


@jax.jit
def traced_update(x):
    history.append(x)
    return x * 2.0
