"""Wire-tier contracts: the HTTP/JSON protocol over a REAL server
(arena/net/server.py, arena/net/protocol.py).

Every test here drives an actual `ThreadingHTTPServer` on an ephemeral
localhost port through `WireClient` — the same stack the frontend
bench's producers and readers use. The envelope contract (staleness
watermark + request trace id side by side in EVERY JSON response) is
this file's reason to exist; the mutation audit carries the
wire-response-omits-staleness-watermark mutant and
`test_every_wire_response_carries_watermark_and_trace_id` is its named
kill. One server is shared module-wide (session cost: one engine, one
port), with per-test state asserted as deltas.
"""

import numpy as np
import pytest

from arena.match import Matchmaker
from arena.net import (
    ArenaHTTPServer,
    FrontDoor,
    ProtocolError,
    WireClient,
    make_response,
    parse_path,
    parse_submit_body,
)
from arena.obs import Observability
from arena.serving import ArenaServer

PLAYERS = 48


@pytest.fixture(scope="module")
def wire():
    obs = Observability()
    srv = ArenaServer(num_players=PLAYERS, max_staleness_matches=0, obs=obs)
    rng = np.random.default_rng(0)
    a = rng.integers(0, PLAYERS, 400).astype(np.int32)
    b = ((a + 1 + rng.integers(0, PLAYERS - 1, 400)) % PLAYERS).astype(np.int32)
    srv.engine.ingest(a, b)
    frontdoor = FrontDoor(srv.engine, capacity=32, record_applied=True)
    matchmaker = Matchmaker(srv)
    server = ArenaHTTPServer(
        srv, frontdoor=frontdoor, matchmaker=matchmaker
    ).start()
    client = WireClient(server.host, server.port)
    yield server, client
    client.close()
    server.close()
    matchmaker.close()
    frontdoor.close()
    srv.close()


# --- the envelope contract --------------------------------------------------


def test_every_wire_response_carries_watermark_and_trace_id(wire):
    """The ROADMAP item 1 contract: the staleness watermark and the
    request's trace id ride side by side in EVERY JSON response —
    query endpoints, submit, healthz, and even protocol errors. The
    /stats Prometheus body carries the pair in headers instead (also
    asserted). The audit's envelope mutant dies here."""
    server, client = wire
    json_paths = [
        "/leaderboard?offset=0&limit=5",
        "/player/3",
        "/h2h?a=1&b=2",
        "/healthz",
        "/match?n=2",
        "/nope-not-an-endpoint",  # 404s keep the envelope too
    ]
    for path in json_paths:
        _status, resp = client.get(path)
        assert "watermark" in resp, f"{path} response lost the watermark"
        assert "trace_id" in resp, f"{path} response lost the trace id"
        assert resp["watermark"] == server.server.engine.matches_applied
    status, resp = client.submit([0, 1], [2, 3], producer="envelope-test")
    assert status == 202
    assert "watermark" in resp and "trace_id" in resp
    server.frontdoor.flush()
    # Handled endpoints run under a net.<endpoint> root span: the
    # trace id is real and resolves in the tracer.
    _status, resp = client.get("/leaderboard?offset=0&limit=1")
    assert resp["trace_id"] > 0
    trace = server.obs.tracer.trace(resp["trace_id"])
    assert any(s.name == "net.leaderboard" for s in trace)
    assert any(s.name == "serve.query" for s in trace)
    # /stats: Prometheus text body, envelope in headers.
    status, text, headers = client.get_with_headers("/stats")
    assert status == 200
    assert headers["X-Arena-Watermark"] == str(
        server.server.engine.matches_applied
    )
    assert int(headers["X-Arena-Trace-Id"]) > 0
    assert "# TYPE arena_http_requests_total counter" in text


def test_one_request_reads_one_view_and_matches_in_process_query(wire):
    """The wire layer adds transport, not semantics: a /leaderboard
    page equals the in-process `ArenaServer.query` page, row for row,
    and /player//h2h match their query() parts."""
    server, client = wire
    srv = server.server
    _status, over_wire = client.get("/leaderboard?offset=0&limit=10")
    direct = srv.query(leaderboard=(0, 10))
    assert over_wire["leaderboard"] == direct["leaderboard"]
    assert over_wire["view_seq"] == direct["view_seq"]
    _status, player = client.get("/player/7")
    assert player["players"] == srv.query(players=[7])["players"]
    _status, h2h = client.get("/h2h?a=3&b=4")
    assert h2h["pairs"] == srv.query(pairs=[(3, 4)])["pairs"]
    page = [row["rating"] for row in over_wire["leaderboard"]]
    assert page == sorted(page, reverse=True)


def test_submit_over_wire_lands_in_the_total_order(wire):
    server, client = wire
    frontdoor = server.frontdoor
    before = server.server.engine.matches_ingested
    seqs = []
    for producer in ("wire-a", "wire-b"):
        status, resp = client.submit(
            [0, 1, 2], [3, 4, 5], producer=producer
        )
        assert status == 202
        assert resp["matches"] == 3
        assert resp["producer"] == producer
        seqs.append(resp["seq"])
    assert seqs[1] == seqs[0] + 1  # global sequence numbers, in order
    frontdoor.flush()
    assert server.server.engine.matches_ingested == before + 6


def test_malformed_requests_are_structured_errors_not_crashes(wire):
    """400/404/405 with a JSON error body (envelope included) — and
    the handler thread survives to serve the next request."""
    server, client = wire
    cases = [
        ("GET", "/player/not-an-int", 400),
        ("GET", "/player/999999", 400),  # out of range: query reject
        ("GET", "/h2h?a=1", 400),  # missing b
        ("GET", "/leaderboard?offset=x", 400),
        ("GET", "/unknown", 404),
        ("GET", "/submit", 405),  # wrong method
    ]
    for method, path, want in cases:
        status, resp = client.get(path) if method == "GET" else (None, None)
        assert status == want, (path, status, resp)
        assert "error" in resp and "watermark" in resp
    status, resp = client.post("/submit", {"winners": "nope", "losers": []})
    assert status == 400 and "winners" in resp["error"]
    status, resp = client.post(
        "/submit", {"winners": [0], "losers": [1], "producer": ""}
    )
    assert status == 400
    # Out-of-range ids are rejected at admission, engine untouched.
    before = server.frontdoor.admitted_batches
    status, resp = client.post(
        "/submit", {"winners": [PLAYERS + 5], "losers": [0]}
    )
    assert status == 400 and "player ids" in resp["error"]
    assert server.frontdoor.admitted_batches == before
    # The server still works.
    status, _resp = client.get("/healthz")
    assert status == 200


def test_wire_counters_flow_into_stats_through_one_registry(wire):
    """Satellite: `ArenaServer.stats()` reports the wire tier through
    the SAME registry the handlers write and /stats renders — requests
    by endpoint and by status, sheds by policy. One schema, no second
    registry."""
    server, client = wire
    before = server.server.stats()["net"]
    for _ in range(3):
        client.get("/healthz")
    client.get("/definitely-404")
    after = server.server.stats()["net"]
    assert after["requests"] >= before["requests"] + 4
    assert (
        after["requests_by_endpoint"]["healthz"]
        >= before["requests_by_endpoint"].get("healthz", 0) + 3
    )
    assert (
        after["requests_by_status"]["404"]
        >= before["requests_by_status"].get("404", 0) + 1
    )
    assert isinstance(after["shed_batches_by_policy"], dict)
    # The same numbers are visible in the Prometheus exposition.
    _status, text, _headers = client.get_with_headers("/stats")
    assert 'arena_http_requests_total{endpoint="healthz",status="200"}' in text


def test_read_only_replica_answers_503_on_submit():
    obs = Observability()
    srv = ArenaServer(num_players=8, obs=obs)
    srv.engine.ingest(
        np.array([0, 1], np.int32), np.array([2, 3], np.int32)
    )
    with ArenaHTTPServer(srv, frontdoor=None) as server:
        client = WireClient(server.host, server.port)
        status, resp = client.submit([0], [1])
        assert status == 503
        assert "front door" in resp["error"]
        assert "watermark" in resp  # even a 503 keeps the envelope
        status, _resp = client.get("/leaderboard?offset=0&limit=3")
        assert status == 200  # reads still serve
        client.close()
    srv.close()


# --- protocol pure functions (no server needed) -----------------------------


def test_parse_path_routes_and_statuses():
    assert parse_path("GET", "/leaderboard?offset=5&limit=2") == (
        "leaderboard", {"offset": 5, "limit": 2},
    )
    assert parse_path("GET", "/leaderboard") == (
        "leaderboard", {"offset": 0, "limit": 50},
    )
    assert parse_path("GET", "/player/12") == ("player", {"player": 12})
    assert parse_path("GET", "/h2h?a=1&b=2") == ("h2h", {"a": 1, "b": 2})
    assert parse_path("POST", "/submit") == ("submit", {})
    assert parse_path("GET", "/stats") == ("stats", {})
    assert parse_path("GET", "/healthz") == ("healthz", {})
    # PR 20: the matchmaking plane. `policy` passes through only when
    # present (the matchmaker applies its own default), `n` defaults
    # to the wire-level proposal count.
    assert parse_path("GET", "/match") == ("match", {"n": 16})
    assert parse_path("GET", "/match?n=8&policy=fair&tenant=1") == (
        "match", {"n": 8, "policy": "fair", "tenant": 1},
    )
    for method, path, status in [
        ("GET", "/", 404),
        ("GET", "/player", 404),
        ("GET", "/player/1/extra", 404),
        ("POST", "/leaderboard", 405),
        ("GET", "/h2h?a=1&b=x", 400),
        ("GET", "/match?n=x", 400),
        ("POST", "/match", 405),
        ("GET", "/match/extra", 404),
    ]:
        with pytest.raises(ProtocolError) as exc:
            parse_path(method, path)
        assert exc.value.status == status, (method, path)


def test_parse_submit_body_validates_shape():
    w, l, producer, tenant, category = parse_submit_body(
        b'{"winners": [1, 2], "losers": [3, 4], "producer": "p1"}'
    )
    assert w.dtype == np.int32 and list(w) == [1, 2] and list(l) == [3, 4]
    assert producer == "p1"
    assert tenant is None and category is None
    _w, _l, producer, _t, _c = parse_submit_body(
        b'{"winners": [], "losers": []}'
    )
    assert producer == "local"
    for raw in [
        b"not json",
        b"[1, 2]",
        b'{"winners": [1.5], "losers": [2]}',
        b'{"winners": [true], "losers": [false]}',
        b'{"winners": [1], "losers": "x"}',
        b'{"winners": [1], "losers": [2], "producer": 7}',
    ]:
        with pytest.raises(ProtocolError) as exc:
            parse_submit_body(raw)
        assert exc.value.status == 400


def test_parse_submit_body_tenant_and_category():
    _w, _l, _p, tenant, category = parse_submit_body(
        b'{"winners": [1], "losers": [2], "tenant": 3}'
    )
    assert tenant == 3 and category is None
    _w, _l, _p, tenant, category = parse_submit_body(
        b'{"winners": [1], "losers": [2], "category": "vision"}'
    )
    assert tenant is None and category == "vision"
    for raw in [
        b'{"winners": [1], "losers": [2], "tenant": "x"}',
        b'{"winners": [1], "losers": [2], "tenant": 1.5}',
        b'{"winners": [1], "losers": [2], "tenant": true}',
        b'{"winners": [1], "losers": [2], "category": ""}',
        b'{"winners": [1], "losers": [2], "category": 7}',
        b'{"winners": [1], "losers": [2], "tenant": 0, "category": "a"}',
    ]:
        with pytest.raises(ProtocolError) as exc:
            parse_submit_body(raw)
        assert exc.value.status == 400, raw


def test_make_response_is_the_authoritative_envelope():
    """The envelope replaces any payload-supplied watermark/trace pair
    with the authoritative one — no endpoint can drift."""
    out = make_response(
        {"x": 1, "watermark": 999, "trace_id": 999},
        watermark=42, trace_id=7,
    )
    assert out == {"x": 1, "watermark": 42, "trace_id": 7}


# --- the /debug ops plane (PR 13) ------------------------------------------


def test_parse_path_routes_the_debug_family():
    assert parse_path("GET", "/debug/window") == ("debug_window", {})
    assert parse_path("GET", "/debug/slo") == ("debug_slo", {})
    assert parse_path("GET", "/debug/profile") == ("debug_profile", {})
    assert parse_path("GET", "/debug/trace/42") == (
        "debug_trace", {"trace_id": 42},
    )
    for method, path, status in [
        ("GET", "/debug", 404),
        ("GET", "/debug/nope", 404),
        ("GET", "/debug/trace", 404),
        ("GET", "/debug/trace/abc", 400),
        ("POST", "/debug/window", 405),
        ("POST", "/debug/trace/42", 405),
    ]:
        with pytest.raises(ProtocolError) as exc:
            parse_path(method, path)
        assert exc.value.status == status, (method, path)


def test_debug_endpoints_serve_the_standard_envelope(wire):
    """Named kill for the audit's debug-endpoint-omits-envelope mutant
    (a debug handler returning a None payload routes into the
    Prometheus-text no-envelope path): every /debug response is a JSON
    dict wearing the watermark + trace_id pair like any other
    endpoint — the ops plane gets no special wire contract."""
    server, client = wire
    for path in ("/debug/window", "/debug/slo", "/debug/profile"):
        status, resp = client.get(path)
        assert status == 200, path
        # dict FIRST: the mutant's symptom is a text/plain str body.
        assert isinstance(resp, dict), path
        assert "watermark" in resp and "trace_id" in resp, path
        assert resp["trace_id"] > 0, path
    status, window = client.get("/debug/window")
    assert window["ring"]["intervals"] >= 1
    assert window["ring"]["error"] is None
    status, slo = client.get("/debug/slo")
    assert "submit-delivery" in slo["objectives"]
    assert slo["alerts_active"] == 0
    status, prof = client.get("/debug/profile")
    assert prof["running"] is True  # wire.start() started the sampler
    assert prof["error"] is None


def test_debug_trace_resolves_a_request_trace(wire):
    """/debug/trace/{id} closes the loop the envelope opens: the
    trace_id every response carries resolves over the SAME wire into
    that request's recorded spans (the operator's 'show me that slow
    request' move, no process access needed)."""
    server, client = wire
    status, resp = client.get("/leaderboard?offset=0&limit=5")
    assert status == 200
    tid = resp["trace_id"]
    status, traced = client.get(f"/debug/trace/{tid}")
    assert status == 200
    assert traced["queried_trace_id"] == tid
    names = [s["name"] for s in traced["spans"]]
    assert "net.leaderboard" in names
    root = next(s for s in traced["spans"] if s["name"] == "net.leaderboard")
    assert root["parent_id"] == 0
    # The envelope's own trace_id belongs to THIS debug request.
    assert traced["trace_id"] != tid
    # An id the ring never held is a structured 404, envelope included.
    status, missing = client.get("/debug/trace/999999999")
    assert status == 404
    assert isinstance(missing, dict) and "error" in missing
    assert "watermark" in missing and "trace_id" in missing


def test_hostile_label_values_round_trip_through_the_wire_stats(wire):
    """Satellite (a): a producer name full of quotes, backslashes, and
    newlines must come back out of /stats as ONE well-formed escaped
    label value — not a split line, not a broken quote (the Prometheus
    text format's escaping rules for label values)."""
    server, client = wire
    hostile = 'ev"il\\x\nproducer'
    status, _resp = client.submit(
        np.asarray([1], np.int32), np.asarray([2], np.int32),
        producer=hostile,
    )
    assert status == 202
    server.frontdoor.flush()
    status, text = client.get("/stats")
    assert status == 200
    escaped = 'producer="ev\\"il\\\\x\\nproducer"'
    assert escaped in text
    # The raw value must NOT appear unescaped (a newline inside a
    # label value would split the sample line in two).
    for line in text.splitlines():
        assert not line.endswith('ev"il'), "unescaped newline split a line"
    assert "# HELP arena_http_requests_total" in text


# --- the golden envelope: exact response shapes (jaxlint v6) ---------------

# Every JSON endpoint's EXACT top-level key set. This is the live half
# of the v6 schema contracts: the linter pins the renderers' shape
# facts against the checked-in sidecars statically, this table pins
# the real HTTP responses against the same shapes at runtime. A key
# added or dropped anywhere in the render stack fails here in the
# same commit — wire drift is a reviewed diff of this table plus the
# sidecar, never a surprise in a reader's parser.
_ENVELOPE = {"watermark", "trace_id"}
_QUERY_PARTS = {"matches_ingested", "staleness", "stale", "view_seq",
                "view_ratings_sum"}
GOLDEN_RESPONSE_KEYS = {
    "/healthz": _ENVELOPE | {"status", "front_end", "matchmaker",
                             "players", "matches_ingested"},
    # PR 20: the matchmaking plane's proposal page.
    "/match?n=4": _ENVELOPE | {"matches_ingested", "staleness", "stale",
                               "view_seq", "policy", "n", "proposals"},
    "/leaderboard?offset=0&limit=5": _ENVELOPE | _QUERY_PARTS | {"leaderboard"},
    "/player/3": _ENVELOPE | _QUERY_PARTS | {"players"},
    "/h2h?a=1&b=2": _ENVELOPE | _QUERY_PARTS | {"pairs"},
    "/debug/window": _ENVELOPE | {"window_s", "counters", "gauges",
                                  "histograms", "ring"},
    "/debug/slo": _ENVELOPE | {"objectives", "alerts_active",
                               "alerts_fired_total", "window_s"},
    "/debug/profile": _ENVELOPE | {"hz", "samples", "running", "error",
                                   "roles", "top"},
    # PR 18: the replication log page a replica's SegmentCursor reads.
    "/log?after_seq=-1&limit=2": _ENVELOPE | {"records", "next_seq",
                                              "log_len", "base_watermark"},
}

# Time-travel responses are the query shape plus the as_of markers
# (asserted separately: ?as_of needs a TimeTravelIndex wired in).
_AS_OF_KEYS = {"as_of", "as_of_watermark"}


def test_every_endpoint_matches_its_golden_key_set(wire):
    server, client = wire
    for path, expected in GOLDEN_RESPONSE_KEYS.items():
        _status, resp = client.get(path)
        assert set(resp) == expected, (
            f"{path}: {sorted(set(resp) ^ expected)} drifted"
        )
    # POST endpoints: the batch query and submit acks.
    _status, batch = client.batch_query([{"leaderboard": [0, 3]}])
    assert set(batch) == _ENVELOPE | {"view_seq", "stale", "queries",
                                      "results"}
    # Each batch result is a full per-query response: envelope, query
    # parts, and the requested view slice.
    assert set(batch["results"][0]) == _ENVELOPE | _QUERY_PARTS | {
        "leaderboard"
    }
    status, ack = client.submit([0, 1], [2, 3], producer="golden-test")
    assert status == 202
    assert set(ack) == _ENVELOPE | {"seq", "producer", "matches",
                                    "pending_batches"}
    server.frontdoor.flush()
    # /debug/trace: resolve a real id so the shape is the found-path one.
    _status, page = client.get("/leaderboard?offset=0&limit=1")
    _status, traced = client.get(f"/debug/trace/{page['trace_id']}")
    assert set(traced) == _ENVELOPE | {"queried_trace_id", "spans"}
    # Row shapes: the leaderboard player row is itself a contracted
    # schema (wire-player-row) — pin it too.
    _status, board = client.get("/leaderboard?offset=0&limit=3")
    for row in board["leaderboard"]:
        assert set(row) == {"player", "rating", "lo", "hi", "wins",
                            "losses", "rank"}
    # /log record rows are the wire-log-segment record shape.
    _status, log_page = client.get("/log?after_seq=-1&limit=1")
    for rec in log_page["records"]:
        assert set(rec) == {"seq", "kind", "winners", "losers",
                            "record_watermark", "tenant"}
    # /match proposal rows are the wire-match proposal shape.
    _status, page = client.get("/match?n=4")
    assert page["proposals"], "48 ingested players must yield proposals"
    for row in page["proposals"]:
        assert set(row) == {"a", "b", "p_a_beats_b", "score"}


def test_as_of_responses_match_the_golden_query_shape(wire, tmp_path):
    """`?as_of=` answers are the EXACT query response shape plus the
    two time-travel markers — same sidecar (wire-query-response), same
    row schema, historical watermark in the envelope."""
    from arena.net.replica import TimeTravelIndex

    server, client = wire
    server.frontdoor.flush()
    snap = tmp_path / "golden-asof"
    server.server.snapshot(snap)
    as_of = int(server.server.engine.matches_applied)
    server.time_travel = TimeTravelIndex(
        server.server, server.frontdoor, snapshots=[snap]
    )
    try:
        _status, doc = client.get(f"/leaderboard?offset=0&limit=3&as_of={as_of}")
        assert set(doc) == GOLDEN_RESPONSE_KEYS[
            "/leaderboard?offset=0&limit=5"
        ] | _AS_OF_KEYS
        for row in doc["leaderboard"]:
            assert set(row) == {"player", "rating", "lo", "hi", "wins",
                                "losses", "rank"}
        _status, doc = client.get(f"/player/3?as_of={as_of}")
        assert set(doc) == GOLDEN_RESPONSE_KEYS["/player/3"] | _AS_OF_KEYS
        assert doc["watermark"] == doc["as_of_watermark"]
    finally:
        server.time_travel = None


def test_golden_key_sets_stay_inside_the_checked_in_sidecars():
    """The bridge between this file's live table and the linter's
    static sidecars: every key the golden table pins is declared by
    the corresponding schema sidecar (fields + envelope), so the two
    shape sources cannot drift apart silently."""
    import json as _json

    from arena.analysis.schema import SCHEMAS_DIR

    def declared(name):
        record = _json.loads((SCHEMAS_DIR / f"{name}.json").read_text())
        return set(record["fields"]) | set(record.get("arrays", ()))

    by_sidecar = {
        "/healthz": "wire-healthz",
        "/leaderboard?offset=0&limit=5": "wire-query-response",
        "/player/3": "wire-query-response",
        "/h2h?a=1&b=2": "wire-query-response",
        "/debug/window": "wire-debug-window",
        "/debug/slo": "wire-debug-slo",
        "/debug/profile": "wire-debug-profile",
        "/log?after_seq=-1&limit=2": "wire-log-segment",
        "/match?n=4": "wire-match",
    }
    envelope = declared("wire-envelope")
    assert envelope == _ENVELOPE
    for path, sidecar in by_sidecar.items():
        undeclared = GOLDEN_RESPONSE_KEYS[path] - declared(sidecar) - envelope
        assert not undeclared, f"{path}: {sorted(undeclared)} not in {sidecar}"
    # The as_of markers ride the same wire-query-response sidecar.
    assert _AS_OF_KEYS <= declared("wire-query-response")
