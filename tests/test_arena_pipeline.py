"""Overlapped-ingest pipeline contracts (arena/pipeline.py + engine).

The load-bearing property is PR 3's equivalence extended across a
thread boundary: any stream of batches through `ingest_async` must
land on EXACTLY the ratings the synchronous `ingest` path produces
(same staged layout, same jitted function, same order — bit-exact),
and both must equal a cold per-batch `update` replay. Around it, the
lifecycle contracts the first concurrent subsystem needs pinned:

- bounded-queue backpressure in BOTH policies (block waits and loses
  nothing; drop-oldest sheds raw batches and counts them, and a
  dropped batch never touches the match store);
- shutdown mid-stream drains without loss (and the non-drain shutdown
  still dispatches everything already merged, so store and ratings
  can never disagree);
- empty batches and compaction-boundary batches through the packer
  thread;
- a dead/never-started packer raises `PipelineError` instead of
  hanging the caller;
- zero steady-state jit compiles with the packer thread running
  (thread-aware `RecompileSentinel`).

The backpressure tests stall the packer deterministically by holding
the match store's own lock (the same lock the packer merges under —
no test seams in the pipeline).
"""

import threading
import time

import numpy as np
import pytest

from arena import engine, ingest, pipeline
from arena.analysis import sanitize
from arena.engine import ArenaEngine

P = 40


def make_matches(n, num_players=P, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, n).astype(np.int32)
    b = ((a + 1 + rng.integers(0, num_players - 1, n)) % num_players).astype(
        np.int32
    )
    return a, b


def random_split(w, l, seed, max_batches=8):
    """Random contiguous split, always including one empty batch."""
    rng = np.random.default_rng(seed)
    cuts = np.sort(rng.integers(0, len(w) + 1, rng.integers(1, max_batches)))
    bounds = [0, *cuts.tolist(), len(w)]
    batches = [(w[a:b], l[a:b]) for a, b in zip(bounds, bounds[1:])]
    batches.insert(int(rng.integers(0, len(batches) + 1)), (w[:0], l[:0]))
    return batches


def wait_until(predicate, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() > deadline:
            pytest.fail(f"timed out waiting for {what}")
        time.sleep(0.005)


# --- the equivalence property (the satellite's named test) -----------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_async_matches_sync_bit_exact(seed):
    """Property: ANY random split (empty batch included) streamed
    through ingest_async == sync ingest BIT-EXACT == cold per-batch
    update — and the chunked BT refit over the async history matches
    the cold single-bucket fit. Also the fast kill for the
    packer-thread-never-started mutant: with no packer, flush() raises
    PipelineError instead of returning ratings."""
    w, l = make_matches(900, seed=seed)
    batches = random_split(w, l, seed=100 + seed)
    eng_async, eng_sync, eng_cold = ArenaEngine(P), ArenaEngine(P), ArenaEngine(P)
    for bw, bl in batches:
        eng_async.ingest_async(bw, bl)
    r_async = np.asarray(eng_async.flush())
    for bw, bl in batches:
        eng_sync.ingest(bw, bl)
        eng_cold.update(bw, bl)
    np.testing.assert_array_equal(r_async, np.asarray(eng_sync.ratings))
    np.testing.assert_array_equal(r_async, np.asarray(eng_cold.ratings))
    assert eng_async.matches_ingested == len(w)
    chunked = np.asarray(eng_async.refit_incremental(num_iters=25, chunk_entries=512))
    single = np.asarray(eng_cold.bt_strengths(num_iters=25))
    np.testing.assert_allclose(chunked, single, atol=1e-3)
    eng_async.shutdown()


def test_compaction_boundary_batches_through_ingest_async():
    """Batches sized to land ON and then cross the store's compaction
    limit, with the galloping merge running on the PACKER thread; the
    grouping stays exact and the ratings stay bit-exact to sync."""
    w, l = make_matches(600, seed=7)
    eng_async, eng_sync = ArenaEngine(P), ArenaEngine(P)
    for eng in (eng_async, eng_sync):
        eng._store.compact_threshold = 400  # floor (main is small here)
    eng_async.ingest_async(w[:200], l[:200])  # tail lands exactly on 400
    eng_async.ingest_async(w[200:201], l[200:201])  # 402 > 400: compacts
    eng_async.ingest_async(w[201:], l[201:])
    r_async = np.asarray(eng_async.flush())
    assert eng_async._store.compactions >= 1
    eng_sync.ingest(w[:200], l[:200])
    eng_sync.ingest(w[200:201], l[200:201])
    eng_sync.ingest(w[201:], l[201:])
    np.testing.assert_array_equal(r_async, np.asarray(eng_sync.ratings))
    # The merged grouping built under the packer's lock is exact.
    perm, bounds = eng_async._store.grouping()
    assert np.array_equal(np.sort(perm), np.arange(2 * 600))
    assert int(bounds[-1]) == 2 * 600
    eng_async.shutdown()


def test_empty_batch_through_ingest_async_is_a_no_op():
    eng = ArenaEngine(P)
    before = np.asarray(eng.ratings).copy()
    eng.ingest_async([], [])
    np.testing.assert_array_equal(np.asarray(eng.flush()), before)
    assert eng.matches_ingested == 0
    assert eng._pipeline.pending() == 0
    eng.shutdown()


def test_ingest_async_rejects_bad_batch_at_the_call_site():
    """Validation runs on the CALLING thread before anything is
    queued: a malformed batch raises ValueError right there and no
    engine or pipeline state changes."""
    eng = ArenaEngine(8)
    eng.ingest_async([0, 1], [2, 3])
    eng.flush()
    before = np.asarray(eng.ratings).copy()
    with pytest.raises(ValueError, match="player ids"):
        eng.ingest_async([0, 8], [1, 2])
    np.testing.assert_array_equal(np.asarray(eng.flush()), before)
    assert eng.matches_ingested == 2
    assert eng._pipeline.submitted == 1  # the bad batch never enqueued
    eng.shutdown()


def test_sync_calls_drain_pending_async_work_first():
    """Program order across the sync/async boundary: a sync ingest (or
    update) issued after ingest_async must apply AFTER everything
    already submitted."""
    w, l = make_matches(300, seed=3)
    eng_mixed, eng_sync = ArenaEngine(P), ArenaEngine(P)
    eng_mixed.ingest_async(w[:100], l[:100])
    eng_mixed.ingest(w[100:200], l[100:200])  # barrier + sync batch
    eng_mixed.ingest_async(w[200:250], l[200:250])
    eng_mixed.update(w[250:], l[250:])  # update() is a barrier too
    r_mixed = np.asarray(eng_mixed.flush())
    for a, b in ((0, 100), (100, 200), (200, 250)):
        eng_sync.ingest(w[a:b], l[a:b])
    eng_sync.update(w[250:], l[250:])
    np.testing.assert_array_equal(r_mixed, np.asarray(eng_sync.ratings))
    eng_mixed.shutdown()


# --- backpressure ----------------------------------------------------------


def stalled_packer(eng):
    """Hold the match store's lock so the packer blocks at its first
    store merge — the deterministic stall the backpressure tests need
    (same lock the packer uses; no pipeline test seams)."""
    return eng._store._lock


def test_backpressure_block_policy_waits_and_loses_nothing():
    w, l = make_matches(120, seed=4)
    batches = [(w[i * 20 : (i + 1) * 20], l[i * 20 : (i + 1) * 20]) for i in range(6)]
    eng = ArenaEngine(P)
    pipe = eng.start_pipeline(capacity=2, policy="block")
    lock = stalled_packer(eng)
    submitted_all = threading.Event()

    def producer():
        for bw, bl in batches:
            eng.ingest_async(bw, bl)
        submitted_all.set()

    with lock:  # packer stalls inside its first store merge
        worker = threading.Thread(target=producer, daemon=True)
        worker.start()
        # The packer grabs batch 1, the queue holds 2 and 3; batch 4's
        # submit must BLOCK (capacity 2), not drop and not proceed.
        wait_until(lambda: pipe._packing, what="packer to pick up a batch")
        wait_until(lambda: pipe.submitted == 3, what="queue to fill")
        time.sleep(0.1)
        assert not submitted_all.is_set(), "block policy failed to block"
        assert pipe.dropped_batches == 0
    worker.join(timeout=10.0)
    assert submitted_all.is_set()
    r_async = np.asarray(eng.flush())
    assert pipe.dropped_batches == 0 and pipe.dropped_matches == 0
    eng_sync = ArenaEngine(P)
    for bw, bl in batches:
        eng_sync.ingest(bw, bl)
    np.testing.assert_array_equal(r_async, np.asarray(eng_sync.ratings))
    eng.shutdown()


def test_backpressure_drop_oldest_sheds_and_counts():
    """drop-oldest: a full queue evicts the OLDEST raw batch. Dropped
    batches never reached the match store, so the final ratings and
    history equal a sync run over exactly the surviving batches."""
    w, l = make_matches(100, seed=5)
    batches = [(w[i * 20 : (i + 1) * 20], l[i * 20 : (i + 1) * 20]) for i in range(5)]
    eng = ArenaEngine(P)
    pipe = eng.start_pipeline(capacity=2, policy="drop-oldest")
    lock = stalled_packer(eng)
    with lock:
        eng.ingest_async(*batches[0])  # packer picks this up, stalls
        wait_until(lambda: pipe._packing, what="packer to pick up batch 0")
        eng.ingest_async(*batches[1])  # queue: [1]
        eng.ingest_async(*batches[2])  # queue: [1, 2]
        eng.ingest_async(*batches[3])  # full -> drops 1, queue: [2, 3]
        eng.ingest_async(*batches[4])  # full -> drops 2, queue: [3, 4]
    r_async = np.asarray(eng.flush())
    assert pipe.dropped_batches == 2
    assert pipe.dropped_matches == 40
    assert eng.matches_ingested == 60  # only batches 0, 3, 4 exist
    eng_sync = ArenaEngine(P)
    for i in (0, 3, 4):
        eng_sync.ingest(*batches[i])
    np.testing.assert_array_equal(r_async, np.asarray(eng_sync.ratings))
    eng.shutdown()


# --- shutdown / drain ------------------------------------------------------


def test_shutdown_mid_stream_drains_without_loss():
    w, l = make_matches(800, seed=8)
    batches = random_split(w, l, seed=9)
    eng = ArenaEngine(P)
    for bw, bl in batches:
        eng.ingest_async(bw, bl)
    r_async = np.asarray(eng.shutdown(drain=True))  # no explicit flush first
    assert eng._pipeline is None
    assert eng.matches_ingested == len(w)
    eng_sync = ArenaEngine(P)
    for bw, bl in batches:
        eng_sync.ingest(bw, bl)
    np.testing.assert_array_equal(r_async, np.asarray(eng_sync.ratings))


def test_non_drain_shutdown_drops_raw_but_keeps_merged_consistent():
    """close(drain=False) drops batches still in the RAW queue, but a
    batch the packer already merged into the store is ALWAYS
    dispatched — the store and the ratings can never disagree."""
    w, l = make_matches(80, seed=10)
    batches = [(w[i * 20 : (i + 1) * 20], l[i * 20 : (i + 1) * 20]) for i in range(4)]
    eng = ArenaEngine(P)
    pipe = eng.start_pipeline(capacity=8)
    lock = stalled_packer(eng)
    closer = threading.Thread(target=lambda: eng.shutdown(drain=False), daemon=True)
    with lock:
        for bw, bl in batches:
            eng.ingest_async(bw, bl)
        wait_until(lambda: pipe._packing, what="packer to pick up batch 0")
        closer.start()
        wait_until(lambda: pipe.dropped_batches == 3, what="raw queue drop")
    closer.join(timeout=10.0)
    assert not closer.is_alive()
    assert pipe.dropped_matches == 60
    assert eng.matches_ingested == 20  # batch 0 was merged -> dispatched
    eng_sync = ArenaEngine(P)
    eng_sync.ingest(*batches[0])
    np.testing.assert_array_equal(
        np.asarray(eng.ratings), np.asarray(eng_sync.ratings)
    )


def test_submit_after_close_raises_and_engine_restarts_lazily():
    eng = ArenaEngine(P)
    w, l = make_matches(30, seed=12)
    eng.ingest_async(w, l)
    pipe = eng._pipeline
    eng.shutdown()
    with pytest.raises(pipeline.PipelineError, match="closed"):
        pipe.submit(w, l)
    # The engine starts a fresh pipeline transparently (the lazy
    # restart IS what this test pins — the post-shutdown calls are the
    # documented contract, hence the lifecycle-rule suppressions).
    eng.ingest_async(w, l)  # jaxlint: disable=use-after-close
    assert eng._pipeline is not pipe
    eng.flush()  # jaxlint: disable=use-after-close
    assert eng.matches_ingested == 60
    eng.shutdown()


def test_start_pipeline_twice_and_bad_config_raise():
    eng = ArenaEngine(P)
    eng.start_pipeline(capacity=2)
    with pytest.raises(RuntimeError, match="already running"):
        eng.start_pipeline()
    eng.shutdown()
    # Deliberate post-shutdown starts: config validation must reject
    # these BEFORE any pipeline spins up (shutdown is restartable).
    with pytest.raises(ValueError, match="policy"):
        eng.start_pipeline(policy="newest-wins")  # jaxlint: disable=use-after-close
    with pytest.raises(ValueError, match="capacity"):
        eng.start_pipeline(capacity=0)  # jaxlint: disable=use-after-close


def test_dead_packer_raises_instead_of_hanging(monkeypatch):
    """Every blocking wait re-checks packer liveness: a packer that
    never started (or died) surfaces as PipelineError at the next
    flush, never as a hang."""
    monkeypatch.setattr(pipeline.threading.Thread, "start", lambda self: None)
    eng = ArenaEngine(P)
    w, l = make_matches(10, seed=13)
    eng.ingest_async(w, l)
    with pytest.raises(pipeline.PipelineError, match="packer thread"):
        eng.flush()


def test_packer_error_surfaces_on_flush_and_drops_queue():
    """An exception in the packer (not reachable through validated
    engine input — forced here) is recorded, queued work is counted
    dropped, and flush()/submit() re-raise it as PipelineError."""
    eng = ArenaEngine(P)
    pipe = eng.start_pipeline(capacity=8)
    boom = RuntimeError("forced pack failure")

    def exploding_pack(w, l):
        raise boom

    eng._pack_for_pipeline = exploding_pack
    w, l = make_matches(10, seed=14)
    eng.ingest_async(w, l)
    with pytest.raises(pipeline.PipelineError, match="forced pack failure"):
        eng.flush()
    assert pipe.dropped_batches == 1
    with pytest.raises(pipeline.PipelineError):
        pipe.submit(w, l)
    eng._pipeline = None  # the broken pipeline is unusable; detach


# --- steady state ----------------------------------------------------------


def test_steady_state_async_ingest_causes_zero_recompiles():
    """The acceptance criterion with the packer thread running: after
    warmup, arbitrary batch sizes through ingest_async add ZERO
    jit-cache entries (thread-aware sentinel) and the staging pool
    stays fixed."""
    eng = ArenaEngine(P)
    w, l = make_matches(engine.MIN_BUCKET, seed=15)
    eng.ingest_async(w[:10], l[:10])
    eng.ingest_async(w[:20], l[:20])
    eng.flush()  # warmup: floor bucket compiled, both slots exist
    sentinel = sanitize.RecompileSentinel(update=eng.num_compiles)
    slots_after_warmup = eng._staging.slots_allocated
    for n in (1, 7, 100, 255, engine.MIN_BUCKET):
        eng.ingest_async(w[:n], l[:n])
    eng.flush()
    sentinel.assert_no_new_compiles()
    assert eng._staging.slots_allocated == slots_after_warmup
    assert eng._staging.in_flight() == 0, "drained pipeline left slots marked"
    eng.shutdown()


def test_recompile_sentinel_sees_compiles_from_other_threads():
    """The thread-aware half: jit caches are process-global, so a
    compile triggered on a worker thread moves a sentinel built on the
    main thread."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 3.0)
    f(jnp.zeros(3))
    sentinel = sanitize.RecompileSentinel(f=f)
    worker = threading.Thread(target=lambda: f(jnp.zeros(9)), daemon=True)
    worker.start()
    worker.join(timeout=30.0)
    with pytest.raises(sanitize.RecompileError, match="f: 1 -> 2"):
        sentinel.assert_no_new_compiles()


def test_pipeline_counters_and_pending():
    eng = ArenaEngine(P)
    w, l = make_matches(100, seed=16)
    pending_after = eng.ingest_async(w, l)
    assert pending_after in (0, 1)  # may already have been dispatched
    eng.flush()
    pipe = eng._pipeline
    assert pipe.pending() == 0
    assert pipe.submitted == 1 and pipe.completed == 1
    assert pipe.host_pack_s > 0 and pipe.dispatch_s >= 0
    eng.shutdown()


# --- queue spill (PR 5: the serving snapshot's resume-mid-stream seed) -----


def test_close_spill_returns_raw_queue_fifo_without_dropping():
    """close(spill=True) extracts the still-raw queue (FIFO, counted
    spilled not dropped) while batches already merged are dispatched —
    exactly the split a durable snapshot persists: store+ratings agree,
    the spilled remainder resumes on restore."""
    w, l = make_matches(100, seed=21)
    batches = [(w[i * 20 : (i + 1) * 20], l[i * 20 : (i + 1) * 20]) for i in range(5)]
    eng = ArenaEngine(P)
    pipe = eng.start_pipeline(capacity=8)
    lock = stalled_packer(eng)
    result = {}

    def closer():
        result["spilled"] = eng.shutdown(spill=True)

    with lock:
        for bw, bl in batches:
            eng.ingest_async(bw, bl)
        wait_until(lambda: pipe._packing, what="packer to pick up batch 0")
        worker = threading.Thread(target=closer, daemon=True)
        worker.start()
        wait_until(lambda: not pipe._raw, what="raw queue spill")
    worker.join(timeout=10.0)
    spilled = result["spilled"]
    assert pipe.spilled_batches == 4 and pipe.spilled_matches == 80
    assert pipe.dropped_batches == 0 and pipe.dropped_matches == 0
    assert eng.matches_ingested == 20  # batch 0 merged -> dispatched
    assert [tuple(sw.tolist()) for sw, _sl in spilled] == [
        tuple(bw.tolist()) for bw, _bl in batches[1:]
    ]
    # Resubmitting the spill reproduces the uninterrupted stream.
    for sw, sl in spilled:
        eng.ingest(sw, sl)
    eng_sync = ArenaEngine(P)
    for bw, bl in batches:
        eng_sync.ingest(bw, bl)
    np.testing.assert_array_equal(
        np.asarray(eng.ratings), np.asarray(eng_sync.ratings)
    )


def test_close_spill_with_empty_queue_returns_nothing():
    eng = ArenaEngine(P)
    w, l = make_matches(30, seed=22)
    eng.ingest_async(w, l)
    eng.flush()
    assert eng.shutdown(spill=True) == []
    assert eng.matches_ingested == 30
