"""Sharded-update correctness on a forced multi-device CPU mesh.

conftest.py sets XLA_FLAGS=--xla_force_host_platform_device_count=4
before the backend initializes, so these tests exercise the real
mesh/shard_map/psum machinery with no TPU. They pin semantics (sharded
== unsharded), not wall-clock — on this 1-core image host devices
share a core.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from arena import ratings as R
from arena import sharding


def test_forced_cpu_mesh_has_multiple_devices():
    """If this fails the XLA_FLAGS forcing in conftest.py broke and
    every other test in this file is silently single-device."""
    assert len(jax.devices()) >= 2


def make_batch(num_matches, num_players, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.integers(0, num_players, num_matches).astype(np.int32)
    l = ((w + 1 + rng.integers(0, num_players - 1, num_matches)) % num_players).astype(
        np.int32
    )
    return jnp.asarray(w), jnp.asarray(l)


def test_sharded_update_equals_unsharded():
    mesh = sharding.build_mesh()
    ndev = mesh.devices.size
    w, l = make_batch(64 * ndev, 40)
    r = jnp.full((40,), R.DEFAULT_BASE, jnp.float32)
    want = R.elo_batch_update(r, w, l)
    got = sharding.shard_elo_batch_update(mesh, r, w, l)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_sharded_epoch_equals_unsharded_epoch():
    mesh = sharding.build_mesh()
    ndev = mesh.devices.size
    nb, b, n = 3, 32 * ndev, 25
    rng = np.random.default_rng(1)
    w = rng.integers(0, n, (nb, b)).astype(np.int32)
    l = ((w + 1 + rng.integers(0, n - 1, (nb, b))) % n).astype(np.int32)
    valid = np.ones((nb, b), np.float32)
    r0 = jnp.full((n,), R.DEFAULT_BASE, jnp.float32)
    want = r0
    for i in range(nb):
        want = R.elo_batch_update(want, jnp.asarray(w[i]), jnp.asarray(l[i]))
    epoch = sharding.jit_sharded_elo_epoch(mesh)
    got = epoch(r0, jnp.asarray(w), jnp.asarray(l), jnp.asarray(valid))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


def test_sharded_update_rejects_indivisible_batch():
    mesh = sharding.build_mesh()
    if mesh.devices.size == 1:
        pytest.fail("forced mesh unexpectedly single-device")
    w, l = make_batch(mesh.devices.size * 8 + 1, 10)
    r = jnp.full((10,), R.DEFAULT_BASE, jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        sharding.shard_elo_batch_update(mesh, r, w, l)


def test_build_mesh_subset_and_bounds():
    mesh = sharding.build_mesh(num_devices=2)
    assert mesh.devices.size == 2
    assert mesh.axis_names == (sharding.DATA_AXIS,)
    with pytest.raises(ValueError, match="only"):
        sharding.build_mesh(num_devices=len(jax.devices()) + 1)


def test_match_partition_rules_first_match_wins_and_scalars_replicate():
    tree = {
        "ratings": jnp.zeros((16,)),
        "bt": {"strengths": jnp.zeros((16,)), "prior": jnp.float32(0.1)},
        "counts": jnp.zeros((16,), jnp.int32),
    }
    rules = [
        (r"bt/strengths", P(sharding.DATA_AXIS)),
        (r"ratings|counts", P(sharding.DATA_AXIS)),
    ]
    specs = sharding.match_partition_rules(rules, tree)
    assert specs["ratings"] == P(sharding.DATA_AXIS)
    assert specs["bt"]["strengths"] == P(sharding.DATA_AXIS)
    assert specs["counts"] == P(sharding.DATA_AXIS)
    # The scalar leaf matched no rule and must not need one.
    assert specs["bt"]["prior"] == P()


def test_match_partition_rules_unmatched_leaf_is_an_error():
    tree = {"mystery": jnp.zeros((8,))}
    with pytest.raises(ValueError, match="no partition rule matched"):
        sharding.match_partition_rules([(r"ratings", P(sharding.DATA_AXIS))], tree)


def test_match_partition_rules_regex_is_search_not_fullmatch():
    """Rules behave like the SNIPPETS pattern: re.search over the
    '/'-joined path, so a substring rule covers nested state."""
    tree = {"opt_state": {"ratings_momentum": jnp.zeros((4, 4))}}
    specs = sharding.match_partition_rules([(r"ratings", P(None, sharding.DATA_AXIS))], tree)
    assert specs["opt_state"]["ratings_momentum"] == P(None, sharding.DATA_AXIS)


def test_place_replicated_puts_state_on_every_device():
    mesh = sharding.build_mesh()
    r = sharding.place_replicated(mesh, jnp.arange(12.0))
    assert len(r.sharding.device_set) == mesh.devices.size
    np.testing.assert_array_equal(np.asarray(r), np.arange(12.0))
