"""Serialized-schema contract analyzer (jaxlint v6).

A `# schema: <name>@v<N>` clause on a def/class header (see
`arena.analysis.project.parse_schema`) declares the function a writer
or reader of the named serialized format — the snapshot manifest and
`arrays.bin` layout, the wire envelope and per-endpoint response
renders, the front door's `applied_log` replication records, the spill
records. The format's recorded shape lives in a checked-in sidecar
JSON (``arena/analysis/schemas/<name>.json``, or a ``schemas/``
directory next to the module for corpus fixtures), so changing a
serialized shape is a reviewable diff, not an archaeology project.

Per contracted function the analyzer extracts concrete shape FACTS
from the code — dict-literal keys, string-keyed subscript stores and
loads, ``.get("key")`` reads, membership/iteration tuples of string
literals, ``("name", value)`` record tags, ordered ``[("name", arr),
...]`` array tables, and np dtype constructors resolved through the
v3 abstract-value machinery — and enforces three shape rules:

- ``schema-drift-without-version-bump``: a VERSIONED format (its
  sidecar names a ``version_constant``) produces a key the sidecar
  does not record, reorders the recorded array table, or changes a
  recorded dtype, and the named module version constant was not
  bumped past the recorded version. Replicas parse these bytes; a
  silent shape change is a fleet-wide parse error.
- ``undeclared-serialized-field``: an UNVERSIONED format (wire
  responses — additive evolution, no version constant) produces a
  key its sidecar does not declare. Add the field to the sidecar so
  readers know it exists, or stop writing it.
- ``reader-writer-schema-mismatch``: any contracted function CONSUMES
  a key the sidecar does not declare — a reader (``restore``,
  ``WireClient`` parses, spill resubmission) depending on a field no
  writer is contracted to produce.

The fourth rule cashes in the v5 effect-summary machinery for ROADMAP
item 2's bit-exact-replay precondition:

- ``replication-boundary-write``: for every class whose methods carry
  `# deterministic; mutates:` contracts (the apply roots), the union
  of their declared write sets is REPLICATED STATE. Any method of the
  class outside the apply roots' transitive call closure (computed to
  a fixpoint over the call edges the symbol table resolves) whose own
  raw effect summary writes one of those attributes is a finding: a
  replica replaying the log in sequence order would never execute
  that write, so the write forks primary and replica state.
  Admission-side attributes a class legitimately writes on its intake
  path are exempted in ``schemas/replication-boundary.json`` (keyed
  by class name, each with a recorded "why"); lifecycle dunders and
  v4 `# protocol:` methods are exempt by construction.

No-claim semantics throughout: unresolvable calls contribute no
closure edges, unextractable expressions contribute no facts. Facts
are one-sided — a contracted function touching only a few declared
keys is fine (per-function facts are subsets of the format); only
NEW produced keys, NEW consumed keys, extracted-order mismatches, and
dtype contradictions are findings.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib

from arena.analysis import absint, effects
from arena.analysis.jaxlint import rule
from arena.analysis.project import dotted

RULE_DRIFT = "schema-drift-without-version-bump"
RULE_MISMATCH = "reader-writer-schema-mismatch"
RULE_UNDECLARED = "undeclared-serialized-field"
RULE_BOUNDARY = "replication-boundary-write"

_RULE_NAMES = (RULE_DRIFT, RULE_MISMATCH, RULE_UNDECLARED, RULE_BOUNDARY)

# The checked-in recorded shapes. A `schemas/` directory NEXT TO the
# contracted module wins over this one, so corpus fixtures carry their
# own sidecars without polluting the real registry.
SCHEMAS_DIR = pathlib.Path(__file__).resolve().parent / "schemas"

# Methods never reachable from the apply path by design: constructors
# and context-manager plumbing initialize or tear down the state the
# apply path replays ONTO; they are not part of the replayed history.
_LIFECYCLE_METHODS = frozenset({"__init__", "__enter__", "__exit__", "__del__"})


# --- sidecar loading -------------------------------------------------------


def _sidecar_path(module_path: str, name: str):
    local = pathlib.Path(module_path).resolve().parent / "schemas" / f"{name}.json"
    if local.exists():
        return local
    global_ = SCHEMAS_DIR / f"{name}.json"
    if global_.exists():
        return global_
    return None


def _load_sidecar(module_path: str, name: str):
    """(record dict, path) for the schema's sidecar, or (None, None)
    when no sidecar exists. Unreadable JSON is treated as missing —
    the drift rule reports it either way."""
    path = _sidecar_path(module_path, name)
    if path is None:
        return None, None
    try:
        return json.loads(path.read_text(encoding="utf-8")), path
    except (OSError, ValueError):
        return None, path


def _load_exemptions(module_path: str) -> dict:
    """class name -> frozenset of exempt attrs from the
    replication-boundary sidecar (empty when absent)."""
    record, _path = _load_sidecar(module_path, "replication-boundary")
    out = {}
    if record is None:
        return out
    for cls_name, entry in record.get("exempt", {}).items():
        out[cls_name] = frozenset(entry.get("attrs", ()))
    return out


# --- fact extraction -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Facts:
    """Shape facts extracted from one contracted function."""

    produced: frozenset  # keys this code writes into the format
    consumed: frozenset  # keys this code requires from the format
    arrays: tuple  # ordered array-table names, () when none extracted
    dtypes: dict  # key -> dtype name, for resolvable constructors


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _value_dtype(value):
    """dtype name a serialized value is constructed with, or None —
    `np.asarray(x, np.float32)`, `zeros(n, dtype="int32")`,
    `x.astype(np.int32)` all resolve via the v3 dtype lattice."""
    if not isinstance(value, ast.Call):
        return None
    for kw in value.keywords:
        if kw.arg == "dtype":
            return absint._resolve_dtype(kw.value)
    fname = dotted(value.func)
    tail = fname.split(".")[-1] if fname else ""
    if tail == "astype" and value.args:
        return absint._resolve_dtype(value.args[0])
    if tail in ("asarray", "array", "zeros", "ones", "full", "empty"):
        if len(value.args) >= 2:
            return absint._resolve_dtype(value.args[1])
    return None


def _all_str_elts(node):
    """The element strings when EVERY element of a tuple/list/set
    literal is a string constant, else None."""
    elts = getattr(node, "elts", None)
    if not elts:
        return None
    out = [_const_str(e) for e in elts]
    if any(s is None for s in out):
        return None
    return out


def _tuple_first_strs(node):
    """Ordered first-element names when a list literal is a table of
    >= 2 tuples each tagged by a leading string constant — the
    `[("keys", arr), ("pos", arr), ...]` array-table idiom."""
    if not isinstance(node, ast.List) or len(node.elts) < 2:
        return None
    names = []
    for elt in node.elts:
        if not (isinstance(elt, ast.Tuple) and len(elt.elts) >= 2):
            return None
        name = _const_str(elt.elts[0])
        if name is None:
            return None
        names.append(name)
    return tuple(names)


def _extract_facts(decl_node) -> _Facts:
    """One walk over the contracted def/class body. Reader-shaped
    string-literal collections (for/comprehension iteration tuples,
    membership-test tuples, required-set literals) are CONSUMED keys
    and excluded from the produced-tag extraction."""
    produced, consumed = set(), set()
    arrays = ()
    dtypes = {}
    reader_collections = set()  # node ids routed to `consumed`
    for node in ast.walk(decl_node):
        it = None
        if isinstance(node, (ast.For, ast.AsyncFor, ast.comprehension)):
            it = node.iter
        elif isinstance(node, ast.Compare):
            for cmp_node in node.comparators:
                if _all_str_elts(cmp_node) is not None:
                    reader_collections.add(id(cmp_node))
        if it is not None and _all_str_elts(it) is not None:
            reader_collections.add(id(it))
    for node in ast.walk(decl_node):
        if isinstance(node, ast.Dict):
            for key_node, value in zip(node.keys, node.values):
                key = _const_str(key_node)
                if key is None:
                    continue
                produced.add(key)
                found = _value_dtype(value)
                if found is not None:
                    dtypes[key] = found
        elif isinstance(node, ast.Subscript):
            key = _const_str(node.slice)
            if key is None:
                continue
            if isinstance(node.ctx, ast.Store):
                produced.add(key)
            else:  # Load or Del: the key must exist to be read/removed
                consumed.add(key)
        elif isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname and fname.split(".")[-1] == "get" and node.args:
                key = _const_str(node.args[0])
                if key is not None:
                    consumed.add(key)
        elif isinstance(node, ast.Set):
            keys = _all_str_elts(node)
            if keys is not None:
                consumed.update(keys)
        elif isinstance(node, (ast.Tuple, ast.List)):
            if id(node) in reader_collections:
                consumed.update(_all_str_elts(node))
                continue
            order = _tuple_first_strs(node)
            if order is not None and len(order) > len(arrays):
                arrays = order
            if (isinstance(node, ast.Tuple) and len(node.elts) >= 2
                    and isinstance(node.ctx, ast.Load)):
                key = _const_str(node.elts[0])
                if key is not None:
                    produced.add(key)
                    found = _value_dtype(node.elts[1])
                    if found is not None:
                        dtypes[key] = found
    return _Facts(frozenset(produced), frozenset(consumed), arrays, dtypes)


# --- version-bump detection ------------------------------------------------


def _module_int_constant(tree, name):
    """Module-level `NAME = <int literal>` binding, or None."""
    for node in tree.body:
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = (node.target,)
        else:
            continue
        value = node.value
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, int)
                and not isinstance(value.value, bool)):
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == name:
                return value.value
    return None


def _version_bumped(tree, sidecar, annotated_version) -> bool:
    """Whether the writer's module already bumped past the recorded
    version: the sidecar's named version constant when the module
    binds it, the `@vN` annotation otherwise."""
    recorded = int(sidecar.get("version", 0))
    const_name = sidecar.get("version_constant")
    if const_name is not None:
        found = _module_int_constant(tree, const_name)
        if found is not None:
            return found > recorded  # a bump is strictly-greater, never equal
    return annotated_version > recorded


# --- the module pass -------------------------------------------------------


def _resolve_decl(sym, qualname):
    """The ast node a schema contract is attached to: a module
    function, a `Cls.method`, or a class header."""
    if qualname in sym.functions:
        return sym.functions[qualname]
    if qualname in sym.classes:
        return sym.classes[qualname].node
    if "." in qualname:
        cls_name, mname = qualname.split(".", 1)
        cls = sym.classes.get(cls_name)
        if cls is not None:
            return cls.methods.get(mname)
    return None


def _schema_pass(ctx, findings):
    sym = ctx.symbols
    for qualname in sorted(sym.schemas):
        name, version = sym.schemas[qualname]
        node = _resolve_decl(sym, qualname)
        if node is None:
            continue
        sidecar, sidecar_path = _load_sidecar(sym.path, name)
        if sidecar is None:
            where = sidecar_path or (SCHEMAS_DIR / f"{name}.json")
            findings[RULE_DRIFT].append(ctx.finding(
                node, RULE_DRIFT,
                f"`{qualname}` declares `schema: {name}@v{version}` but no "
                f"recorded shape exists — check in the sidecar `{where}`",
            ))
            continue
        facts = _extract_facts(node)
        declared = (frozenset(sidecar.get("fields", ()))
                    | frozenset(sidecar.get("arrays", ())))
        new_produced = sorted(facts.produced - declared)
        recorded_arrays = tuple(sidecar.get("arrays", ()))
        order_drift = bool(facts.arrays and recorded_arrays
                           and facts.arrays != recorded_arrays)
        recorded_dtypes = sidecar.get("dtypes", {})
        dtype_drift = sorted(
            f"{key}: {recorded_dtypes[key]} -> {found}"
            for key, found in facts.dtypes.items()
            if key in recorded_dtypes and recorded_dtypes[key] != found
        )
        if "version_constant" in sidecar:
            drifted = []
            if new_produced:
                drifted.append("new field(s) " + ", ".join(new_produced))
            if order_drift:
                drifted.append(
                    "array order " + "/".join(facts.arrays)
                    + " != recorded " + "/".join(recorded_arrays)
                )
            if dtype_drift:
                drifted.append("dtype " + "; ".join(dtype_drift))
            if drifted and not _version_bumped(ctx.tree, sidecar, version):
                findings[RULE_DRIFT].append(ctx.finding(
                    node, RULE_DRIFT,
                    f"`{qualname}` drifts `{name}` ({'; '.join(drifted)}) "
                    f"without bumping `{sidecar['version_constant']}` past "
                    f"v{sidecar.get('version', 0)} — replicas parse these "
                    f"bytes; bump the version and update the sidecar",
                ))
        else:
            for key in new_produced:
                findings[RULE_UNDECLARED].append(ctx.finding(
                    node, RULE_UNDECLARED,
                    f"`{qualname}` writes field `{key}` not declared by "
                    f"schema `{name}` — add it to the sidecar so readers "
                    f"know it exists, or stop writing it",
                ))
        undeclared_reads = sorted(facts.consumed - declared)
        if undeclared_reads:
            findings[RULE_MISMATCH].append(ctx.finding(
                node, RULE_MISMATCH,
                f"`{qualname}` consumes field(s) "
                f"{', '.join(undeclared_reads)} that schema `{name}` does "
                f"not declare — no contracted writer produces them",
            ))


def _replication_pass(ctx, out):
    sym = ctx.symbols
    project = ctx.project
    exempt = _load_exemptions(sym.path)
    mods = (list(project.modules.values()) if project is not None
            else [sym])
    nodes = {}
    for mod in mods:
        for qualname, fn_node, cls_name in effects._iter_module_functions(mod):
            nodes[f"{mod.name}::{qualname}"] = (mod, cls_name, fn_node)
    summaries = {}

    def raw(key):
        cached = summaries.get(key)
        if cached is None:
            mod, cls_name, fn_node = nodes[key]
            methods = (set(mod.classes[cls_name].methods)
                       if cls_name is not None else frozenset())
            summary, callee_names = effects._raw_summary(fn_node, key, methods)
            edges = set()
            for fname in callee_names:
                target = effects._resolve_callee(mod, cls_name, fname, project)
                if target is not None and target != key and target in nodes:
                    edges.add(target)
            cached = (summary, frozenset(edges))
            summaries[key] = cached
        return cached

    for cls in sym.classes.values():
        roots, protected = [], set()
        for mname in cls.methods:
            contract = sym.contracts.get(f"{cls.name}.{mname}")
            if (contract is not None and contract["deterministic"]
                    and contract["mutates"]):
                roots.append(f"{sym.name}::{cls.name}.{mname}")
                protected |= set(contract["mutates"])
        protected -= exempt.get(cls.name, frozenset())
        if not roots or not protected:
            continue
        closure = set(roots)
        frontier = list(roots)
        while frontier:  # transitive apply closure, to fixpoint over call edges
            nxt = []
            for key in frontier:
                for callee in raw(key)[1]:
                    if callee not in closure:
                        closure.add(callee)
                        nxt.append(callee)
            frontier = nxt
        skip = _LIFECYCLE_METHODS | cls.protocol_methods()
        for mname in sorted(cls.methods):
            key = f"{sym.name}::{cls.name}.{mname}"
            if key in closure or mname in skip:
                continue
            summary, _edges = raw(key)
            bad = sorted(set(summary.self_writes) & protected)
            if bad:
                out.append(ctx.finding(
                    cls.methods[mname], RULE_BOUNDARY,
                    f"`{cls.name}.{mname}` writes replicated state "
                    f"({', '.join(bad)}) outside the `# deterministic` "
                    f"apply closure — a replica replaying the log never "
                    f"executes this write, forking primary and replica; "
                    f"route it through the apply path or exempt the attr "
                    f"in schemas/replication-boundary.json with a reason",
                ))


def _analysis(ctx):
    cached = getattr(ctx, "_schema_findings", None)
    if cached is None:
        cached = {name: [] for name in _RULE_NAMES}
        _schema_pass(ctx, cached)
        _replication_pass(ctx, cached[RULE_BOUNDARY])
        ctx._schema_findings = cached
    return cached


# --- the four v6 rules -----------------------------------------------------


@rule(
    RULE_DRIFT,
    "a versioned serialized format (`# schema: name@vN` with a sidecar "
    "version constant) gains a field, reorders its array table, or changes "
    "a dtype without bumping the named version constant",
    severity="error",
)
def _check_schema_drift(ctx):
    yield from _analysis(ctx)[RULE_DRIFT]


@rule(
    RULE_MISMATCH,
    "a `# schema:`-contracted reader consumes a field its schema sidecar "
    "does not declare — no contracted writer produces it",
    severity="error",
)
def _check_reader_writer_mismatch(ctx):
    yield from _analysis(ctx)[RULE_MISMATCH]


@rule(
    RULE_UNDECLARED,
    "a `# schema:`-contracted writer of an unversioned wire format emits a "
    "field its sidecar does not declare — declare it or stop writing it",
    severity="error",
)
def _check_undeclared_field(ctx):
    yield from _analysis(ctx)[RULE_UNDECLARED]


@rule(
    RULE_BOUNDARY,
    "a method outside the `# deterministic` apply closure writes an "
    "attribute in the apply path's `mutates:` closure — log replay would "
    "never execute the write, forking replica state from the primary",
    severity="error",
)
def _check_replication_boundary(ctx):
    yield from _analysis(ctx)[RULE_BOUNDARY]
