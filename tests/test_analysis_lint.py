"""jaxlint contracts: clean tree lints clean, every rule fires on the
corpus, suppressions work, and the CLI honors its exit codes.

The clean-tree assertion is the CI wiring the tentpole asks for: the
linter runs over `arena/`, `bench.py`, and `tests/` inside tier-1, so
any commit that introduces a hot-path hazard (host sync in a jitted
body, use-after-donate, unblocked timing, ...) turns the suite red in
the same commit. Most checks run in-process (the linter is stdlib-only
and parses the repo in milliseconds); exactly one subprocess pins the
real `python -m arena.analysis` entrypoint because that is the
documented operator command.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from arena.analysis import jaxlint

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "arena" / "analysis" / "badcorpus"
CLEAN_TARGETS = [str(REPO / "arena"), str(REPO / "bench.py"), str(REPO / "tests")]

# Per-file contract: each corpus module trips EXACTLY its own rule.
# (Asserting set equality, not membership, keeps corpus files honest —
# a file that started tripping a second rule means either the file or
# a rule drifted.)
CORPUS_EXPECTED = {
    "bad_mutable_closure.py": {"mutable-closure"},
    "bad_host_sync.py": {"host-sync-in-jit"},
    "bad_nonstatic_shape.py": {"nonstatic-shape-arg"},
    "bad_use_after_donate.py": {"use-after-donate"},
    "bad_timing.py": {"timing-without-block"},
    "bad_timing_span.py": {"timing-without-block"},
    "bad_jnp_host.py": {"jnp-on-host-path"},
    "bad_handler_host_path.py": {"jnp-on-host-path"},
    "bad_sharding_spec.py": {"sharding-spec-arity"},
    # jaxlint v2: the concurrency lock-discipline analyzer.
    "bad_unguarded_write.py": {"unguarded-shared-write"},
    "bad_blocking_locked.py": {"blocking-while-locked"},
    "bad_lock_order.py": {"lock-order-inversion"},
    "bad_liveness_recheck.py": {"thread-no-liveness-recheck"},
    # jaxlint v3: the abstract-interpretation families.
    "bad_unbucketed_jit_shape.py": {"unbucketed-shape-at-jit-boundary"},
    "bad_dtype_drift.py": {"dtype-drift-into-kernel"},
    "bad_wire_taint.py": {"unvalidated-wire-input"},
    # jaxlint v4: the lifecycle/resource typestate analyzer.
    "bad_resource_leak_exception.py": {"resource-leaked-on-exception"},
    "bad_use_after_close.py": {"use-after-close"},
    "bad_lock_held_raise.py": {"lock-held-across-raise"},
    "bad_missing_finally.py": {"missing-finally-for-paired-call"},
    # jaxlint v5: the interprocedural effect-contract analyzer.
    "bad_nondeterministic_contract.py": {"nondeterminism-in-deterministic-fn"},
    "bad_impure_render.py": {"hidden-state-read-in-pure-render"},
    "bad_check_then_act.py": {"check-then-act-race"},
    "bad_undeclared_mutation.py": {"undeclared-mutation-in-contract"},
    # jaxlint v6: the serialized-schema contract analyzer.
    "bad_schema_drift.py": {"schema-drift-without-version-bump"},
    "bad_undeclared_field.py": {"undeclared-serialized-field"},
    "bad_reader_writer_mismatch.py": {"reader-writer-schema-mismatch"},
    "bad_replication_boundary_write.py": {"replication-boundary-write"},
}

# The --format=json per-finding schema (the mechanical consumption
# contract): one object per line, these keys exactly.
JSON_KEYS = {"rule", "path", "line", "col", "message", "suppressed", "severity"}


def test_clean_tree_has_zero_findings():
    """The repo's own hot path obeys every invariant the linter checks.
    A finding here is a real regression (or a new rule that needs
    tuning/suppression) — fix it, don't relax this test."""
    findings = jaxlint.lint_paths(CLEAN_TARGETS)
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_every_rule_fires_on_the_corpus():
    findings = jaxlint.lint_paths([str(CORPUS)])
    fired = {f.rule for f in findings}
    assert fired == set(jaxlint.RULES), (
        f"rules never exercised by the corpus: {set(jaxlint.RULES) - fired}"
    )


def test_each_corpus_file_trips_exactly_its_rule():
    # The manifest must cover every corpus file and every rule.
    files = {p.name for p in CORPUS.glob("bad_*.py")}
    assert files == set(CORPUS_EXPECTED)
    for name, expected in CORPUS_EXPECTED.items():
        found = {f.rule for f in jaxlint.lint_paths([str(CORPUS / name)])}
        assert found == expected, f"{name}: found {found}, expected {expected}"


def test_host_sync_rule_names_each_call_form():
    """Both halves of the rule must fire: the named-callable set
    (print/float/np.asarray — the half a blinded flag set would drop)
    AND the .item() method branch. Membership per call form, not just
    per rule, so neither half can silently rot."""
    findings = jaxlint.lint_paths([str(CORPUS / "bad_host_sync.py")])
    messages = "\n".join(f.message for f in findings)
    for call_form in ("`print(...)`", "`float(...)`", "`np.asarray(...)`", ".item()"):
        assert call_form in messages, f"host-sync rule no longer flags {call_form}"


def test_default_targets_cover_the_ingest_and_pipeline_modules():
    """The seven rules gate every NEW hot path: arena/ingest.py,
    arena/pipeline.py, arena/serving.py, the arena/obs/ package, and
    the arena/net/ wire tier must be inside the default-target walk
    (so `python -m arena.analysis` and the clean-tree test both lint
    them) and must themselves lint clean."""
    walked = {
        str(f) for f in jaxlint.iter_python_files(jaxlint.default_targets())
    }
    for mod in (
        "ingest.py", "pipeline.py", "serving.py",
        "obs/__init__.py", "obs/metrics.py", "obs/tracing.py",
        "obs/context.py", "obs/debug.py", "obs/regress.py",
        "obs/windows.py", "obs/slo.py", "obs/profile.py",
        "net/__init__.py", "net/protocol.py", "net/frontdoor.py",
        "net/server.py", "net/fastpath.py",
        "analysis/project.py", "analysis/concurrency.py",
    ):
        path = str(REPO / "arena" / mod)
        assert path in walked, f"default targets no longer cover arena/{mod}"
        findings = jaxlint.lint_paths([path])
        assert findings == [], "\n".join(f.format() for f in findings)


def test_wire_handler_hot_path_lints_clean_while_corpus_twin_fires():
    """The corpus carries the request-handler-shaped hazard
    (bad_handler_host_path.py: jnp sort on the per-request host path —
    flagged), and the REAL wire handlers are pinned NOT to trip it:
    arena/net/server.py answers from prebuilt NumPy views, stdlib
    only."""
    corpus_findings = jaxlint.lint_paths(
        [str(CORPUS / "bad_handler_host_path.py")]
    )
    assert {f.rule for f in corpus_findings} == {"jnp-on-host-path"}
    real = jaxlint.lint_paths([
        str(REPO / "arena" / "net" / "server.py"),
        str(REPO / "arena" / "net" / "frontdoor.py"),
        str(REPO / "arena" / "net" / "protocol.py"),
        str(REPO / "arena" / "net" / "fastpath.py"),
    ])
    assert real == [], "\n".join(f.format() for f in real)


def test_obs_span_api_does_not_trip_the_timing_rule():
    """The corpus carries the DIY span (bad_timing_span.py: inline
    clock reads around an async dispatch — flagged); the real tracing
    API keeps its clock reads inside `_Span.__enter__`/`__exit__`, so
    an instrumented dispatch lints clean — spans time host stages, not
    unblocked device work, and the linter agrees."""
    diy = (CORPUS / "bad_timing_span.py").read_text()
    assert {f.rule for f in jaxlint.lint_source(diy, "diy.py")} == {
        "timing-without-block"
    }
    instrumented = (
        "import jax.numpy as jnp\n"
        "from arena.obs import Observability\n"
        "obs = Observability()\n"
        "def dispatch_epoch(x):\n"
        "    with obs.span('engine.jit_dispatch'):\n"
        "        y = jnp.dot(x, x)\n"
        "    return y\n"
    )
    assert jaxlint.lint_source(instrumented, "ok.py") == []
    # Trace-context propagation carries IDS, it does not time device
    # work: an attach-wrapped cross-thread dispatch (the pipeline's
    # packer shape) must not trip the timing rule either.
    carried = (
        "import jax.numpy as jnp\n"
        "from arena.obs import Observability, attach\n"
        "obs = Observability()\n"
        "def pack_on_worker(ctx, x):\n"
        "    with attach(ctx):\n"
        "        with obs.span('pipeline.pack'):\n"
        "            y = jnp.dot(x, x)\n"
        "    return y\n"
    )
    assert jaxlint.lint_source(carried, "ok_ctx.py") == []


def test_sharding_spec_rule_flags_both_failure_modes():
    """Both halves of sharding-spec-arity must fire on the corpus
    file: the undefined-axis finding AND the in_specs/function arity
    mismatch — membership per failure mode so neither half can rot."""
    findings = jaxlint.lint_paths([str(CORPUS / "bad_sharding_spec.py")])
    messages = "\n".join(f.message for f in findings)
    assert "'model'" in messages, "undefined-axis half no longer fires"
    assert "2 specs" in messages and "3 arguments" in messages, (
        "arity half no longer fires"
    )


@pytest.mark.parametrize("good", [
    # The repo's own idiom: axis name behind a module constant, specs
    # matching the wrapped function's arity.
    "from functools import partial\n"
    "import numpy as np\n"
    "import jax\n"
    "from jax.experimental.shard_map import shard_map\n"
    "from jax.sharding import Mesh\n"
    "from jax.sharding import PartitionSpec as P\n"
    "AXIS = 'data'\n"
    "mesh = Mesh(np.array(jax.devices()), (AXIS,))\n"
    "@partial(shard_map, mesh=mesh, in_specs=(P(), P(AXIS)), out_specs=P())\n"
    "def ok(r, w):\n"
    "    return r + w\n",
    # No mesh constructed in this module: axis names are unknowable,
    # the rule must stay quiet rather than guess.
    "from functools import partial\n"
    "from jax.experimental.shard_map import shard_map\n"
    "from jax.sharding import PartitionSpec as P\n"
    "def build(mesh):\n"
    "    @partial(shard_map, mesh=mesh, in_specs=(P('model'),), out_specs=P())\n"
    "    def ok(x):\n"
    "        return x\n"
    "    return ok\n",
])
def test_sharding_spec_rule_sanctioned_patterns(good):
    assert jaxlint.lint_source(good, "ok.py") == []


def test_default_walk_skips_the_corpus():
    """`jaxlint arena/` must not see badcorpus/ (clean tree stays
    clean) while linting the corpus dir explicitly must."""
    over_arena = jaxlint.lint_paths([str(REPO / "arena")])
    assert all("badcorpus" not in f.path for f in over_arena)
    assert jaxlint.lint_paths([str(CORPUS)]) != []


def test_inline_suppression_mutes_only_the_named_rule():
    bad = (CORPUS / "bad_timing.py").read_text()
    assert jaxlint.lint_source(bad, "t.py") != []
    muted = bad.replace(
        "elapsed = time.perf_counter() - t0",
        "elapsed = time.perf_counter() - t0  # jaxlint: disable=timing-without-block",
    )
    assert jaxlint.lint_source(muted, "t.py") == []
    wrong_rule = bad.replace(
        "elapsed = time.perf_counter() - t0",
        "elapsed = time.perf_counter() - t0  # jaxlint: disable=mutable-closure",
    )
    assert jaxlint.lint_source(wrong_rule, "t.py") != []
    mute_all = bad.replace(
        "elapsed = time.perf_counter() - t0",
        "elapsed = time.perf_counter() - t0  # jaxlint: disable=all",
    )
    assert jaxlint.lint_source(mute_all, "t.py") == []


def test_suppression_covers_decorated_def_header():
    """Regression (v2 satellite): the finding points at the in_specs
    line INSIDE a multi-line decorator; the directive sits on the `def`
    line — the enclosing statement's header. v1 matched only the
    flagged line, so this exact comment was silently ignored."""
    src = (
        "from functools import partial\n"
        "import numpy as np\n"
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "from jax.sharding import Mesh\n"
        "from jax.sharding import PartitionSpec as P\n"
        "mesh = Mesh(np.array(jax.devices()), ('data',))\n"
        "@partial(\n"
        "    shard_map,\n"
        "    mesh=mesh,\n"
        "    in_specs=(P('model'),),\n"
        "    out_specs=P(),\n"
        ")\n"
        "def f(x):\n"
        "    return x\n"
    )
    assert {f.rule for f in jaxlint.lint_source(src, "d.py")} == {
        "sharding-spec-arity"
    }
    muted = src.replace(
        "def f(x):", "def f(x):  # jaxlint: disable=sharding-spec-arity"
    )
    assert jaxlint.lint_source(muted, "d.py") == []
    wrong_rule = src.replace(
        "def f(x):", "def f(x):  # jaxlint: disable=mutable-closure"
    )
    assert jaxlint.lint_source(wrong_rule, "d.py") != []


def test_suppression_covers_wrapped_with_header():
    """Regression (v2 satellite): the poisoned read sits on an inner
    line of a wrapped `with` header; the directive sits after the
    closing colon. The directive covers the statement HEADER only — a
    violation in the with BODY must still fire."""
    src = (
        "import jax\n"
        "f = jax.jit(lambda s, d: s + d, donate_argnums=(0,))\n"
        "def g(state, delta, ctx_over):\n"
        "    f(state, delta)\n"
        "    with ctx_over(\n"
        "        state\n"
        "    ):  # jaxlint: disable=use-after-donate\n"
        "        pass\n"
    )
    assert jaxlint.lint_source(src, "w.py") == []
    unmuted = src.replace("  # jaxlint: disable=use-after-donate", "")
    findings = jaxlint.lint_source(unmuted, "w.py")
    assert {f.rule for f in findings} == {"use-after-donate"}
    assert findings[0].line == 6  # the read is on the wrapped header line
    # The directive must NOT leak into the body.
    body_violation = src.replace("        pass\n", "        h = state\n")
    assert {f.rule for f in jaxlint.lint_source(body_violation, "w.py")} == {
        "use-after-donate"
    }


def test_json_format_lines_carry_rule(capsys):
    """`--format=json`: one JSON object per finding per line with the
    full mechanical schema — a consumer greps rc and parses lines, no
    human-format scraping."""
    rc = jaxlint.main(
        ["--format=json", str(CORPUS / "bad_use_after_donate.py")]
    )
    assert rc == 1
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines
    for line in lines:
        obj = json.loads(line)
        assert set(obj) == JSON_KEYS
        assert obj["rule"] == "use-after-donate"
        assert obj["severity"] == "error"
        assert obj["suppressed"] is False


def test_json_format_flags_suppressed_findings_rc_unchanged(tmp_path, capsys):
    """Suppressed findings appear in JSON output flagged
    suppressed=true and do NOT flip the exit code — rc semantics are
    identical across formats."""
    bad = (CORPUS / "bad_timing.py").read_text().replace(
        "elapsed = time.perf_counter() - t0",
        "elapsed = time.perf_counter() - t0  # jaxlint: disable=timing-without-block",
    )
    target = tmp_path / "muted.py"
    target.write_text(bad)
    rc = jaxlint.main(["--format=json", str(target)])
    assert rc == 0  # suppressed-only: clean exit, same as human format
    lines = capsys.readouterr().out.strip().splitlines()
    objs = [json.loads(line) for line in lines]
    assert objs and all(o["suppressed"] is True for o in objs)
    assert {o["rule"] for o in objs} == {"timing-without-block"}


def test_syntax_error_is_a_finding_not_a_crash():
    findings = jaxlint.lint_source("def broken(:\n", "b.py")
    assert len(findings) == 1
    assert findings[0].rule == "syntax-error"


def test_main_in_process_exit_codes():
    assert jaxlint.main(CLEAN_TARGETS) == 0
    assert jaxlint.main([str(CORPUS)]) == 1
    assert jaxlint.main([str(REPO / "does-not-exist")]) == 2
    assert jaxlint.main(["--list-rules"]) == 0


def test_findings_name_real_lines(capsys):
    """CLI output is path:line:col: rule: message — clickable and
    stable enough for CI grepping."""
    rc = jaxlint.main([str(CORPUS / "bad_use_after_donate.py")])
    assert rc == 1
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    path, line, _col, rule_name = out[0].split(":", 3)
    assert path.endswith("bad_use_after_donate.py")
    src_line = (CORPUS / "bad_use_after_donate.py").read_text().splitlines()[
        int(line) - 1
    ]
    assert "state" in src_line
    assert rule_name.strip().startswith("use-after-donate")


@pytest.mark.parametrize("good", [
    # Rebinding to the donating call's result is the sanctioned pattern.
    "import jax\n"
    "f = jax.jit(lambda s, d: s + d, donate_argnums=(0,))\n"
    "def ok(state, delta):\n"
    "    state = f(state, delta)\n"
    "    return state + 1.0\n",
    # Timing with block_until_ready in the region is honest.
    "import time\nimport jax\nimport jax.numpy as jnp\n"
    "def ok(x):\n"
    "    t0 = time.perf_counter()\n"
    "    y = jax.block_until_ready(jnp.dot(x, x))\n"
    "    return y, time.perf_counter() - t0\n",
    # jnp compute in a TRACED body is the correct placement.
    "import jax\nimport jax.numpy as jnp\nimport numpy as np\n"
    "@jax.jit\n"
    "def ok(x):\n"
    "    return jnp.cumsum(x)\n"
    "def host(x):\n"
    "    return np.asarray(ok(jnp.asarray(x)))\n",
    # static_argnums declared: the shape arg is deliberate.
    "import jax\n"
    "f = jax.jit(lambda x, n: x, static_argnums=(1,))\n"
    "def ok(batch):\n"
    "    return f(batch, batch.shape[0])\n",
])
def test_sanctioned_patterns_lint_clean(good):
    assert jaxlint.lint_source(good, "ok.py") == []


def test_cli_subprocess_contract():
    """The documented operator command, end to end: the acceptance
    criterion's clean run (rc 0, empty stdout) and the corpus run
    (rc 1, findings on stdout). Two plain-`python` spawns (~1.7s each
    on this image — `-S` is not an option here because `-m
    arena.analysis` imports the arena package, whose __init__ pulls
    jax from site-packages)."""
    clean = subprocess.run(
        [
            sys.executable, "-m", "arena.analysis",
            "arena/", "arena/ingest.py", "bench.py",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert clean.stdout.strip() == ""
    corpus = subprocess.run(
        [sys.executable, "-m", "arena.analysis", "arena/analysis/badcorpus"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert corpus.returncode == 1
    assert "use-after-donate" in corpus.stdout
    # --format=json over the same corpus: identical rc, every stdout
    # line a JSON object with the pinned schema (the satellite's
    # machine-consumption contract, end to end).
    as_json = subprocess.run(
        [
            sys.executable, "-m", "arena.analysis", "--format=json",
            "arena/analysis/badcorpus",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert as_json.returncode == 1
    json_lines = [json.loads(line) for line in as_json.stdout.splitlines()]
    assert json_lines
    assert all(set(obj) == JSON_KEYS for obj in json_lines)
    assert all(obj["severity"] in jaxlint.SEVERITIES for obj in json_lines)
    assert {obj["rule"] for obj in json_lines} == set(jaxlint.RULES)
    # --format=sarif over the same corpus: rc unchanged, stdout is ONE
    # SARIF 2.1.0 document (the v4 satellite's CI-annotation contract,
    # through the real entrypoint).
    as_sarif = subprocess.run(
        [
            sys.executable, "-m", "arena.analysis", "--format=sarif",
            "arena/analysis/badcorpus",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert as_sarif.returncode == 1
    doc = json.loads(as_sarif.stdout)
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert {r["ruleId"] for r in results} == set(jaxlint.RULES)


# --- v3 CLI satellites: rule selection + multi-bad-path reporting ---------


def test_rules_flag_runs_only_the_named_rules(capsys):
    """--rules=<a,b> runs the named rules in isolation (how the
    expensive abstract-interp families run alone); rc semantics
    unchanged — findings rc 1, clean rc 0."""
    target = str(CORPUS / "bad_use_after_donate.py")
    rc = jaxlint.main(["--rules=use-after-donate", target])
    assert rc == 1
    assert "use-after-donate" in capsys.readouterr().out
    # The same file under an unrelated rule selection is clean: rc 0.
    rc = jaxlint.main(["--rules=mutable-closure", target])
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_disable_flag_skips_the_named_rules(capsys):
    target = str(CORPUS / "bad_use_after_donate.py")
    rc = jaxlint.main(["--disable=use-after-donate", target])
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""
    # --rules then --disable compose: select two, disable one.
    multi = str(CORPUS / "bad_dtype_drift.py")
    rc = jaxlint.main([
        "--rules=dtype-drift-into-kernel,use-after-donate",
        "--disable=dtype-drift-into-kernel", multi,
    ])
    assert rc == 0


def test_unknown_rule_name_is_a_usage_error(capsys):
    assert jaxlint.main(["--rules=no-such-rule", str(CORPUS)]) == 2
    assert "no-such-rule" in capsys.readouterr().err
    assert jaxlint.main(["--disable=also-not-a-rule", str(CORPUS)]) == 2
    assert "also-not-a-rule" in capsys.readouterr().err


def test_list_rules_names_severity_for_every_rule(capsys):
    assert jaxlint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name, r in jaxlint.RULES.items():
        assert f"{name} [{r.severity}]:" in out


def test_rc2_reports_every_bad_path_in_one_run(capsys):
    """The rc-2 satellite: BOTH missing targets named, each on its own
    line, in one run (previously effectively first-error-only), in the
    human format..."""
    rc = jaxlint.main([str(REPO / "nope-one"), str(REPO / "nope-two"),
                       str(CORPUS)])
    assert rc == 2
    err_lines = [
        line for line in capsys.readouterr().err.splitlines()
        if line.startswith("jaxlint:")
    ]
    assert len(err_lines) == 2
    assert "nope-one" in err_lines[0] and "nope-two" in err_lines[1]


def test_rc2_reports_every_bad_path_as_json_lines(capsys):
    """...and in --format=json: one structured object per bad path."""
    rc = jaxlint.main([
        "--format=json", str(REPO / "nope-one"), str(REPO / "nope-two"),
    ])
    assert rc == 2
    objs = [json.loads(line) for line in capsys.readouterr().out.splitlines()]
    assert len(objs) == 2
    assert all(obj["error"] == "bad-path" for obj in objs)
    assert [pathlib.Path(o["path"]).name for o in objs] == [
        "nope-one", "nope-two"
    ]


def test_unreadable_file_reports_rc2_with_path_named(
    tmp_path, capsys, monkeypatch
):
    """A directory walk that hits an unreadable .py file reports it
    (rc 2, path named) instead of crashing — and still names EVERY
    other bad path in the same run. (chmod can't simulate this under
    the root test runner, so the read failure is injected.)"""
    (tmp_path / "ok.py").write_text("x = 1\n")
    blocked = tmp_path / "blocked.py"
    blocked.write_text("y = 2\n")
    real_read_text = pathlib.Path.read_text

    def flaky_read_text(self, *args, **kwargs):
        if self.name == "blocked.py":
            raise PermissionError(13, "Permission denied")
        return real_read_text(self, *args, **kwargs)

    monkeypatch.setattr(pathlib.Path, "read_text", flaky_read_text)
    rc = jaxlint.main([str(tmp_path), str(tmp_path / "missing-too")])
    assert rc == 2
    err = capsys.readouterr().err
    assert "blocked.py" in err
    assert "missing-too" in err


# --- v4 CLI satellites: SARIF output + baseline files ---------------------


def test_sarif_format_document_shape(capsys):
    """--format=sarif emits ONE SARIF 2.1.0 document: rule descriptors
    for every rule referenced, and per result the rule id, severity
    level, message text, and a 1-based physical location — the minimal
    shape CI annotation tooling consumes. rc semantics unchanged."""
    rc = jaxlint.main(["--format=sarif", str(CORPUS / "bad_use_after_donate.py")])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    assert len(doc["runs"]) == 1
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "jaxlint"
    assert {r["id"] for r in driver["rules"]} == {"use-after-donate"}
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    results = doc["runs"][0]["results"]
    assert results
    for res in results:
        assert res["ruleId"] == "use-after-donate"
        assert res["level"] in jaxlint.SEVERITIES
        assert res["message"]["text"]
        region = res["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1 and region["startColumn"] >= 1
        uri = res["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
        assert uri.endswith("bad_use_after_donate.py")
        assert "suppressions" not in res  # nothing suppressed here


def test_sarif_marks_suppressed_findings_rc_unchanged(tmp_path, capsys):
    """Suppressed findings appear in the SARIF document carrying an
    inSource suppression object (the SARIF spelling of the JSON
    format's suppressed flag) and do NOT flip the exit code."""
    bad = (CORPUS / "bad_timing.py").read_text().replace(
        "elapsed = time.perf_counter() - t0",
        "elapsed = time.perf_counter() - t0  # jaxlint: disable=timing-without-block",
    )
    target = tmp_path / "muted.py"
    target.write_text(bad)
    rc = jaxlint.main(["--format=sarif", str(target)])
    assert rc == 0
    results = json.loads(capsys.readouterr().out)["runs"][0]["results"]
    assert results
    assert all(
        res["suppressions"] == [{"kind": "inSource"}] for res in results
    )


def test_baseline_write_then_filter(tmp_path, capsys):
    """First run against a missing baseline file WRITES it (rc 0 — the
    dirty tree is recorded, not failed); the second run reports only
    findings absent from it."""
    baseline = tmp_path / "baseline.json"
    target = str(CORPUS / "bad_use_after_donate.py")
    rc = jaxlint.main([f"--baseline={baseline}", target])
    assert rc == 0
    captured = capsys.readouterr()
    assert captured.out.strip() == ""  # nothing reported on the write run
    assert "baseline written" in captured.err
    keys = json.loads(baseline.read_text())["findings"]
    assert keys and all(k.startswith("use-after-donate::") for k in keys)
    # Re-run: every finding is baselined, rc drops to 0, stdout empty.
    rc = jaxlint.main([f"--baseline={baseline}", target])
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""
    # A target with findings NOT in the baseline still fails.
    rc = jaxlint.main(
        [f"--baseline={baseline}", str(CORPUS / "bad_timing.py")]
    )
    assert rc == 1
    assert "timing-without-block" in capsys.readouterr().out


def test_baseline_is_line_drift_tolerant(tmp_path, capsys):
    """Baseline keys are rule+path+message — moving a known finding to
    a different line (unrelated edits above it) must not resurrect
    it."""
    src = (CORPUS / "bad_use_after_donate.py").read_text()
    target = tmp_path / "mod.py"
    target.write_text(src)
    baseline = tmp_path / "baseline.json"
    assert jaxlint.main([f"--baseline={baseline}", str(target)]) == 0
    capsys.readouterr()
    # Drift every finding down three lines without changing its message.
    target.write_text("# pad\n# pad\n# pad\n" + src)
    rc = jaxlint.main([f"--baseline={baseline}", str(target)])
    assert rc == 0, capsys.readouterr().out
    assert capsys.readouterr().out.strip() == ""


def test_baseline_malformed_file_is_rc2(tmp_path, capsys):
    baseline = tmp_path / "baseline.json"
    baseline.write_text("{not json")
    rc = jaxlint.main(
        [f"--baseline={baseline}", str(CORPUS / "bad_timing.py")]
    )
    assert rc == 2
    assert "baseline" in capsys.readouterr().err
    # Valid JSON of the wrong shape is equally a usage error.
    baseline.write_text(json.dumps([1, 2, 3]))
    rc = jaxlint.main(
        [f"--baseline={baseline}", str(CORPUS / "bad_timing.py")]
    )
    assert rc == 2


# --- v5 CLI satellites: baseline x --rules composition + --jobs -----------


def test_baseline_records_its_rule_coverage(tmp_path, capsys):
    """Regression (v5 satellite): a baseline written under --rules=<X>
    only ever SAW rule X — it must not act as an allowlist for rules
    it never ran. The file records its coverage; a later full-registry
    run reports the other rules' findings as NEW (rc 1)."""
    baseline = tmp_path / "baseline.json"
    # bad_timing.py trips timing-without-block; write a baseline that
    # covers only mutable-closure (which the file does NOT trip).
    target = str(CORPUS / "bad_timing.py")
    rc = jaxlint.main(
        ["--rules=mutable-closure", f"--baseline={baseline}", target]
    )
    assert rc == 0
    capsys.readouterr()
    data = json.loads(baseline.read_text())
    assert data["rules"] == ["mutable-closure"]  # coverage recorded
    # Full-registry run against that narrow baseline: the timing
    # finding is OUTSIDE the baseline's coverage, so it is new — rc 1.
    rc = jaxlint.main([f"--baseline={baseline}", target])
    assert rc == 1
    assert "timing-without-block" in capsys.readouterr().out


def test_full_baseline_composes_with_rules_subset(tmp_path, capsys):
    """The other half of the composition: a baseline written under the
    FULL registry (coverage "all") still suppresses its findings when
    replayed under a --rules subset."""
    baseline = tmp_path / "baseline.json"
    target = str(CORPUS / "bad_timing.py")
    assert jaxlint.main([f"--baseline={baseline}", target]) == 0
    capsys.readouterr()
    assert json.loads(baseline.read_text())["rules"] == "all"
    rc = jaxlint.main(
        ["--rules=timing-without-block", f"--baseline={baseline}", target]
    )
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_legacy_baseline_without_coverage_key_still_filters(tmp_path, capsys):
    """A pre-v5 baseline file (no "rules" key) means full coverage —
    existing operator baselines keep suppressing, not resurrecting."""
    baseline = tmp_path / "baseline.json"
    target = str(CORPUS / "bad_timing.py")
    assert jaxlint.main([f"--baseline={baseline}", target]) == 0
    capsys.readouterr()
    data = json.loads(baseline.read_text())
    del data["rules"]
    baseline.write_text(json.dumps(data))
    rc = jaxlint.main([f"--baseline={baseline}", target])
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_parallel_lint_is_bit_identical_to_serial():
    """--jobs=N is a wall-clock knob ONLY: the findings list (order,
    lines, messages, suppression flags) is byte-for-byte the serial
    result, over both the corpus and the clean tree."""
    serial = jaxlint.lint_paths([str(CORPUS)], keep_suppressed=True)
    parallel = jaxlint.lint_paths([str(CORPUS)], keep_suppressed=True, jobs=4)
    assert [f.__dict__ for f in serial] == [f.__dict__ for f in parallel]
    assert serial  # non-vacuous: the corpus does produce findings
    assert jaxlint.lint_paths(CLEAN_TARGETS, jobs=4) == []


def test_jobs_flag_cli_contract(capsys):
    """--jobs through the real arg parser: rc semantics unchanged at
    any N, and a non-positive N is a usage error (rc 2)."""
    rc = jaxlint.main(["--jobs=4", str(CORPUS / "bad_use_after_donate.py")])
    assert rc == 1
    assert "use-after-donate" in capsys.readouterr().out
    assert jaxlint.main(["--jobs=4"] + CLEAN_TARGETS) == 0
    assert jaxlint.main(["--jobs=0", str(CORPUS)]) == 2
    assert "jobs" in capsys.readouterr().err


# --- v6 satellites: parse memoization + --gate one-shot CI mode -----------


def test_parse_memo_cold_vs_warm_bit_identical():
    """The parse memo is a wall-clock knob ONLY: a cold run (cache
    cleared) and a warm run over the corpus return byte-for-byte
    identical findings, suppressed ones included."""
    jaxlint.clear_parse_cache()
    cold = jaxlint.lint_paths([str(CORPUS)], keep_suppressed=True)
    warm = jaxlint.lint_paths([str(CORPUS)], keep_suppressed=True)
    assert cold  # non-vacuous: the corpus does produce findings
    assert [f.__dict__ for f in cold] == [f.__dict__ for f in warm]


def test_parse_memo_warm_run_skips_reparse(monkeypatch):
    """A warm run performs ZERO ast.parse calls (the memo serves the
    tree + comment tables) — kills a memo that silently became a
    no-op. The single jaxlint call site is the only parse in the
    analysis package, so counting it is exact."""
    jaxlint.clear_parse_cache()
    target = str(CORPUS / "bad_timing.py")
    jaxlint.lint_paths([target])
    parses = []
    real_parse = jaxlint.ast.parse

    def counting_parse(*args, **kwargs):
        parses.append(args)
        return real_parse(*args, **kwargs)

    monkeypatch.setattr(jaxlint.ast, "parse", counting_parse)
    warm = jaxlint.lint_paths([target])
    assert parses == [], "warm lint re-parsed a memoized file"
    assert {f.rule for f in warm} == {"timing-without-block"}


def test_parse_memo_does_not_serve_stale_trees(tmp_path):
    """Content-keyed, not path/mtime-keyed: rewriting a file between
    runs must yield the NEW file's findings — a stale hit here would
    silently pass a dirty tree."""
    target = tmp_path / "evolving.py"
    target.write_text((CORPUS / "bad_timing.py").read_text())
    assert {f.rule for f in jaxlint.lint_paths([str(target)])} == {
        "timing-without-block"
    }
    target.write_text("x = 1\n")
    assert jaxlint.lint_paths([str(target)]) == []


def test_gate_one_shot_writes_sarif_next_to_rc(tmp_path, monkeypatch, capsys):
    """`--gate` is the one-command CI mode: full registry over the
    default targets, rc semantics unchanged (clean tree -> 0), and a
    SARIF 2.1.0 document written to ./jaxlint.sarif for annotation
    tooling. Suppressed findings appear in the document carrying
    inSource suppression objects, never in the exit code."""
    monkeypatch.chdir(tmp_path)
    rc = jaxlint.main(["--gate"])
    assert rc == 0
    captured = capsys.readouterr()
    assert "jaxlint.sarif" in captured.err
    doc = json.loads((tmp_path / "jaxlint.sarif").read_text())
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    # rc was 0, so anything in the document must be suppressed-only.
    assert all(
        res.get("suppressions") == [{"kind": "inSource"}] for res in results
    )


def test_gate_rejects_conflicting_configuration(capsys):
    """--gate IS the fixed configuration: combining it with explicit
    paths, --rules/--disable, or --baseline is a usage error (rc 2)."""
    for extra in (
        [str(CORPUS)],
        ["--rules=mutable-closure"],
        ["--disable=mutable-closure"],
        ["--baseline=b.json"],
    ):
        assert jaxlint.main(["--gate"] + extra) == 2
        assert "--gate" in capsys.readouterr().err
