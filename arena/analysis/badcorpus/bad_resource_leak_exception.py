"""jaxlint corpus: acquired resource with no release on any path.

`StagedBuffer` declares the `stage->release` protocol on its class
header; `pack_and_send` stages a slot and then hands the batch to the
wire — a call that can raise — without EVER releasing. Both the normal
exit and every exceptional exit leak the slot, and with the in-flight
marker set nothing downstream can retire it: the next stage() of this
bucket stalls forever. Rule: resource-leaked-on-exception."""


class StagedBuffer:  # protocol: stage->release
    """Double-buffered staging slots, PR 4 shape: stage marks a slot
    in flight, release() retires the oldest."""

    def __init__(self):
        self._in_flight = 0

    def stage(self, batch):
        self._in_flight += 1
        return batch

    def release(self):
        self._in_flight -= 1


def pack_and_send(batch, wire):
    buf = StagedBuffer()
    buf.stage(batch)
    wire.send(batch)  # can raise — and nobody ever releases the slot
