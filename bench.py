"""Benchmark entrypoint for the driver.

The reference repository `mark1222/arena` is empty (zero files — see
SURVEY.md and NON_GRAFTABLE.md for the verification evidence), so there is
no workload to benchmark and no baseline to compare against
(BASELINE.json: "N/A — no runnable entrypoint to benchmark").

This script exists so the driver's mandatory bench step records the true
state in machine-readable form instead of crashing on a missing file. It
deliberately reports no performance number: any number here would be
fabricated. The reported value is the *observed* count of entries (files,
directories, symlinks) under the reference mount, so a future re-mount of
a non-empty reference shows up here instead of being masked by a
hardcoded zero.

Distinct metrics for distinct failure modes (each still exactly one JSON
line on stdout, exit code 0 — the driver contract):

- ``non_graftable_reference_is_empty`` — mount present and readable;
  value is the observed entry count (0 today; >0 would mean the
  reference changed and SURVEY.md is obsolete).
- ``reference_mount_missing_or_unreadable`` — mount absent, not a
  directory, or not traversable; value -1.
- ``reference_scan_error`` — the mount passed the initial checks but the
  recursive walk raised OSError partway through (stale mount, entry
  vanishing mid-iteration, unreadable subtree); value -1.

The reference path can be overridden with the GRAFT_REFERENCE_PATH
environment variable so tests can exercise every branch against temp
directories without touching the real mount.
"""

import json
import os
import pathlib
import sys

DEFAULT_REFERENCE = "/root/reference"


def _count_entries(reference: pathlib.Path) -> int:
    """Recursive entry count with I/O errors OBSERVABLE, not swallowed.

    pathlib's glob machinery suppresses scan errors (PermissionError on
    3.12, all OSErrors on 3.13+), which would silently undercount a
    mount that goes stale or has an unreadable subtree — reporting a
    half-scanned tree as authoritative. os.walk with onerror re-raising
    makes every scandir failure propagate to the caller instead.
    """

    def _raise(err):
        raise err

    count = 0
    for _dirpath, dirnames, filenames in os.walk(reference, onerror=_raise):
        count += len(dirnames) + len(filenames)
    return count


def scan(reference: pathlib.Path) -> dict:
    """Return the bench result dict for the given reference mount."""
    try:
        accessible = reference.is_dir() and os.access(reference, os.R_OK | os.X_OK)
    except OSError:
        accessible = False
    if not accessible:
        return {
            "metric": "reference_mount_missing_or_unreadable",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
        }
    try:
        count = _count_entries(reference)
    except OSError:
        return {
            "metric": "reference_scan_error",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
        }
    return {
        "metric": "non_graftable_reference_is_empty",
        "value": count,
        "unit": "reference_entries",
        "vs_baseline": None,
    }


def main() -> int:
    reference = pathlib.Path(os.environ.get("GRAFT_REFERENCE_PATH", DEFAULT_REFERENCE))
    print(json.dumps(scan(reference)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
