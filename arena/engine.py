"""Arena rating engine: ingestion, shape-bucketed batching, jitted updates.

The host-side half of the hot path. Three jobs:

1. **Ingest** (`pack_batch` / `pack_epoch`): turn raw match outcome
   arrays into the device-resident layout the scatter-free update
   needs — a per-batch permutation grouping the concatenated
   [winners, losers] indices by player, plus segment boundaries. This
   is a cheap O(B) NumPy counting sort per batch, computed ONCE per
   ingested batch; every Elo epoch and every Bradley–Terry iteration
   over that batch then runs with zero XLA scatters (the CPU scatter
   is the single most expensive op in the naive-jit formulation — see
   `arena/ratings.py`).

2. **Shape-bucketed batching** (`bucket_size`): arena traffic arrives
   in variable-size batches; jitting on raw sizes would recompile per
   distinct size. Batches are padded up to the next power-of-two
   bucket (masked with `valid`), so the jit cache holds one executable
   per BUCKET, not per size — `test_arena_engine.py` asserts zero
   recompiles across varying sizes via the jit cache stats.

3. **`ArenaEngine`**: the stateful online wrapper — holds the ratings
   vector, feeds batches through a single jitted update with the
   ratings buffer donated (XLA reuses the old buffer for the new
   ratings instead of allocating), and exposes leaderboard reads and
   batched Bradley–Terry fits over everything ingested so far. Since
   PR 3 it also fronts the INCREMENTAL path (`arena/ingest.py`):
   `ingest()` packs through reusable double-buffered staging slots and
   merges the whole-set grouping incrementally, and
   `refit_incremental()` runs the chunked Bradley–Terry fit over that
   grouping — no repack-the-world, peak bucket one chunk. Since PR 4
   the OVERLAPPED path (`arena/pipeline.py`) rides the same slots:
   `ingest_async()` hands batches to a background packer thread and
   `flush()` drains them, bit-exact to `ingest()`; sync calls
   interleaved with async ones drain the pipeline first, so program
   order is preserved no matter how the two are mixed.
"""

import operator
import threading
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from arena import ratings as R
from arena.obs import NULL as NULL_OBS

# Floor keeps tiny batches from generating one bucket per power of two
# at the small end where padding is nearly free anyway.
MIN_BUCKET = 256


def bucket_size(n, min_bucket=MIN_BUCKET):
    """Smallest power-of-two >= n (>= min_bucket). Static per jit cache
    entry: all batch sizes in (bucket/2, bucket] share one executable."""
    if n < 0:
        raise ValueError(f"batch size must be >= 0, got {n}")
    b = min_bucket
    while b < n:
        b *= 2
    return b


class PackedBatch(NamedTuple):
    """Device-resident, bucket-padded match batch plus its grouping.

    winners/losers/valid: (bucket,) — padded slots have valid == 0 and
    index 0 (their delta is masked to zero, so the index never matters).
    perm: (2*bucket,) permutation sorting concat([winners, losers]) by
    player; bounds: (num_players+1,) segment start offsets in that
    order. num_real is the unpadded match count (host int).
    """

    winners: jax.Array
    losers: jax.Array
    valid: jax.Array
    perm: jax.Array
    bounds: jax.Array
    num_real: int


def _validate_matches(num_players, winners, losers):
    """Reject malformed outcome arrays BEFORE they reach the packed
    layout. An out-of-range id would not crash downstream — the
    counting-sort grouping and the masked scatter would silently fold
    the bogus update into padded slots or neighboring players — so the
    only honest failure point is ingest."""
    if winners.shape != losers.shape or winners.ndim != 1:
        raise ValueError("winners/losers must be 1-D arrays of equal length")
    if winners.size:
        lo = int(min(winners.min(), losers.min()))
        hi = int(max(winners.max(), losers.max()))
        if lo < 0 or hi >= num_players:
            raise ValueError(
                f"player ids must be in [0, {num_players}); got range "
                f"[{lo}, {hi}]"
            )


def _validate_tenant(num_tenants, tenant):
    """Wire-input sanitizer for the tenant key — the tenancy analogue
    of `_validate_matches`. An unknown tenant must be a reject at
    admission: past this point the id becomes a composite-space offset,
    and an out-of-range tenant would silently fold its matches into a
    neighboring tenant's leaderboard."""
    try:
        t = operator.index(tenant)  # ints and np ints; no floats/strings
    except TypeError:
        raise ValueError(
            f"tenant must be an integer, got {tenant!r}"
        ) from None
    if isinstance(tenant, bool):
        raise ValueError(f"tenant must be an integer, got {tenant!r}")
    if not 0 <= t < num_tenants:
        raise ValueError(
            f"unknown tenant {t}: this arena serves tenants "
            f"[0, {num_tenants})"
        )
    return t


def _group_by_player(combined, num_players):
    """Counting-sort grouping of a combined index array (host NumPy)."""
    order = np.argsort(combined, kind="stable").astype(np.int32)
    bounds = np.searchsorted(
        combined[order], np.arange(num_players + 1), side="left"
    ).astype(np.int32)
    return order, bounds


def pack_batch(num_players, winners, losers, min_bucket=MIN_BUCKET, dtype=np.float32,
               tenant=0, players_per_tenant=None):
    """Pad one match batch to its bucket and precompute its grouping.

    `tenant=`/`players_per_tenant=` pack a tenant-local batch into the
    composite id space (`tenant * players_per_tenant + player`) — the
    grouping then keys on composite ids, so tenant is the leading sort
    key for free (composite ids sort tenant-major). `num_players` is
    always the COMPOSITE bound."""
    winners = np.asarray(winners, dtype=np.int32)
    losers = np.asarray(losers, dtype=np.int32)
    if tenant:
        if players_per_tenant is None:
            raise ValueError("tenant != 0 requires players_per_tenant")
        _validate_matches(players_per_tenant, winners, losers)
        off = np.int32(int(tenant) * int(players_per_tenant))
        winners = winners + off
        losers = losers + off
    _validate_matches(num_players, winners, losers)
    n = winners.shape[0]
    b = bucket_size(n, min_bucket)
    pad = b - n
    w = np.concatenate([winners, np.zeros(pad, np.int32)])
    l = np.concatenate([losers, np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(n, dtype), np.zeros(pad, dtype)])
    perm, bounds = _group_by_player(np.concatenate([w, l]), num_players)
    return PackedBatch(
        jnp.asarray(w), jnp.asarray(l), jnp.asarray(valid),
        jnp.asarray(perm), jnp.asarray(bounds), n,
    )


class PackedEpoch(NamedTuple):
    """All batches of a match set, stacked for `ratings.elo_epoch`'s scan."""

    winners: jax.Array  # (num_batches, B)
    losers: jax.Array  # (num_batches, B)
    valid: jax.Array  # (num_batches, B)
    perms: jax.Array  # (num_batches, 2B)
    bounds: jax.Array  # (num_batches, num_players+1)
    num_real: int


def _pow2_ceil(n):
    """Smallest power of two >= n (n >= 1)."""
    p = 1
    while p < n:
        p *= 2
    return p


def pack_epoch(num_players, winners, losers, batch_size, dtype=np.float32,
               pad_batches_pow2=False, min_batches=None):
    """Split a match set into fixed-size batches and pack each one.

    The last batch is padded to `batch_size` (the scan needs one fixed
    shape). Grouping cost is one counting sort per batch — amortized
    over every epoch/iteration run against the result.

    `pad_batches_pow2=True` additionally pads the NUMBER of batches up
    to a power of two (floored at `min_batches` when given) with fully
    invalid batches — all-zero indices, valid == 0, so every padded
    batch is a rating no-op. This is the epoch-level twin of the pow2
    bucket contract: a jitted epoch consumer (the bootstrap resampler)
    then sees O(log N) distinct shapes as history grows instead of one
    per batch count — the `refresh_intervals` recompile source ROADMAP
    item 5 names. Callers that want a longer compile-free horizon pass
    `min_batches` = the padded count of the largest epoch they plan to
    serve (the soak bench pins its whole run to one executable this
    way).
    """
    winners = np.asarray(winners, dtype=np.int32)
    losers = np.asarray(losers, dtype=np.int32)
    _validate_matches(num_players, winners, losers)
    n = winners.shape[0]
    if n == 0:
        raise ValueError("cannot pack an empty match set")
    nb = -(-n // batch_size)
    if pad_batches_pow2:
        nb = _pow2_ceil(max(nb, min_batches or 1))
    pad = nb * batch_size - n
    w = np.concatenate([winners, np.zeros(pad, np.int32)]).reshape(nb, batch_size)
    l = np.concatenate([losers, np.zeros(pad, np.int32)]).reshape(nb, batch_size)
    valid = np.concatenate([np.ones(n, dtype), np.zeros(pad, dtype)]).reshape(
        nb, batch_size
    )
    perms = np.empty((nb, 2 * batch_size), np.int32)
    bounds = np.empty((nb, num_players + 1), np.int32)
    for i in range(nb):
        perms[i], bounds[i] = _group_by_player(
            np.concatenate([w[i], l[i]]), num_players
        )
    return PackedEpoch(
        jnp.asarray(w), jnp.asarray(l), jnp.asarray(valid),
        jnp.asarray(perms), jnp.asarray(bounds), n,
    )


class ArenaEngine:  # protocol: shutdown
    """Online Elo over a fixed player set, with batched Bradley–Terry.

    One jitted update function serves every batch: its input shapes are
    (bucket,) so the compile cache grows with the number of DISTINCT
    BUCKETS touched, never with the number of distinct batch sizes
    (`num_compiles()` exposes the cache size; tests pin it). The
    ratings buffer is donated on every call — the old buffer is dead
    the moment the update is dispatched, and XLA reuses it in place.
    """

    # Single-tenant by default: tenant 0 is the whole arena. The
    # multi-tenant subclass (arena.tenancy.MultiTenantEngine) widens
    # these and re-routes the update through the fused per-tenant
    # kernel; the shared ingest SIGNATURE carries `tenant=` everywhere
    # so the front door / wire never special-case the engine flavor.
    num_tenants = 1

    def __init__(
        self,
        num_players,
        k=R.DEFAULT_K,
        scale=R.DEFAULT_SCALE,
        base=R.DEFAULT_BASE,
        min_bucket=MIN_BUCKET,
        dtype=jnp.float32,
        obs=None,
    ):
        if num_players < 2:
            raise ValueError("an arena needs at least two players")
        self.num_players = num_players
        # Per-tenant roster size == the whole roster when single-tenant
        # (the multi-tenant subclass narrows it to its per-tenant P).
        self.players_per_tenant = num_players
        self.k = k
        self.scale = scale
        self.base = base
        self.min_bucket = min_bucket
        self._dtype = dtype
        # Observability (arena.obs.Observability). Defaults to the
        # shared no-op instance: an engine nobody asked to measure
        # pays constant-time null calls, records nothing, allocates
        # nothing — and the bench hard-gates that even the LIVE
        # registry stays under 3% on the ingest/pipeline paths.
        self.obs = obs if obs is not None else NULL_OBS
        self.ratings = jnp.full((num_players,), base, dtype)
        # ONE match store serves every path: update() and ingest()
        # both feed the mergeable CSR, so Bradley–Terry refits (single
        # -bucket bt_strengths or chunked refit_incremental) always see
        # the full history regardless of which ingest path ran.
        # Imported lazily: arena.ingest imports this module's
        # primitives at its own top level.
        from arena import ingest as ingest_mod

        self._ingest_mod = ingest_mod
        self._store = ingest_mod.MergeableCSR(num_players, obs=self.obs)
        self._staging = None  # built on first ingest()
        self._pipeline = None  # built on first ingest_async()
        # Matches whose rating update has been DISPATCHED — the serving
        # watermark. Lags matches_ingested by whatever the async
        # pipeline still holds. The lock makes (ratings, watermark)
        # an atomic pair: the serving layer copies both under it, so a
        # view can never mix one batch's ratings with another's count —
        # and, because the update DONATES the old ratings buffer, the
        # copy must not race the dispatch that consumes it.
        self._view_lock = threading.Lock()
        self.matches_applied = 0
        self._update = jax.jit(
            partial(R.elo_batch_update_sorted, k=k, scale=scale),
            donate_argnums=(0,),
        )
        # The bootstrap resampler is jitted ONCE per engine (k/scale
        # are fixed at construction). A fresh jax.jit wrapper per
        # refresh — the old shape of this code — re-traced and
        # re-COMPILED on every interval refresh no matter how carefully
        # the epoch shapes were padded; one cached wrapper plus the
        # pow2-padded epoch layout is what makes interval refreshes
        # compile-free in steady state (ROADMAP item 5, soak-gated).
        self._bootstrap_fn = R.jit_elo_bootstrap(k=k, scale=scale)

    def set_obs(self, obs):
        """Re-point the engine (and its store/staging) at a new
        observability handle — how `ArenaServer` upgrades a default
        null-instrumented engine to its live registry. The pipeline
        reads `engine.obs` per event, so it needs no rewiring."""
        self.obs = obs
        self._store._obs = obs
        if self._staging is not None:
            self._staging._obs = obs

    @property
    def matches_ingested(self):
        return self._store.num_matches

    def _apply(self, packed):
        with self.obs.span("engine.jit_dispatch"), self._view_lock:
            self.ratings = self._update(
                self.ratings,
                packed.winners,
                packed.losers,
                packed.valid.astype(self._dtype),
                packed.perm,
                packed.bounds,
            )
            self.matches_applied += packed.num_real
        return self.ratings

    def ratings_snapshot(self):
        """Atomic `(ratings copy, applied-match watermark)` pair — the
        raw material of a serving view. The copy is explicit
        (`np.array(copy=True)`): `np.asarray` of a CPU jax array can
        alias the device buffer, and the very next `_apply` DONATES
        that buffer — an aliased view would be read-after-donate."""
        with self._view_lock:
            return np.array(self.ratings, copy=True), self.matches_applied

    def adopt_state(self, ratings, store):  # deterministic; mutates: ratings, _store, matches_applied
        """Install restored state (the serving layer's snapshot hook):
        ratings vector + match store, replacing the fresh-engine
        empties. Refuses on an engine that has already ingested —
        restore-into-live must go through `ArenaServer.restore`, which
        builds a fresh engine and swaps it in whole."""
        if self._store.num_matches or self.matches_applied:
            raise RuntimeError(
                "adopt_state requires a fresh engine; this one has "
                f"{self._store.num_matches} matches ingested"
            )
        r = np.asarray(ratings, np.float32)
        if store.num_players != self.num_players or r.shape != (self.num_players,):
            raise ValueError(
                f"restored state is for {store.num_players} players / "
                f"ratings shape {r.shape}; engine has {self.num_players}"
            )
        with self._view_lock:
            self.ratings = jnp.asarray(r)
            self._store = store
            self.matches_applied = store.num_matches
        return self.ratings

    def update(self, winners, losers, tenant=None):  # deterministic; mutates: _store, ratings, matches_applied
        """Ingest one batch of outcomes and apply one batched Elo round."""
        if tenant is not None:
            _validate_tenant(self.num_tenants, tenant)
        self._drain_pipeline()
        # Root span: this batch's trace id — every nested stage span
        # (store add, jit dispatch) parents under it (arena.obs.context).
        with self.obs.span("batch.update"):
            packed = pack_batch(
                self.num_players, winners, losers, self.min_bucket, np.float32
            )
            self._store.add(winners, losers)
            return self._apply(packed)

    def _ensure_staging(self):
        if self._staging is None:
            self._staging = self._ingest_mod.StagingBuffers(
                self.num_players, self.min_bucket, np.float32, obs=self.obs
            )
        return self._staging

    def _drain_pipeline(self):
        """Barrier: finish all pending async work first, so sync calls
        interleaved with `ingest_async` keep their program order."""
        if self._pipeline is not None:
            self._pipeline.flush()

    def _dispatch_packed(self, packed):
        """Apply one staged batch and retire its staging slot — the
        dispatch half of the pipeline, and the same pairing the sync
        path uses, so slot lifetime is identical on both."""
        with self.obs.span("engine.apply"):
            try:
                return self._apply(packed)
            finally:
                self._staging.release()

    def ingest(self, winners, losers, tenant=None):  # deterministic; mutates: _store, _staging, ratings, matches_applied
        """`update` on the incremental path: the batch is packed
        through reusable double-buffered staging slots (zero host
        allocations and zero new jit compiles in steady state) and
        merged into the incrementally-maintained whole-set grouping
        (O(d log d) delta sort + deferred galloping merge) instead of
        being re-grouped from scratch at the next refit. Identical
        rating semantics to `update` — same jitted function, same
        packed layout — pinned by tests."""
        if tenant is not None:
            _validate_tenant(self.num_tenants, tenant)
        self._drain_pipeline()
        w = np.asarray(winners, np.int32)
        l = np.asarray(losers, np.int32)
        _validate_matches(self.num_players, w, l)
        # Root span: the sync-path batch trace (csr merge, staging,
        # dispatch, apply all nest under it on this thread).
        with self.obs.span("batch.ingest"):
            self._ensure_staging()
            self._store.add(w, l)
            if w.shape[0] == 0:
                return self.ratings  # nothing to dispatch
            return self._dispatch_packed(self._staging.stage(w, l))

    # --- the overlapped (async) ingest path --------------------------

    def _pack_for_pipeline(self, w, l):  # deterministic; mutates: _store, _staging
        """Packer-thread half of one async batch: merge into the store,
        fill the next staging slot. Returns None for an empty batch
        (nothing to dispatch). block=True: if both slots of the bucket
        are in-flight, wait for the dispatching thread to release one
        — that wait IS the fill/dispatch overlap window."""
        self._ensure_staging()
        self._store.add(w, l)
        if w.shape[0] == 0:
            return None
        return self._staging.stage(w, l, block=True)

    def start_pipeline(self, capacity=None, policy=None, producer=None):
        """Explicitly start the overlapped-ingest pipeline (to pick a
        queue capacity/backpressure policy, or a `producer` metric
        label for a multi-producer front door); `ingest_async` starts
        one with defaults on first use otherwise."""
        from arena import pipeline as pipeline_mod

        if self._pipeline is not None:
            raise RuntimeError(
                "pipeline already running; shutdown() it before starting "
                "another"
            )
        kwargs = {}
        if capacity is not None:
            kwargs["capacity"] = capacity
        if policy is not None:
            kwargs["policy"] = policy
        if producer is not None:
            kwargs["producer"] = producer
        self._pipeline = pipeline_mod.IngestPipeline(self, **kwargs)
        return self._pipeline

    def ingest_async(self, winners, losers, producer=None, tenant=None):
        """`ingest` through the overlapped pipeline: the batch is
        validated HERE (a malformed batch raises at the call site, no
        state change) and handed to the background packer thread;
        the rating update is dispatched by later `ingest_async`/
        `flush()` calls on the calling thread. Rating semantics are
        bit-exact `ingest()` — same slots, same jitted update, same
        order — the async-ness only moves the host packing off the
        caller's critical path. `producer` labels THIS batch's submit
        metrics (the multi-producer front door passes each batch's
        original producer through). Returns the number of batches
        still pending (0 means everything submitted so far has
        applied)."""
        if tenant is not None:
            _validate_tenant(self.num_tenants, tenant)
        w = np.asarray(winners, np.int32)
        l = np.asarray(losers, np.int32)
        _validate_matches(self.num_players, w, l)
        if self._pipeline is None:
            self.start_pipeline()
        # Root span: the async batch's trace id. submit() captures the
        # context inside this span and ships it with the queue item, so
        # the packer's pack/merge spans and the eventual dispatch spans
        # — on whatever threads they run — parent back to THIS root.
        with self.obs.span("batch.submit"):
            self._pipeline.submit(w, l, producer=producer)
        return self._pipeline.pending()

    def flush(self):
        """Drain the async pipeline (if any) and block until the
        ratings buffer is ready. The ratings returned reflect every
        `ingest_async` batch submitted before the flush."""
        self._drain_pipeline()
        jax.block_until_ready(self.ratings)
        return self.ratings

    def shutdown(self, drain=True, spill=False):
        """Stop the pipeline thread. drain=True (default) applies
        everything still queued; drain=False drops raw batches (see
        `IngestPipeline.close`). spill=True instead RETURNS the
        still-raw queued batches as `(winners, losers)` pairs (FIFO,
        not counted dropped) for a durable snapshot to persist — the
        caller owns resubmitting them. Safe to call with no pipeline;
        after shutdown, `ingest_async` starts a fresh pipeline lazily.
        Returns the ratings normally, the spilled batch list when
        spill=True."""
        spilled = []
        if self._pipeline is not None:
            try:
                spilled = self._pipeline.close(drain=drain, spill=spill)
            finally:
                self._pipeline = None
        return spilled if spill else self.ratings

    def refit_incremental(self, num_iters=50, prior=0.1, chunk_entries=None):
        """Chunked Bradley–Terry refit over the incremental grouping.

        Reuses the mergeable CSR (at most one tail merge, never a
        re-pack of the world) and chunks the MM segment sums over the
        epoch layout — the largest allocated bucket is one chunk, not
        the single pow2 pad of the whole match set (`bt_strengths`'s
        layout). Same model, same fixed point as `bt_strengths`;
        equivalence is property-tested.
        """
        self._drain_pipeline()
        if self._store.num_matches == 0:
            raise ValueError("no matches ingested")
        if chunk_entries is None:
            chunk_entries = self._ingest_mod.DEFAULT_CHUNK_ENTRIES
        perm, bounds = self._store.grouping()
        perms, chunk_bounds = self._ingest_mod.chunk_layout(
            perm, bounds, chunk_entries
        )
        w = self._store.winners()
        win_counts = jnp.asarray(
            np.bincount(w, minlength=self.num_players).astype(np.float32)
        )
        fit = R.jit_bt_fit_chunked(self.num_players, num_iters=num_iters, prior=prior)
        return fit(
            jnp.asarray(w),
            jnp.asarray(self._store.losers()),
            jnp.asarray(perms),
            jnp.asarray(chunk_bounds),
            win_counts,
        )

    def bootstrap_ratings(self, num_rounds=32, seed=0, batch_size=8192,
                          min_batches=None):
        """Bootstrap rating samples: `num_rounds` Poisson-resampled
        epochs over the full ingested history, vmapped over a seeded
        key array (`ratings.elo_bootstrap`). Each round replays the
        whole match set from the base rating with per-match Poisson(1)
        weights — the weight multiplies the same `valid` mask the
        padded slots use, so resampling rides the precomputed grouping
        with zero re-sorts. Deterministic under a fixed seed. Returns
        a (num_rounds, num_players) ndarray of rating samples; the
        serving layer turns them into (lo, hi) intervals.

        Epoch batch boundaries here are `batch_size` re-splits of the
        history, not the original ingest boundaries — the bootstrap
        measures resampling uncertainty, not a bit-exact replay (the
        crash-restart property owns that). The batch COUNT is padded
        to a power of two (fully-invalid no-op batches) and the
        resampler jit is cached per engine, so refreshing intervals as
        history grows compiles O(log N) times total, not once per
        refresh — `min_batches` extends the padding to a planned
        horizon for a strictly compile-free window (the soak bench's
        zero-recompile gate rides this)."""
        self._drain_pipeline()
        if self._store.num_matches == 0:
            raise ValueError("no matches ingested")
        if num_rounds < 1:
            raise ValueError(f"num_rounds must be >= 1, got {num_rounds}")
        packed = pack_epoch(
            self.num_players,
            self._store.winners(),
            self._store.losers(),
            batch_size,
            pad_batches_pow2=True,
            min_batches=min_batches,
        )
        keys = jax.random.split(jax.random.PRNGKey(seed), num_rounds)
        samples = self._bootstrap_fn(
            jnp.full((self.num_players,), self.base, self._dtype),
            packed.winners,
            packed.losers,
            packed.valid,
            packed.perms,
            packed.bounds,
            keys,
        )
        return np.asarray(samples)

    def num_compiles(self):
        """Jit-cache size of the update fn — the recompile budget the
        bucketing exists to cap (one entry per bucket ever touched)."""
        return self._update._cache_size()

    def num_bootstrap_compiles(self):
        """Jit-cache size of the cached bootstrap resampler — with the
        pow2-padded epoch layout this grows O(log history), and stays
        FLAT across interval refreshes within a padded horizon (the
        serving sentinel and the soak bench watch it)."""
        return self._bootstrap_fn._cache_size()

    def leaderboard(self, top_k=None):
        """(player_id, rating) pairs, best first (async work drained)."""
        self._drain_pipeline()
        r = np.asarray(self.ratings)
        order = np.argsort(-r)
        if top_k is not None:
            order = order[:top_k]
        return [(int(i), float(r[i])) for i in order]

    def bt_strengths(self, num_iters=50, prior=0.1, batch_size=None):
        """Batched Bradley–Terry MLE over every match ingested so far.

        Independent of the online Elo state — a from-scratch MLE refit,
        the standard periodic companion to online ratings. Runs as one
        fused scan over `num_iters` MM steps (see `ratings.bt_fit`).
        """
        self._drain_pipeline()
        if self._store.num_matches == 0:
            raise ValueError("no matches ingested")
        w = self._store.winners()
        l = self._store.losers()
        b = bucket_size(len(w), self.min_bucket) if batch_size is None else batch_size
        # One whole-set "batch": BT iterates over the full match set.
        packed = pack_batch(self.num_players, w, l, b)
        win_counts = jnp.asarray(
            np.bincount(w, minlength=self.num_players).astype(np.float32)
        )
        fit = R.jit_bt_fit(self.num_players, num_iters=num_iters, prior=prior)
        return fit(
            packed.winners,
            packed.losers,
            packed.valid,
            packed.perm,
            packed.bounds,
            win_counts,
        )
