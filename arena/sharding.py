"""Device-sharded arena updates: mesh building, partition rules, shard_map.

Adapts the retrieved SNIPPETS.md patterns to this engine:

- `match_partition_rules` is the regex-rule -> `PartitionSpec` matcher
  (SNIPPETS [1]), reimplemented over `jax.tree_util` path flattening so
  it needs no external tree library. Library code stays decoupled from
  any particular model of the state tree: rules are ordered
  (first match wins), scalars are never partitioned.
- `shard_elo_batch_update` is the SNIPPETS [2]/[3] data-parallel
  pattern via `shard_map`: the match batch is sharded across the mesh's
  data axis, every device computes a full-size delta vector from its
  shard with a LOCAL `segment_sum` scatter (1/ndev of the scatter work,
  the op that dominates this update on CPU), and one `psum` combines
  them. Ratings stay replicated — they are O(players), tiny next to
  O(matches).

Positional `PartitionSpec` indices (SNIPPETS [2]) are not available in
the JAX pinned on this image (0.4.x); the mesh axis is addressed by
name, with the name kept in ONE constant so callers stay decoupled the
same way positional specs would allow.

Everything here runs on CPU meshes made with
`XLA_FLAGS=--xla_force_host_platform_device_count=N` (how the tests
exercise it — no TPU required). On this 1-core image that proves
correctness and the sharding mechanics, not wall-clock scaling; the
bench reports per-device-count numbers honestly rather than claiming a
speedup a single core cannot deliver.
"""

import re
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from arena import ratings as R

# The single mesh axis arena shards over: match batches are data.
DATA_AXIS = "data"


def build_mesh(num_devices=None, devices=None):
    """A 1-D device mesh over the data axis.

    Defaults to every visible device. CPU tests force multiple devices
    via XLA_FLAGS=--xla_force_host_platform_device_count=N (set before
    the backend initializes).
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:num_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def tree_path_names(tree, sep="/"):
    """Flatten a pytree into (path-string, leaf) pairs, '/'-joined —
    the name form the partition rules match against."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, leaf in flat:
        parts = []
        for entry in path:
            if hasattr(entry, "key"):
                parts.append(str(entry.key))
            elif hasattr(entry, "name"):
                parts.append(str(entry.name))
            elif hasattr(entry, "idx"):
                parts.append(str(entry.idx))
            else:
                parts.append(str(entry))
        names.append((sep.join(parts), leaf))
    return names


def match_partition_rules(rules, tree):
    """PartitionSpec pytree from ordered (regex, spec) rules.

    Scalars (0-d or single-element leaves) are never partitioned.
    Every other leaf must match a rule — an unmatched leaf is an error,
    not a silent replication, so a renamed state field cannot quietly
    lose its sharding.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    named = tree_path_names(tree)
    specs = []
    for (name, leaf), _ in zip(named, flat):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                specs.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matched leaf {name!r}")
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_elo_batch_update(
    mesh, ratings, winners, losers, valid=None, k=R.DEFAULT_K, scale=R.DEFAULT_SCALE
):
    """One batched Elo round, match-sharded across the mesh's data axis.

    Batch length must be divisible by the mesh's device count (bucket
    sizes are powers of two, so any pow2 device count divides them).
    Semantically identical to `ratings.elo_batch_update` — segment sums
    are associative, so sharding the matches and psumming the per-shard
    delta vectors is the same reduction in a different order
    (equivalence is pinned in tests).
    """
    ndev = mesh.devices.size
    if winners.shape[0] % ndev != 0:
        raise ValueError(
            f"batch of {winners.shape[0]} not divisible by {ndev} devices"
        )
    if valid is None:
        # ones_like, not ones(winners.shape): the mask mirrors an
        # argument that already crossed the boundary, so its shape is
        # the caller's bucketing contract — spelling it as a derived
        # size would read as a fresh raw-length shape (and jaxlint v3's
        # unbucketed-shape-at-jit-boundary flags exactly that).
        valid = jnp.ones_like(winners, dtype=ratings.dtype)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    )
    def sharded_delta(r, w, l, v):
        d = R.elo_deltas(r, w, l, v, k, scale)
        local = jax.ops.segment_sum(
            jnp.concatenate([d, -d]),
            jnp.concatenate([w, l]),
            num_segments=r.shape[0],
        )
        return jax.lax.psum(local, DATA_AXIS)

    return ratings + sharded_delta(ratings, winners, losers, valid)


def jit_sharded_elo_epoch(mesh, k=R.DEFAULT_K, scale=R.DEFAULT_SCALE):
    """Scan of sharded batch updates, compiled once per mesh.

    Stacked inputs as in `ratings.elo_epoch`; each scan step is one
    sharded round. Ratings are donated (replicated buffer reused).
    """

    def epoch(ratings, winners, losers, valid):
        def step(r, batch):
            w, l, v = batch
            return shard_elo_batch_update(mesh, r, w, l, v, k, scale), None

        ratings, _ = jax.lax.scan(step, ratings, (winners, losers, valid))
        return ratings

    return jax.jit(epoch, donate_argnums=(0,))


def place_replicated(mesh, tree):
    """Put a pytree on the mesh fully replicated (P() everywhere) —
    how the ratings state enters a sharded computation."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
