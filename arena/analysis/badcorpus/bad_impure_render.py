"""jaxlint corpus: a `# pure-render(view)` that reads hidden state.

`row` is declared a pure function of its parameters and the immutable
`view` — the precondition a `(page, watermark)`-keyed byte cache
needs. But it also reads `self._theme`: two renders at the same
watermark can differ, so a cached page silently serves the wrong
bytes after the theme changes. Rule: hidden-state-read-in-pure-render.
"""


class Leaderboard:
    def __init__(self):
        self._theme = "dark"

    def row(self, view, p):  # pure-render(view)
        return {
            "player": p,
            "rating": float(view.ratings[p]),
            "theme": self._theme,  # hidden: not part of the view
        }
