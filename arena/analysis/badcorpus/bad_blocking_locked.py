"""jaxlint corpus: blocking calls made while holding a lock.

Every other thread that needs `_lock` stalls for the full queue wait /
sleep — and `stop()` joins the worker WHILE holding the lock the
worker needs to finish, the classic self-deadlock. Rule:
blocking-while-locked."""

import queue
import threading
import time


class LockedConsumer:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        item = self._q.get(block=True)
        while item is not None:
            item = self._q.get(block=True)

    def consume_next(self):
        with self._lock:
            item = self._q.get(block=True)  # queue wait under the lock
            time.sleep(0.01)  # and a sleep on top
            return item

    def stop(self):
        with self._lock:
            self._q.put(None, block=True)
            self._thread.join()  # the worker may need _lock: deadlock
