"""jaxlint corpus: inconsistent lock nesting order.

`credit()` takes accounts-then-audit, `debit()` takes
audit-then-accounts: run concurrently, each can hold its first lock
while waiting forever for the other's. The lock-order graph (which
spans MODULES in a real project walk — both orders here happen to sit
in one file) makes the cycle a lint finding instead of a 3am incident.
Rule: lock-order-inversion."""

import threading


class Ledger:
    def __init__(self):
        self._accounts = threading.Lock()
        self._audit = threading.Lock()
        self.balance = 0
        self.entries = 0

    def credit(self, n):
        with self._accounts:
            with self._audit:
                self.balance += n
                self.entries += 1

    def debit(self, n):
        with self._audit:
            with self._accounts:
                self.balance -= n
                self.entries += 1
