"""jaxlint corpus: request bytes mutating engine state unvalidated.

The wire tier's contract (arena/net/protocol.py) is that every submit
body passes `parse_submit_body` — JSON shape, integer lists, producer
string — before anything touches the engine, and the engine's own
`_validate_matches`/`pack_batch` bounds checks reject out-of-range
ids at admission. This handler skips all of it: bytes off the socket
(`self.rfile`) go through `json.loads` straight into `engine.update`,
so a malformed or hostile body reaches the mutation path with no
validator on any path. Rule: unvalidated-wire-input."""

import json
from http.server import BaseHTTPRequestHandler


class RawIngestHandler(BaseHTTPRequestHandler):
    """POST /submit, minus every check the front door exists for."""

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length)
        doc = json.loads(raw)
        engine = self.server.engine
        engine.update(doc["winners"], doc["losers"])
        self.send_response(202)
        self.end_headers()
