"""jaxlint corpus: a raw-input-length shape crossing the jit boundary.

`len(matches)` / `weights.shape[0]` vary with every ingested batch;
an array born with that size and handed to a jitted kernel compiles a
NEW executable per distinct size — the exact recompile class the pow2
bucket contract (engine.bucket_size / pack_batch / pack_epoch /
chunk_layout) exists to cap, and the one the soak gate's
`recompile_events == 0` would only catch after the fact at runtime.
Rule: unbucketed-shape-at-jit-boundary."""

import jax
import jax.numpy as jnp
import numpy as np

score = jax.jit(lambda x: x.sum())


def ingest(matches):
    """Every batch size mints a fresh executable: `deltas` is shaped
    by the raw match count, never routed through a bucketing op."""
    n = len(matches)
    deltas = np.zeros(n, np.float32)
    return score(jnp.asarray(deltas))


def rescale(weights):
    """Same hazard spelled through `.shape[0]` off an ingest array."""
    padded = np.zeros(weights.shape[0], np.float32)
    return score(jnp.asarray(padded))
