"""Benchmark entrypoint for the driver.

The reference repository `mark1222/arena` is empty (zero files — see
SURVEY.md and NON_GRAFTABLE.md for the verification evidence), so there is
no workload to benchmark and no baseline to compare against
(BASELINE.json: "N/A — no runnable entrypoint to benchmark").

This script exists so the driver's mandatory bench step records the true
state in machine-readable form instead of crashing on a missing file. It
deliberately reports no performance number: any number here would be
fabricated. The reported value is the *observed* count of entries (files,
directories, symlinks) under the reference mount, so a future re-mount of
a non-empty reference shows up here instead of being masked by a
hardcoded zero. A missing or unreadable mount is reported as a distinct
metric rather than as value 0.
"""

import json
import os
import pathlib

REFERENCE = pathlib.Path("/root/reference")

if REFERENCE.is_dir() and os.access(REFERENCE, os.R_OK | os.X_OK):
    result = {
        "metric": "non_graftable_reference_is_empty",
        "value": sum(1 for _ in REFERENCE.rglob("*")),
        "unit": "reference_entries",
        "vs_baseline": None,
    }
else:
    result = {
        "metric": "reference_mount_missing_or_unreadable",
        "value": -1,
        "unit": "reference_entries",
        "vs_baseline": None,
    }

print(json.dumps(result))
