"""Continuous sampling profiler: wall-clock attribution by thread role.

The third leg of the live ops plane: `windows.py` says WHAT is slow
right now, `slo.py` says WHETHER it matters, and this module says
WHERE the time goes. A `SamplingProfiler` thread walks
`sys._current_frames()` at a configurable hz (default ~67 — a prime
period so the sampler does not phase-lock with 10ms/100ms work loops)
and folds each thread's stack into a `(role, "f1;f2;...")` counter —
the collapsed-stack format flamegraph tooling eats directly.

**Roles, not thread ids.** The system already names its long-lived
threads (`arena-ingest-packer`, `arena-frontdoor-merge`,
`arena-wire-server`, the stdlib's per-request HTTP workers); samples
aggregate under those stable role names so "the packer spends 40% of
its wall clock in `_pack_batch`" survives thread restarts and reads
the same across runs. Frame keys drop line numbers
(`file.py:function`) so one hot function is one row, not fifty.

**Overhead is bounded by construction**: sampling cost is per-SAMPLE
(a handful of dict walks at hz), never per-request, and the stack
table is capacity-bounded (overflow is counted, not grown). The
ingest/pipeline bench overhead gates run with the profiler ON, so the
<3% live-vs-null budget covers it.

**Liveness discipline (PR 10)**: `wait_for_sample()` re-checks
sampler liveness on every bounded wait, and a sampler that died
surfaces its failure through `health()` into `ArenaServer.stats()` —
an explicit error, never a silently frozen profile. `NullProfiler` is
the no-op twin. No jax imports in this package.
"""

import os
import sys
import threading
import time

DEFAULT_HZ = 67.0
DEFAULT_MAX_STACKS = 2048
DEFAULT_MAX_DEPTH = 64

# Bounded wait quantum for liveness re-checks while blocked on samples.
_WAIT_QUANTUM_S = 0.05

# Thread-name substring -> role. First match wins; unmatched threads
# fold under "other" (MainThread included — a test driving the system
# from the main thread shows up there).
ROLE_PATTERNS = (
    ("arena-ingest-packer", "packer"),
    ("arena-frontdoor-merge", "dispatcher"),
    ("arena-wire-server", "http-accept"),
    ("arena-wire-eventloop", "http-eventloop"),  # the fast read path
    ("arena-wire-submit-", "http-worker"),  # the event loop's submit pool
    ("arena-replica-tail", "replica-tail"),  # log fetch over the wire
    ("arena-replica-replay", "replica-replay"),  # strict-seq apply
    ("Thread-", "http-worker"),  # stdlib ThreadingHTTPServer workers
    ("arena-obs-window", "window"),
    ("arena-obs-profiler", "profiler"),
)


def thread_role(name):
    """Stable role for a thread name (see ROLE_PATTERNS)."""
    for pattern, role in ROLE_PATTERNS:
        if pattern in name:
            return role
    return "other"


class ProfilerError(RuntimeError):
    """Profiler misuse or a dead sampler thread."""


class SamplingProfiler:  # protocol: start->close
    """Samples every live thread's stack at `hz`, folding into
    per-role collapsed stacks."""

    def __init__(self, hz=DEFAULT_HZ, max_stacks=DEFAULT_MAX_STACKS,
                 max_depth=DEFAULT_MAX_DEPTH):
        if hz <= 0 or max_stacks < 1 or max_depth < 1:
            raise ProfilerError(
                f"profiler needs hz > 0, max_stacks >= 1, max_depth >= 1,"
                f" got ({hz}, {max_stacks}, {max_depth})"
            )
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._period = 1.0 / self.hz
        self._cv = threading.Condition()
        self._stacks = {}  # guarded_by: _cv ((role, folded) -> count)
        self._role_samples = {}  # guarded_by: _cv (role -> thread-samples)
        self._samples = 0  # guarded_by: _cv (sampling sweeps taken)
        self._truncated = 0  # guarded_by: _cv (stacks past max_stacks)
        self._thread = None  # guarded_by: _cv
        self._closed = False  # guarded_by: _cv
        self._failure = None  # guarded_by: _cv (sampler death reason)

    # --- sampling -----------------------------------------------------

    def _sample_locked(self):
        """One sweep over every live thread's current frame (the
        sampling thread itself excluded — its own act of sampling is
        not signal)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            role = thread_role(names.get(tid, ""))
            frames = []
            f = frame
            while f is not None and len(frames) < self.max_depth:
                code = f.f_code
                frames.append(
                    f"{os.path.basename(code.co_filename)}:{code.co_name}"
                )
                f = f.f_back
            folded = ";".join(reversed(frames))
            key = (role, folded)
            if key in self._stacks or len(self._stacks) < self.max_stacks:
                self._stacks[key] = self._stacks.get(key, 0) + 1
            else:
                self._truncated += 1
            self._role_samples[role] = self._role_samples.get(role, 0) + 1
        self._samples += 1
        self._cv.notify_all()

    def sample_now(self):
        """Take one sweep synchronously (deterministic tests, and the
        bench's pre-bundle flush)."""
        with self._cv:
            self._sample_locked()
            return self._samples

    def _run(self):
        try:
            while True:
                with self._cv:
                    if self._closed:
                        return
                    self._cv.wait(timeout=self._period)
                    if self._closed:
                        return
                    self._sample_locked()
        except Exception as exc:  # surfaced via health()/wait_for_sample
            with self._cv:
                self._failure = f"{type(exc).__name__}: {exc}"
                self._cv.notify_all()

    def start(self):
        """(Re)start the sampler thread; idempotent while one is
        alive."""
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._closed = False
            self._failure = None
            self._thread = threading.Thread(
                target=self._run, name="arena-obs-profiler", daemon=True
            )
            self._thread.start()
        return self

    def close(self):
        """Stop the sampler; accumulated stacks remain readable."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    # --- liveness (PR 10 discipline) ---------------------------------

    def _check_sampler_locked(self):
        """Raise if the sampler died — every blocked wait re-checks
        this, so a dead sampler is an explicit `ProfilerError`, never
        a silent hang on a frozen profile."""
        if self._failure is not None:
            raise ProfilerError(f"sampler thread died: {self._failure}")
        if self._thread is None:
            raise ProfilerError(
                "no sampler thread running (start() the profiler before "
                "waiting on samples)"
            )
        if not self._thread.is_alive() and not self._closed:
            raise ProfilerError(
                "sampler thread died without recording a failure"
            )

    def wait_for_sample(self, samples=1, timeout=10.0):
        """Block until `samples` more sweeps land, re-checking sampler
        liveness every bounded wait."""
        deadline = time.monotonic() + timeout
        with self._cv:
            target = self._samples + samples
            while self._samples < target:
                self._check_sampler_locked()
                if time.monotonic() >= deadline:
                    raise ProfilerError(
                        f"profiler took no sample within {timeout:g}s"
                    )
                self._cv.wait(timeout=_WAIT_QUANTUM_S)
            return self._samples

    def health(self):
        """Sampler liveness + accounting for `stats()`: `error` is
        non-None ONLY when a started sampler died (not when the
        profiler simply was never started or was cleanly closed)."""
        with self._cv:
            error = self._failure
            thread = self._thread
            if (
                error is None
                and thread is not None
                and not thread.is_alive()
                and not self._closed
            ):
                error = "sampler thread died without recording a failure"
            return {
                "running": thread is not None and thread.is_alive(),
                "hz": self.hz,
                "samples": self._samples,
                "distinct_stacks": len(self._stacks),
                "truncated": self._truncated,
                "error": error,
            }

    @property
    def samples(self):
        with self._cv:
            return self._samples

    # --- reads --------------------------------------------------------

    def collapsed(self):
        """Collapsed-stack text (``role;f1;f2 count`` per line, hottest
        first) — feed straight to flamegraph tooling; written into the
        debug bundle as `profile.txt`."""
        with self._cv:
            items = sorted(
                self._stacks.items(), key=lambda kv: (-kv[1], kv[0])
            )
        return "\n".join(
            f"{role};{folded} {count}" if folded else f"{role} {count}"
            for (role, folded), count in items
        ) + ("\n" if items else "")

    def snapshot(self, top=20):  # schema: wire-debug-profile@v1
        """The `/debug/profile` payload: accounting + per-role sample
        split + the hottest `top` stacks."""
        with self._cv:
            roles = dict(sorted(self._role_samples.items()))
            items = sorted(
                self._stacks.items(), key=lambda kv: (-kv[1], kv[0])
            )[: max(0, int(top))]
            health = {
                "running": (
                    self._thread is not None and self._thread.is_alive()
                ),
                "samples": self._samples,
                "error": self._failure,
            }
        return {
            "hz": self.hz,
            "samples": health["samples"],
            "running": health["running"],
            "error": health["error"],
            "roles": roles,
            "top": [
                {"role": role, "stack": folded, "count": count}
                for (role, folded), count in items
            ],
        }


class NullProfiler:
    """No-op twin: identical surface, constant-time, never samples."""

    enabled = False
    hz = 0.0
    samples = 0

    def start(self):
        return self

    def close(self):
        return None

    def sample_now(self):
        return 0

    def wait_for_sample(self, samples=1, timeout=10.0):
        return 0

    def health(self):
        return {"running": False, "hz": 0.0, "samples": 0,
                "distinct_stacks": 0, "truncated": 0, "error": None}

    def collapsed(self):
        return ""

    def snapshot(self, top=20):
        return {"hz": 0.0, "samples": 0, "running": False, "error": None,
                "roles": {}, "top": []}
