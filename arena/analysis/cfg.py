"""Per-function intraprocedural CFG with exception edges (jaxlint v4).

jaxlint v1–v3 reason over normal control flow only; the bug class this
module unlocks is *exceptional-path* state corruption — a staging slot
never released after a failed dispatch, a lock held across a raise.
`build_cfg(fn_node)` turns one `ast.FunctionDef` into a statement-level
graph where EVERY raise-capable statement carries an exception edge to
wherever an exception actually goes: the enclosing handler dispatch,
through each enclosing `finally` copy, or the function's synthetic
raise-exit.

Model (deliberately simple, deliberately honest):

- One node per statement, plus synthetic nodes: ``entry``, ``exit``
  (normal return), ``raise-exit`` (unwound out of the function),
  ``join`` (loop exits / try fall-through / handler dispatch), and
  ``with-unwind`` (the ``__exit__``-on-unwind call a `with` guarantees).
- Edges are ``(successor_index, kind)`` with kind ``"normal"`` or
  ``"exception"``. An exception edge leaves the statement that raised;
  the typestate analyzer treats the two kinds differently (a call that
  raises never completed, so its acquire never happened).
- ``finally`` is modeled by DUPLICATION: one copy of the finalbody per
  distinct continuation (fall-through, each return/break/continue
  route, exception propagation), memoized per target. That is what
  makes "the release sits in a finally" visibly dominate both edge
  kinds — the property the CFG tests pin.
- ``try/except``: exception edges from body statements go to a single
  handler-dispatch join, which fans out to every handler; unless some
  handler is a catch-all (bare ``except`` / ``Exception`` /
  ``BaseException``), the dispatch also keeps an unmatched path to the
  enclosing frame. ``else`` and handler bodies propagate OUTWARD (their
  exceptions are not caught by this try's handlers).
- ``with``: body exceptions route through a synthetic with-unwind node
  (``__exit__`` runs) before propagating. Abrupt normal exits (return
  out of a `with`) take the plain frame route — `with` cleanup on the
  normal path is PR 10's lock analyzer's territory; this module is
  about the exceptional one.
- Raise-capability is syntactic: a statement whose own expressions
  contain a call, subscript, binary op, raise, or assert can raise;
  `for`/`with` headers always can (iterator/context protocol). Plain
  name/attribute reads are deemed safe — the linter is heuristic and
  tuned so the clean tree stays clean.

No new dependencies: stdlib `ast` only, and no imports from the rest
of the analysis package — `lifecycle.py` builds on top of this, never
the other way around.
"""

from __future__ import annotations

import ast

EDGE_NORMAL = "normal"
EDGE_EXC = "exception"

# Node kinds.
K_ENTRY = "entry"
K_EXIT = "exit"
K_RAISE = "raise-exit"
K_STMT = "stmt"
K_JOIN = "join"
K_WITH_UNWIND = "with-unwind"

_RAISING_EXPRS = (ast.Call, ast.Subscript, ast.BinOp)


def stmt_can_raise(stmt) -> bool:
    """Can this statement's OWN evaluation raise? (Headers only for
    compound statements — their bodies are separate nodes.)"""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.With, ast.AsyncWith)):
        return True  # iterator / context-manager protocol calls
    if isinstance(stmt, ast.Match):
        return True  # subject evaluation + pattern/guard machinery
    for expr in _own_exprs(stmt):
        for node in ast.walk(expr):
            if isinstance(node, _RAISING_EXPRS):
                return True
    return False


_STMT_LIST_FIELDS = ("body", "orelse", "finalbody", "handlers", "cases")


def _own_exprs(stmt):
    """A statement's own expression roots (header expressions for
    compound statements), excluding nested statement lists."""
    for field, value in ast.iter_fields(stmt):
        if field in _STMT_LIST_FIELDS:
            continue
        if isinstance(value, ast.AST):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.AST):
                    yield v


class CFGNode:
    __slots__ = ("idx", "kind", "stmt", "raise_capable", "succs")

    def __init__(self, idx, kind, stmt=None, raise_capable=False):
        self.idx = idx
        self.kind = kind
        self.stmt = stmt  # the ast statement (or handler) this models
        self.raise_capable = raise_capable
        self.succs = []  # [(successor idx, edge kind), ...]

    def __repr__(self):  # pragma: no cover - debugging aid
        line = getattr(self.stmt, "lineno", "-")
        return f"<CFGNode {self.idx} {self.kind} line={line} succs={self.succs}>"


class CFG:
    """One function's graph. `nodes[entry_idx]` / `exit_idx` /
    `raise_idx` are the synthetic endpoints; statement nodes map back
    to their ast node via `.stmt` (finally duplication means one
    statement may own several nodes)."""

    def __init__(self, fn_node):
        self.fn = fn_node
        self.nodes = []
        self.entry_idx = self._add(K_ENTRY)
        self.exit_idx = self._add(K_EXIT)
        self.raise_idx = self._add(K_RAISE)

    def _add(self, kind, stmt=None, raise_capable=False) -> int:
        node = CFGNode(len(self.nodes), kind, stmt, raise_capable)
        self.nodes.append(node)
        return node.idx

    def _edge(self, src, dst, kind):
        if (dst, kind) not in self.nodes[src].succs:
            self.nodes[src].succs.append((dst, kind))

    def stmt_nodes(self, stmt):
        """Every node modeling `stmt` (≥2 when finally duplication or
        handler fanning copied it)."""
        return [n for n in self.nodes if n.stmt is stmt]

    def reachable_from(self, start_idx) -> set:
        seen = {start_idx}
        stack = [start_idx]
        while stack:
            for succ, _kind in self.nodes[stack.pop()].succs:
                if succ not in seen:
                    seen.add(succ)
                    stack.append(succ)
        return seen


class _Frame:
    """Where abrupt exits go from the current nesting level: exceptions
    (`exc`), `return` (`ret`), `break` (`brk`), `continue` (`cont`) —
    each already routed through any enclosing finally copies."""

    __slots__ = ("exc", "ret", "brk", "cont")

    def __init__(self, exc, ret, brk=None, cont=None):
        self.exc = exc
        self.ret = ret
        self.brk = brk
        self.cont = cont

    def replaced(self, **kw):
        out = _Frame(self.exc, self.ret, self.brk, self.cont)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


_CATCH_ALL_TAILS = ("Exception", "BaseException")


def _handler_is_catch_all(handler) -> bool:
    if handler.type is None:
        return True
    exprs = (
        handler.type.elts if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for expr in exprs:
        name = _dotted(expr)
        if name and name.split(".")[-1] in _CATCH_ALL_TAILS:
            return True
    return False


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Builder:
    def __init__(self, fn_node):
        self.cfg = CFG(fn_node)

    def build(self):
        cfg = self.cfg
        frame = _Frame(exc=cfg.raise_idx, ret=cfg.exit_idx)
        entry, dangling = self._block(cfg.fn.body, frame)
        cfg._edge(cfg.entry_idx, entry if entry is not None else cfg.exit_idx,
                  EDGE_NORMAL)
        for d in dangling:
            cfg._edge(d, cfg.exit_idx, EDGE_NORMAL)
        return cfg

    def _block(self, stmts, frame):
        """(entry idx or None, dangling fall-through node idxs)."""
        entry = None
        dangling = []
        for stmt in stmts:
            s_entry, s_dangling = self._stmt(stmt, frame)
            if entry is None:
                entry = s_entry
            for d in dangling:
                self.cfg._edge(d, s_entry, EDGE_NORMAL)
            dangling = s_dangling
            if not dangling:
                break  # everything after an unconditional exit is dead
        return entry, dangling

    def _simple(self, stmt, frame):
        """One node; exception edge iff the statement can raise. This
        is the single point every raise-capable statement passes
        through — the exception edge below is THE edge the CFG property
        tests (and the exception-edge-dropped mutant) police."""
        can_raise = stmt_can_raise(stmt)
        idx = self.cfg._add(K_STMT, stmt, can_raise)
        if can_raise:
            self.cfg._edge(idx, frame.exc, EDGE_EXC)
        return idx

    def _stmt(self, stmt, frame):
        cfg = self.cfg
        if isinstance(stmt, ast.Return):
            idx = self._simple(stmt, frame)
            cfg._edge(idx, frame.ret, EDGE_NORMAL)
            return idx, []
        if isinstance(stmt, ast.Raise):
            idx = self._simple(stmt, frame)
            return idx, []
        if isinstance(stmt, ast.Break):
            idx = self._simple(stmt, frame)
            if frame.brk is not None:
                cfg._edge(idx, frame.brk, EDGE_NORMAL)
            return idx, []
        if isinstance(stmt, ast.Continue):
            idx = self._simple(stmt, frame)
            if frame.cont is not None:
                cfg._edge(idx, frame.cont, EDGE_NORMAL)
            return idx, []
        if isinstance(stmt, ast.If):
            header = self._simple(stmt, frame)
            b_entry, b_dangling = self._block(stmt.body, frame)
            cfg._edge(header, b_entry, EDGE_NORMAL)
            dangling = list(b_dangling)
            if stmt.orelse:
                o_entry, o_dangling = self._block(stmt.orelse, frame)
                cfg._edge(header, o_entry, EDGE_NORMAL)
                dangling += o_dangling
            else:
                dangling.append(header)  # test-false falls through
            return header, dangling
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, frame)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, frame)
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            return self._try(stmt, frame)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, frame)
        # Simple statements (and nested defs/classes, whose bodies are
        # separate scopes the analyzer visits on their own).
        idx = self._simple(stmt, frame)
        return idx, [idx]

    def _loop(self, stmt, frame):
        cfg = self.cfg
        header = self._simple(stmt, frame)
        after = cfg._add(K_JOIN)
        body_frame = frame.replaced(brk=after, cont=header)
        b_entry, b_dangling = self._block(stmt.body, body_frame)
        cfg._edge(header, b_entry, EDGE_NORMAL)
        for d in b_dangling:
            cfg._edge(d, header, EDGE_NORMAL)  # back edge
        if stmt.orelse:
            o_entry, o_dangling = self._block(stmt.orelse, frame)
            cfg._edge(header, o_entry, EDGE_NORMAL)
            for d in o_dangling:
                cfg._edge(d, after, EDGE_NORMAL)
        else:
            cfg._edge(header, after, EDGE_NORMAL)
        return header, [after]

    def _with(self, stmt, frame):
        cfg = self.cfg
        header = self._simple(stmt, frame)
        unwind = cfg._add(K_WITH_UNWIND, stmt)
        cfg._edge(unwind, frame.exc, EDGE_EXC)
        body_frame = frame.replaced(exc=unwind)
        b_entry, b_dangling = self._block(stmt.body, body_frame)
        cfg._edge(header, b_entry, EDGE_NORMAL)
        return header, list(b_dangling)

    def _try(self, stmt, frame):
        cfg = self.cfg
        after = cfg._add(K_JOIN)
        fin_memo = {}

        def fin(target):
            """Entry of the finally copy continuing to `target` (or
            `target` itself when there is no finalbody)."""
            if not stmt.finalbody:
                return target
            if target not in fin_memo:
                # The copy is built against the OUTER frame: a raise or
                # return inside a finalbody propagates outward (through
                # any enclosing finallies), never back into this one.
                f_entry, f_dangling = self._block(stmt.finalbody, frame)
                fin_memo[target] = f_entry
                for d in f_dangling:
                    cfg._edge(d, target, EDGE_NORMAL)
            return fin_memo[target]

        # The frame for code whose exceptions are NOT caught here but
        # still run the finally: else-clauses, handler bodies, and the
        # body of a finally-only try.
        outward = _Frame(
            exc=fin(frame.exc),
            ret=fin(frame.ret),
            brk=fin(frame.brk) if frame.brk is not None else None,
            cont=fin(frame.cont) if frame.cont is not None else None,
        )

        if stmt.handlers:
            dispatch = cfg._add(K_JOIN)
            for handler in stmt.handlers:
                h_node = cfg._add(K_STMT, handler)
                cfg._edge(dispatch, h_node, EDGE_NORMAL)
                h_entry, h_dangling = self._block(handler.body, outward)
                cfg._edge(h_node, h_entry, EDGE_NORMAL)
                for d in h_dangling:
                    cfg._edge(d, fin(after), EDGE_NORMAL)
            if not any(_handler_is_catch_all(h) for h in stmt.handlers):
                cfg._edge(dispatch, fin(frame.exc), EDGE_NORMAL)
            body_exc = dispatch
        else:
            body_exc = outward.exc

        body_frame = _Frame(exc=body_exc, ret=outward.ret,
                            brk=outward.brk, cont=outward.cont)
        b_entry, b_dangling = self._block(stmt.body, body_frame)
        if stmt.orelse:
            o_entry, o_dangling = self._block(stmt.orelse, outward)
            for d in b_dangling:
                cfg._edge(d, o_entry, EDGE_NORMAL)
            b_dangling = o_dangling
        for d in b_dangling:
            cfg._edge(d, fin(after), EDGE_NORMAL)
        if b_entry is None:  # empty body cannot parse, but stay total
            b_entry = fin(after)
        return b_entry, [after]

    def _match(self, stmt, frame):
        cfg = self.cfg
        header = self._simple(stmt, frame)
        dangling = [header]  # no case matched
        for case in stmt.cases:
            c_entry, c_dangling = self._block(case.body, frame)
            cfg._edge(header, c_entry, EDGE_NORMAL)
            dangling += c_dangling
        return header, dangling


def build_cfg(fn_node) -> CFG:
    """The CFG of one `ast.FunctionDef` / `ast.AsyncFunctionDef` body.
    Nested defs/classes appear as single opaque statements."""
    return _Builder(fn_node).build()
