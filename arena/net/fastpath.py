"""The fast wire read path: byte cache + event-loop front end (PR 16).

The baseline wire tier pinned ~40 queries/s against ~8,000 in-process —
a 200x transport tax paid to thread-per-connection dispatch and
per-request JSON rendering. This module removes both:

- `ResponseCache` — a **watermark-keyed byte cache**. A leaderboard
  page / player row / h2h response is rendered once per (endpoint,
  params, view generation) and served as bytes until the serving view
  changes. The key carries the view's `seq`, which advances whenever
  the view watermark advances (and on every other refresh — intervals
  and win/loss counts can change without the watermark moving), so a
  cached response can never outlive the view that rendered it. The
  render itself is safe to cache because `ArenaServer._player_row` is
  contract-`# pure-render(view)` under jaxlint: a hidden-state read
  that would poison the cache is a lint error before it ships.

- **Head-splice rendering** (`render_head` / `complete_response`).
  Every JSON response carries a per-request ``trace_id`` next to the
  watermark, which would defeat byte caching — so the cache stores the
  response *head* (the full envelope minus the trailing trace_id pair
  and closing brace) and each request completes it with its own trace
  id in one bytes-concat. `make_response` appends the authoritative
  watermark/trace_id pair LAST, so the splice is byte-identical to a
  fresh `json.dumps(make_response(...))` — the property the bench's
  cache-consistency hard gate (`verify_cache_consistency`) re-checks
  against live traffic.

- `EventLoopFrontEnd` — a `selectors`-based (epoll on Linux, stdlib
  only) single-thread event loop for the read path. GET endpoints and
  POST /query are answered inline on the loop (they only read
  immutable views and the cache); POST /submit is handed to a small
  blocking worker pool, because `FrontDoor.submit` may block on
  admission backpressure and the loop must never block. Per-connection
  responses stay in request order: while a submit is in flight the
  connection's parser is paused, and the pool's completion re-enters
  the loop through a socketpair wakeup.

Stale serves bypass the cache entirely (restore in progress, or a
pipeline deeper than the staleness bound): the ``stale`` flag and live
staleness number pass through unmodified, exactly as the slow path
reports them.
"""

import json
import http.client
import selectors
import socket
import threading
import time

from arena.net import protocol

DEFAULT_CACHE_CAPACITY = 256
# Hot leaderboard pages rebuilt eagerly at view-refresh time: the top
# of the board (what everyone polls) and the default page size.
DEFAULT_PRERENDER_PAGES = ((0, 10), (0, protocol.DEFAULT_PAGE_LIMIT))
DEFAULT_SUBMIT_WORKERS = 4

LOOP_THREAD_NAME = "arena-wire-eventloop"
SUBMIT_WORKER_PREFIX = "arena-wire-submit-"

# HTTP framing bounds: a request that exceeds them is answered once
# (431/413) and the connection closed — never an unbounded buffer.
MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 16 * 1024 * 1024
RECV_BYTES = 256 * 1024
LISTEN_BACKLOG = 128
SELECT_TIMEOUT_S = 0.05

_ACCEPT = "accept"  # selector data tags for the two non-connection fds
_WAKE = "wake"

CACHEABLE_ENDPOINTS = ("leaderboard", "player", "h2h")


# --- byte rendering ---------------------------------------------------------


def cache_key(endpoint, params):
    """The cache key: endpoint + canonicalized parse_path params."""
    return (endpoint, tuple(sorted(params.items())))


def render_head(payload, watermark):  # schema: wire-envelope@v1
    """Render a response payload into a cacheable byte head: the full
    JSON envelope minus the trailing ``"trace_id"`` pair and the
    closing brace. `make_response` strips any payload-supplied
    watermark/trace pair and appends the authoritative pair LAST (in
    insertion order, which `json.dumps` preserves), so
    `complete_response(head, tid)` is byte-identical to dumping
    `make_response(payload, watermark=..., trace_id=tid)` fresh."""
    envelope = protocol.make_response(payload, watermark=watermark, trace_id=0)
    del envelope["trace_id"]
    text = json.dumps(envelope)
    return text[:-1].encode("utf-8")


def complete_response(head, trace_id):
    """Splice THIS request's trace id onto a cached head."""
    return head + b', "trace_id": ' + str(trace_id).encode("ascii") + b"}"


def render_query_payload(srv, view, stale, endpoint, params,
                         staleness=None):  # schema: wire-read-params@v1
    """Map one cacheable GET endpoint's parsed params onto a
    `_query_parts` render against an already-chosen view. `staleness`
    defaults to the view-stable distance (ingested-at-clone minus
    watermark) so the rendered bytes are a pure function of
    (view, params); stale serves pass the live distance instead —
    honesty outranks cacheability there, and they are never cached."""
    if staleness is None:
        staleness = view.matches_ingested - view.watermark
    # Tenant rides the parsed params (present only when the request
    # carried `?tenant=`), so the byte-cache key — (endpoint, sorted
    # params) — distinguishes tenants with no cache-side logic at all.
    tenant = params.get("tenant")
    if endpoint == "leaderboard":
        return srv._query_parts(
            view, stale, (params["offset"], params["limit"]), None, None,
            0, staleness=staleness, tenant=tenant,
        )
    if endpoint == "player":
        return srv._query_parts(
            view, stale, None, [params["player"]], None, 0,
            staleness=staleness, tenant=tenant,
        )
    if endpoint == "h2h":
        return srv._query_parts(
            view, stale, None, None, [(params["a"], params["b"])], 0,
            staleness=staleness, tenant=tenant,
        )
    raise ValueError(f"endpoint {endpoint!r} is not cacheable")


def serve_cached(wire, endpoint, params):
    """The GET fast path: serve leaderboard/player/h2h bytes from the
    watermark-keyed cache when the current view still matches; render
    and fill otherwise. Returns (status, head, view_watermark) — the
    head is completed with the request's own trace id at write time.
    Stale serves bypass the cache in BOTH directions (no hit, no
    fill): the stale flag and live staleness pass through unmodified,
    and a stale render can never be served to a fresh reader."""
    srv = wire.server
    view, stale = srv._serve_view()
    key = cache_key(endpoint, params)
    if not stale:
        head = wire.cache.get(key, view.seq)
        if head is not None:
            return 200, head, view.watermark
    # Miss: render under a serve.query span (same trace story as the
    # slow path — the net.<endpoint> root span is already open).
    with srv.obs.span("serve.query"):
        live = srv._staleness(view) if stale else None
        payload = render_query_payload(
            srv, view, stale, endpoint, params, staleness=live
        )
    srv._c_queries.inc()
    head = render_head(payload, view.watermark)
    if not stale:
        wire.cache.put(key, view.seq, head)
    return 200, head, view.watermark


def verify_cache_consistency(wire):
    """The cache-consistency hard gate: re-render every entry of the
    CURRENT view generation from scratch and compare bytes. Returns
    (checked, mismatches) — a non-empty mismatch list means the cache
    would have served bytes that differ from a fresh render at the
    same watermark, which no deploy gets to ignore (the frontend bench
    raises on it)."""
    srv = wire.server
    view, stale = srv._serve_view()
    if stale:
        return 0, []
    checked = 0
    mismatches = []
    for key, (seq, head) in wire.cache.entries():
        if seq != view.seq:
            continue
        endpoint, param_items = key
        payload = render_query_payload(
            srv, view, False, endpoint, dict(param_items)
        )
        checked += 1
        if render_head(payload, view.watermark) != head:
            mismatches.append(key)
    return checked, mismatches


# --- the watermark-keyed byte cache -----------------------------------------


class ResponseCache:  # protocol: close
    """Watermark-keyed response byte cache for the wire read path.

    Maps (endpoint, params) -> (view_seq, head bytes). A `get` hits
    only when the stored generation equals the CURRENT view's seq —
    the seq advances whenever the view watermark advances (and on any
    other refresh), so invalidation is structural, not time-based:
    cached bytes can never outlive their view. Capacity-bounded;
    eviction drops dead-generation entries first and counts every
    removal. All methods are thread-safe (the event loop, the submit
    pool, and the prerender listener all touch it)."""

    def __init__(self, obs, capacity=DEFAULT_CACHE_CAPACITY,
                 clock=time.monotonic):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._lock = threading.Lock()
        self._entries = {}  # guarded_by: _lock  (key -> (view_seq, head))
        self._gen = -1  # guarded_by: _lock  (newest view seq cached)
        self._born = clock()  # guarded_by: _lock  (generation birth time)
        self._closed = False  # guarded_by: _lock
        self._c_hits = obs.counter("arena_wire_cache_hits_total")
        self._c_misses = obs.counter("arena_wire_cache_misses_total")
        self._c_evictions = obs.counter("arena_wire_cache_evictions_total")
        self._c_prerenders = obs.counter("arena_wire_cache_prerenders_total")
        self._g_age = obs.gauge("arena_wire_cache_age_seconds")

    def get(self, key, view_seq):
        """The cached head for `key` IF it was rendered from the view
        generation `view_seq`, else None (counted as a miss)."""
        with self._lock:
            self._g_age.set(self._clock() - self._born)
            entry = self._entries.get(key)
            if entry is not None and entry[0] == view_seq:
                self._c_hits.inc()
                return entry[1]
            self._c_misses.inc()
            return None

    def put(self, key, view_seq, head, prerendered=False):
        """Store a rendered head for one view generation. Stale puts
        (an older generation than the newest cached) are dropped — a
        slow render must never clobber a fresher entry."""
        with self._lock:
            if self._closed or view_seq < self._gen:
                return
            if view_seq > self._gen:
                self._gen = view_seq
                self._born = self._clock()
            self._g_age.set(self._clock() - self._born)
            if key not in self._entries and len(self._entries) >= self.capacity:
                self._evict_locked()
            self._entries[key] = (view_seq, head)
            if prerendered:
                self._c_prerenders.inc()

    def _evict_locked(self):
        """Make room: drop every dead-generation entry if any exist,
        else the oldest-inserted live one. Caller holds `_lock`."""
        dead = [
            k for k, (seq, _head) in self._entries.items() if seq < self._gen
        ]
        victims = dead if dead else [next(iter(self._entries))]
        for k in victims:
            del self._entries[k]
        self._c_evictions.inc(len(victims))

    def entries(self):
        """A consistent snapshot of (key, (view_seq, head)) items —
        what the consistency gate walks."""
        with self._lock:
            return list(self._entries.items())

    def size(self):
        with self._lock:
            return len(self._entries)

    def close(self):
        """Terminal: drop every entry and refuse further fills (gets
        keep answering None — readers drain through the render path)."""
        with self._lock:
            self._entries.clear()
            self._closed = True


# --- the event-loop front end -----------------------------------------------


class _FrameError(Exception):
    """Malformed HTTP framing: answered once, then the connection
    closes (the framing statuses: 400/413/431/501/505)."""

    def __init__(self, status, message):
        super().__init__(message)
        self.status = status
        self.message = message


class _Conn:
    """Per-connection state, owned by the loop thread. The submit pool
    sees a `_Conn` only as an opaque token inside a job tuple — every
    field mutation happens on the loop thread."""

    __slots__ = ("sock", "inbuf", "outbuf", "events", "busy", "close_after",
                 "closed")

    def __init__(self, sock):
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.events = selectors.EVENT_READ
        self.busy = False  # a /submit response is pending in the pool
        self.close_after = False
        self.closed = False


def _parse_request(conn):
    """Parse one complete HTTP/1.x request off the connection's input
    buffer, consuming it. Returns (method, target, body, keep_alive),
    or None when the buffer doesn't hold a full request yet. Raises
    `_FrameError` on malformed framing. Content-Length bodies only —
    `WireClient` (and `http.client` generally) never sends chunked."""
    buf = conn.inbuf
    head_end = buf.find(b"\r\n\r\n")
    if head_end < 0:
        if len(buf) > MAX_HEADER_BYTES:
            raise _FrameError(431, "request headers too large")
        return None
    lines = bytes(buf[:head_end]).decode("latin-1").split("\r\n")
    parts = lines[0].split()
    if len(parts) != 3:
        raise _FrameError(400, f"malformed request line: {lines[0]!r}")
    method, target, version = parts
    if version not in ("HTTP/1.1", "HTTP/1.0"):
        raise _FrameError(505, f"unsupported HTTP version: {version!r}")
    headers = {}
    for line in lines[1:]:
        name, sep, value = line.partition(":")
        if not sep:
            raise _FrameError(400, f"malformed header line: {line!r}")
        headers[name.strip().lower()] = value.strip()
    if headers.get("transfer-encoding"):
        raise _FrameError(501, "chunked request bodies are not supported")
    try:
        length = int(headers.get("content-length", "0") or "0")
    except ValueError:
        raise _FrameError(400, "malformed Content-Length") from None
    if length < 0 or length > MAX_BODY_BYTES:
        raise _FrameError(413, f"request body of {length} bytes refused")
    total = head_end + 4 + length
    if len(buf) < total:
        return None
    body = bytes(buf[head_end + 4: total])
    del buf[:total]
    connection = headers.get("connection", "").lower()
    keep = (
        connection != "close"
        if version == "HTTP/1.1"
        else connection == "keep-alive"
    )
    return method, target, body, keep


def _frame(status, body, content_type, watermark, trace_id, keep_alive):
    """One HTTP/1.1 response as bytes — the same envelope headers the
    threaded handler sends (X-Arena-Watermark / X-Arena-Trace-Id on
    every response, /stats reads the pair from here)."""
    reason = http.client.responses.get(status, "Unknown")
    head = (
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: {content_type}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"X-Arena-Watermark: {watermark}\r\n"
        f"X-Arena-Trace-Id: {trace_id}\r\n"
        f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n"
        "\r\n"
    )
    return head.encode("latin-1") + body


def _is_submit(target):
    path = target.split("?", 1)[0]
    return [p for p in path.split("/") if p] == ["submit"]


class EventLoopFrontEnd:  # protocol: start->close
    """Single-thread `selectors` event loop serving the wire read path.

    Reads (GETs, POST /query) are answered inline on the loop — they
    only touch immutable views and the byte cache, so the whole read
    tier is one thread, no per-connection stacks, no handler thread
    churn. POST /submit goes to a small blocking worker pool, because
    `FrontDoor.submit` may block on admission backpressure and the
    loop must never block; the pool's completions re-enter the loop
    through a socketpair wakeup, and a connection's parser pauses
    while its submit is in flight so responses stay in request order.

    `start()` spawns the loop + workers; `close()` stops and joins
    them and closes every socket. The owning `ArenaHTTPServer` drives
    both ends of the protocol."""

    def __init__(self, wire, host="127.0.0.1", port=0,
                 submit_workers=DEFAULT_SUBMIT_WORKERS):
        if submit_workers < 1:
            raise ValueError(
                f"submit_workers must be >= 1, got {submit_workers}"
            )
        self.wire = wire
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(LISTEN_BACKLOG)
        self._listen.setblocking(False)
        self.host, self.port = self._listen.getsockname()[:2]
        # The pool->loop completion channel: workers append under the
        # lock and poke the socketpair; the loop drains both.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._done = []  # guarded_by: _done_lock
        self._done_lock = threading.Lock()
        self._jobs = _JobQueue()
        self._conns = set()  # loop-thread-only connection registry
        self._stop = threading.Event()
        self._thread = None
        self._workers = []
        self._num_workers = submit_workers

    # --- lifecycle ---------------------------------------------------

    def start(self):
        if self._thread is not None:
            raise RuntimeError("event loop already started")
        self._thread = threading.Thread(
            target=self._run, name=LOOP_THREAD_NAME, daemon=True
        )
        self._workers = [
            threading.Thread(
                target=self._worker,
                name=f"{SUBMIT_WORKER_PREFIX}{i}",
                daemon=True,
            )
            for i in range(self._num_workers)
        ]
        self._thread.start()
        for worker in self._workers:
            worker.start()
        return self

    def close(self):
        self._stop.set()
        for _worker in self._workers:
            self._jobs.put(None)  # one poison pill per worker
        self._wake()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        for worker in self._workers:
            worker.join(timeout=10.0)
        self._workers = []
        self._listen.close()
        self._wake_r.close()
        self._wake_w.close()

    # --- the loop ----------------------------------------------------

    def _run(self):
        sel = selectors.DefaultSelector()
        sel.register(self._listen, selectors.EVENT_READ, _ACCEPT)
        sel.register(self._wake_r, selectors.EVENT_READ, _WAKE)
        try:
            while not self._stop.is_set():
                for key, mask in sel.select(timeout=SELECT_TIMEOUT_S):
                    data = key.data
                    if data is _ACCEPT:
                        self._accept(sel)
                    elif data is _WAKE:
                        self._drain_done(sel)
                    else:
                        if data.closed:
                            continue
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(sel, data)
                        if mask & selectors.EVENT_READ and not data.closed:
                            self._on_readable(sel, data)
        finally:
            for conn in list(self._conns):
                self._drop(sel, conn)
            sel.close()

    def _accept(self, sel):
        while True:
            try:
                sock, _addr = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock)
            self._conns.add(conn)
            sel.register(sock, selectors.EVENT_READ, conn)

    def _on_readable(self, sel, conn):
        try:
            chunk = conn.sock.recv(RECV_BYTES)
        except BlockingIOError:
            return
        except OSError:
            self._drop(sel, conn)
            return
        if not chunk:  # peer closed
            self._drop(sel, conn)
            return
        conn.inbuf += chunk
        self._advance(sel, conn)

    def _on_writable(self, sel, conn):
        if conn.outbuf:
            try:
                with memoryview(conn.outbuf) as view:
                    sent = conn.sock.send(view)
            except BlockingIOError:
                return
            except OSError:
                self._drop(sel, conn)
                return
            del conn.outbuf[:sent]
        self._update_events(sel, conn)

    def _advance(self, sel, conn):
        """Parse-and-answer every complete request buffered on `conn`
        (keep-alive pipelining), pausing while a submit is pooled so
        responses keep request order."""
        while not conn.busy and not conn.closed:
            try:
                req = _parse_request(conn)
            except _FrameError as exc:
                body = json.dumps({"error": exc.message}).encode("utf-8")
                conn.outbuf += _frame(
                    exc.status, body, "application/json", 0, 0,
                    keep_alive=False,
                )
                conn.close_after = True
                break
            if req is None:
                break
            method, target, body, keep = req
            if not keep:
                conn.close_after = True
            if method == "POST" and _is_submit(target):
                conn.busy = True
                self._jobs.put((conn, method, target, body, keep))
                break
            conn.outbuf += _frame(
                *self._handle(method, target, body), keep_alive=keep
            )
            if conn.close_after:
                break
        self._update_events(sel, conn)

    def _handle(self, method, target, body):
        """One request through the shared wire core; a crash anywhere
        degrades to a structured 500 (the loop thread must survive)."""
        try:
            return self.wire.handle_request(method, target, body)
        except Exception as exc:  # noqa: BLE001 — front-end last resort
            detail = json.dumps(
                {"error": f"{type(exc).__name__}: {exc}"}
            ).encode("utf-8")
            return 500, detail, "application/json", 0, 0

    def _update_events(self, sel, conn):
        if conn.closed:
            return
        if conn.close_after and not conn.outbuf and not conn.busy:
            self._drop(sel, conn)
            return
        events = selectors.EVENT_READ
        if conn.outbuf:
            events |= selectors.EVENT_WRITE
        if events != conn.events:
            conn.events = events
            sel.modify(conn.sock, events, conn)

    def _drop(self, sel, conn):
        if conn.closed:
            return
        conn.closed = True
        try:
            sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._conns.discard(conn)

    # --- the submit pool ---------------------------------------------

    def _worker(self):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            conn, method, target, body, keep = job
            frame = _frame(*self._handle(method, target, body),
                           keep_alive=keep)
            with self._done_lock:
                self._done.append((conn, frame))
            self._wake()

    def _drain_done(self, sel):
        while True:
            try:
                if not self._wake_r.recv(4096):
                    break
            except (BlockingIOError, OSError):
                break
        with self._done_lock:
            done, self._done = self._done, []
        for conn, frame in done:
            if conn.closed:
                continue
            conn.busy = False
            conn.outbuf += frame
            self._advance(sel, conn)

    def _wake(self):
        try:
            self._wake_w.send(b"\x01")
        except OSError:
            pass


class _JobQueue:
    """Tiny blocking FIFO (Condition + list): the loop enqueues submit
    jobs without blocking, workers block on `get`."""

    def __init__(self):
        self._cv = threading.Condition()
        self._items = []  # guarded_by: _cv

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._cv.notify()

    def get(self):
        with self._cv:
            while not self._items:
                self._cv.wait()
            return self._items.pop(0)
