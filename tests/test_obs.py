"""Observability-layer contracts (arena/obs/ + its wiring).

The load-bearing properties:

- EXACTNESS under concurrency: counter increments and histogram
  records from N threads sum exactly (per-metric locks — a lost update
  here silently corrupts every p99 the system reports);
- bucket-boundary semantics: log2 histograms place a value exactly ON
  a bucket's upper bound INTO that bucket (`le` semantics) — the
  mutation audit carries a wrong-bucket mutant;
  test_histogram_bucket_boundary_values_land_exactly is its named kill;
- the trace ring is bounded newest-wins: overflow keeps the newest
  spans and counts the drops (`trace_dropped`), so tracing can stay on
  in production with fixed memory;
- the Null twins are true no-ops with the identical interface (the
  uninstrumented baseline the bench overhead gate compares against);
- the wiring: a live-instrumented engine/pipeline records the stage
  spans and policy-labeled drop counters, `ArenaServer.stats()` folds
  everything (sanitizer counters included — the audit carries a
  stats-drops-sentinel-counters mutant killed by
  test_stats_reports_absorbed_sentinel_counters_from_registry) into
  one JSON-serializable dict, and `render()` is Prometheus-shaped.
"""

import json
import threading

import numpy as np
import pytest

from arena import obs as obs_pkg
from arena.engine import ArenaEngine
from arena.obs.metrics import Histogram, NullRegistry, Registry
from arena.obs.tracing import NullTracer, Tracer
from arena.serving import ArenaServer

P = 40


def make_matches(n, num_players=P, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, n).astype(np.int32)
    b = ((a + 1 + rng.integers(0, num_players - 1, n)) % num_players).astype(
        np.int32
    )
    return a, b


# --- exactness under concurrency -------------------------------------------


def test_concurrent_counter_and_histogram_sums_are_exact():
    """N threads hammering one counter and one histogram lose NOTHING:
    the totals equal the arithmetic sum of every increment/record."""
    reg = Registry()
    counter = reg.counter("arena_test_total")
    hist = reg.histogram("arena_test_seconds")
    threads, per_thread = 8, 2000

    def worker(tid):
        for i in range(per_thread):
            counter.inc()
            hist.record(1e-6 * (1 + (i + tid) % 7))

    workers = [
        threading.Thread(target=worker, args=(t,)) for t in range(threads)
    ]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=60.0)
    assert counter.value == threads * per_thread
    assert hist.count == threads * per_thread
    # Every record also landed in exactly one bucket.
    assert int(hist._counts.sum()) == threads * per_thread


def test_labeled_counters_are_distinct_and_summable():
    reg = Registry()
    reg.counter("arena_drops_total", policy="block").inc(3)
    reg.counter("arena_drops_total", policy="drop-oldest").inc(4)
    assert reg.counter("arena_drops_total", policy="block").value == 3
    assert reg.counter_sum("arena_drops_total") == 7
    assert reg.counter_sum("never_incremented_total") == 0


# --- histogram bucket semantics --------------------------------------------


def test_histogram_bucket_boundary_values_land_exactly():
    """`le` semantics: a value exactly ON an upper bound belongs to
    THAT bucket; epsilon above it belongs to the next. The mutation
    audit carries a wrong-bucket mutant; this is its named kill."""
    h = Histogram("t", {}, base=1e-3, num_buckets=8)
    # Bounds are 1e-3 * 2**i. Exactly on bound i -> bucket i.
    for i in range(8):
        assert h.bucket_index(1e-3 * 2.0**i) == i, f"bound {i}"
    # Epsilon above a bound -> the NEXT bucket.
    assert h.bucket_index(1e-3 * 1.0000001) == 1
    assert h.bucket_index(1e-3 * 2.0000001) == 2
    # At or below base (incl. zero/negative) -> bucket 0.
    assert h.bucket_index(0.0) == 0
    assert h.bucket_index(-1.0) == 0
    assert h.bucket_index(0.5e-3) == 0
    # Past the last bound -> the overflow slot.
    assert h.bucket_index(1e-3 * 2.0**7 + 1.0) == 8
    h.record(1e-3 * 2.0**3)
    assert int(h._counts[3]) == 1 and h.count == 1


def test_histogram_percentiles_are_conservative_bucket_bounds():
    h = Histogram("t", {}, base=1.0, num_buckets=6)
    assert h.percentile(0.5) is None  # empty: no fabricated number
    for v in [1, 1, 1, 1, 1, 1, 1, 1, 1, 30]:  # 90% in bucket 0, one in [16,32]
        h.record(v)
    assert h.percentile(0.5) == 1.0
    assert h.percentile(0.99) == 32.0  # upper bound of 30's bucket
    h.record(1e9)  # overflow: the honest answer is "past the range"
    assert h.percentile(1.0) == float("inf")


def test_histogram_rejects_degenerate_shape():
    with pytest.raises(ValueError, match="base > 0"):
        Histogram("t", {}, base=0.0)
    with pytest.raises(ValueError, match="base > 0"):
        Histogram("t", {}, base=1.0, num_buckets=0)


# --- trace ring ------------------------------------------------------------


def test_trace_ring_overflow_keeps_newest_and_counts_drops():
    tr = Tracer(capacity=8)
    for i in range(20):
        tr.record_span(f"s{i}", float(i), 0.5)
    assert tr.dropped == 12
    assert tr.recorded == 20
    kept = [r.name for r in tr.spans()]
    assert kept == [f"s{i}" for i in range(12, 20)]  # newest 8, in order
    # Span ids are MONOTONIC and never reset with the ring: wraparound
    # keeps allocation order intact (the evicted-parent classification
    # in orphans() stands on this).
    ids = [r.span_id for r in tr.spans()]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    assert min(ids) > 8  # the evicted rows' ids are NOT reused


def test_span_context_manager_records_duration_and_thread():
    tr = Tracer(capacity=8)
    with tr.span("work"):
        pass
    [rec] = tr.spans()
    assert rec.name == "work" and rec.duration >= 0.0 and rec.start > 0.0
    assert rec.tid == threading.get_ident()
    # A context-less span is the ROOT of its own fresh trace.
    assert rec.parent_id == 0 and rec.trace_id > 0 and rec.span_id > 0


def test_chrome_trace_export_shape():
    tr = Tracer(capacity=4)
    with tr.span("stage"):
        pass
    events = tr.export_chrome_trace()
    assert len(events) == 1
    ev = events[0]
    assert ev["ph"] == "X" and ev["name"] == "stage"
    assert ev["ts"] >= 0 and ev["dur"] >= 0 and "tid" in ev
    doc = json.loads(tr.export_chrome_trace_json())
    assert doc["traceEvents"] == events
    with pytest.raises(ValueError, match="capacity"):
        Tracer(capacity=0)


# --- the Null twins --------------------------------------------------------


def test_null_registry_and_tracer_are_true_noops():
    reg = NullRegistry()
    c = reg.counter("x", policy="p")
    c.inc(100)
    assert c.value == 0
    h = reg.histogram("y")
    h.record(1.0)
    assert h.count == 0 and h.percentile(0.5) is None
    reg.gauge("z").set(5.0)
    assert reg.render() == "" and reg.counter_sum("x") == 0
    assert reg.dump() == {"counters": {}, "gauges": {}, "histograms": {}}
    tr = NullTracer()
    with tr.span("a"):
        pass
    tr.record_span("b", 0.0, 1.0)
    assert tr.spans() == [] and tr.dropped == 0 and tr.recorded == 0
    assert not obs_pkg.NULL.enabled and obs_pkg.Observability().enabled


# --- exposition ------------------------------------------------------------


def test_render_is_prometheus_shaped():
    o = obs_pkg.Observability()
    o.counter("arena_q_total", policy="block").inc(2)
    o.histogram("arena_lat_seconds", base=1e-3, num_buckets=4).record(1e-3)
    text = o.render()
    assert "# TYPE arena_q_total counter" in text
    assert 'arena_q_total{policy="block"} 2' in text
    assert "# TYPE arena_lat_seconds histogram" in text
    assert 'arena_lat_seconds_bucket{le="0.001"} 1' in text
    assert 'arena_lat_seconds_bucket{le="+Inf"} 1' in text
    assert "arena_lat_seconds_count 1" in text
    # Cumulative buckets: every later bound carries the earlier count.
    assert 'arena_lat_seconds_bucket{le="0.002"} 1' in text


def test_dump_is_one_json_line():
    o = obs_pkg.Observability()
    o.counter("a_total").inc()
    o.histogram("b_seconds").record(0.5)
    with o.span("s"):
        pass
    line = json.dumps(o.dump())
    doc = json.loads(line)
    assert doc["counters"]["a_total"] == 1
    assert doc["histograms"]["b_seconds"]["count"] == 1
    assert doc["trace"]["spans_recorded"] == 1


# --- wiring: engine / pipeline / serving -----------------------------------


def test_live_engine_records_stage_spans_and_counters():
    """An engine handed a live Observability traces the whole sync
    path: csr merge, staging, jit dispatch, apply — and the ingest
    counters move. The default engine (NULL) records nothing."""
    o = obs_pkg.Observability()
    eng = ArenaEngine(P, obs=o)
    w, l = make_matches(300, seed=1)
    eng.ingest(w, l)
    names = {name for name, *_ in o.tracer.spans()}
    assert {"ingest.csr_merge", "ingest.staging", "engine.jit_dispatch",
            "engine.apply"} <= names
    assert o.registry.counter_sum("arena_ingest_matches_total") == 300
    plain = ArenaEngine(P)
    plain.ingest(w, l)
    assert plain.obs is obs_pkg.NULL
    assert plain.obs.tracer.spans() == []


def test_pipeline_drop_counters_land_in_registry_policy_labeled():
    """The drop-oldest shed shows up as policy-labeled registry
    counters (the one schema stats() reports from), alongside the
    pipeline's own attributes."""
    o = obs_pkg.Observability()
    eng = ArenaEngine(P, obs=o)
    pipe = eng.start_pipeline(capacity=2, policy="drop-oldest")
    w, l = make_matches(100, seed=2)
    batches = [(w[i * 20:(i + 1) * 20], l[i * 20:(i + 1) * 20]) for i in range(5)]
    with eng._store._lock:  # stall the packer inside its first merge
        eng.ingest_async(*batches[0])
        deadline = [0]
        while not pipe._packing and deadline[0] < 2000:
            deadline[0] += 1
            threading.Event().wait(0.005)
        assert pipe._packing
        for batch in batches[1:]:
            eng.ingest_async(*batch)  # capacity 2: two oldest raw drop
    eng.flush()
    assert pipe.dropped_batches == 2
    # Producer-labeled since PR 7 (defaults to "local"): the
    # multi-producer front door lands on this schema, not a rename.
    c = o.registry.counter("arena_pipeline_dropped_batches_total",
                           policy="drop-oldest", producer="local")
    assert c.value == 2
    assert o.registry.counter(
        "arena_pipeline_dropped_matches_total", policy="drop-oldest",
        producer="local",
    ).value == 40
    assert o.registry.counter_sum("arena_pipeline_dropped_batches_total") == 2
    assert {"pipeline.pack", "pipeline.dispatch"} <= {
        name for name, *_ in o.tracer.spans()
    }
    eng.shutdown()


def test_stats_reports_pipeline_drops_and_spills_one_schema():
    """ArenaServer.stats()["pipeline"] carries drop AND spill counts
    from the registry — one place, one schema — and survives a
    pipeline restart (registry counters are stream totals)."""
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    eng = srv.engine
    w, l = make_matches(60, seed=3)
    eng.ingest_async(w[:30], l[:30])
    eng.flush()
    spilled = eng.shutdown(spill=True)
    assert spilled == []  # drained: nothing raw to spill
    eng.ingest_async(w[30:], l[30:])  # fresh pipeline starts lazily
    eng.flush()
    stats = srv.stats()
    assert stats["pipeline"]["pending"] == 0
    assert stats["pipeline"]["dropped_batches"] == 0
    assert stats["pipeline"]["spilled_batches"] == 0
    assert stats["matches_ingested"] == 60
    eng.shutdown()


def test_stats_reports_absorbed_sentinel_counters_from_registry():
    """The sentinel/guard counters are ABSORBED into the registry and
    reported from it: the engine's warmup compile must show up in
    stats()["recompile_events"] AND in the registry counter/dump. The
    mutation audit carries a stats-drops-sentinel-counters mutant;
    this is its named kill."""
    srv = ArenaServer(
        num_players=P, max_staleness_matches=0, donation_sample_every=1
    )
    w, l = make_matches(100, seed=4)
    srv.engine.ingest(w, l)  # warmup compile -> one recompile event
    stats = srv.stats()
    assert stats["recompile_events"] >= 1
    assert stats["donation_calls"] >= 1
    reg = srv.obs.registry
    assert reg.counter("arena_recompile_events_total").value == (
        stats["recompile_events"]
    )
    assert stats["obs"]["counters"]["arena_recompile_events_total"] == (
        stats["recompile_events"]
    )
    # Re-reads never double-count (delta absorption).
    again = srv.stats()
    assert again["recompile_events"] == stats["recompile_events"]


def test_stats_is_one_json_line_with_query_latency_histogram():
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    w, l = make_matches(200, seed=5)
    srv.engine.ingest(w, l)
    srv.query(leaderboard=(0, 5), players=[0], pairs=[(0, 1)])
    line = json.dumps(srv.stats())  # must be JSON-serializable whole
    doc = json.loads(line)
    assert doc["queries"] == 1
    hist = doc["obs"]["histograms"]["arena_query_latency_seconds"]
    assert hist["count"] == 1 and hist["p99"] is not None
    assert "arena_query_staleness_matches" in doc["obs"]["histograms"]
    assert "serve.query" in {n for n, *_ in srv.obs.tracer.spans()}
    # Prometheus render of the same registry is non-empty and typed.
    assert "# TYPE arena_queries_total counter" in srv.obs.render()


def test_server_upgrades_null_engine_to_live_obs():
    eng = ArenaEngine(P)
    assert eng.obs is obs_pkg.NULL
    srv = ArenaServer(engine=eng)
    assert eng.obs is srv.obs and srv.obs.enabled
    assert eng._store._obs is srv.obs  # store rewired too
    # An explicit obs wins over everything.
    o = obs_pkg.Observability()
    srv2 = ArenaServer(num_players=P, obs=o)
    assert srv2.obs is o and srv2.engine.obs is o


# --- the pow2-padded bootstrap epoch (the recompile-source fix) ------------


def test_bootstrap_refreshes_are_compile_free_as_history_grows():
    """ROADMAP item 5's first half, pinned: with the pow2-padded epoch
    layout and the per-engine cached resampler, interval refreshes as
    history grows within a padded horizon add ZERO bootstrap compiles
    after the first — and the padding batches are rating no-ops (same
    samples as the tight layout would give for identical weights is
    NOT asserted; determinism and zero compiles are)."""
    eng = ArenaEngine(P)
    w, l = make_matches(3000, seed=6)
    eng.ingest(w[:1000], l[:1000])
    # Horizon covers the whole test: every refresh shares one shape.
    horizon = 8  # pow2 >= ceil(3000/512)
    s1 = eng.bootstrap_ratings(num_rounds=4, seed=0, batch_size=512,
                               min_batches=horizon)
    compiles_after_first = eng.num_bootstrap_compiles()
    assert compiles_after_first >= 1
    eng.ingest(w[1000:2000], l[1000:2000])
    eng.bootstrap_ratings(num_rounds=4, seed=0, batch_size=512,
                          min_batches=horizon)
    eng.ingest(w[2000:], l[2000:])
    s3 = eng.bootstrap_ratings(num_rounds=4, seed=0, batch_size=512,
                               min_batches=horizon)
    assert eng.num_bootstrap_compiles() == compiles_after_first, (
        "bootstrap recompiled as history grew inside the padded horizon"
    )
    assert s1.shape == (4, P) and s3.shape == (4, P)
    # Deterministic under a fixed seed at fixed history.
    s3b = eng.bootstrap_ratings(num_rounds=4, seed=0, batch_size=512,
                                min_batches=horizon)
    np.testing.assert_array_equal(s3, s3b)


def test_pack_epoch_pow2_padding_batches_are_rating_noops():
    """The padded epoch applies IDENTICAL ratings to the tight one:
    padding batches are fully invalid (valid == 0), so the epoch scan
    over them is a no-op."""
    import jax.numpy as jnp

    from arena import ratings as R
    from arena.engine import pack_epoch

    w, l = make_matches(700, seed=7)
    tight = pack_epoch(P, w, l, 256)
    padded = pack_epoch(P, w, l, 256, pad_batches_pow2=True, min_batches=8)
    assert tight.winners.shape[0] == 3
    assert padded.winners.shape[0] == 8
    assert float(padded.valid[3:].sum()) == 0.0
    fn = R.jit_elo_epoch(P, donate=False)
    r0 = jnp.full((P,), R.DEFAULT_BASE, jnp.float32)
    r_tight = fn(r0, tight.winners, tight.losers, tight.valid, tight.perms,
                 tight.bounds)
    r_pad = fn(r0, padded.winners, padded.losers, padded.valid, padded.perms,
               padded.bounds)
    np.testing.assert_array_equal(np.asarray(r_tight), np.asarray(r_pad))


# --- Prometheus exposition hardening (PR 13 satellite a) -------------------


def test_render_emits_help_and_type_lines():
    """Every exposed metric family leads with `# HELP` then `# TYPE`
    (the order Prometheus's parser requires), with the registered
    help text for known arena metrics and an honest default for ad-hoc
    ones."""
    reg = Registry()
    reg.counter("arena_ingest_matches_total").inc(3)
    reg.gauge("arena_test_depth").set(1)
    reg.histogram("arena_test_seconds").record(0.01)
    text = reg.render()
    lines = text.splitlines()
    for name, kind in [
        ("arena_ingest_matches_total", "counter"),
        ("arena_test_depth", "gauge"),
        ("arena_test_seconds", "histogram"),
    ]:
        help_idx = lines.index(
            next(l for l in lines if l.startswith(f"# HELP {name} "))
        )
        type_idx = lines.index(f"# TYPE {name} {kind}")
        assert help_idx == type_idx - 1, name
    # Known metrics get their registered help text; unknown ones get
    # the explicit no-help default rather than a fabricated one.
    assert (
        "# HELP arena_ingest_matches_total "
        "matches ingested into the CSR store" in text
    )
    assert "# HELP arena_test_depth arena metric (no help text" in text


def test_render_escapes_hostile_label_values():
    """Label values containing quotes, backslashes, and newlines are
    escaped per the Prometheus text format (\\\\ then \\" then \\n) so
    one hostile producer name cannot corrupt the whole exposition."""
    reg = Registry()
    reg.counter("arena_test_total", producer='ev"il\\x\np').inc(2)
    text = reg.render()
    assert 'arena_test_total{producer="ev\\"il\\\\x\\np"} 2' in text
    # Exactly the comment lines may start with '#'; every other line
    # must be a well-formed `name{labels} value` sample — the raw
    # newline would have produced a dangling `p"} 2` fragment line.
    for line in text.splitlines():
        assert line.startswith("#") or line.split()[0][0].isalpha(), line
