"""Tests for bench.py — the repo's only driver-facing runtime surface.

The driver contract: ``python bench.py`` prints exactly ONE JSON line on
stdout and exits 0, in every state the reference mount can be in (empty,
populated, missing, unreadable, or going stale mid-scan). There is no
reference workload to benchmark (the reference tree is empty — see
SURVEY.md / NON_GRAFTABLE.md), so these tests check honesty and
robustness of the reporting, not performance. Since round 3 the line
also embeds the fingerprint verification, which these tests pin down —
including that a broken verification can never break the contract.

No test skips under root: the permission-denied branch that chmod
cannot reach as root is exercised by monkeypatching os.access.
"""

import json
import os
import pathlib
import sys

import bench
import verify_reference

ALL_METRICS = {
    "non_graftable_reference_is_empty",
    "reference_tree_non_empty",
    "reference_mount_missing_or_unreadable",
    "reference_scan_error",
}


def run_main(monkeypatch, capsys, reference, repo):
    """In-process ``python bench.py`` with the contract asserted."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(reference))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(repo))
    # Pin the hygiene check's "not a git repo" state for fake repos even
    # when TMPDIR sits inside a checkout (see test_verify_reference).
    monkeypatch.setenv("GIT_CEILING_DIRECTORIES", str(pathlib.Path(repo).parent))
    rc = bench.main()
    captured = capsys.readouterr()
    assert rc == 0
    assert captured.err == ""
    return assert_line_contract(captured.out)


def assert_line_contract(stdout_text):
    """Exactly one JSON line with the documented keys."""
    lines = stdout_text.splitlines()
    assert len(lines) == 1
    assert stdout_text.endswith("\n")
    result = json.loads(lines[0])
    assert set(result) == {"metric", "value", "unit", "vs_baseline", "verification"}
    assert result["unit"] == "reference_entries"
    assert result["vs_baseline"] is None
    return result


def test_empty_reference(tmp_path, fake_repo, monkeypatch, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    result = run_main(monkeypatch, capsys, empty, fake_repo)
    assert result["metric"] == "non_graftable_reference_is_empty"
    assert result["value"] == 0
    verification = result["verification"]
    assert verification["exit_code"] == verify_reference.EXIT_MATCH
    assert verification["matches_fingerprint"] is True
    assert verification["drift"] == []
    # The human-facing explanation rides along so BENCH_r*.json
    # self-describes without the SKILL.md exit-code table.
    assert verification["note"] == "reference still empty; non-graftable verdict stands"
    # Hygiene field only appears when something is uncommitted (the fake
    # repo is not a git work tree, so the check degrades to null → omitted).
    assert "uncommitted_round_artifacts" not in verification


def test_populated_reference(tmp_path, fake_repo, monkeypatch, capsys):
    """A re-mounted non-empty reference must surface a non-zero count
    under a state-neutral metric name (not the *_is_empty one), with
    fingerprint drift and the manifest path embedded in the same line."""
    populated = tmp_path / "populated"
    (populated / "src").mkdir(parents=True)
    (populated / "src" / "main.cu").write_text("// not empty\n")
    (populated / "README.md").write_text("hello\n")
    result = run_main(monkeypatch, capsys, populated, fake_repo)
    assert result["metric"] == "reference_tree_non_empty"
    assert result["value"] == 3  # src/, src/main.cu, README.md
    verification = result["verification"]
    assert verification["exit_code"] == verify_reference.EXIT_DRIFT
    assert verification["matches_fingerprint"] is False
    assert verification["transient_environment_failure"] is False
    assert {d["fact"] for d in verification["drift"]} == {"reference_entry_count"}
    assert "DRIFT" in verification["note"]
    assert pathlib.Path(verification["manifest"]).read_text()  # manifest written
    # Shape classification rides along so BENCH_r*.json can never show
    # a VCS-metadata-only remount as a plain source tree.
    assert verification["manifest_shape"] == "working-tree"


def test_missing_reference(tmp_path, fake_repo, monkeypatch, capsys):
    result = run_main(monkeypatch, capsys, tmp_path / "does-not-exist", fake_repo)
    assert result["metric"] == "reference_mount_missing_or_unreadable"
    assert result["value"] == -1
    assert result["verification"]["exit_code"] == verify_reference.EXIT_TRANSIENT
    assert result["verification"]["transient_environment_failure"] is True


def test_reference_is_not_a_directory(tmp_path, fake_repo, monkeypatch, capsys):
    """bench's metric stays state-neutral (its job is observation, not
    verdict), while the embedded verification carries the gate's
    discrimination: a file AT the mount path is persistent drift
    (rc 1, type named), not a transient failure."""
    not_a_dir = tmp_path / "file"
    not_a_dir.write_text("x")
    result = run_main(monkeypatch, capsys, not_a_dir, fake_repo)
    assert result["metric"] == "reference_mount_missing_or_unreadable"
    assert result["value"] == -1
    verification = result["verification"]
    assert verification["exit_code"] == verify_reference.EXIT_DRIFT
    assert verification["transient_environment_failure"] is False
    assert verification["mount_type_error"].startswith("not a directory:")
    assert "NOT a directory" in verification["note"]


def test_unreadable_reference(tmp_path):
    """chmod 000 on the mount. As root the permission bits are bypassed
    (documented in SKILL.md) and the dir scans as empty — in that case
    this asserts the bypass behavior, and the denied branch itself is
    covered by test_access_denied_reference. Never skips."""
    locked = tmp_path / "locked"
    locked.mkdir()
    locked.chmod(0o000)
    try:
        result = bench.scan(locked)
        if os.access(locked, os.R_OK | os.X_OK):  # running as root
            assert result["metric"] == "non_graftable_reference_is_empty"
            assert result["value"] == 0
        else:
            assert result["metric"] == "reference_mount_missing_or_unreadable"
            assert result["value"] == -1
    finally:
        locked.chmod(0o755)


def test_access_denied_reference(tmp_path, monkeypatch):
    """The os.access()==False branch (bench.scan's accessibility gate),
    unreachable via chmod when the suite runs as root."""
    monkeypatch.setattr(os, "access", lambda *args, **kwargs: False)
    result = bench.scan(tmp_path)
    assert result["metric"] == "reference_mount_missing_or_unreadable"
    assert result["value"] == -1


def test_scan_error_mid_iteration(tmp_path, monkeypatch):
    """An OSError partway through the walk (stale mount, unreadable
    subtree) maps to a distinct metric instead of a traceback or a
    silent undercount. The failure is injected at the os.scandir layer
    that the real walk uses, so this exercises bench's actual error
    propagation — pathlib.rglob would have swallowed the error, which
    is why bench does not use it."""
    (tmp_path / "ok").mkdir()
    bad = tmp_path / "bad"
    bad.mkdir()
    real_scandir = os.scandir

    def flaky_scandir(path=".", *args, **kwargs):
        if pathlib.Path(path) == bad:
            raise OSError("mount went stale mid-iteration")
        return real_scandir(path, *args, **kwargs)

    monkeypatch.setattr(os, "scandir", flaky_scandir)
    result = bench.scan(tmp_path)
    assert result["metric"] == "reference_scan_error"
    assert result["value"] == -1


def test_stat_error_during_access_check(tmp_path, monkeypatch):
    """is_dir() itself raising OSError maps to missing_or_unreadable."""

    def broken_is_dir(self):
        raise OSError("stale file handle")

    monkeypatch.setattr(pathlib.Path, "is_dir", broken_is_dir)
    result = bench.scan(tmp_path)
    assert result["metric"] == "reference_mount_missing_or_unreadable"
    assert result["value"] == -1


def test_broken_verification_cannot_break_contract(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The embedded verification is best-effort: if verify() itself
    blows up, bench must still print its one line and exit 0, with the
    failure visible as an error field rather than a traceback."""

    def boom(*args, **kwargs):
        raise RuntimeError("verification exploded")

    monkeypatch.setattr(verify_reference, "verify", boom)
    empty = tmp_path / "empty"
    empty.mkdir()
    result = run_main(monkeypatch, capsys, empty, fake_repo)
    assert result["metric"] == "non_graftable_reference_is_empty"
    assert result["verification"] == {
        "error": "verification_unavailable",
        "detail": "RuntimeError: verification exploded",
    }


def test_unexpected_crash_degrades_to_error_metric(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Anything escaping scan()'s own guards must degrade to the distinct
    bench_internal_error metric — one JSON line, rc 0, the crash visible
    in an error field — never a nonzero exit with zero JSON lines
    (breaking the driver contract) and never a report shaped like an
    authoritative empty tree."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(tmp_path / "ref"))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(fake_repo))

    def boom(reference):
        raise RuntimeError("unexpected bench bug")

    monkeypatch.setattr(bench, "scan", boom)
    rc = bench.main()
    captured = capsys.readouterr()
    assert rc == 0
    assert captured.err == ""
    lines = captured.out.splitlines()
    assert len(lines) == 1
    result = json.loads(lines[0])
    assert result == {
        "metric": "bench_internal_error",
        "value": -1,
        "unit": "reference_entries",
        "vs_baseline": None,
        "error": "RuntimeError: unexpected bench bug",
    }


def test_unserializable_result_degrades_to_literal_error_line(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A result json.dumps cannot serialize is a crash like any other:
    the fallback line (built from literals) must still satisfy the
    one-line/rc-0 contract with the failure visible."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(tmp_path / "ref"))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(fake_repo))
    monkeypatch.setattr(
        bench,
        "scan",
        lambda reference: {
            "metric": "non_graftable_reference_is_empty",
            "value": 0,
            "unit": "reference_entries",
            "vs_baseline": object(),  # json.dumps chokes on this
        },
    )
    rc = bench.main()
    captured = capsys.readouterr()
    assert rc == 0
    lines = captured.out.splitlines()
    assert len(lines) == 1
    result = json.loads(lines[0])
    assert result["metric"] == "bench_internal_error"
    assert result["value"] == -1
    assert result["error"].startswith("TypeError")


def test_broken_stdout_exits_nonzero_never_silent_success(
    tmp_path, fake_repo, monkeypatch
):
    """When stdout itself is unwritable no JSON line is physically
    possible; bench must exit nonzero (the documented single exception
    to rc 0) rather than report success with empty output."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(tmp_path / "ref"))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(fake_repo))

    def broken_write(*args, **kwargs):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(sys.stdout, "write", broken_write)
    assert bench.main() == 1


def test_buffered_write_failure_exits_nonzero_inside_the_guard(
    tmp_path, fake_repo, monkeypatch
):
    """With a block-buffered stdout (file/pipe) a doomed write lands in
    the buffer and print() returns happily; the failure only surfaces
    at flush. bench flushes INSIDE its guard so that failure is ITS
    rc 1, not CPython's interpreter-exit status 120 (which is outside
    bench's documented contract)."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(tmp_path / "ref"))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(fake_repo))
    failures = iter([OSError(28, "No space left on device")])

    def deferred_failure():
        # Raise exactly once — for the flush bench itself performs.
        # pytest's capture machinery flushes this same stdout object
        # again during teardown, before the monkeypatch is undone, and
        # a second raise there would fail the test from the outside.
        for exc in failures:
            raise exc

    monkeypatch.setattr(sys.stdout, "flush", deferred_failure)
    assert bench.main() == 1


def test_failed_write_never_appends_to_a_partial_line(
    tmp_path, fake_repo, monkeypatch
):
    """Once a write of the result line has been attempted and failed,
    stdout may hold a PARTIAL line — bench must not write anything
    more (a fallback appended to the fragment would exit 0 with one
    unparseable line, a masquerade worse than silence)."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(tmp_path / "ref"))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(fake_repo))
    writes = []

    def bursting_write(s):
        writes.append(s)  # the fragment "reached" the pipe...
        raise OSError(32, "Broken pipe")  # ...then the write failed

    monkeypatch.setattr(sys.stdout, "write", bursting_write)
    assert bench.main() == 1
    # print(line) attempts write(line) first and dies there; the
    # trailing-newline write and any fallback must never follow.
    assert len(writes) == 1
    assert writes[0].startswith('{"metric"')


def test_exception_with_raising_str_still_degrades_cleanly(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """exc_detail runs inside every degradation path, so an exception
    whose own __str__ raises must not cascade: the fallback line must
    still print (rc 0, one line) with the class name preserved."""

    class EvilError(Exception):
        def __str__(self):
            raise RuntimeError("__str__ is broken too")

    assert (
        bench.exc_detail(EvilError())
        == "EvilError: <exception message unavailable: __str__ raised>"
    )

    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(tmp_path / "ref"))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(fake_repo))

    def boom(reference):
        raise EvilError()

    monkeypatch.setattr(bench, "scan", boom)
    rc = bench.main()
    captured = capsys.readouterr()
    assert rc == 0
    lines = captured.out.splitlines()
    assert len(lines) == 1
    result = json.loads(lines[0])
    assert result["metric"] == "bench_internal_error"
    assert result["error"].startswith("EvilError")


def test_verification_summary_reinserts_repo_dir_into_sys_path(
    tmp_path, fake_repo, monkeypatch
):
    """The lazy-import arm that restores the repo dir to sys.path —
    needed when bench runs as a script from a foreign cwd and nothing
    else has made verify_reference importable."""
    ref = tmp_path / "ref"
    ref.mkdir()
    scan_result = bench.scan(ref)
    monkeypatch.setattr(
        sys, "path", [p for p in sys.path if p != str(bench._REPO_DIR)]
    )
    # Drop the cached module too, so the lazy import genuinely resolves
    # through the inserted path instead of a sys.modules cache hit —
    # otherwise a broken insert would go unnoticed.
    monkeypatch.delitem(sys.modules, "verify_reference", raising=False)
    summary = bench.verification_summary(ref, fake_repo, scan_result)
    assert str(bench._REPO_DIR) in sys.path
    assert summary["exit_code"] == verify_reference.EXIT_MATCH


def test_fingerprint_corrupt_surfaces_in_verification(
    tmp_path, fake_repo, monkeypatch, capsys
):
    (fake_repo / "reference_fingerprint.json").write_text("{not json")
    empty = tmp_path / "empty"
    empty.mkdir()
    result = run_main(monkeypatch, capsys, empty, fake_repo)
    verification = result["verification"]
    assert verification["exit_code"] == verify_reference.EXIT_FINGERPRINT_CORRUPT
    assert verification["error"] == "fingerprint_missing_or_corrupt"
    assert "repo bug" in verification["note"]


def test_manifest_error_surfaces_in_bench_line(
    tmp_path, fake_repo, deny_manifest_write, monkeypatch, capsys
):
    """A failed manifest write during a drift event must leave a trace in
    the bench line (the one artifact the driver provably records), not
    vanish silently."""
    populated = tmp_path / "populated"
    (populated / "src").mkdir(parents=True)
    result = run_main(monkeypatch, capsys, populated, fake_repo)
    verification = result["verification"]
    assert verification["exit_code"] == verify_reference.EXIT_DRIFT
    assert "manifest" not in verification
    assert verification["manifest_error"] == "OSError: read-only file system"


def test_unreadable_sidecar_surfaces_as_transient_in_bench_line(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """bench's embedded verification must carry the same sidecar
    transient discipline as the gate: an unreadable sidecar shows exit
    code 3 with the read-failure detail, never a false match or false
    drift — while bench's own one-line/rc-0 contract holds."""
    empty = tmp_path / "empty"
    empty.mkdir()
    real_os_open = os.open

    def deny(target, *args, **kwargs):
        if pathlib.Path(target).name == "PAPERS.md":
            raise PermissionError(13, "Permission denied")
        return real_os_open(target, *args, **kwargs)

    monkeypatch.setattr(os, "open", deny)
    result = run_main(monkeypatch, capsys, empty, fake_repo)
    assert result["metric"] == "non_graftable_reference_is_empty"
    verification = result["verification"]
    assert verification["exit_code"] == verify_reference.EXIT_TRANSIENT
    assert verification["matches_fingerprint"] is False
    assert verification["transient_environment_failure"] is True
    assert verification["sidecar_errors"]["papers_md_sha256"].startswith(
        "PermissionError"
    )
    assert "TRANSIENT" in verification["note"]


def test_uncommitted_round_artifacts_surface_in_bench_line(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """When the hygiene check finds uncommitted driver artifacts, they
    ride along in the bench line — the one artifact provably recorded
    every round."""
    import subprocess

    subprocess.run(
        ["git", "-C", str(fake_repo), "init", "-q"], check=True, capture_output=True
    )
    (fake_repo / "BENCH_r09.json").write_text("{}\n")
    empty = tmp_path / "empty"
    empty.mkdir()
    result = run_main(monkeypatch, capsys, empty, fake_repo)
    assert "BENCH_r09.json" in result["verification"]["uncommitted_round_artifacts"]


def test_e2e_real_mount_contract(e2e):
    """Against the real configured mount, via the driver's exact
    invocation (plain ``python bench.py`` from a foreign cwd), the
    contract holds and the metric is one of the documented ones."""
    run = e2e["bench_real"]
    assert run.rc == 0
    assert run.err == ""
    result = assert_line_contract(run.out)
    assert result["metric"] in ALL_METRICS
    assert "verification" in result


def test_e2e_populated_reference(e2e):
    """End-to-end subprocess run against a populated mount: state-neutral
    metric, drift in the embedded verification, manifest written —
    all through the real argv/env/stdout plumbing."""
    run = e2e["bench_populated"]
    assert run.rc == 0
    assert run.err == ""
    result = assert_line_contract(run.out)
    assert result["metric"] == "reference_tree_non_empty"
    assert result["value"] == 3
    assert result["verification"]["exit_code"] == verify_reference.EXIT_DRIFT
    assert (run.repo / verify_reference.MANIFEST_NAME).exists()


def test_exc_detail_empty_message_falls_back_to_class_name():
    """str(exc) can be empty (bare OSError()); the detail must still
    name the class instead of degrading to 'ClassName: '."""
    assert bench.exc_detail(OSError()) == "OSError"
    assert bench.exc_detail(OSError(5, "Input/output error")).startswith(
        "OSError: "
    )
    assert len(bench.exc_detail(ValueError("x" * 1000))) <= 200
