"""jaxlint corpus: timing asynchronous dispatch without blocking.

JAX dispatch is asynchronous — the second clock read happens while the
device is still computing, so `elapsed` measures dispatch overhead,
not the work. Rule: timing-without-block."""

import time

import jax.numpy as jnp


def time_epoch(x):
    t0 = time.perf_counter()
    y = jnp.dot(x, x)
    elapsed = time.perf_counter() - t0
    return y, elapsed
