"""Path-sensitive lifecycle/resource protocol analyzer (jaxlint v4).

The `# protocol:` comment on a class header (see
`arena.analysis.project.parse_protocols`) declares its resource
protocol: `# protocol: stage->release` means every `stage()` call
creates an obligation discharged by `release()`; `# protocol: close`
means `close()` is terminal — method calls on the object after it are
use-after-close. This module runs a typestate analysis over the
exception-edge CFG (`arena.analysis.cfg`) for every function, tracking
obligations and terminal states along BOTH edge kinds, and registers
four rules on the result:

- ``resource-leaked-on-exception``: an obligation reaches function
  exit (normal or exceptional) with no release and no ownership
  transfer (returned / yielded / stored on self).
- ``missing-finally-for-paired-call``: the function DOES release, but
  only on the fall-through path — an exception between acquire and
  release leaks. (The release-in-a-finally shape is clean because the
  finally copy sits on both edge kinds.)
- ``lock-held-across-raise``: a manual ``lock.acquire()`` (the kind
  `with` would have scoped) escaped by a raise before ``release()``.
  Composes with PR 10's lock rules, which see `with`-held locks only.
- ``use-after-close``: a method call on an object on some path after
  its terminal lifecycle method.

Semantics that keep the clean tree clean (and honest):

- On an EXCEPTION edge the out-state applies releases/closes/kills but
  never acquires: a call that raised never completed, so it acquired
  nothing — and a `release()` line's own exception edge does not
  un-release what the finally already handled.
- Ownership transfer: an acquire under a `return`/`yield`, assigned to
  a `self.` attribute, or bound to a name that escapes that way, is
  the CALLER's obligation — not tracked here.
- A class's own protocol methods (and `__enter__`/`__exit__`/
  `__del__`) are exempt: the body of `close()` is precisely where
  "unpaired" calls are the implementation.
- One interprocedural hop (same depth as the lock-order and taint
  analyzers): a release inside a same-class method or a same-module /
  imported helper the symbol table resolves is credited at the call
  site.

Type binding is heuristic, like everything in jaxlint: `self` binds to
the enclosing class; `self.attr = Ctor()` anywhere in the class binds
the attribute; `name = Ctor()` / `name = self.attr` bind locals. A
constructor name that resolves to nothing still TAIL-matches a
protocol-declaring class if the tail is unique project-wide (covers
dynamically-imported module handles like `self._ingest_mod.X(...)`).
Untypeable receivers produce no events — no claim, no false positive.
"""

from __future__ import annotations

import ast
import itertools

from arena.analysis.cfg import (
    EDGE_NORMAL,
    K_STMT,
    build_cfg,
)
from arena.analysis.jaxlint import rule
from arena.analysis.project import LOCK_FACTORY_TAILS, dotted

RULE_LEAK = "resource-leaked-on-exception"
RULE_USE_AFTER_CLOSE = "use-after-close"
RULE_LOCK_RAISE = "lock-held-across-raise"
RULE_MISSING_FINALLY = "missing-finally-for-paired-call"

_RULE_NAMES = (RULE_LEAK, RULE_USE_AFTER_CLOSE, RULE_LOCK_RAISE,
               RULE_MISSING_FINALLY)

_ALWAYS_EXEMPT = {"__enter__", "__exit__", "__del__"}


class _Obligation:
    __slots__ = ("oid", "key", "cls", "acquire", "release", "node", "kind")

    def __init__(self, oid, key, cls, acquire, release, node, kind):
        self.oid = oid
        self.key = key          # dotted receiver, e.g. "self._staging"
        self.cls = cls          # ClassSymbols or None (locks)
        self.acquire = acquire  # method name that opened it
        self.release = release  # method name that discharges it
        self.node = node        # the acquiring ast.Call
        self.kind = kind        # "pair" | "lock"


def _iter_functions(tree):
    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from walk(child, cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child)
            else:
                yield from walk(child, cls)

    yield from walk(tree, None)


def _scope_walk(scope):
    """ast.walk confined to one scope (no nested defs/classes)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


def _release_methods(cls_sym) -> set:
    return {b for _a, b in cls_sym.protocol_pairs}


def _acquire_methods(cls_sym) -> dict:
    return {a: b for a, b in cls_sym.protocol_pairs}


def _target_keys(tgt):
    """Dotted keys a binding target (re)binds — Tuple/List unpacked,
    inner expressions NOT walked (so `self.x = ...` kills `self.x`,
    never `self`)."""
    if isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _target_keys(elt)
        return
    if isinstance(tgt, ast.Starred):
        yield from _target_keys(tgt.value)
        return
    key = dotted(tgt)
    if key is not None:
        yield key


def _eval_order_exprs(stmt):
    """A statement's own expression roots in (approximate) evaluation
    order — value before targets for assignments, header expressions
    only for compound statements."""
    if isinstance(stmt, ast.Assign):
        return [stmt.value]
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Assert):
        return [e for e in (stmt.test, stmt.msg) if e is not None]
    roots = []
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers", "cases"):
            continue
        if isinstance(value, ast.AST):
            roots.append(value)
        elif isinstance(value, list):
            roots.extend(v for v in value if isinstance(v, ast.AST))
    return roots


class _ModuleLifecycle:
    """One module's lifecycle pass: per-function CFG + typestate
    fixpoint, findings bucketed per rule."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.findings = {name: [] for name in _RULE_NAMES}
        self._seen = set()
        self._oid_counter = itertools.count()
        self._attr_types_cache = {}
        # Tail name -> [ClassSymbols] over every protocol-declaring
        # class the project table can see (the ctor tail-match pool).
        self._protocol_index = {}
        mods = (ctx.project.modules.values() if ctx.project is not None
                else [ctx.symbols])
        for mod in mods:
            for cls in mod.classes.values():
                if cls.has_protocols():
                    self._protocol_index.setdefault(cls.name, []).append(cls)

    def run(self):
        ctx = self.ctx
        for fn_node, cls_node in _iter_functions(ctx.tree):
            if ctx.is_traced_def(fn_node):
                continue
            self._analyze_function(fn_node, cls_node)
        return self

    # -- type binding -------------------------------------------------------

    def _resolve_ctor(self, call):
        """ClassSymbols the constructor call builds, or None."""
        fname = dotted(call.func)
        if not fname:
            return None
        sym = self.ctx.symbols
        if fname in sym.classes:
            return sym.classes[fname]
        project = self.ctx.project
        parts = fname.split(".")
        if project is not None:
            for i in range(len(parts), 0, -1):
                head = ".".join(parts[:i])
                if head not in sym.imports:
                    continue
                src_name, symbol = sym.imports[head]
                rest = parts[i:]
                if symbol is not None:
                    rest = [symbol] + rest
                src = project.module(src_name)
                if src is None and rest:
                    src = project.module(f"{src_name}.{rest[0]}")
                    rest = rest[1:]
                if src is not None and len(rest) == 1 and rest[0] in src.classes:
                    return src.classes[rest[0]]
        candidates = self._protocol_index.get(parts[-1], [])
        if len(candidates) == 1:
            return candidates[0]
        return None

    def _attr_types(self, cls_node):
        """attr name -> ClassSymbols for `self.X = Ctor()` assignments
        anywhere in the class body."""
        cached = self._attr_types_cache.get(id(cls_node))
        if cached is not None:
            return cached
        out = {}
        for sub in ast.walk(cls_node):
            if not (isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)):
                continue
            cls = self._resolve_ctor(sub.value)
            if cls is None:
                continue
            for tgt in sub.targets:
                key = dotted(tgt)
                if key and key.startswith("self.") and key.count(".") == 1:
                    out[key.split(".", 1)[1]] = cls
        self._attr_types_cache[id(cls_node)] = out
        return out

    def _local_bindings(self, fn_node):
        """(name -> ClassSymbols, local lock names) from one linear
        pass over the function's own statements."""
        types, locks = {}, set()
        attr_types = (self._attr_types(self._cls_node)
                      if self._cls_node is not None else {})
        for node in _scope_walk(fn_node):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            bound_cls = None
            is_lock = False
            if isinstance(value, ast.Call):
                fname = dotted(value.func)
                if fname and fname.split(".")[-1] in LOCK_FACTORY_TAILS:
                    is_lock = True
                else:
                    bound_cls = self._resolve_ctor(value)
            else:
                vname = dotted(value)
                if vname is None:
                    pass
                elif vname.startswith("self.") and vname.count(".") == 1:
                    bound_cls = attr_types.get(vname.split(".", 1)[1])
                elif vname in types:
                    bound_cls = types[vname]
                elif vname in locks:
                    is_lock = True
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if bound_cls is not None:
                        types[tgt.id] = bound_cls
                    elif is_lock:
                        locks.add(tgt.id)
        return types, locks

    def _key_class(self, key):
        """ClassSymbols a dotted receiver key binds to, or None."""
        if key == "self":
            return self._self_cls
        if key.startswith("self.") and key.count(".") == 1:
            if self._cls_node is None:
                return None
            return self._attr_types(self._cls_node).get(key.split(".", 1)[1])
        if "." not in key:
            return self._local_types.get(key)
        return None

    def _is_lock(self, key):
        if key.startswith("self.") and key.count(".") == 1:
            cls_sym = self._cls_sym
            return (cls_sym is not None
                    and key.split(".", 1)[1] in cls_sym.lock_attrs)
        if "." not in key:
            return (key in self._local_locks
                    or key in self.ctx.symbols.module_locks)
        return False

    # -- ownership transfer -------------------------------------------------

    def _escaping_names(self, fn_node):
        """Names whose value leaves the function: returned, yielded, or
        stored on self."""
        out = set()

        def add_expr(expr):
            if isinstance(expr, ast.Name):
                out.add(expr.id)
            elif isinstance(expr, (ast.Tuple, ast.List)):
                for elt in expr.elts:
                    add_expr(elt)

        for node in _scope_walk(fn_node):
            if isinstance(node, ast.Return) and node.value is not None:
                add_expr(node.value)
            elif isinstance(node, (ast.Yield, ast.YieldFrom)):
                if node.value is not None:
                    add_expr(node.value)
            elif isinstance(node, ast.Assign):
                if any(
                    (dotted(t) or "").startswith("self.")
                    for t in node.targets
                ):
                    add_expr(node.value)
        return out

    def _transferred(self, call):
        """Is this acquire's result handed to the caller / object state
        (so the obligation is not this function's to discharge)? Two
        shapes: the call's RESULT escapes (returned / yielded / stored
        on self / bound to an escaping name), or the RECEIVER itself is
        an escaping local (`r.stage(b); ...; return r` — the factory
        idiom hands the half-open object, obligation and all, to the
        caller)."""
        if isinstance(call.func, ast.Attribute):
            recv = dotted(call.func.value)
            if recv is not None and "." not in recv and recv in self._escaping:
                return True
        node = call
        while True:
            parent = self._parents.get(id(node))
            if parent is None:
                return False
            if isinstance(parent, (ast.Yield, ast.YieldFrom)):
                return True
            if isinstance(parent, ast.stmt):
                break
            node = parent
        if isinstance(parent, ast.Return):
            return True
        if isinstance(parent, ast.Assign):
            for tgt in parent.targets:
                key = dotted(tgt)
                if key is None:
                    continue
                if key.startswith("self."):
                    return True
                if key in self._escaping:
                    return True
        return False

    # -- one-hop helper credit ----------------------------------------------

    def _resolve_function(self, fname):
        sym = self.ctx.symbols
        if fname in sym.functions:
            return sym.functions[fname]
        project = self.ctx.project
        if project is None:
            return None
        parts = fname.split(".")
        for i in range(len(parts), 0, -1):
            head = ".".join(parts[:i])
            if head not in sym.imports:
                continue
            src_name, symbol = sym.imports[head]
            rest = parts[i:]
            if symbol is not None:
                rest = [symbol] + rest
            src = project.module(src_name)
            if src is None and rest:
                src = project.module(f"{src_name}.{rest[0]}")
                rest = rest[1:]
            if src is not None and len(rest) == 1 and rest[0] in src.functions:
                return src.functions[rest[0]]
        return None

    def _helper_released_keys(self, call, fname):
        """Caller keys a one-hop callee releases: `self.M()` scanning M
        for `self.attr.release()`-shaped calls, plus param-matched
        releases for tracked objects passed positionally."""
        parts = fname.split(".")
        callee = None
        same_class = False
        if parts[0] == "self" and len(parts) == 2 and self._cls_node is not None:
            for item in self._cls_node.body:
                if (isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and item.name == parts[1]):
                    callee = item
                    same_class = True
                    break
        elif len(parts) == 1:
            callee = self._resolve_function(fname)
        if callee is None:
            return set()
        keys = set()
        if same_class:
            attr_types = self._attr_types(self._cls_node)
            for node in _scope_walk(callee):
                if not isinstance(node, ast.Call):
                    continue
                cf = dotted(node.func)
                if not cf or not cf.startswith("self.") or cf.count(".") != 2:
                    continue
                _self, attr, meth = cf.split(".")
                tcls = attr_types.get(attr)
                if tcls is not None and (
                    meth in _release_methods(tcls)
                    or meth in tcls.protocol_terminal
                ):
                    keys.add(f"self.{attr}")
                if (meth == "release" and self._cls_sym is not None
                        and attr in self._cls_sym.lock_attrs):
                    keys.add(f"self.{attr}")
        params = [a.arg for a in callee.args.posonlyargs + callee.args.args]
        if params and params[0] == "self":
            params = params[1:]
        argmap = {}
        for pname, argexpr in zip(params, call.args):
            k = dotted(argexpr)
            if k is None:
                continue
            tcls = self._key_class(k)
            if tcls is not None or self._is_lock(k):
                argmap[pname] = (k, tcls)
        if argmap:
            for node in _scope_walk(callee):
                if not isinstance(node, ast.Call):
                    continue
                cf = dotted(node.func)
                if not cf or "." not in cf:
                    continue
                root, meth = cf.rsplit(".", 1)
                if root not in argmap:
                    continue
                key, tcls = argmap[root]
                if tcls is not None:
                    if (meth in _release_methods(tcls)
                            or meth in tcls.protocol_terminal):
                        keys.add(key)
                elif meth == "release":
                    keys.add(key)
        return keys

    # -- events ---------------------------------------------------------------

    def _call_events(self, call, events):
        fname = dotted(call.func)
        if fname is None:
            return
        if "." in fname:
            recv, meth = fname.rsplit(".", 1)
        else:
            recv, meth = None, fname
        if recv is not None:
            cls_sym = self._key_class(recv)
            if cls_sym is not None and cls_sym.has_protocols():
                acquires = _acquire_methods(cls_sym)
                if meth in acquires:
                    if not self._transferred(call):
                        obl = _Obligation(
                            next(self._oid_counter), recv, cls_sym, meth,
                            acquires[meth], call, "pair",
                        )
                        self._obls[obl.oid] = obl
                        events.append(("acq", obl.oid, recv))
                    return
                if meth in _release_methods(cls_sym):
                    events.append(("rel", recv))
                    return
                if meth in cls_sym.protocol_terminal:
                    events.append(("close", recv))
                    return
                if cls_sym.protocol_terminal:
                    events.append(("use", recv, meth, call, cls_sym))
                return
            if meth in ("acquire", "release") and self._is_lock(recv):
                if meth == "acquire":
                    obl = _Obligation(
                        next(self._oid_counter), recv, None, "acquire",
                        "release", call, "lock",
                    )
                    self._obls[obl.oid] = obl
                    events.append(("acq", obl.oid, recv))
                else:
                    events.append(("rel", recv))
                return
        for key in sorted(self._helper_released_keys(call, fname)):
            events.append(("helper-rel", key))

    def _stmt_events(self, stmt):
        cached = self._events_cache.get(id(stmt))
        if cached is not None:
            return cached
        events = []

        def visit(node):
            if isinstance(node, (ast.Lambda, ast.GeneratorExp)):
                return  # lazy bodies don't execute at this statement
            for child in ast.iter_child_nodes(node):
                visit(child)
            if isinstance(node, ast.Call):
                self._call_events(node, events)

        for root in _eval_order_exprs(stmt):
            visit(root)
        killed = []
        if isinstance(stmt, ast.Assign):
            killed = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            killed = [stmt.target]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            killed = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            killed = stmt.targets
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            killed = [i.optional_vars for i in stmt.items
                      if i.optional_vars is not None]
        for tgt in killed:
            for key in _target_keys(tgt):
                events.append(("kill", key))
        events = tuple(events)
        self._events_cache[id(stmt)] = events
        return events

    def _node_events(self, node):
        stmt = node.stmt
        if (node.kind != K_STMT or stmt is None
                or not isinstance(stmt, ast.stmt)):
            return ()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return ()
        return self._stmt_events(stmt)

    # -- the transfer function ------------------------------------------------

    def _apply(self, events, state, normal):
        open_, closed = set(state[0]), set(state[1])
        for ev in events:
            tag = ev[0]
            if tag == "acq":
                # A call that raised never completed: its acquire did
                # not happen on the exception edge.
                if normal:
                    open_.add(ev[1])
            elif tag in ("rel", "helper-rel"):
                key = ev[1]
                open_ = {o for o in open_ if self._obls[o].key != key}
            elif tag == "close":
                key = ev[1]
                closed.add(key)
                open_ = {o for o in open_ if self._obls[o].key != key}
            elif tag == "kill":
                key = ev[1]
                closed.discard(key)
                open_ = {o for o in open_ if self._obls[o].key != key}
        return (frozenset(open_), frozenset(closed))

    # -- per-function analysis ------------------------------------------------

    def _exempt(self, fn_node):
        if fn_node.name in _ALWAYS_EXEMPT:
            return True
        cls_sym = self._cls_sym
        if cls_sym is not None and fn_node.name in cls_sym.protocol_methods():
            # close()/release() bodies are where "unpaired" calls ARE
            # the implementation.
            return True
        return False

    def _analyze_function(self, fn_node, cls_node):
        self._cls_node = cls_node
        self._cls_sym = (self.ctx.symbols.classes.get(cls_node.name)
                         if cls_node is not None else None)
        self._self_cls = self._cls_sym
        if self._exempt(fn_node):
            return
        self._local_types, self._local_locks = self._local_bindings(fn_node)
        self._escaping = self._escaping_names(fn_node)
        self._parents = {
            id(child): parent
            for parent in ast.walk(fn_node)
            for child in ast.iter_child_nodes(parent)
        }
        self._obls = {}
        self._events_cache = {}
        cfg = build_cfg(fn_node)
        events = [self._node_events(n) for n in cfg.nodes]
        if not self._obls and not any(
            ev and any(e[0] in ("use", "close") for e in ev) for ev in events
        ):
            return  # nothing tracked — skip the fixpoint
        bottom = None
        in_states = [bottom] * len(cfg.nodes)
        in_states[cfg.entry_idx] = (frozenset(), frozenset())
        work = [cfg.entry_idx]
        while work:
            idx = work.pop()
            state = in_states[idx]
            outs = {}
            for succ, kind in cfg.nodes[idx].succs:
                out = outs.get(kind)
                if out is None:
                    out = self._apply(events[idx], state, kind == EDGE_NORMAL)
                    outs[kind] = out
                prev = in_states[succ]
                merged = out if prev is None else (
                    prev[0] | out[0], prev[1] | out[1]
                )
                if merged != prev:
                    in_states[succ] = merged
                    work.append(succ)
        self._report(fn_node, cfg, events, in_states)

    def _report(self, fn_node, cfg, events, in_states):
        # use-after-close: replay each node's events from its in-state.
        for node in cfg.nodes:
            evs = events[node.idx]
            if not evs or in_states[node.idx] is None:
                continue
            if not any(e[0] == "use" for e in evs):
                continue
            state = in_states[node.idx]
            closed = set(state[1])
            for ev in evs:
                if ev[0] == "use":
                    _tag, key, meth, call, cls_sym = ev
                    if key in closed:
                        term = sorted(cls_sym.protocol_terminal)[0]
                        self._emit(
                            RULE_USE_AFTER_CLOSE, call,
                            f"`{key}.{meth}()` may run after terminal "
                            f"`{key}.{term}()` — {cls_sym.name}'s "
                            f"lifecycle ends at `{term}()`",
                        )
                elif ev[0] == "close":
                    closed.add(ev[1])
                elif ev[0] == "kill":
                    closed.discard(ev[1])
        # leaks at the two exits.
        exit_state = in_states[cfg.exit_idx]
        raise_state = in_states[cfg.raise_idx]
        leak_normal = set(exit_state[0]) if exit_state is not None else set()
        leak_exc = set(raise_state[0]) if raise_state is not None else set()
        released_keys = {
            ev[1]
            for evs in events
            for ev in evs
            if ev[0] in ("rel", "helper-rel", "close")
        }
        for oid in sorted(leak_normal | leak_exc):
            obl = self._obls[oid]
            if obl.kind == "lock":
                if oid in leak_exc and oid not in leak_normal:
                    self._emit(
                        RULE_LOCK_RAISE, obl.node,
                        f"`{obl.key}.acquire()` in `{fn_node.name}` can be "
                        f"escaped by a raise before `{obl.key}.release()` — "
                        f"use `with {obl.key}:` or release in a finally",
                    )
                continue
            pair = f"{obl.acquire}->{obl.release}"
            if oid in leak_normal:
                self._emit(
                    RULE_LEAK, obl.node,
                    f"`{obl.key}.{obl.acquire}()` opens a {obl.cls.name} "
                    f"{pair} obligation that reaches the exit of "
                    f"`{fn_node.name}` with no `{obl.release}()` and no "
                    "ownership transfer",
                )
            elif obl.key in released_keys:
                self._emit(
                    RULE_MISSING_FINALLY, obl.node,
                    f"`{obl.key}.{obl.release}()` pairs with "
                    f"`{obl.key}.{obl.acquire}()` only on the fall-through "
                    f"path of `{fn_node.name}` — an exception between them "
                    f"leaks the {obl.cls.name}; move the release into a "
                    "finally",
                )
            else:
                self._emit(
                    RULE_LEAK, obl.node,
                    f"`{obl.key}.{obl.acquire}()` opens a {obl.cls.name} "
                    f"{pair} obligation with no reachable `{obl.release}()` "
                    f"on the exceptional paths out of `{fn_node.name}`",
                )

    def _emit(self, rule_name, node, message):
        key = (rule_name, node.lineno, node.col_offset)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings[rule_name].append(
            self.ctx.finding(node, rule_name, message)
        )


def _analysis(ctx):
    cached = getattr(ctx, "_lifecycle_findings", None)
    if cached is None:
        cached = _ModuleLifecycle(ctx).run().findings
        ctx._lifecycle_findings = cached
    return cached


# --- the four v4 rules -------------------------------------------------------


@rule(
    RULE_LEAK,
    "an acquired resource (a class's `# protocol: a->b` obligation) reaches "
    "function exit — normal or exceptional — with no release and no "
    "ownership transfer",
    severity="error",
)
def _check_resource_leak(ctx):
    yield from _analysis(ctx)[RULE_LEAK]


@rule(
    RULE_USE_AFTER_CLOSE,
    "a method call on an object on some path after its terminal lifecycle "
    "method (`# protocol: close`) — the object is dead at that point",
    severity="error",
)
def _check_use_after_close(ctx):
    yield from _analysis(ctx)[RULE_USE_AFTER_CLOSE]


@rule(
    RULE_LOCK_RAISE,
    "a manually-paired lock.acquire() escaped by a raise before release() — "
    "the shape `with lock:` would have scoped; composes with the PR 10 "
    "lock rules, which only see with-held locks",
    severity="error",
)
def _check_lock_held_across_raise(ctx):
    yield from _analysis(ctx)[RULE_LOCK_RAISE]


@rule(
    RULE_MISSING_FINALLY,
    "an acquire/release pair whose release is reachable only on the "
    "fall-through path — an exception between the calls leaks; the release "
    "belongs in a finally",
    severity="warning",
)
def _check_missing_finally(ctx):
    yield from _analysis(ctx)[RULE_MISSING_FINALLY]
