"""Tests for verify_reference.py — the mechanical round-start gate.

Contract: exactly one JSON line on stdout; exit codes are distinct per
failure mode so exit-code-only consumers can never conflate them:
0 = live state matches the committed fingerprint; 1 = genuine drift
(reference tree non-empty, sidecar content changed, a sidecar appearing
or disappearing, or the mount path existing as a non-directory — a
file/FIFO/symlink loop in its place); 2 = the fingerprint itself is
missing or corrupt; 3 = transient environment failure (mount absent —
including a dangling symlink — /unreadable/stale, or a sidecar that
exists but cannot be read) — NOT evidence the surveyed state changed;
4 = the gate itself crashed (never conflated with drift's rc 1).

A non-empty observed tree must additionally produce a per-file manifest
(reference_manifest_observed.json) to bootstrap the mandated SURVEY.md
rewrite, without disturbing the one-line stdout contract.
"""

import hashlib
import json
import os
import pathlib
import shutil
import stat as stat_module
import subprocess
import sys
import time

import pytest

import bench
import verify_reference


def run_main(monkeypatch, capsys, reference, repo):
    """In-process ``python verify_reference.py``; returns (rc, result)."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(reference))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(repo))
    # Pin the hygiene check's "not a git repo" state: without a ceiling,
    # a TMPDIR inside any checkout would make git discover the enclosing
    # work tree from the fake repo dir.
    monkeypatch.setenv("GIT_CEILING_DIRECTORIES", str(pathlib.Path(repo).parent))
    rc = verify_reference.main()
    captured = capsys.readouterr()
    assert captured.err == ""
    return rc, parse_single_json_line(captured.out)


def parse_single_json_line(stdout_text):
    lines = stdout_text.splitlines()
    assert len(lines) == 1
    return json.loads(lines[0])


def test_empty_reference_matches_fingerprint_exits_0(
    tmp_path, fake_repo, monkeypatch, capsys
):
    ref = tmp_path / "ref"
    ref.mkdir()
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_MATCH == 0
    assert result["reference_empty"] is True
    assert result["matches_fingerprint"] is True
    assert result["drift"] == []
    assert result["manifest"] is None
    assert not (fake_repo / verify_reference.MANIFEST_NAME).exists()


def test_populated_reference_is_drift_exits_1(tmp_path, fake_repo, monkeypatch, capsys):
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// code\n")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT == 1
    assert result["reference_empty"] is False
    assert result["matches_fingerprint"] is False
    assert result["transient_environment_failure"] is False
    assert "DRIFT" in result["note"]
    assert {d["fact"] for d in result["drift"]} == {"reference_entry_count"}
    assert result["observed"]["reference_entry_count"] == 2


def test_populated_reference_writes_manifest(tmp_path, fake_repo, monkeypatch, capsys):
    """The manifest must record every entry (dirs, files, symlinks) with
    relative path, type, size, and file sha256, sorted by path — the
    evidence bootstrap for rewriting SURVEY.md from a real tree."""
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// code\n")
    (ref / "README.md").write_text("hello\n")
    (ref / "link").symlink_to("README.md")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT

    manifest_path = fake_repo / verify_reference.MANIFEST_NAME
    assert result["manifest"] == str(manifest_path)
    assert not list(fake_repo.glob(verify_reference.MANIFEST_NAME + ".*.tmp"))
    manifest = json.loads(manifest_path.read_text())
    assert manifest["reference_path"] == str(ref)
    assert manifest["entry_count"] == 4
    assert [e["path"] for e in manifest["entries"]] == [
        "README.md",
        "link",
        "src",
        "src/main.cu",
    ]
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert by_path["src"]["type"] == "dir"
    assert by_path["link"]["type"] == "symlink"
    assert by_path["link"]["target"] == "README.md"
    assert by_path["src/main.cu"]["type"] == "file"
    assert by_path["src/main.cu"]["size"] == len("// code\n")
    assert (
        by_path["src/main.cu"]["sha256"]
        == hashlib.sha256(b"// code\n").hexdigest()
    )


def _make_hidden_git_tree(root):
    """A reference containing ONLY a .git directory — the upstream
    shape BASELINE.json predicts ("only a bare .git directory")."""
    git = root / ".git"
    (git / "objects" / "ab").mkdir(parents=True)
    (git / "objects" / "ab" / "cdef0123").write_bytes(b"\x78\x9c")
    (git / "refs" / "heads").mkdir(parents=True)
    (git / "refs" / "heads" / "main").write_text("0" * 40 + "\n")
    (git / "HEAD").write_text("ref: refs/heads/main\n")
    (git / "config").write_text("[core]\n\tbare = false\n")
    return root


def test_hidden_git_only_tree_is_flagged_vcs_metadata_only(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A tree whose every entry is .git/** must NOT read as a plain
    source tree: the read order for working files finds nothing there,
    and 'found nothing' must never be mistaken for 'no capabilities' —
    the real source lives in the object store. The gate classifies the
    shape and the note directs the reader to materialize first."""
    ref = _make_hidden_git_tree(tmp_path / "ref")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest_shape"] == "vcs-metadata-only"
    assert "VERSION-CONTROL METADATA" in result["note"]
    assert "materialize" in result["note"]
    assert "SURVEY_REWRITE" in result["note"]
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    assert manifest["shape"] == "vcs-metadata-only"
    assert "SHAPE WARNING" in manifest["comment"]


def test_bare_git_layout_is_flagged_vcs_metadata_only(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The other VCS-only packaging: the mount IS the git directory
    (HEAD/objects/refs at top level, no .git wrapper)."""
    ref = tmp_path / "ref"
    (ref / "objects" / "pack").mkdir(parents=True)
    (ref / "objects" / "pack" / "pack-1234.pack").write_bytes(b"PACK")
    (ref / "refs" / "heads").mkdir(parents=True)
    (ref / "HEAD").write_text("ref: refs/heads/main\n")
    (ref / "config").write_text("[core]\n\tbare = true\n")
    (ref / "packed-refs").write_text("# pack-refs\n")
    # git-generated residue must not defeat the detection: a failed gc
    # or an lfs cache at top level is still a bare repo, not a source
    # tree.
    (ref / "gc.log").write_text("warning: There are too many loose objects\n")
    (ref / "lfs").mkdir()
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest_shape"] == "vcs-metadata-only"
    assert "VERSION-CONTROL METADATA" in result["note"]


def test_gitlink_file_git_entry_classifies_as_gitlink_shape(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A `.git` that is a FILE is a gitlink — a `gitdir: ...` pointer
    to a git dir OUTSIDE the mount. It must get its own shape (the
    vcs-only playbook's `git clone <mount>` cannot work on it) and the
    note must say to read the pointer before attempting any clone
    (advisor finding verify_reference.py:537)."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / ".git").write_text("gitdir: /somewhere/else/worktrees/arena\n")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest_shape"] == "vcs-metadata-gitlink"
    assert "GITLINK FILE" in result["note"]
    assert "read the pointer" in result["note"]
    assert "git clone" in result["note"]
    # The gitlink note replaces (not augments) the dir-shape clone advice.
    assert "materialize the committed tree read-only" not in result["note"]
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    assert manifest["shape"] == "vcs-metadata-gitlink"
    assert "GITLINK" in manifest["comment"]


def test_gitlink_vs_git_dir_classification_unit():
    """The classification detail: `.git` as FILE -> gitlink shape;
    `.git` as dir (or unknown type) -> vcs-metadata-only as before."""
    classify = verify_reference.classify_manifest_shape
    assert (
        classify([{"path": ".git", "type": "file", "size": 30, "sha256": "aa"}])
        == "vcs-metadata-gitlink"
    )
    assert (
        classify(
            [
                {"path": ".git", "type": "dir"},
                {"path": ".git/HEAD", "type": "file"},
            ]
        )
        == "vcs-metadata-only"
    )
    # Entries without a type key (older manifests) keep the old verdict.
    assert classify([{"path": ".git"}]) == "vcs-metadata-only"


def test_git_metadata_plus_working_files_is_working_tree(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Any non-git top-level entry means working files exist: the
    normal read order applies and no materialize warning fires."""
    ref = _make_hidden_git_tree(tmp_path / "ref")
    (ref / "README.md").write_text("real working file\n")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest_shape"] == "working-tree"
    assert "VERSION-CONTROL METADATA" not in result["note"]


def test_bare_like_layout_without_head_is_working_tree(tmp_path):
    """Strictness arm: git-ish names alone don't trigger the VCS-only
    classification — the load-bearing HEAD/objects/refs trio must all
    be present (a tree with 'info' and 'logs' dirs is just a tree)."""
    entries = [{"path": p} for p in ("info", "logs", "objects", "objects/x")]
    assert (
        verify_reference.classify_manifest_shape(entries) == "working-tree"
    )
    entries = [
        {"path": p}
        for p in ("HEAD", "objects", "objects/x", "refs", "refs/heads")
    ]
    assert (
        verify_reference.classify_manifest_shape(entries) == "vcs-metadata-only"
    )


def test_empty_entries_classify_as_emptied_between_walks():
    """classify_manifest_shape only runs after the counting walk saw a
    non-empty tree; an empty entries list means the mount emptied in
    between and must NOT read as 'working-tree' (a non-empty claim
    with entry_count 0 is internally contradictory evidence)."""
    assert (
        verify_reference.classify_manifest_shape([])
        == verify_reference.MANIFEST_SHAPE_EMPTIED
        == "emptied-between-walks"
    )


def test_tree_emptied_between_walks_manifest_never_claims_non_empty(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The race end-to-end: counting walk sees entries, manifest walk
    sees none. The gate still reports drift (the count DID change), but
    the written manifest must describe the instability — not assert 'a
    NON-EMPTY reference tree was observed' above entry_count 0."""
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    monkeypatch.setattr(verify_reference, "build_manifest", lambda reference: [])
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest_shape"] == "emptied-between-walks"
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    assert manifest["shape"] == "emptied-between-walks"
    assert manifest["entry_count"] == 0
    assert "NON-EMPTY" not in manifest["comment"]
    assert "EMPTIED BETWEEN WALKS" in manifest["comment"]


def test_matching_nonempty_vcs_only_fingerprint_keeps_the_shape_warning(
    tmp_path, monkeypatch, capsys
):
    """After a deliberate re-pin to a VCS-only tree, rc 0 must STILL
    carry the materialize warning — a match is not permission to survey
    the metadata as if it were source."""
    from conftest import make_fake_repo

    ref = _make_hidden_git_tree(tmp_path / "ref")
    count = sum(len(d) + len(f) for _, d, f in os.walk(ref))
    repo = make_fake_repo(tmp_path, entry_count=count)
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_MATCH
    assert "NON-EMPTY" in result["note"]
    assert result["manifest_shape"] == "vcs-metadata-only"
    assert "VERSION-CONTROL METADATA" in result["note"]


def test_vcs_only_warning_survives_a_failed_manifest_write(
    tmp_path, fake_repo, deny_manifest_write, monkeypatch, capsys
):
    """The shape is evidence from the WALK, not a property of repo-dir
    writability: a read-only repo dir / full disk on remount day must
    not silently drop the verdict-critical materialize warning."""
    ref = _make_hidden_git_tree(tmp_path / "ref")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest"] is None
    assert result["manifest_error"] == "OSError: read-only file system"
    assert result["manifest_shape"] == "vcs-metadata-only"
    assert "VERSION-CONTROL METADATA" in result["note"]
    assert "materialize" in result["note"]


def test_unwritable_manifest_does_not_break_the_gate(
    tmp_path, fake_repo, deny_manifest_write, monkeypatch, capsys
):
    """If the manifest cannot be written (read-only repo dir), the gate
    still reports drift with rc 1 and one JSON line; the failure is
    surfaced as manifest_error instead of a crash, and the note must not
    point the reader at a manifest that was never written."""
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest"] is None
    # Class plus message: "OSError" alone cannot distinguish a write
    # failure from a stale-mount read failure.
    assert result["manifest_error"] == "OSError: read-only file system"
    assert "manifest for the observed entries" not in result["note"]
    assert not list(fake_repo.glob(verify_reference.MANIFEST_NAME + "*"))


def test_unreadable_file_is_marked_in_manifest(tmp_path, fake_repo, monkeypatch, capsys):
    """A file whose contents cannot be read must carry an explicit error
    marker in the manifest — sha256:null alone is indistinguishable from
    a benign dir/symlink entry, which would make the evidence look
    complete when it is not."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / "ok.txt").write_text("fine\n")
    (ref / "broken.txt").write_text("secret\n")
    (ref / "badlink").symlink_to("ok.txt")
    real_os_open = os.open
    real_readlink = os.readlink

    def flaky_os_open(target, *args, **kwargs):
        if pathlib.Path(target).name == "broken.txt":
            raise PermissionError("no read access")
        return real_os_open(target, *args, **kwargs)

    def flaky_readlink(path, *args, **kwargs):
        if pathlib.Path(path).name == "badlink":
            raise OSError("stale handle")
        return real_readlink(path, *args, **kwargs)

    monkeypatch.setattr(os, "open", flaky_os_open)
    monkeypatch.setattr(os, "readlink", flaky_readlink)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    manifest = json.loads(
        (fake_repo / verify_reference.MANIFEST_NAME).read_text()
    )
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert by_path["broken.txt"]["sha256"] is None
    assert by_path["broken.txt"]["error"] == "PermissionError: no read access"
    assert by_path["badlink"]["type"] == "symlink"
    assert by_path["badlink"]["target"] is None
    assert by_path["badlink"]["error"] == "OSError: stale handle"
    assert by_path["ok.txt"]["sha256"] == hashlib.sha256(b"fine\n").hexdigest()
    assert "error" not in by_path["ok.txt"]


def test_fifo_in_reference_tree_cannot_hang_the_manifest(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A FIFO (or other special file) inside an observed non-empty tree
    is recorded as type 'special' WITHOUT being opened: a blocking read
    of a writer-less FIFO would hang the gate forever and break the
    one-line output contract — the same hazard the sidecar reads guard
    against. On failure this test hangs rather than asserts, which is
    the loudest possible signal."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / "normal.txt").write_text("data\n")
    os.mkfifo(ref / "pipe")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert by_path["pipe"]["type"] == "special"
    assert by_path["pipe"]["sha256"] is None
    assert by_path["pipe"]["mode"].startswith("p")
    assert by_path["normal.txt"]["sha256"] == hashlib.sha256(b"data\n").hexdigest()
    assert manifest["entry_count"] == 2


def test_matching_nonempty_fingerprint_retires_the_emptiness_note(
    tmp_path, monkeypatch, capsys
):
    """After a deliberate fingerprint update to a re-populated reference,
    a clean match (rc 0) must not keep claiming the reference is empty."""
    from conftest import make_fake_repo

    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// code\n")
    repo = make_fake_repo(tmp_path, entry_count=2)
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_MATCH
    assert result["matches_fingerprint"] is True
    assert result["reference_empty"] is False
    assert "still empty" not in result["note"]
    assert "NON-EMPTY" in result["note"]
    assert (repo / verify_reference.MANIFEST_NAME).exists()


def test_sidecar_drift_during_mount_outage_is_drift_not_transient(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Genuine sidecar drift must exit 1 even when the mount is also
    unscannable this run — rc 3 would hide the drift from exit-code-only
    consumers, who would just retry the mount forever."""
    (fake_repo / "PAPERS.md").write_text("# PAPERS\n\nnew retrieved content\n")
    rc, result = run_main(monkeypatch, capsys, tmp_path / "gone", fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["transient_environment_failure"] is True
    assert {d["fact"] for d in result["drift"]} == {
        "papers_md_sha256",
        "reference_entry_count",
    }
    assert "DRIFT" in result["note"]
    assert "could not be scanned" in result["note"]


def test_missing_reference_is_transient_exits_3(tmp_path, fake_repo, monkeypatch, capsys):
    rc, result = run_main(monkeypatch, capsys, tmp_path / "gone", fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT == 3
    assert result["observed"]["reference_entry_count"] == "mount_missing_or_unreadable"
    # The exit code and the JSON evidence must both self-describe this as
    # environmental, not as the reference having changed (SKILL.md).
    assert result["transient_environment_failure"] is True
    assert "TRANSIENT" in result["note"]
    assert result["manifest"] is None


def test_scan_error_is_transient_exits_3(tmp_path, fake_repo, monkeypatch, capsys):
    """A mid-walk OSError (via the shared bench.scan) is a transient
    environment failure with its own exit code, not drift."""
    ref = tmp_path / "ref"
    bad = ref / "bad"
    bad.mkdir(parents=True)
    real_scandir = os.scandir

    def flaky_scandir(path=".", *args, **kwargs):
        if pathlib.Path(path) == bad:
            raise OSError("mount went stale mid-iteration")
        return real_scandir(path, *args, **kwargs)

    monkeypatch.setattr(os, "scandir", flaky_scandir)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT
    assert result["observed"]["reference_entry_count"] == "scan_error"
    assert result["transient_environment_failure"] is True


def test_mid_walk_swap_to_file_escalates_scan_error_to_drift(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The walk started (so bench.scan reports 'scan_error', not
    'mount_missing'), but by observation time the mount path is a
    regular FILE: a persistent type swap that must escalate to drift
    rc 1 IN THIS RUN — not idle as transient rc 3 until the next run
    re-observes it (advisor finding verify_reference.py:678)."""
    ref = tmp_path / "ref"
    ref.write_text("was a directory when the walk began\n")
    monkeypatch.setattr(
        bench,
        "scan",
        lambda reference: {
            "metric": "reference_scan_error",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
            "error": "OSError: mount went stale mid-iteration",
        },
    )
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT == 1
    assert result["transient_environment_failure"] is False
    assert result["observed"]["reference_entry_count"] == "mount_not_a_directory"
    assert result["mount_type_error"].startswith("not a directory: -")
    assert "NOT a directory" in result["note"]


def test_scan_error_with_healthy_dir_observation_stays_transient(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The other arm of the same escalation: a mid-walk OSError while
    the path still observes as a healthy directory is a genuine
    transient — the re-observation must not manufacture drift."""
    ref = tmp_path / "ref"
    ref.mkdir()
    monkeypatch.setattr(
        bench,
        "scan",
        lambda reference: {
            "metric": "reference_scan_error",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
            "error": "OSError: flaky",
        },
    )
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT
    assert result["observed"]["reference_entry_count"] == "scan_error"
    assert "mount_type_error" not in result


def test_file_at_mount_path_is_drift_exits_1(tmp_path, fake_repo, monkeypatch, capsys):
    """A regular file sitting AT the mount path is a persistent state
    change — rc 1 with the type named, never rc 3's "re-run and it'll
    clear" (the same conflation class the sidecars shed in round 4)."""
    ref = tmp_path / "ref"
    ref.write_text("I am not a directory\n")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT == 1
    assert result["transient_environment_failure"] is False
    assert result["observed"]["reference_entry_count"] == "mount_not_a_directory"
    assert {d["fact"] for d in result["drift"]} == {"reference_entry_count"}
    assert result["mount_type_error"].startswith("not a directory: -")
    assert "NOT a directory" in result["note"]
    assert "persistent" in result["note"]


def test_symlink_to_file_at_mount_path_is_drift_exits_1(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The observation follows symlinks (like bench.scan's is_dir): a
    symlink whose target is a file is still a non-directory mount."""
    target = tmp_path / "target"
    target.write_text("x\n")
    ref = tmp_path / "ref"
    ref.symlink_to(target)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["observed"]["reference_entry_count"] == "mount_not_a_directory"
    assert result["mount_type_error"].startswith("not a directory:")


def test_fifo_at_mount_path_is_drift_and_cannot_hang(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A writer-less FIFO at the mount path must classify as drift
    WITHOUT blocking the gate: the O_NONBLOCK open + fstat pattern
    (same as observe_sidecar) is what makes this test terminate."""
    ref = tmp_path / "ref"
    os.mkfifo(ref)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["transient_environment_failure"] is False
    assert result["observed"]["reference_entry_count"] == "mount_not_a_directory"
    # filemode of a FIFO starts with 'p'; the permission bits depend on
    # the umask, so only the type character is asserted.
    assert result["mount_type_error"].startswith("not a directory: p")


def test_symlink_loop_at_mount_path_is_drift_exits_1(
    tmp_path, fake_repo, monkeypatch, capsys
):
    ref = tmp_path / "ref"
    ref.symlink_to(ref)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["observed"]["reference_entry_count"] == "mount_not_a_directory"
    assert "Too many levels of symbolic links" in result["mount_type_error"]


def test_dangling_symlink_at_mount_path_is_transient_exits_3(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A dangling symlink resolves to nothing: for the MOUNT that is
    absence (transient — the driver recreates the mount every round),
    mirroring observe_sidecar where a dangling symlink is 'absent'."""
    ref = tmp_path / "ref"
    ref.symlink_to(tmp_path / "nowhere")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT == 3
    assert result["observed"]["reference_entry_count"] == "mount_missing_or_unreadable"
    assert result["transient_environment_failure"] is True
    assert "mount_type_error" not in result


def test_unreadable_mount_type_observation_stays_transient(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """If the type observation itself hits a permissions hiccup (any
    OSError other than ELOOP/ENXIO/absence), the true state is unknown
    — rc 3, never escalated to drift."""
    ref = tmp_path / "ref"
    ref.write_text("wrong type, but unreadable\n")
    real_open = os.open

    def deny(path, flags, *args, **kwargs):
        if pathlib.Path(path) == ref:
            raise PermissionError(13, "Permission denied", str(path))
        return real_open(path, flags, *args, **kwargs)

    monkeypatch.setattr(os, "open", deny)
    # bench.scan's os.access also consults the real file; PermissionError
    # from os.open is what scan's is_dir/access path never sees, so force
    # the scan-side inaccessibility too.
    monkeypatch.setattr(os, "access", lambda *a, **k: False)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT
    assert result["observed"]["reference_entry_count"] == "mount_missing_or_unreadable"
    assert result["transient_environment_failure"] is True


def test_mount_healthy_again_by_observation_time_stays_transient(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Race arm: the scan said inaccessible but the type observation
    sees a healthy directory — the earlier failure stands as transient
    (a re-run will see the directory), never as wrong-type drift."""
    ref = tmp_path / "ref"
    ref.mkdir()
    monkeypatch.setattr(
        bench,
        "scan",
        lambda reference: {
            "metric": "reference_mount_missing_or_unreadable",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
        },
    )
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT
    assert result["observed"]["reference_entry_count"] == "mount_missing_or_unreadable"
    assert "mount_type_error" not in result


def test_mount_fstat_failure_is_unreadable_not_drift(tmp_path, monkeypatch):
    """The post-open fstat arm of the mount observation: a read failure
    AFTER a successful open leaves the type unknown — unreadable
    (transient), never wrong-type drift."""
    ref = tmp_path / "ref"
    ref.mkdir()

    def broken_fstat(fd):
        raise OSError(5, "Input/output error")

    monkeypatch.setattr(os, "fstat", broken_fstat)
    state, detail = verify_reference.observe_mount_type(ref)
    assert state == verify_reference.MOUNT_UNREADABLE
    assert detail == "OSError: [Errno 5] Input/output error"


def test_manifest_walk_failure_leaves_shape_unknown_but_reports_drift(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """If the manifest's own traversal dies (distinct from the counting
    walk, which succeeded), the gate still reports drift rc 1 with
    manifest_error — and no shape claim, because only a walk can
    classify a shape."""
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)

    def walk_died(reference):
        raise OSError(116, "Stale file handle")

    monkeypatch.setattr(verify_reference, "build_manifest", walk_died)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest"] is None
    assert result["manifest_error"] == "OSError: [Errno 116] Stale file handle"
    assert "manifest_shape" not in result
    assert "VERSION-CONTROL METADATA" not in result["note"]


def test_sweep_glob_failure_does_not_block_manifest_write(tmp_path, monkeypatch):
    """The stale-tmp sweep is best-effort at BOTH levels: repo.glob
    itself raising (not just a per-file stat/unlink) must not stop the
    manifest from being written."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / "f").write_text("x\n")
    repo = tmp_path / "repo"
    repo.mkdir()

    def broken_glob(self, pattern):
        raise OSError("glob exploded")

    monkeypatch.setattr(pathlib.Path, "glob", broken_glob)
    manifest_path = verify_reference.write_manifest(ref, repo)
    written = json.loads(pathlib.Path(manifest_path).read_text())
    assert written["entry_count"] == 1


def test_changed_baseline_sidecar_is_drift_exits_1(
    tmp_path, fake_repo, monkeypatch, capsys
):
    ref = tmp_path / "ref"
    ref.mkdir()
    (fake_repo / "BASELINE.json").write_text('{"north_star": "now it has code!"}\n')
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert {d["fact"] for d in result["drift"]} == {"baseline_json_sha256"}
    # the reference itself is still empty; only the sidecar moved
    assert result["reference_empty"] is True
    assert result["manifest"] is None


def test_snippets_appearing_is_drift_exits_1(tmp_path, monkeypatch, capsys):
    from conftest import make_fake_repo

    ref = tmp_path / "ref"
    ref.mkdir()
    repo = make_fake_repo(tmp_path, with_snippets=True)
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert {d["fact"] for d in result["drift"]} == {"snippets_md_sha256"}
    (drift_entry,) = result["drift"]
    assert drift_entry["fingerprint"] == "absent"
    assert drift_entry["observed"] == hashlib.sha256(b"# SNIPPETS\n").hexdigest()


def test_count_entries_delegates_to_bench(tmp_path):
    """bench.scan and the round-start gate must agree on the same mount,
    including when the caller hands over a precomputed scan result."""
    (tmp_path / "a").mkdir()
    (tmp_path / "a" / "b.txt").write_text("x")
    assert verify_reference.count_entries(tmp_path) == 2
    assert verify_reference.count_entries(tmp_path / "gone") == (
        "mount_missing_or_unreadable"
    )
    precomputed = bench.scan(tmp_path)
    assert verify_reference.count_entries(tmp_path, scan_result=precomputed) == 2


def test_missing_fingerprint_exits_2(tmp_path, monkeypatch, capsys):
    ref = tmp_path / "ref"
    ref.mkdir()
    repo = tmp_path / "bare"
    repo.mkdir()
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT == 2
    assert result["error"] == "fingerprint_missing_or_corrupt"


def test_corrupt_fingerprint_exits_2(tmp_path, fake_repo, monkeypatch, capsys):
    ref = tmp_path / "ref"
    ref.mkdir()
    (fake_repo / "reference_fingerprint.json").write_text("{not json")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT
    assert result["error"] == "fingerprint_missing_or_corrupt"


def test_non_object_json_fingerprint_exits_2(tmp_path, fake_repo, monkeypatch, capsys):
    """Valid JSON that is not an object (null, list, scalar) is corrupt,
    not drift: must take the exit-2 path, not crash with rc 1."""
    ref = tmp_path / "ref"
    ref.mkdir()
    for payload in ("null", "[]", '"x"', "42"):
        (fake_repo / "reference_fingerprint.json").write_text(payload)
        rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
        assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT, payload
        assert result["error"] == "fingerprint_missing_or_corrupt"


def test_non_int_fingerprint_count_exits_2(tmp_path, fake_repo, monkeypatch, capsys):
    """A fingerprint whose reference_entry_count is not a non-negative
    int is corrupt. Otherwise an error sentinel pasted into the
    fingerprint (e.g. from an observed block captured during a mount
    outage) would make every future transient failure 'match' with rc 0
    and a verdict-retiring note."""
    fingerprint = json.loads((fake_repo / "reference_fingerprint.json").read_text())
    for bad_count in ("mount_missing_or_unreadable", "scan_error", None, -1, 1.5, True):
        fingerprint["reference_entry_count"] = bad_count
        (fake_repo / "reference_fingerprint.json").write_text(json.dumps(fingerprint))
        rc, result = run_main(monkeypatch, capsys, tmp_path / "gone", fake_repo)
        assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT, bad_count
        assert result["error"] == "fingerprint_missing_or_corrupt"


def test_invalid_fingerprint_sidecar_fields_exit_2(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Missing/null/mistyped sidecar facts are fingerprint corruption
    (rc 2: fix the repo), not sidecar drift (rc 1: verdict-affecting
    workflow) — the same asymmetry guard as for the entry count. A
    pinned "unreadable" is corrupt too: it would make every future
    transient read failure 'match' with rc 0."""
    ref = tmp_path / "ref"
    ref.mkdir()
    good = json.loads((fake_repo / "reference_fingerprint.json").read_text())
    mutations = [
        ("baseline_json_sha256", None),
        ("papers_md_sha256", 42),
        ("snippets_md_sha256", True),
        ("snippets_md_sha256", "unreadable"),
        ("papers_md_sha256", "not-a-hex-digest"),
        ("baseline_json_sha256", "DELETE"),
    ]
    for key, value in mutations:
        fingerprint = dict(good)
        if value == "DELETE":
            del fingerprint[key]
        else:
            fingerprint[key] = value
        (fake_repo / "reference_fingerprint.json").write_text(json.dumps(fingerprint))
        rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
        assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT, (key, value)
        assert result["error"] == "fingerprint_missing_or_corrupt"


@pytest.mark.parametrize(
    "filename,fact",
    [
        ("BASELINE.json", "baseline_json_sha256"),
        ("PAPERS.md", "papers_md_sha256"),
        ("SNIPPETS.md", "snippets_md_sha256"),
    ],
)
def test_unreadable_sidecar_is_transient_exits_3(
    tmp_path, fake_repo, monkeypatch, capsys, filename, fact
):
    """An OSError reading a sidecar means its true state is UNKNOWN:
    rc 3 (transient), never rc 1 (false drift) and never rc 0 (false
    match). For SNIPPETS.md this is the present-but-unreadable case a
    Path.exists() check would have silently collapsed into 'absent' —
    a false rc-0 match against a fingerprint that pins absence."""
    ref = tmp_path / "ref"
    ref.mkdir()
    if not (fake_repo / filename).exists():
        (fake_repo / filename).write_text("present but unreadable\n")
    real_os_open = os.open

    def deny(target, *args, **kwargs):
        if pathlib.Path(target).name == filename:
            raise PermissionError(13, "Permission denied")
        return real_os_open(target, *args, **kwargs)

    monkeypatch.setattr(os, "open", deny)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT
    assert result["matches_fingerprint"] is False
    assert result["transient_environment_failure"] is True
    assert result["observed"][fact] == "unreadable"
    assert {d["fact"] for d in result["drift"]} == {fact}
    assert result["sidecar_errors"][fact].startswith("PermissionError")
    assert "TRANSIENT" in result["note"]
    assert filename in result["note"]


def test_sidecar_disappearing_is_drift_exits_1(tmp_path, fake_repo, monkeypatch, capsys):
    """A genuinely absent sidecar (ENOENT) is a real content fact, not a
    read failure: deletion relative to the fingerprint is drift and must
    not hide behind the transient exit code."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (fake_repo / "PAPERS.md").unlink()
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["transient_environment_failure"] is False
    assert {d["fact"] for d in result["drift"]} == {"papers_md_sha256"}
    (drift_entry,) = result["drift"]
    assert drift_entry["observed"] == "absent"


def test_genuine_drift_with_unreadable_sidecar_still_exits_1(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Confirmed drift outranks a concurrent transient sidecar failure
    (same precedence as the mount-outage case); the note must flag the
    unreadable sidecar as not-confirmed rather than folding it into the
    drift verdict."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (fake_repo / "BASELINE.json").write_text('{"north_star": "changed"}\n')
    real_os_open = os.open

    def deny(target, *args, **kwargs):
        if pathlib.Path(target).name == "PAPERS.md":
            raise OSError(5, "Input/output error")
        return real_os_open(target, *args, **kwargs)

    monkeypatch.setattr(os, "open", deny)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["transient_environment_failure"] is True
    assert {d["fact"] for d in result["drift"]} == {
        "baseline_json_sha256",
        "papers_md_sha256",
    }
    assert result["observed"]["papers_md_sha256"] == "unreadable"
    assert "DRIFT" in result["note"]
    assert "not confirmed" in result["note"]
    assert "PAPERS.md" in result["note"]


def test_gate_crash_exits_4_not_1(tmp_path, fake_repo, monkeypatch, capsys):
    """An unhandled exception must not escape with Python's default exit
    status 1 — that collides with EXIT_DRIFT, so an exit-code-only
    consumer would read a gate crash as 'genuine drift'. The catch-all
    prints one JSON error line and returns the distinct rc 4."""

    def boom(*args, **kwargs):
        raise RuntimeError("gate exploded")

    monkeypatch.setattr(verify_reference, "verify", boom)
    rc, result = run_main(monkeypatch, capsys, tmp_path, fake_repo)
    assert rc == verify_reference.EXIT_INTERNAL_ERROR == 4
    assert result["error"] == "internal_error"
    assert result["detail"] == "RuntimeError: gate exploded"
    assert "repo bug" in result["note"]


def test_broken_bench_import_exits_4_not_1(tmp_path):
    """A missing or broken bench.py is the one crash main()'s rc-4
    catch-all cannot see — the import runs at module load, before main()
    exists — so without its own guard the gate would exit Python's
    default status 1, colliding with EXIT_DRIFT. Must run as a true
    subprocess: the guard is module-level and the live test process has
    already imported a working bench. ``-S`` keeps it cheap (both
    scripts are stdlib-only; sitecustomize's jax import is irrelevant to
    the import-failure plumbing under test)."""
    from conftest import REPO, _clean_env

    shutil.copy2(REPO / "verify_reference.py", tmp_path / "verify_reference.py")
    (tmp_path / "bench.py").write_text("raise RuntimeError('bench import boom')\n")
    env = _clean_env()
    proc = subprocess.run(
        [sys.executable, "-S", str(tmp_path / "verify_reference.py")],
        capture_output=True,
        text=True,
        cwd="/tmp",
        env=env,
        timeout=60,
    )
    assert proc.returncode == verify_reference.EXIT_INTERNAL_ERROR == 4
    result = parse_single_json_line(proc.stdout)
    assert result["error"] == "internal_error"
    assert result["detail"] == "RuntimeError: bench import boom"
    assert "could not import" in result["note"]
    # And importers must still see the real error, not a sys.exit: the
    # lazy `import verify_reference` inside bench.verification_summary
    # degrades on exceptions, so a raise reaches its error field while a
    # SystemExit would kill bench outright.
    probe = (
        "import sys; sys.path.insert(0, sys.argv[1])\n"
        "try:\n"
        "    import verify_reference\n"
        "except RuntimeError as exc:\n"
        "    assert str(exc) == 'bench import boom'\n"
        "    sys.exit(0)\n"
        "sys.exit(5)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-S", "-c", probe, str(tmp_path)],
        capture_output=True,
        text=True,
        cwd="/tmp",
        env=env,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr


def test_stale_manifest_tmp_files_are_swept(tmp_path, fake_repo, monkeypatch, capsys):
    """Temp files orphaned by a crash between mkstemp and os.replace in
    an earlier run are cleaned up by the next manifest write instead of
    accumulating forever — but only OLD ones: a fresh temp file may
    belong to a concurrent run mid-write (bench and the gate can race in
    the same round), and unlinking it would break that run's atomic
    write."""
    orphaned = fake_repo / (verify_reference.MANIFEST_NAME + ".orphan0.tmp")
    orphaned.write_text("{truncated")
    old = time.time() - verify_reference.STALE_TMP_AGE_S - 60
    os.utime(orphaned, (old, old))
    in_flight = fake_repo / (verify_reference.MANIFEST_NAME + ".concurrent.tmp")
    in_flight.write_text("{mid-write")
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert not orphaned.exists()
    assert in_flight.exists()
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    assert manifest["entry_count"] == 1


def test_sidecar_replaced_by_non_regular_file_is_drift_exits_1(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A sidecar path that exists as anything but a regular file —
    directory, FIFO, symlink loop — is a persistent state change, not a
    read hiccup: rc 3's 're-run' advice could never succeed, so it must
    classify as genuine drift with the 'not-a-regular-file' observation
    (never pinnable) and the detail preserved. The FIFO case also
    guards the output contract itself: classification must happen via a
    non-blocking open + fstat of the open descriptor (race-free), since
    a plain blocking open/read of a FIFO with no writer blocks
    forever."""
    ref = tmp_path / "ref"
    ref.mkdir()

    def replace_papers(create):
        (fake_repo / "PAPERS.md").unlink()
        create(fake_repo / "PAPERS.md")

    cases = [
        (lambda p: p.mkdir(), "d"),
        (lambda p: os.mkfifo(p), "p"),
        (lambda p: p.symlink_to(p.name), "loop"),  # ELOOP on stat
    ]
    for create, kind in cases:
        replace_papers(create)
        rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
        assert rc == verify_reference.EXIT_DRIFT, kind
        assert result["transient_environment_failure"] is False, kind
        assert result["observed"]["papers_md_sha256"] == "not-a-regular-file", kind
        assert {d["fact"] for d in result["drift"]} == {"papers_md_sha256"}, kind
        detail = result["sidecar_errors"]["papers_md_sha256"]
        if kind == "loop":
            assert detail.startswith("OSError"), detail
        else:
            assert detail.startswith("not a regular file: " + kind), detail
        if (fake_repo / "PAPERS.md").is_dir():
            (fake_repo / "PAPERS.md").rmdir()
        else:
            (fake_repo / "PAPERS.md").unlink()
        (fake_repo / "PAPERS.md").write_text("# PAPERS\n")


def test_dangling_symlink_sidecar_is_absent(tmp_path, fake_repo, monkeypatch, capsys):
    """A dangling symlink in place of a sidecar has no content: it
    observes as 'absent' (a persistent content fact → drift against a
    pinned hash), not as unreadable/transient."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (fake_repo / "PAPERS.md").unlink()
    (fake_repo / "PAPERS.md").symlink_to("does-not-exist")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["observed"]["papers_md_sha256"] == "absent"
    assert result["transient_environment_failure"] is False


def test_mount_stat_failure_degrades_without_affecting_exit_code(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A stat failure on an EXISTING mount path degrades to an error
    field in the evidence (with class+message); the exit code is decided
    by the scan and sidecar comparison alone."""
    ref = tmp_path / "ref"
    ref.mkdir()

    # (a) the OSError arm of mount_stat itself
    def broken_stat(self, **kwargs):
        raise OSError(116, "Stale file handle")

    with monkeypatch.context() as m:
        m.setattr(pathlib.Path, "stat", broken_stat)
        assert verify_reference.mount_stat(ref) == {
            "error": "OSError: [Errno 116] Stale file handle"
        }

    # (b) a degraded mount_stat does not disturb an otherwise-clean verdict
    monkeypatch.setattr(
        verify_reference,
        "mount_stat",
        lambda path: {"error": "OSError: [Errno 116] Stale file handle"},
    )
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_MATCH
    assert result["mount_stat"] == {"error": "OSError: [Errno 116] Stale file handle"}


def test_scan_count_and_manifest_agree(tmp_path):
    """Invariant: bench.scan's count, build_manifest's length, and
    write_manifest's recorded entry_count agree on the same tree —
    the manifest is the evidence a SURVEY.md rewrite starts from, so it
    must provably match the count that triggered it. Covers nested
    dirs, empty dirs, file/dir/dangling symlinks, and the empty tree."""
    t1 = tmp_path / "t1"
    (t1 / "a" / "b" / "c").mkdir(parents=True)
    (t1 / "a" / "f1").write_text("x")
    (t1 / "a" / "b" / "f2").write_text("y")

    t2 = tmp_path / "t2"
    (t2 / "empty1").mkdir(parents=True)
    (t2 / "empty2").mkdir()

    t3 = tmp_path / "t3"
    (t3 / "d").mkdir(parents=True)
    (t3 / "d" / "f").write_text("z")
    (t3 / "file_link").symlink_to("d/f")
    (t3 / "dir_link").symlink_to("d")  # not followed: counts as ONE entry
    (t3 / "dangling").symlink_to("does-not-exist")
    os.mkfifo(t3 / "pipe")  # special file: counted and recorded, never read

    t4 = tmp_path / "t4"
    t4.mkdir()

    for tree in (t1, t2, t3, t4):
        repo = tmp_path / ("repo_" + tree.name)
        repo.mkdir()
        scanned = bench.scan(tree)["value"]
        assert len(verify_reference.build_manifest(tree)) == scanned, tree
        manifest_path = verify_reference.write_manifest(tree, repo)
        written = json.loads(pathlib.Path(manifest_path).read_text())
        assert written["entry_count"] == scanned, tree


def test_uncommitted_round_artifacts_field(tmp_path, monkeypatch, capsys):
    """Round-artifact hygiene is mechanical, not prose: untracked or
    modified driver artifacts (BENCH_r*/MULTICHIP_r*/VERDICT/ADVICE)
    are listed in the gate's JSON line; unrelated dirty files are not;
    a clean tree reports []; a non-git repo dir reports null. The field
    never affects the exit code."""
    import subprocess

    from conftest import make_fake_repo

    ref = tmp_path / "ref"
    ref.mkdir()
    repo = make_fake_repo(tmp_path)

    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_MATCH
    assert result["uncommitted_round_artifacts"] is None  # not a git repo

    def git(*args):
        subprocess.run(
            [
                "git",
                "-C",
                str(repo),
                "-c",
                "user.email=t@example.com",
                "-c",
                "user.name=t",
                *args,
            ],
            check=True,
            capture_output=True,
        )

    git("init", "-q")
    (repo / "VERDICT.md").write_text("round-N verdict\n")
    git("add", "-A")
    git("commit", "-q", "-m", "baseline")
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_MATCH
    assert result["uncommitted_round_artifacts"] == []

    (repo / "BENCH_r09.json").write_text("{}\n")  # untracked artifact
    # Space + non-ASCII: must come through verbatim (the -z parse), not
    # as git's C-quoted form with literal quotes and escapes.
    (repo / "BENCH_r11 ä.json").write_text("{}\n")
    (repo / "MULTICHIP_r09.json").write_text("{}\n")  # untracked artifact
    (repo / "VERDICT.md").write_text("changed\n")  # modified artifact
    (repo / "unrelated.txt").write_text("x\n")  # dirty but not an artifact
    # A fingerprinted sidecar that is untracked (content unchanged, so no
    # drift) is a hygiene fact too — the round-4 SNIPPETS.md situation.
    git("rm", "--cached", "-q", "PAPERS.md")
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_MATCH
    assert result["uncommitted_round_artifacts"] == [
        "BENCH_r09.json",
        "BENCH_r11 ä.json",
        "MULTICHIP_r09.json",
        "PAPERS.md",
        "VERDICT.md",
    ]

    git("add", "-A")
    git("commit", "-q", "-m", "artifacts committed")
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert result["uncommitted_round_artifacts"] == []


def test_uncommitted_manifest_is_flagged_on_remount_day(
    tmp_path, monkeypatch, capsys
):
    """Remount day is the hygiene backstop's highest-stakes day: the
    playbook (SURVEY_REWRITE.md step 0.4) mandates committing the
    observed manifest before reading the tree further, so the gate must
    flag its OWN just-written manifest as uncommitted in the very same
    run that wrote it — and stop flagging it once committed."""
    import subprocess

    from conftest import make_fake_repo, make_populated_reference

    ref = make_populated_reference(tmp_path)
    repo = make_fake_repo(tmp_path)

    def git(*args):
        subprocess.run(
            [
                "git",
                "-C",
                str(repo),
                "-c",
                "user.email=t@example.com",
                "-c",
                "user.name=t",
                *args,
            ],
            check=True,
            capture_output=True,
        )

    git("init", "-q")
    git("add", "-A")
    git("commit", "-q", "-m", "baseline")
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest"] is not None
    assert result["uncommitted_round_artifacts"] == [
        verify_reference.MANIFEST_NAME
    ]

    git("add", verify_reference.MANIFEST_NAME)
    git("commit", "-q", "-m", "record the observed manifest (playbook 0.4)")
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert result["uncommitted_round_artifacts"] == []


def test_e2e_real_repo_fingerprint_matches_live_mount(e2e):
    """The documented round-start gate, run exactly as documented
    (plain ``python verify_reference.py``): the committed fingerprint
    must match the real repo sidecars, and the live mount must be
    empty (rc 0) or environmentally unavailable (rc 3). Any other
    outcome — in particular a NON-EMPTY remounted reference — fails
    this test loudly: SURVEY.md is then obsolete and must be rewritten
    from the real tree before any build work."""
    run = e2e["verify_real"]
    assert run.err == ""
    result = parse_single_json_line(run.out)
    # .get: the rc-2 outcome emits no drift key; the rc assertion below
    # must then fire with its diagnostic, not a KeyError here.
    sidecar_drift = [
        d for d in result.get("drift", []) if d["fact"] != "reference_entry_count"
    ]
    assert sidecar_drift == [], (
        "reference_fingerprint.json is stale relative to the committed "
        f"sidecars: {sidecar_drift}"
    )
    assert run.rc in (
        verify_reference.EXIT_MATCH,
        verify_reference.EXIT_TRANSIENT,
    ), f"unexpected gate outcome rc={run.rc}: {result}"
    if run.rc == verify_reference.EXIT_MATCH:
        assert result["matches_fingerprint"] is True
        assert result["observed"]["reference_entry_count"] == 0
    else:
        assert result["transient_environment_failure"] is True


def test_e2e_populated_reference_drift(e2e):
    """End-to-end subprocess run against a populated mount: rc 1, one
    JSON line, manifest written — through the real exit-code plumbing
    that round-start scripts consume."""
    run = e2e["verify_populated"]
    assert run.rc == verify_reference.EXIT_DRIFT
    assert run.err == ""
    result = parse_single_json_line(run.out)
    assert "DRIFT" in result["note"]
    assert result["observed"]["reference_entry_count"] == 3
    manifest_path = run.repo / verify_reference.MANIFEST_NAME
    assert manifest_path.exists()
    assert json.loads(manifest_path.read_text())["entry_count"] == 3


# --- Direct coverage of the remaining defensive arms (same standard ---
# --- VERDICT r3 item 6 set for mount_stat: every honesty path must ---
# --- be hit by an explicit test, not incidentally) ---


def _fail_reads_of(monkeypatch, filename):
    """Make every os.read of FILENAME's open fd raise EIO, with the
    open itself succeeding — the post-open failure arm. Tracks fds via
    os.open/os.close wrappers; close removes the fd from the live set
    because fd numbers are recycled (git subprocess pipes would
    otherwise inherit the curse)."""
    real_open, real_close, real_read = os.open, os.close, os.read
    live = set()

    def tracking_open(target, *args, **kwargs):
        fd = real_open(target, *args, **kwargs)
        if pathlib.Path(target).name == filename:
            live.add(fd)
        return fd

    def tracking_close(fd):
        live.discard(fd)
        return real_close(fd)

    def flaky_read(fd, n):
        if fd in live:
            raise OSError(5, "Input/output error")
        return real_read(fd, n)

    monkeypatch.setattr(os, "open", tracking_open)
    monkeypatch.setattr(os, "close", tracking_close)
    monkeypatch.setattr(os, "read", flaky_read)


def test_sidecar_read_failure_after_successful_open_is_unreadable(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """A disk error can surface at READ time with the open having
    succeeded (flaky media, NFS). Same unknown-true-state classification
    as an open failure: rc 3, observation 'unreadable' — the post-open
    arm of observe_sidecar, which the open-denial test cannot reach."""
    ref = tmp_path / "ref"
    ref.mkdir()
    _fail_reads_of(monkeypatch, "PAPERS.md")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT
    assert result["observed"]["papers_md_sha256"] == "unreadable"
    assert result["sidecar_errors"]["papers_md_sha256"].startswith("OSError")
    assert result["transient_environment_failure"] is True


def test_sidecar_open_raising_isadirectory_is_not_a_regular_file(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The IsADirectoryError arm is defensive — Linux opens directories
    O_RDONLY successfully, so real directory-sidecars are caught by the
    fstat branch — but a platform/filesystem that does raise it must
    land on 'not-a-regular-file' (persistent, drift), never on
    'unreadable' (transient)."""
    ref = tmp_path / "ref"
    ref.mkdir()
    real_open = os.open

    def deny(target, *args, **kwargs):
        if pathlib.Path(target).name == "PAPERS.md":
            raise IsADirectoryError(21, "Is a directory")
        return real_open(target, *args, **kwargs)

    monkeypatch.setattr(os, "open", deny)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["observed"]["papers_md_sha256"] == "not-a-regular-file"
    assert result["sidecar_errors"]["papers_md_sha256"].startswith(
        "IsADirectoryError"
    )
    assert result["transient_environment_failure"] is False


def test_git_subprocess_failure_degrades_hygiene_field_to_null(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """git missing or dying (OSError/SubprocessError) must degrade
    uncommitted_round_artifacts to null — undeterminable — without
    touching the drift verdict or the exit code."""
    ref = tmp_path / "ref"
    ref.mkdir()

    def no_git(*args, **kwargs):
        raise FileNotFoundError(2, "No such file or directory: 'git'")

    monkeypatch.setattr(verify_reference.subprocess, "run", no_git)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_MATCH
    assert result["uncommitted_round_artifacts"] is None
    assert result["matches_fingerprint"] is True


def test_manifest_lstat_failure_records_error_entry(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """An entry that vanishes (or goes stale) between the walk and its
    lstat must appear in the manifest as an explicit type:'error' entry
    — silent omission would make the evidence look complete when the
    walk observed an entry it could not describe."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / "ok.txt").write_text("fine\n")
    (ref / "gone.txt").write_text("racing\n")
    real_lstat = pathlib.Path.lstat

    def flaky_lstat(self):
        if self.name == "gone.txt":
            raise OSError(116, "Stale file handle")
        return real_lstat(self)

    monkeypatch.setattr(pathlib.Path, "lstat", flaky_lstat)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert by_path["gone.txt"]["type"] == "error"
    assert by_path["gone.txt"]["error"].startswith("OSError")
    assert by_path["ok.txt"]["type"] == "file"


def test_manifest_entry_swapped_for_special_mid_race_is_recorded_special(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """TOCTOU arm: lstat classified the entry as a regular file, but by
    open+fstat time it is a FIFO. The fstat-on-the-descriptor check must
    reclassify it as 'special' from the SAME object the open returned —
    and must not block doing so (O_NONBLOCK). Simulated by lying in
    lstat over a real FIFO, which exercises the genuine open path."""
    ref = tmp_path / "ref"
    ref.mkdir()
    os.mkfifo(ref / "race")
    real_lstat = pathlib.Path.lstat

    def lying_lstat(self):
        st = real_lstat(self)
        if self.name == "race":
            fake = list(st)
            fake[0] = stat_module.S_IFREG | 0o644
            return os.stat_result(fake)
        return st

    monkeypatch.setattr(pathlib.Path, "lstat", lying_lstat)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    (entry,) = manifest["entries"]
    assert entry["type"] == "special"
    assert entry["sha256"] is None
    assert entry["mode"].startswith("p")


def test_manifest_digest_read_failure_records_unreadable_file(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Read failure AFTER a successful open inside the manifest hashing
    loop: the entry must surface as an unreadable file (sha256:null +
    error), same shape as an open failure — the post-open arm that
    test_unreadable_file_is_marked_in_manifest cannot reach."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / "ok.txt").write_text("fine\n")
    (ref / "flaky.bin").write_text("doomed\n")
    _fail_reads_of(monkeypatch, "flaky.bin")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    manifest = json.loads((fake_repo / verify_reference.MANIFEST_NAME).read_text())
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert by_path["flaky.bin"]["type"] == "file"
    assert by_path["flaky.bin"]["sha256"] is None
    assert by_path["flaky.bin"]["error"].startswith("OSError")
    assert by_path["ok.txt"]["sha256"] is not None


def test_sweep_stat_failure_does_not_block_manifest_write(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """The stale-tmp sweep is best-effort: a stat failure on a candidate
    tmp file is swallowed and the manifest still gets written."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / "a.txt").write_text("x\n")
    cursed = fake_repo / (verify_reference.MANIFEST_NAME + ".dead.tmp")
    cursed.write_text("{")
    real_stat = pathlib.Path.stat

    def flaky_stat(self, **kwargs):
        if self.name.endswith(".dead.tmp"):
            raise OSError(5, "Input/output error")
        return real_stat(self, **kwargs)

    monkeypatch.setattr(pathlib.Path, "stat", flaky_stat)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest"] is not None
    assert "manifest_error" not in result
    assert (fake_repo / verify_reference.MANIFEST_NAME).exists()


def test_manifest_write_failure_with_failed_cleanup_still_degrades(
    tmp_path, fake_repo, deny_manifest_write, monkeypatch, capsys
):
    """Worst case: the manifest write fails AND unlinking the temp file
    fails too. The original write error must still be the one surfaced
    (manifest_error), with rc 1 and one JSON line intact."""
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    real_unlink = pathlib.Path.unlink

    def deny_unlink(self, *args, **kwargs):
        if self.name.startswith(verify_reference.MANIFEST_NAME):
            raise OSError(30, "Read-only file system")
        return real_unlink(self, *args, **kwargs)

    monkeypatch.setattr(pathlib.Path, "unlink", deny_unlink)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest"] is None
    assert result["manifest_error"] == "OSError: read-only file system"


# --- fingerprint stability: the pins match the LIVE repo -------------------


def test_live_sidecars_match_pinned_fingerprint():
    """The drift saga (rounds 4 and 5 re-pins) is settled: every
    sidecar hash pinned in reference_fingerprint.json must equal a
    fresh hash of the live file, so any future edit to BASELINE.json,
    PAPERS.md or SNIPPETS.md shows up HERE — in tier-1 — instead of as
    a surprise EXIT_DRIFT from the driver's next verify round. Note
    BENCH_BASELINE.json is deliberately NOT pinned: perf baselines
    (e.g. the arena_tenant pin) may move without re-surveying the
    reference."""
    repo = pathlib.Path(verify_reference.__file__).resolve().parent
    pins = json.loads((repo / verify_reference.FINGERPRINT_NAME).read_text())
    for key, relpath in verify_reference.SIDECAR_FILES.items():
        observed, detail = verify_reference.observe_sidecar(repo / relpath)
        assert observed not in (
            verify_reference.SIDECAR_UNREADABLE,
            verify_reference.SIDECAR_NOT_A_FILE,
        ), (relpath, detail)
        assert observed == pins[key], (
            f"{relpath} drifted from its reference_fingerprint.json pin: "
            f"re-pin deliberately (see NON_GRAFTABLE.md) or revert the edit"
        )
    assert "BENCH_BASELINE.json" not in set(
        verify_reference.SIDECAR_FILES.values()
    )
