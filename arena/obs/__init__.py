"""arena.obs — zero-dependency observability: metrics, tracing, diagnosis.

The measurement substrate every subsystem reports through (and every
later PR — network tier, replicas, multi-host — will report through):

- `arena.obs.metrics`  — thread-safe registry of counters, gauges, and
  fixed-bucket log2 histograms over preallocated numpy arrays, with a
  Prometheus-style text `render()`, a one-JSON-line `dump()`, and
  per-bucket `(trace_id, value)` latency exemplars.
- `arena.obs.tracing`  — monotonic-clock stage spans in a bounded
  overwrite-oldest ring buffer with MONOTONIC span ids and
  parent/trace links, exportable as Chrome trace-event JSON with
  cross-thread flow events.
- `arena.obs.context`  — the thread-local / cross-thread trace-context
  carrier (`TraceContext`, `attach`) that turns isolated spans into
  one causal tree per request.
- `arena.obs.debug`    — the flight recorder: `dump_debug_bundle()`
  atomically writes one postmortem directory (Chrome trace, registry
  dump, config, recent events + queue-depth timeline).
- `arena.obs.regress`  — the perf-regression watchdog CLI
  (`python -m arena.obs.regress`) comparing the newest bench-history
  line against a pinned baseline.

`Observability` bundles one registry + one tracer (+ a bounded recent-
event log for the flight recorder) behind the small surface the
instrumented modules call (`span`/`counter`/`gauge`/`histogram`/
`event`/`dump`/`render`), and `NULL` is the shared no-op instance:
every call is a constant-time no-op, nothing allocates, nothing is
recorded. `ArenaEngine` defaults to `NULL` (a library user who never
asked for metrics pays a method call, not a measurement — and the
bench hard-gates that the LIVE registry costs < 3% on the ingest and
pipeline paths, so turning it on is cheap too). `ArenaServer` defaults
to a live instance: a serving surface without latency percentiles and
drop counters cannot stand behind any load-shedding policy.

Nothing in this package imports jax — it must load (and its tests must
run) on boxes with no accelerator stack, the same rule as the linter
half of `arena/analysis`.
"""

import time
from collections import deque

from arena.obs.context import TraceContext, attach, current as current_context
from arena.obs.metrics import (
    DEFAULT_LATENCY_BASE,
    DEFAULT_NUM_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    NullRegistry,
    Registry,
)
from arena.obs.tracing import NullTracer, SpanRecord, Tracer

# Recent structured events kept for the flight recorder (drops, spills,
# queue-depth samples). Bounded: a long soak keeps the newest.
DEFAULT_EVENT_CAPACITY = 1024


class Observability:
    """One registry + one tracer + one bounded recent-event log, behind
    the instrumentation surface."""

    enabled = True

    def __init__(self, registry=None, tracer=None, trace_capacity=4096,
                 event_capacity=DEFAULT_EVENT_CAPACITY):
        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else Tracer(trace_capacity)
        self.events = deque(maxlen=event_capacity)

    # --- delegation (the only calls instrumented modules make) -------

    def span(self, name):
        return self.tracer.span(name)

    def counter(self, name, **labels):
        return self.registry.counter(name, **labels)

    def gauge(self, name, **labels):
        return self.registry.gauge(name, **labels)

    def histogram(self, name, base=DEFAULT_LATENCY_BASE,
                  num_buckets=DEFAULT_NUM_BUCKETS, **labels):
        return self.registry.histogram(
            name, base=base, num_buckets=num_buckets, **labels
        )

    def event(self, kind, **fields):
        """Append one structured event (monotonic timestamp + kind +
        fields) to the bounded recent-event log — the drop/spill/
        queue-depth record the flight recorder bundles. Cheap (one
        dict + deque append per EVENT, not per match) and fixed
        memory; never read on the hot path."""
        self.events.append({"t": time.perf_counter(), "kind": kind, **fields})

    def render(self):
        """Prometheus text exposition of the registry."""
        return self.registry.render()

    def dump(self):
        """One JSON-able dict: metrics + trace/event accounting."""
        out = self.registry.dump()
        out["trace"] = {
            "spans_recorded": self.tracer.recorded,
            "trace_dropped": self.tracer.dropped,
            "capacity": self.tracer.capacity,
            "events_recorded": len(self.events),
        }
        return out


class _NullObservability(Observability):
    """The shared no-op instance behind `NULL` (not for direct
    construction — use `NULL`)."""

    enabled = False

    def __init__(self):
        super().__init__(registry=NullRegistry(), tracer=NullTracer(),
                         event_capacity=1)

    def event(self, kind, **fields):
        return None


NULL = _NullObservability()

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "NULL",
    "NullRegistry",
    "NullTracer",
    "Observability",
    "Registry",
    "SpanRecord",
    "TraceContext",
    "Tracer",
    "attach",
    "current_context",
    "DEFAULT_EVENT_CAPACITY",
    "DEFAULT_LATENCY_BASE",
    "DEFAULT_NUM_BUCKETS",
]
