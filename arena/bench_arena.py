"""Benchmark: naive-loop vs vectorized/jitted arena rating updates.

The repo's first real performance number. Emits the same one-JSON-line
rc-0 contract `bench.py` honors (one line on stdout no matter what;
internal failures degrade to a distinct error metric; only an
unwritable stdout exits 1), so the driver can record it the same way.

What is measured (all on synthetic matches from a seeded
Bradley–Terry ground truth, so the workload is reproducible):

- ``naive_epoch_s`` — one full pass of batched Elo over the match set
  via `arena/baseline.py`'s per-match Python/NumPy loop.
- ``jit_epoch_s`` — the same pass (same batch semantics, same batch
  size) through the fused, scatter-free jitted epoch
  (`arena.ratings.elo_epoch`), min over repeats after a warmup call
  (compile time excluded, steady-state measured).
- ``ingest_s`` — the one-time NumPy cost of bucketing/grouping the
  match set (`arena.engine.pack_epoch`). Reported separately and also
  folded into ``speedup_incl_ingest``: ingest is paid once per
  dataset, the epoch cost is paid on every pass (Elo refits,
  bootstrap rounds) and every Bradley–Terry iteration, so the
  headline ``value`` is the steady-state update speedup.
- Bradley–Terry: per-MM-iteration time, naive loop vs fused scan.
- If more than one device is visible (or ARENA_BENCH_DEVICES forces a
  CPU device count), the shard_map data-parallel epoch is timed too —
  reported as numbers per device count, with no speedup claim: on this
  1-core image extra host devices share one core.

The two paths' final ratings are compared BEFORE any speedup is
reported — and the comparison is a HARD GATE, not an annotation: if
``max_diff`` exceeds the tolerance, no speedup is computed at all, the
one JSON line carries the distinct ``arena_bench_equivalence_failure``
metric, and the process exits rc 2 (a measured divergence verdict —
distinct from rc 0's in-contract internal-error degradation and from
rc 1, which stays reserved for an unwritable stdout). A speedup over
code computing something different would be fiction, so it is now
impossible to emit one.

Env knobs (all optional): ARENA_BENCH_MATCHES (100000),
ARENA_BENCH_PLAYERS (1000), ARENA_BENCH_BATCH (8192),
ARENA_BENCH_REPEATS (5), ARENA_BENCH_SEED (0), ARENA_BENCH_BT_ITERS
(25), ARENA_BENCH_TOL (0.5 rating points — the equivalence gate),
ARENA_BENCH_DEVICES (unset — forces a host CPU device count for
the sharded path when the backend is not yet initialized).
"""

import json
import os
import pathlib
import sys
import time

# Must precede any JAX computation (backend init reads XLA_FLAGS; the
# flag is inert after that, which the device-count check below detects).
_FORCED_DEVICES = os.environ.get("ARENA_BENCH_DEVICES")
if _FORCED_DEVICES:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_FORCED_DEVICES}"
        ).strip()

_REPO_DIR = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO_DIR) not in sys.path:
    sys.path.insert(0, str(_REPO_DIR))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import bench  # noqa: E402  (exc_detail — the repo-wide error formatting)
from arena import baseline, engine, ratings, sharding  # noqa: E402

# Max |rating diff| tolerated between the naive float64 loop and the
# float32 scatter-free path, in rating points on the 1500 scale
# (measured ~2e-4 at the default size; budget leaves room for bigger
# runs without letting a real divergence through).
EQUIVALENCE_TOL = 0.5

# Exit codes: 0 = measured (or in-contract internal-error line),
# 1 = stdout unwritable (no JSON line possible), 2 = the two paths
# DIVERGED beyond tolerance — a measured verdict, never conflated
# with a crash (same discipline as the gate's rc 3/rc 4 split).
EXIT_EQUIVALENCE_FAILURE = 2


class EquivalenceError(AssertionError):
    """The naive and vectorized paths disagree beyond tolerance."""

    def __init__(self, max_diff, tol):
        super().__init__(
            f"max |rating diff| {max_diff:.6g} exceeds tolerance {tol:g}; "
            "no speedup may be reported over a divergent computation"
        )
        self.max_diff = max_diff
        self.tol = tol


def _env_int(name, default):
    return int(os.environ.get(name, default))


def make_matches(num_matches, num_players, seed):
    """Synthetic outcomes from a Bradley–Terry ground truth: random
    pairings, winner sampled from true win probability."""
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, num_matches)
    b = (a + 1 + rng.integers(0, num_players - 1, num_matches)) % num_players
    strength = np.linspace(2.0, -2.0, num_players)  # log-strengths
    p_a_wins = 1.0 / (1.0 + np.exp(strength[b] - strength[a]))
    a_wins = rng.random(num_matches) < p_a_wins
    winners = np.where(a_wins, a, b).astype(np.int32)
    losers = np.where(a_wins, b, a).astype(np.int32)
    return winners, losers


def _best_of(fn, repeats):
    """Min wall-clock over repeats; fn must block on its result."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_benchmark():
    num_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    repeats = _env_int("ARENA_BENCH_REPEATS", 5)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    bt_iters = _env_int("ARENA_BENCH_BT_ITERS", 25)

    winners, losers = make_matches(num_matches, num_players, seed)

    # --- naive baseline: full Elo pass, per-match loop ---------------
    t0 = time.perf_counter()
    naive_ratings = baseline.elo_epoch_naive(num_players, winners, losers, batch)
    naive_epoch_s = time.perf_counter() - t0

    # --- ingest (one-time): bucket + group the match set -------------
    t0 = time.perf_counter()
    packed = engine.pack_epoch(num_players, winners, losers, batch)
    jax.block_until_ready(packed.perms)
    ingest_s = time.perf_counter() - t0

    # --- fused jitted epoch ------------------------------------------
    epoch_fn = ratings.jit_elo_epoch(num_players, donate=False)
    r0 = jnp.full((num_players,), ratings.DEFAULT_BASE, jnp.float32)
    args = (packed.winners, packed.losers, packed.valid, packed.perms, packed.bounds)
    jit_ratings = epoch_fn(r0, *args)  # warmup: compile excluded
    jax.block_until_ready(jit_ratings)
    jit_epoch_s = _best_of(
        lambda: jax.block_until_ready(epoch_fn(r0, *args)), repeats
    )

    max_diff = float(np.abs(np.asarray(jit_ratings) - naive_ratings).max())
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))
    equivalence_ok = max_diff < tol
    if not equivalence_ok:
        # Hard gate: nothing below (speedup, BT, sharded numbers) is
        # computed or reported over a divergent pair of paths.
        raise EquivalenceError(max_diff, tol)
    speedup = naive_epoch_s / jit_epoch_s

    # --- Bradley–Terry: per-MM-iteration, naive vs fused -------------
    win_counts = np.bincount(winners, minlength=num_players).astype(np.float64)
    t0 = time.perf_counter()
    baseline.bt_mm_step_naive(
        np.ones(num_players), winners.tolist(), losers.tolist(), win_counts
    )
    bt_naive_iter_s = time.perf_counter() - t0

    whole = engine.pack_batch(
        num_players, winners, losers, min_bucket=engine.bucket_size(num_matches)
    )
    wc32 = jnp.asarray(win_counts.astype(np.float32))
    bt_args = (whole.winners, whole.losers, whole.valid, whole.perm, whole.bounds)
    bt_fit_fn = ratings.jit_bt_fit(num_players, num_iters=bt_iters)

    def bt_run():
        return bt_fit_fn(*bt_args, wc32)

    jax.block_until_ready(bt_run())  # warmup
    bt_jit_iter_s = _best_of(lambda: jax.block_until_ready(bt_run()), repeats) / bt_iters

    # --- sharded path (only meaningful with >1 device) ---------------
    sharded = None
    ndev = len(jax.devices())
    if ndev > 1:
        mesh = sharding.build_mesh()
        sharded_fn = sharding.jit_sharded_elo_epoch(mesh)
        sharded_args = (packed.winners, packed.losers, packed.valid)

        def sharded_run():
            return jax.block_until_ready(
                sharded_fn(jnp.full((num_players,), ratings.DEFAULT_BASE), *sharded_args)
            )

        sharded_run()  # warmup (also compiles)
        sharded_epoch_s = _best_of(sharded_run, repeats)
        sharded = {
            "devices": ndev,
            "epoch_s": round(sharded_epoch_s, 6),
            "matches_per_s": round(num_matches / sharded_epoch_s),
            "note": "CPU host devices share cores; correctness/path proof, not a scaling claim",
        }

    return {
        "metric": "arena_elo_update_speedup",
        "value": round(speedup, 2),
        "unit": "x_vs_naive_baseline",
        "vs_baseline": None,
        "params": {
            "num_matches": num_matches,
            "num_players": num_players,
            "batch_size": batch,
            "repeats": repeats,
            "seed": seed,
        },
        "elo": {
            "naive_epoch_s": round(naive_epoch_s, 6),
            "jit_epoch_s": round(jit_epoch_s, 6),
            "ingest_s": round(ingest_s, 6),
            "naive_matches_per_s": round(num_matches / naive_epoch_s),
            "jit_matches_per_s": round(num_matches / jit_epoch_s),
            "jit_update_latency_us_per_batch": round(
                jit_epoch_s / packed.winners.shape[0] * 1e6, 1
            ),
            "speedup_incl_ingest": round(naive_epoch_s / (jit_epoch_s + ingest_s), 2),
        },
        "bt": {
            "iters": bt_iters,
            "naive_iter_s": round(bt_naive_iter_s, 6),
            "jit_iter_s": round(bt_jit_iter_s, 6),
            "iter_speedup": round(bt_naive_iter_s / bt_jit_iter_s, 2),
        },
        "equivalence_ok": equivalence_ok,
        "max_rating_diff": round(max_diff, 6),
        "sharded": sharded,
    }


def main() -> int:
    rc = 0
    try:
        line = json.dumps(run_benchmark())
    except EquivalenceError as exc:
        # A measured verdict, not a crash: the paths diverged, so the
        # line carries the divergence instead of a speedup and the
        # process exits the distinct equivalence-failure code.
        line = json.dumps(
            {
                "metric": "arena_bench_equivalence_failure",
                "value": -1,
                "unit": "x_vs_naive_baseline",
                "vs_baseline": None,
                "max_rating_diff": round(exc.max_diff, 6),
                "tolerance": exc.tol,
                "error": str(exc),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except Exception as exc:  # noqa: BLE001 — the one-line contract outranks
        line = json.dumps(
            {
                "metric": "arena_bench_internal_error",
                "value": -1,
                "unit": "x_vs_naive_baseline",
                "vs_baseline": None,
                "error": bench.exc_detail(exc),
            }
        )
    # Same single-write discipline as bench.py: one fully-serialized
    # line, flush inside the guard, nothing appended after a failure.
    try:
        print(line)
        sys.stdout.flush()
        return rc
    except Exception:  # noqa: BLE001 — stdout itself is broken
        return 1


if __name__ == "__main__":
    sys.exit(main())
