"""Benchmark entrypoint for the driver.

The reference repository `mark1222/arena` is empty (zero files — see
SURVEY.md and NON_GRAFTABLE.md for the verification evidence), so there is
no workload to benchmark and no baseline to compare against
(BASELINE.json: "N/A — no runnable entrypoint to benchmark").

This script exists so the driver's mandatory bench step records the true
state in machine-readable form instead of crashing on a missing file. It
deliberately reports no performance number: any number here would be
fabricated. The reported value is the *observed* count of entries (files,
directories, symlinks) under the reference mount, so a future re-mount of
a non-empty reference shows up here instead of being masked by a
hardcoded zero. The walk does not follow directory symlinks (os.walk
default), so a symlinked subtree counts as one entry — an undercount of
tree *size*, never of *emptiness*: any nonzero value triggers
investigation.

Distinct metrics for distinct states, so the metric name can never
contradict the value (each still exactly one JSON line on stdout, exit
code 0 — the driver contract):

- ``non_graftable_reference_is_empty`` — mount present and readable,
  observed entry count 0 (the expected state every round).
- ``reference_tree_non_empty`` — mount present and readable, count > 0:
  the reference changed and SURVEY.md is obsolete; value is the count.
- ``reference_mount_missing_or_unreadable`` — mount absent, not a
  directory, or not traversable; value -1.
- ``reference_scan_error`` — the mount passed the initial checks but the
  recursive walk raised OSError partway through (stale mount, entry
  vanishing mid-iteration, unreadable subtree); value -1.
- ``bench_internal_error`` — anything unexpected escaped the states
  above (a repo bug, not evidence about the reference); value -1, with
  an ``error`` field carrying the detail. The contract holds even when
  bench itself is broken — a crash must never exit nonzero with no JSON
  line, and must never masquerade as an authoritative empty tree. This
  covers serialization and print failures too (both sit inside the
  guard; the fallback line is built from literals). The single
  physically-unguardable case is stdout itself being unwritable: no
  line is possible then, and bench exits 1 so the empty output reads
  as the failure it is instead of a silent rc-0 success.

The JSON line also embeds a ``verification`` object — the fingerprint
comparison from verify_reference.verify() — because this is the one
command the driver provably runs every round: reference remounts and
sidecar drift (PAPERS.md/SNIPPETS.md/BASELINE.json changing) land in
BENCH_r*.json automatically, with no human in the loop. The summary
carries the gate's human-facing ``note`` so the artifact self-describes
without the SKILL.md exit-code table, and passes through the gate's
optional evidence fields when present: the remount manifest path and
its ``manifest_shape`` (so a VCS-metadata-only remount can never look
like a plain source tree in a driver artifact), ``mount_type_error``
(a non-directory mount names its type), ``sidecar_errors``, and the
uncommitted round artifacts the hygiene check finds. The embedding is
best-effort: any failure inside verification degrades to an ``error``
field and can never break the one-line / rc-0 contract.

The reference path can be overridden with the GRAFT_REFERENCE_PATH
environment variable (and the fingerprint/sidecar directory with
GRAFT_REPO_PATH) so tests can exercise every branch against temp
directories without touching the real mount.
"""

import json
import os
import pathlib
import sys

DEFAULT_REFERENCE = "/root/reference"
_REPO_DIR = pathlib.Path(__file__).resolve().parent


def exc_detail(exc: BaseException, limit: int = 200) -> str:
    """Class name plus truncated message for error-degradation fields.

    The message matters: `manifest_error: "OSError"` alone cannot
    distinguish a stale-mount read failure from a write failure, and an
    errno/path is exactly what the investigating session needs.
    json.dumps escapes newlines, so embedding this in the one-line
    stdout contract is safe; truncation keeps a pathological message
    from bloating the line. str(exc) is guarded: this function runs
    inside every degradation path, so an exception whose own __str__
    raises must not turn a recoverable crash into an unrecoverable one
    (bench's fallback error line depends on this never raising — only
    a genuinely unwritable stdout may defeat that fallback).
    Lives here (not verify_reference) because the import dependency is
    bench <- verify_reference.
    """
    try:
        message = str(exc)
    except Exception:  # noqa: BLE001 — a raising __str__ must not cascade
        message = "<exception message unavailable: __str__ raised>"
    if not message:
        return exc.__class__.__name__
    return f"{exc.__class__.__name__}: {message}"[:limit]


def guarded_walk(reference: pathlib.Path):
    """os.walk with I/O errors OBSERVABLE, not swallowed.

    pathlib's glob machinery suppresses scan errors (PermissionError on
    3.12, all OSErrors on 3.13+), which would silently undercount a
    mount that goes stale or has an unreadable subtree — reporting a
    half-scanned tree as authoritative. os.walk with onerror re-raising
    makes every scandir failure propagate to the caller instead. This is
    the ONE guarded walk in the repo: the entry count below and
    verify_reference's manifest both iterate it, so they can never
    disagree about what a traversal of the same mount means.
    """

    def _raise(err):
        raise err

    return os.walk(reference, onerror=_raise)


def _count_entries(reference: pathlib.Path) -> int:
    count = 0
    for _dirpath, dirnames, filenames in guarded_walk(reference):
        count += len(dirnames) + len(filenames)
    return count


def scan(reference: pathlib.Path) -> dict:
    """Return the bench result dict for the given reference mount."""
    try:
        accessible = reference.is_dir() and os.access(reference, os.R_OK | os.X_OK)
    except OSError:
        accessible = False
    if not accessible:
        return {
            "metric": "reference_mount_missing_or_unreadable",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
        }
    try:
        count = _count_entries(reference)
    except OSError:
        return {
            "metric": "reference_scan_error",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
        }
    return {
        "metric": (
            "non_graftable_reference_is_empty"
            if count == 0
            else "reference_tree_non_empty"
        ),
        "value": count,
        "unit": "reference_entries",
        "vs_baseline": None,
    }


def verification_summary(reference: pathlib.Path, repo: pathlib.Path, scan_result: dict) -> dict:
    """Best-effort fingerprint evidence for embedding in the bench line.

    Imports verify_reference lazily (it imports this module at top
    level; laziness keeps the dependency one-directional at import
    time) and trims the full evidence line down to the facts a driver
    artifact needs: did anything drift, and what. Exceptions degrade to
    an error field — the driver contract outranks the extra evidence.
    """
    try:
        if str(_REPO_DIR) not in sys.path:
            sys.path.insert(0, str(_REPO_DIR))
        import verify_reference

        result, exit_code = verify_reference.verify(reference, repo, scan_result=scan_result)
        summary = {"exit_code": exit_code}
        if "error" in result:
            summary["error"] = result["error"]
        else:
            summary["matches_fingerprint"] = result["matches_fingerprint"]
            summary["transient_environment_failure"] = result[
                "transient_environment_failure"
            ]
            summary["drift"] = result["drift"]
            if result.get("sidecar_errors"):
                summary["sidecar_errors"] = result["sidecar_errors"]
            if result.get("manifest") is not None:
                summary["manifest"] = result["manifest"]
            if "manifest_error" in result:
                summary["manifest_error"] = result["manifest_error"]
            if "manifest_shape" in result:
                summary["manifest_shape"] = result["manifest_shape"]
            if "mount_type_error" in result:
                summary["mount_type_error"] = result["mount_type_error"]
            # Round-artifact hygiene: only worth a line in the driver
            # artifact when something is actually uncommitted.
            if result.get("uncommitted_round_artifacts"):
                summary["uncommitted_round_artifacts"] = result[
                    "uncommitted_round_artifacts"
                ]
        # The human-facing explanation, so BENCH_r*.json — the one
        # artifact provably recorded every round — self-describes
        # without cross-referencing the SKILL.md exit-code table.
        if "note" in result:
            summary["note"] = result["note"]
        return summary
    except Exception as exc:  # the one-line / rc-0 contract outranks evidence
        return {"error": "verification_unavailable", "detail": exc_detail(exc)}


def main() -> int:
    try:
        reference = pathlib.Path(
            os.environ.get("GRAFT_REFERENCE_PATH", DEFAULT_REFERENCE)
        )
        repo = pathlib.Path(os.environ.get("GRAFT_REPO_PATH", _REPO_DIR))
        result = scan(reference)
        result["verification"] = verification_summary(reference, repo, result)
        line = json.dumps(result)
    except Exception as exc:  # noqa: BLE001 — the driver contract outranks
        # scan() guards OSError and verification_summary guards itself,
        # but anything escaping here would exit rc 1 with a traceback and
        # ZERO JSON lines — breaking the very contract this module exists
        # to uphold. Serialization sits INSIDE the try (a result
        # json.dumps cannot serialize is a crash like any other); the
        # fallback dict is literal-typed strings/ints/None — with
        # exc_detail guaranteed not to raise, its json.dumps cannot
        # fail. The crash stays visible (never reported as an empty
        # tree); the contract stays intact.
        failure = {
            "metric": "bench_internal_error",
            "value": -1,
            "unit": "reference_entries",
            "vs_baseline": None,
            "error": exc_detail(exc),
        }
        line = json.dumps(failure)
    # Exactly ONE write attempt, of a fully serialized line. If it
    # raises, stdout may already hold a PARTIAL line — attempting a
    # second print there would concatenate onto the fragment and exit 0
    # with one unparseable line (a masquerade worse than silence). So
    # once a write has been attempted and failed, nothing more is
    # written: no JSON line is possible, and bench exits nonzero so the
    # mangled/empty output reads as the failure it is. The flush sits
    # INSIDE the guard: with a block-buffered stdout (file/pipe) a
    # failed write only surfaces at flush time, and without this it
    # would surface at interpreter-exit flush instead — CPython's
    # exit 120, outside bench's own contract.
    try:
        print(line)
        sys.stdout.flush()
        return 0
    except Exception:  # noqa: BLE001 — stdout itself is broken
        return 1  # no JSON line was possible


if __name__ == "__main__":
    sys.exit(main())
