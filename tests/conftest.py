"""Shared fixtures for the contract tests.

Two cost facts shape the design (measured on this image):

- plain ``python -c pass`` takes ~1.7s because sitecustomize imports
  jax for every interpreter, and the machine has ONE cpu — so every
  subprocess test costs ~1.7s of wall clock that cannot be parallelized
  away;
- in-process calls to bench.main() / verify_reference.main() cost
  milliseconds.

So the matrix of mount states is tested in-process (monkeypatched env +
capsys), and only FOUR true-subprocess end-to-end runs exist — two per
script. Per script, one runs exactly as the driver does (plain
``python``, paying the site cost) and one runs with ``-S`` (site
skipped; both scripts import only the stdlib, so site processing is
irrelevant to the argv/env/stdout/rc plumbing under test). All four are
launched concurrently, but only on the FIRST request of the ``e2e``
fixture — a partial run (``-k``, ``--collect-only``) that deselects the
e2e tests never spawns them and never touches the real mount or repo.
(One more test spawns ``-S`` subprocesses outside this fixture:
test_broken_bench_import_exits_4_not_1 exercises the gate's
module-level import guard, which is unreachable in-process by
construction; ``-S`` keeps those spawns ~ms, not ~1.7s.)
"""

import hashlib
import json
import os
import pathlib
import shutil
import subprocess
import sys
import tempfile
from types import SimpleNamespace

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Force a 4-device CPU mesh for the arena sharding tests. XLA reads
# XLA_FLAGS at first backend initialization, which happens on first
# device use — after this conftest runs (sitecustomize merely IMPORTS
# jax at interpreter start; that does not initialize a backend). The
# bench/verify subprocess tests inherit the flag harmlessly: those
# scripts never touch a jax device. Guarded so an explicit operator
# setting wins.
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=4"
    ).strip()

BASELINE_CONTENT = '{"north_star": "non-graftable"}\n'
PAPERS_CONTENT = "# PAPERS\n"


def make_fake_repo(
    root: pathlib.Path,
    name: str = "repo",
    with_snippets: bool = False,
    entry_count: int = 0,
):
    """A fake repo dir whose fingerprint matches its own sidecars.

    The fingerprint always pins SNIPPETS.md as "absent" (the rounds-1-3
    upstream state); with_snippets=True creates the file anyway, i.e. a
    sidecar-appeared drift scenario.
    """
    repo = root / name
    repo.mkdir(parents=True)
    (repo / "BASELINE.json").write_text(BASELINE_CONTENT)
    (repo / "PAPERS.md").write_text(PAPERS_CONTENT)
    if with_snippets:
        (repo / "SNIPPETS.md").write_text("# SNIPPETS\n")
    fingerprint = {
        "reference_entry_count": entry_count,
        "baseline_json_sha256": hashlib.sha256(BASELINE_CONTENT.encode()).hexdigest(),
        "papers_md_sha256": hashlib.sha256(PAPERS_CONTENT.encode()).hexdigest(),
        "snippets_md_sha256": "absent",
    }
    (repo / "reference_fingerprint.json").write_text(json.dumps(fingerprint))
    return repo


def make_populated_reference(root: pathlib.Path, name: str = "ref"):
    """A non-empty reference tree: src/, src/main.cu, README.md (3 entries)."""
    ref = root / name
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// not empty\n")
    (ref / "README.md").write_text("hello\n")
    return ref


@pytest.fixture
def fake_repo(tmp_path):
    return make_fake_repo(tmp_path)


def _clean_env(**overrides):
    """os.environ minus GRAFT_* (test overrides) and GIT_* (a hook's
    GIT_DIR/GIT_INDEX_FILE would skew the hygiene check; the fake-repo
    runs re-add GIT_CEILING_DIRECTORIES explicitly)."""
    env = {
        k: v
        for k, v in os.environ.items()
        if not (k.startswith("GRAFT_") or k.startswith("GIT_"))
    }
    env.update(overrides)
    return env


def _launch_e2e():
    root = pathlib.Path(tempfile.mkdtemp(prefix="graft-e2e-"))
    bench_ref = make_populated_reference(root, "bench_ref")
    bench_repo = make_fake_repo(root, "bench_repo")
    verify_ref = make_populated_reference(root, "verify_ref")
    verify_repo = make_fake_repo(root, "verify_repo")

    def spawn(script, env, site=True):
        argv = [sys.executable] + ([] if site else ["-S"]) + [str(REPO / script)]
        return subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd="/tmp",  # must work from any cwd
        )

    procs = {
        # Exactly the driver's invocation: plain python, real mount, real repo.
        "bench_real": SimpleNamespace(
            proc=spawn("bench.py", _clean_env()), repo=REPO
        ),
        "bench_populated": SimpleNamespace(
            proc=spawn(
                "bench.py",
                _clean_env(
                    GRAFT_REFERENCE_PATH=str(bench_ref),
                    GRAFT_REPO_PATH=str(bench_repo),
                    # Pin "fake repo is not inside a git work tree" even
                    # when TMPDIR sits inside a checkout.
                    GIT_CEILING_DIRECTORIES=str(root),
                ),
                site=False,
            ),
            repo=bench_repo,
        ),
        # Exactly the documented round-start gate: plain python, real everything.
        "verify_real": SimpleNamespace(
            proc=spawn("verify_reference.py", _clean_env()), repo=REPO
        ),
        "verify_populated": SimpleNamespace(
            proc=spawn(
                "verify_reference.py",
                _clean_env(
                    GRAFT_REFERENCE_PATH=str(verify_ref),
                    GRAFT_REPO_PATH=str(verify_repo),
                    GIT_CEILING_DIRECTORIES=str(root),
                ),
                site=False,
            ),
            repo=verify_repo,
        ),
    }
    return root, procs


_E2E_STATE = {"root": None, "procs": None}


@pytest.fixture(scope="session")
def e2e():
    root, procs = _launch_e2e()
    _E2E_STATE["root"], _E2E_STATE["procs"] = root, procs
    results = {}
    for name, entry in procs.items():
        out, err = entry.proc.communicate(timeout=120)
        results[name] = SimpleNamespace(
            rc=entry.proc.returncode, out=out, err=err, repo=entry.repo
        )
    return results


@pytest.fixture
def deny_manifest_write(monkeypatch):
    """Writing the manifest fails like a read-only repo dir; everything
    else writes normally. Shared so the name-based match lives in one
    place if the manifest write strategy ever changes. startswith: the
    atomic write goes through MANIFEST_NAME + '.tmp'."""
    import verify_reference

    real_write_text = pathlib.Path.write_text

    def deny(self, *args, **kwargs):
        if self.name.startswith(verify_reference.MANIFEST_NAME):
            raise OSError("read-only file system")
        return real_write_text(self, *args, **kwargs)

    monkeypatch.setattr(pathlib.Path, "write_text", deny)


def pytest_sessionfinish(session, exitstatus):
    if _E2E_STATE["procs"]:
        for entry in _E2E_STATE["procs"].values():
            if entry.proc.poll() is None:
                entry.proc.kill()
                entry.proc.wait()
    if _E2E_STATE["root"]:
        shutil.rmtree(_E2E_STATE["root"], ignore_errors=True)
