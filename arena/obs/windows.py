"""Sliding-window views over the cumulative metrics registry.

PR 6's registry is since-boot by design (monotone counters survive any
read pattern), which makes it useless for "what is the p99 RIGHT NOW".
This module adds the live half without touching the hot path: a
`SlidingWindow` keeps a ring of CUMULATIVE boundary snapshots (one per
rotation interval, e.g. 12 x 5s) and merges on read by diffing a fresh
cumulative snapshot against the oldest retained boundary. Because the
ring stores cumulative states, not per-interval deltas:

1. **Record stays free.** Counters/gauges/histograms are untouched —
   no extra work per `inc()`/`record()`. The only new cost is one
   registry sweep per rotation (each metric read under its OWN
   existing per-metric lock, never a registry-wide freeze), so the
   <3% live-vs-null overhead gate extends to windowed mode unchanged.
2. **Windowed counts are exact.** A window delta is `now - boundary`
   of exact cumulative values — the N-thread exactness property of the
   cumulative registry carries over to every window, pinned by a
   tier-1 test mirroring PR 6's concurrent-increment test.
3. **Quantiles come free.** A histogram window diff is a per-bucket
   counts subtraction; `Histogram._quantile_bucket` over the delta
   counts gives windowed p50/p99 with the same conservative
   upper-bound semantics (within one log2 bucket of the exact
   percentile, property-tested against numpy offline).

Rotation is hybrid: every read path calls `_advance_locked()` first
(correct with no threads at all — tests drive a fake clock), and
`start()` additionally spawns an "arena-obs-window" rotation thread so
an idle server still rotates and `/debug/window` never serves a stale
ring. The thread follows the PR 10 liveness discipline: every blocking
wait on rotation progress re-checks the rotator's liveness
(`thread-no-liveness-recheck`), so a dead rotator surfaces as a
`WindowError` / `health()["error"]`, never a silently frozen window.

`NullWindow` is the `NullRegistry`-style no-op twin. No jax imports in
this package.
"""

import threading
import time

import numpy as np

from arena.obs.metrics import Histogram, _label_suffix

DEFAULT_INTERVALS = 12
DEFAULT_INTERVAL_S = 5.0

# Bounded wait quantum: blocked readers wake at least this often to
# re-check rotator liveness (the PR 10 discipline).
_WAIT_QUANTUM_S = 0.05


class WindowError(RuntimeError):
    """Sliding-window misuse or a dead rotation thread."""


def _label_match(labels, match):
    """True when `labels` superset-matches `match`; a wanted value
    ending in ``*`` is a prefix pattern (e.g. ``status="5*"``)."""
    if not match:
        return True
    for key, want in match.items():
        have = labels.get(key)
        if have is None:
            return False
        if isinstance(want, str) and want.endswith("*"):
            if not str(have).startswith(want[:-1]):
                return False
        elif have != want:
            return False
    return True


class WindowHistogram:
    """A histogram's delta between two boundary snapshots: per-bucket
    counts with the live metric's bounds, supporting the same
    conservative bucket-upper-bound percentile read."""

    __slots__ = ("bounds", "counts", "count", "sum", "elapsed_s")

    def __init__(self, bounds, counts, count, sum_, elapsed_s):
        self.bounds = bounds
        self.counts = counts
        self.count = count
        self.sum = sum_
        self.elapsed_s = elapsed_s

    @property
    def rate_per_s(self):
        return self.count / self.elapsed_s if self.elapsed_s > 0 else 0.0

    def percentile(self, q):
        """Windowed quantile: upper bound of the bucket holding
        quantile q of the WINDOW's observations (None when the window
        saw none, +inf in overflow — same contract as the cumulative
        `Histogram.percentile`)."""
        if self.count == 0:
            return None
        idx = Histogram._quantile_bucket(self.counts, self.count, q)
        if idx >= self.bounds.size:
            return float("inf")
        return float(self.bounds[idx])

    def to_payload(self):  # schema: wire-debug-window@v1
        out = {
            "count": int(self.count),
            "rate_per_s": round(self.rate_per_s, 6),
            "sum": round(float(self.sum), 9),
        }
        for name, q in (("p50", 0.5), ("p99", 0.99)):
            p = self.percentile(q)
            out[name] = None if p is None else (
                p if p != float("inf") else "inf"
            )
        return out


class WindowDelta:
    """The merged view between the window's oldest retained boundary
    and a fresh cumulative snapshot — what SLO evaluation and
    `/debug/window` read from."""

    __slots__ = ("elapsed_s", "_old", "_now")

    def __init__(self, old, now):
        self._old = old
        self._now = now
        self.elapsed_s = max(0.0, now["t"] - old["t"])

    def _keys(self, table, name, match):
        for key in self._now[table]:
            if key[0] != name:
                continue
            if _label_match(dict(key[1]), match):
                yield key

    def counter_delta(self, name, match=None):
        """Exact windowed count: sum of `now - boundary` over every
        label set matching `match` (metrics born inside the window
        diff against an implicit zero)."""
        old = self._old["counters"]
        total = 0
        for key in self._keys("counters", name, match):
            total += self._now["counters"][key] - old.get(key, 0)
        return total

    def counter_rate(self, name, match=None):
        if self.elapsed_s <= 0:
            return 0.0
        return self.counter_delta(name, match) / self.elapsed_s

    def gauge(self, name, match=None):
        """Latest value of the first matching gauge (gauges are
        last-write-wins; a window diff of one is meaningless)."""
        for key in self._keys("gauges", name, match):
            return self._now["gauges"][key]
        return None

    def histogram(self, name, match=None):
        """Per-bucket delta merged across every matching label set
        (series with mismatched bucket layouts are skipped rather than
        mis-added)."""
        bounds = None
        counts = None
        count = 0
        sum_ = 0.0
        old = self._old["hists"]
        for key in self._keys("hists", name, match):
            n_counts, n_count, n_sum, n_bounds = self._now["hists"][key]
            o_counts, o_count, o_sum, _b = old.get(
                key, (None, 0, 0.0, n_bounds)
            )
            d_counts = (
                n_counts.copy() if o_counts is None else n_counts - o_counts
            )
            if bounds is None:
                bounds = n_bounds
                counts = d_counts
            elif n_bounds.shape == bounds.shape and (
                n_bounds == bounds
            ).all():
                counts = counts + d_counts
            else:
                continue
            count += n_count - o_count
            sum_ += n_sum - o_sum
        if bounds is None:
            bounds = np.zeros(0, np.float64)
            counts = np.zeros(1, np.int64)
        return WindowHistogram(bounds, counts, count, sum_, self.elapsed_s)

    def to_payload(self):
        """JSON-able window view: non-zero counter deltas/rates, gauge
        spot values, histogram windows with p50/p99."""
        counters = {}
        for key, now_v in sorted(self._now["counters"].items()):
            delta = now_v - self._old["counters"].get(key, 0)
            if delta == 0:
                continue
            rate = delta / self.elapsed_s if self.elapsed_s > 0 else 0.0
            counters[key[0] + _label_suffix(dict(key[1]))] = {
                "delta": delta,
                "rate_per_s": round(rate, 6),
            }
        gauges = {
            key[0] + _label_suffix(dict(key[1])): value
            for key, value in sorted(self._now["gauges"].items())
        }
        histograms = {}
        for key in sorted(self._now["hists"]):
            h = self.histogram(key[0], match=dict(key[1]))
            if h.count:
                histograms[key[0] + _label_suffix(dict(key[1]))] = (
                    h.to_payload()
                )
        return {
            "window_s": round(self.elapsed_s, 3),
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
        }


class SlidingWindow:  # protocol: start->close
    """Ring of cumulative boundary snapshots over one `Registry`.

    The ring holds `intervals` slots; `_head` is the slot the NEXT
    boundary overwrites, which makes `ring[_head]` always the OLDEST
    retained boundary — a full-window read spans between `intervals`
    and `intervals + 1` rotation intervals of history. `delta(k)`
    reads against the boundary k rotations back for the fast SLO
    windows.
    """

    def __init__(self, registry, intervals=DEFAULT_INTERVALS,
                 interval_s=DEFAULT_INTERVAL_S, clock=time.monotonic):
        if intervals < 1 or interval_s <= 0:
            raise WindowError(
                f"window needs intervals >= 1 and interval_s > 0, got "
                f"({intervals}, {interval_s})"
            )
        self._registry = registry
        self.intervals = int(intervals)
        self.interval_s = float(interval_s)
        self._clock = clock
        self._cv = threading.Condition()
        seed = self._snap_cumulative()
        self._ring = [seed] * self.intervals  # guarded_by: _cv
        self._head = 0  # guarded_by: _cv (next slot to overwrite = oldest)
        self._boundary = seed["t"] + self.interval_s  # guarded_by: _cv
        self._rotations = 0  # guarded_by: _cv
        self._thread = None  # guarded_by: _cv
        self._closed = False  # guarded_by: _cv
        self._failure = None  # guarded_by: _cv (rotator death reason)

    # --- snapshotting -------------------------------------------------

    def _snap_cumulative(self):
        """One cumulative snapshot of every metric, each read under its
        own per-metric lock (no registry-wide freeze, no window lock
        required — pure reads of monotone state)."""
        counters, gauges, hists = {}, {}, {}
        for (name, lkey), metric in self._registry._sorted_metrics():
            key = (name, lkey)
            kind = type(metric).__name__
            if kind == "Counter":
                counters[key] = metric.value
            elif kind == "Gauge":
                gauges[key] = metric.value
            else:
                counts, count, sum_ = metric.counts_snapshot()
                hists[key] = (counts, count, sum_, metric.bounds)
        return {"t": self._clock(), "counters": counters, "gauges": gauges,
                "hists": hists}

    # --- rotation -----------------------------------------------------

    def advance(self):
        """Rotate every boundary the clock has crossed (0..n slots);
        cheap no-op between boundaries. Every read path calls this, so
        the window is correct even with no rotation thread."""
        with self._cv:
            return self._advance_locked()

    def _advance_locked(self):
        now = self._clock()
        if now < self._boundary:
            return 0
        crossed = int((now - self._boundary) // self.interval_s) + 1
        snap = self._snap_cumulative()
        for _ in range(min(crossed, len(self._ring))):
            self._ring[self._head] = snap
            self._head = (self._head + 1) % len(self._ring)
            self._rotations += 1
        self._boundary += crossed * self.interval_s
        self._cv.notify_all()
        return crossed

    def _run(self):
        try:
            while True:
                with self._cv:
                    if self._closed:
                        return
                    pause = max(
                        _WAIT_QUANTUM_S,
                        min(self._boundary - self._clock(), self.interval_s),
                    )
                    self._cv.wait(timeout=pause)
                    if self._closed:
                        return
                    self._advance_locked()
        except Exception as exc:  # surfaced via health()/wait_for_rotation
            with self._cv:
                self._failure = f"{type(exc).__name__}: {exc}"
                self._cv.notify_all()

    def start(self):
        """(Re)start the rotation thread; idempotent while one is
        alive."""
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._closed = False
            self._failure = None
            self._thread = threading.Thread(
                target=self._run, name="arena-obs-window", daemon=True
            )
            self._thread.start()
        return self

    def close(self):
        """Stop the rotation thread (reads keep working in on-read
        mode afterwards)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    # --- liveness (PR 10 discipline) ---------------------------------

    def _check_rotator_locked(self):
        """Raise if the rotation thread died — callers blocked on
        rotation progress re-check this every wakeup so a dead rotator
        is an explicit error, never a silent hang."""
        if self._failure is not None:
            raise WindowError(
                f"window rotation thread died: {self._failure}"
            )
        if self._thread is None:
            raise WindowError(
                "no rotation thread running (start() the window before "
                "waiting on rotations)"
            )
        if not self._thread.is_alive() and not self._closed:
            raise WindowError(
                "window rotation thread died without recording a failure"
            )

    def wait_for_rotation(self, rotations=1, timeout=10.0):
        """Block until the ring rotates `rotations` more times,
        re-checking rotator liveness every bounded wait."""
        deadline = time.monotonic() + timeout
        with self._cv:
            target = self._rotations + rotations
            while self._rotations < target:
                self._check_rotator_locked()
                if time.monotonic() >= deadline:
                    raise WindowError(
                        f"window did not rotate {rotations}x within "
                        f"{timeout:g}s"
                    )
                self._cv.wait(timeout=_WAIT_QUANTUM_S)
            return self._rotations

    def health(self):
        """Rotator liveness + accounting for `stats()`: `error` is
        None in on-read mode and after a clean close — non-None ONLY
        when a started rotator died."""
        with self._cv:
            error = self._failure
            thread = self._thread
            if (
                error is None
                and thread is not None
                and not thread.is_alive()
                and not self._closed
            ):
                error = (
                    "window rotation thread died without recording a "
                    "failure"
                )
            return {
                "mode": "thread" if thread is not None else "on-read",
                "intervals": self.intervals,
                "interval_s": self.interval_s,
                "rotations": self._rotations,
                "error": error,
            }

    # --- reads --------------------------------------------------------

    def delta(self, intervals=None):
        """Merged `WindowDelta` over the last `intervals` boundaries
        (default: the full ring)."""
        with self._cv:
            self._advance_locked()
            k = (
                self.intervals
                if intervals is None
                else max(1, min(int(intervals), self.intervals))
            )
            old = self._ring[(self._head - k) % len(self._ring)]
        return WindowDelta(old, self._snap_cumulative())

    def read(self, intervals=None):  # schema: wire-debug-window@v1
        """The `/debug/window` payload: the merged window view plus
        ring accounting and rotator health."""
        out = self.delta(intervals=intervals).to_payload()
        out["ring"] = self.health()
        return out


class NullWindow:
    """No-op twin (the `NullRegistry` discipline): identical surface,
    constant-time everywhere, never spawns a thread."""

    enabled = False
    intervals = 0
    interval_s = 0.0

    def start(self):
        return self

    def close(self):
        return None

    def advance(self):
        return 0

    def wait_for_rotation(self, rotations=1, timeout=10.0):
        return 0

    def health(self):
        return {"mode": "null", "intervals": 0, "interval_s": 0.0,
                "rotations": 0, "error": None}

    def delta(self, intervals=None):
        empty = {"t": 0.0, "counters": {}, "gauges": {}, "hists": {}}
        return WindowDelta(empty, empty)

    def read(self, intervals=None):
        return {"window_s": 0.0, "counters": {}, "gauges": {},
                "histograms": {}, "ring": self.health()}
