"""Deliberately naive pure-Python/NumPy loop baseline for the arena engine.

This module is the measuring stick for `arena/bench_arena.py`: the
idiomatic first implementation a researcher writes — ratings in a NumPy
array, one Python loop iteration per match, one expected-score
computation per match via `10 ** x`. Nothing here is artificially
pessimized (no sleeps, no redundant work); it is simply unvectorized,
so it pays Python interpreter and NumPy scalar-dispatch overhead per
match instead of per batch (~1.2µs/match measured on this image,
vs ~20ns/match for the fused jitted path).

Semantics are IDENTICAL to the optimized path (`arena/ratings.py`):
batched updates where every expected score in a batch reads the
ratings at batch start and deltas are accumulated then applied. The
bench verifies numerical agreement between the two paths before it
reports any speedup — a speedup over code computing something else
would be fiction.

Keep this file boring. It exists to be correct and slow.
"""

import numpy as np

from arena.ratings import DEFAULT_BASE, DEFAULT_K, DEFAULT_SCALE


def elo_expected_naive(r_winner, r_loser, scale=DEFAULT_SCALE):
    """Textbook Elo expectation, one match at a time."""
    return 1.0 / (1.0 + 10.0 ** ((r_loser - r_winner) / scale))


def elo_batch_update_naive(ratings, winners, losers, k=DEFAULT_K, scale=DEFAULT_SCALE):
    """One batched Elo round as a per-match Python loop.

    `ratings` is a NumPy float array (mutated in place and returned);
    winners/losers are Python ints or anything indexable into it.
    """
    deltas = np.zeros_like(ratings)
    for w, l in zip(winners, losers):
        e = elo_expected_naive(ratings[w], ratings[l], scale)
        d = k * (1.0 - e)
        deltas[w] += d
        deltas[l] -= d
    ratings += deltas
    return ratings


def elo_epoch_naive(
    num_players,
    winners,
    losers,
    batch_size,
    k=DEFAULT_K,
    scale=DEFAULT_SCALE,
    base=DEFAULT_BASE,
):
    """A full pass over the match list in batch-sized rounds."""
    ratings = np.full(num_players, base, dtype=np.float64)
    winners = [int(w) for w in winners]
    losers = [int(l) for l in losers]
    for start in range(0, len(winners), batch_size):
        elo_batch_update_naive(
            ratings,
            winners[start : start + batch_size],
            losers[start : start + batch_size],
            k,
            scale,
        )
    return ratings


def bt_mm_step_naive(strengths, winners, losers, win_counts, prior=0.1):
    """One Bradley–Terry MM iteration as a per-match Python loop.

    Same update rule as `arena.ratings.bt_mm_step` (Hunter 2004 with a
    ghost-player prior and unit-geometric-mean gauge), accumulated one
    match at a time.
    """
    n = len(strengths)
    denom = np.zeros(n, dtype=np.float64)
    for w, l in zip(winners, losers):
        inv = 1.0 / (strengths[w] + strengths[l])
        denom[w] += inv
        denom[l] += inv
    denom += 2.0 * prior / (strengths + 1.0)
    new = (np.asarray(win_counts) + prior) / denom
    new *= np.exp(-np.mean(np.log(new)))
    return new


def bt_fit_naive(num_players, winners, losers, num_iters=50, prior=0.1):
    """Bradley–Terry MLE by looping `bt_mm_step_naive`."""
    winners = [int(w) for w in winners]
    losers = [int(l) for l in losers]
    win_counts = np.bincount(winners, minlength=num_players).astype(np.float64)
    strengths = np.ones(num_players, dtype=np.float64)
    for _ in range(num_iters):
        strengths = bt_mm_step_naive(strengths, winners, losers, win_counts, prior)
    return strengths
