"""jaxlint corpus: calling into an object after its terminal method.

`Feed` declares `# protocol: close` — close() is the end of the
object's life (threads joined, buffers dropped). `shutdown_and_flush`
closes the feed and then polls it, exactly the shape that turns into a
silent no-op or an attribute error at 3am depending on which fields
close() tore down. Rule: use-after-close."""


class Feed:  # protocol: close
    """A poll-able source whose close() drops the underlying buffer."""

    def __init__(self):
        self._buffer = []

    def poll(self):
        return self._buffer.pop() if self._buffer else None

    def close(self):
        self._buffer = None


def shutdown_and_flush(sink):
    feed = Feed()
    feed.close()
    sink.write(feed.poll())  # the feed is dead: poll() after close()
