"""Incremental ingestion: mergeable CSR packing + double-buffered staging.

PR 1 made the rating math ~55-70x faster but left the host standing
still: every cold pass re-sorts and re-groups the ENTIRE match set
(`engine.pack_epoch`, one NumPy counting sort per batch, ~50ms per
100k matches), and the whole-set Bradley–Terry refit re-packs from
scratch into a single pow2 bucket. This module removes the
repack-the-world pattern so the engine can absorb a continuous arena
match stream:

1. **`MergeableCSR`** — the whole-set per-player grouping kept as a
   MERGEABLE structure instead of a recompute-from-scratch artifact.
   The packed match set lives as sorted per-player runs (`_keys`
   ascending player id, `_pos` the matching entry positions) plus a
   small unsorted delta tail of recently added batches. Merging a new
   batch costs an O(d log d) sort of just the delta; when the tail
   outgrows the LSM-style size-ratio limit (main/size_ratio entries,
   floored at `compact_threshold` — so merge cost stays amortized
   O(size_ratio) per entry as the base grows unbounded), ONE linear
   galloping merge (`_gallop_merge`: vectorized binary/exponential
   search of the sorted tail into the runs, then two fancy-index
   copies) folds it into the main runs — the full O(N log N) re-sort
   never happens again after the first build. All mutations and
   `clone()` run under one internal lock, so the pipeline's packer
   thread and a concurrent snapshot can never observe a
   mid-compaction structure. Entry positions use the INTERLEAVED
   convention: match i's winner entry is position 2i, its loser entry
   2i+1, so previously-merged positions never shift when matches are
   appended (the concat([winners, losers]) convention of
   `engine.pack_batch` would renumber every loser entry on each
   append).

2. **`StagingBuffers`** — double-buffered, bucket-sized host staging
   for the per-batch Elo path. Two reusable slots per pow2 bucket:
   a merge fills one slot's preallocated arrays in place while the
   device may still be consuming the previous dispatch's slot
   (dispatch is asynchronous), so steady-state ingest performs zero
   host-side buffer allocations and — because slot shapes ARE the
   pow2 buckets — zero new jit compiles (enforced with
   `RecompileSentinel` in tests and in `bench_arena.py`'s ingest
   mode). On this CPU backend "pinned" is a no-op and `jnp.asarray`
   still copies host→device; the reuse is host-side, and the
   two-slot rotation is the shape an accelerator backend needs for
   true transfer/compute overlap.

3. **`chunk_layout`** — splits the merged whole-set grouping into the
   epoch layout (multiple fixed-size chunks over the SORTED entry
   order) that `ratings.bt_fit_chunked` scans, instead of padding
   everything into one pow2 bucket. Padded slots in the last chunk
   point at a sentinel position (one appended zero in the values
   array), so no validity mask is needed: the match arrays themselves
   are exact-length. The largest allocated bucket becomes one chunk
   (`chunk_entries`), not `2*pow2(num_matches)` — the 2x memory cliff
   the ISSUE names.

Everything here is host-side NumPy (jnp only at the final
device-transfer boundary), matching the ingest discipline jaxlint's
`jnp-on-host-path` rule enforces.
"""

import threading
from collections import deque

import numpy as np

import jax.numpy as jnp

from arena.engine import (
    MIN_BUCKET,
    PackedBatch,
    _validate_matches,
    bucket_size,
)
from arena.obs import NULL as NULL_OBS

# Floor on the tail entries (2 per match) tolerated before a galloping
# merge folds the delta into the main runs. The live limit is
# LSM-style size-ratio: compact when the tail outgrows main/size_ratio
# entries (see `MergeableCSR._compact_limit`), with this floor keeping
# tiny early sets from compacting on every add. 16384 entries = 8192
# matches, one default bench batch.
DEFAULT_COMPACT_THRESHOLD = 16_384

# LSM size-ratio: the delta tail may grow to main/size_ratio entries
# before a compaction folds it in. Each merge is O(main + tail) and is
# amortized over >= main/size_ratio newly added entries, so merge cost
# stays amortized O(size_ratio) per entry NO MATTER how large the base
# grows — the fixed-count threshold this replaces degraded to one
# O(main) merge per fixed-size batch as main grew unbounded.
DEFAULT_SIZE_RATIO = 8

# Sorted-order entries per chunk in the epoch layout handed to the
# chunked Bradley-Terry fit (2 entries per match -> 8192 matches).
DEFAULT_CHUNK_ENTRIES = 16_384


def _gallop_merge(keys_a, pos_a, keys_b, pos_b):
    """Linear merge of two sorted (keys, pos) runs, no re-sort.

    `keys_b` is binary/exponential-searched into `keys_a` in one
    vectorized `searchsorted` (the galloping step), then both runs are
    placed with two fancy-index copies — O(len_a + len_b) data
    movement, never an O(n log n) sort over the combined set.
    side="right" appends new entries AFTER existing equal keys, so a
    player's run stays ordered by insertion time.
    """
    if keys_a.size == 0:
        return keys_b.copy(), pos_b.copy()
    if keys_b.size == 0:
        return keys_a, pos_a
    out_k = np.empty(keys_a.size + keys_b.size, keys_a.dtype)
    out_p = np.empty(pos_a.size + pos_b.size, pos_a.dtype)
    b_dest = np.searchsorted(keys_a, keys_b, side="right") + np.arange(
        keys_b.size, dtype=np.int64
    )
    out_k[b_dest] = keys_b
    out_p[b_dest] = pos_b
    a_mask = np.ones(out_k.size, bool)
    a_mask[b_dest] = False
    out_k[a_mask] = keys_a
    out_p[a_mask] = pos_a
    return out_k, out_p


class MergeableCSR:
    """Whole-set per-player grouping maintained incrementally.

    Holds the full match history (`winners()`/`losers()`, growable
    arrays with amortized doubling) AND its grouping: for every match
    two entries (winner at interleaved position 2i, loser at 2i+1),
    grouped by player id. `add` sorts only the new batch and appends
    it to the delta tail; `compact` gallop-merges the tail into the
    main sorted runs; `grouping` returns the merged `(perm, bounds)` —
    drop-in for `sorted_segment_sum` over interleaved values.
    """

    def __init__(
        self,
        num_players,
        compact_threshold=DEFAULT_COMPACT_THRESHOLD,
        size_ratio=DEFAULT_SIZE_RATIO,
        obs=None,
    ):
        if num_players < 2:
            raise ValueError("an arena needs at least two players")
        if size_ratio < 1:
            raise ValueError(f"size_ratio must be >= 1, got {size_ratio}")
        self.num_players = num_players
        self.compact_threshold = compact_threshold
        self.size_ratio = size_ratio
        # Observability handle (arena.obs.Observability); defaults to
        # the shared no-op instance, so an uninstrumented store pays a
        # constant-time null call per batch, never a measurement.
        self._obs = obs if obs is not None else NULL_OBS
        # One lock covers every mutation AND clone(): the pipeline's
        # packer thread merges batches under it, so a concurrent
        # clone()/grouping() from another thread always snapshots a
        # consistent structure (never mid-compaction). RLock because
        # grouping() compacts and add() may compact. The `guarded_by`
        # annotations below are the jaxlint contract: every write to
        # these attributes outside __init__ must hold this lock
        # (`unguarded-shared-write` polices it statically).
        self._lock = threading.RLock()
        self.num_matches = 0  # guarded_by: _lock
        self.compactions = 0  # guarded_by: _lock
        # Main sorted runs: keys ascending player id, pos the
        # interleaved entry positions in that order.
        self._keys = np.empty(0, np.int32)  # guarded_by: _lock
        self._pos = np.empty(0, np.int32)  # guarded_by: _lock
        # Delta tail: per-batch sorted runs not yet merged into main.
        self._tail_keys = []  # guarded_by: _lock
        self._tail_pos = []  # guarded_by: _lock
        self._tail_entries = 0  # guarded_by: _lock
        # Match history, capacity-doubled so add() is amortized O(d).
        self._w = np.empty(1024, np.int32)  # guarded_by: _lock
        self._l = np.empty(1024, np.int32)  # guarded_by: _lock

    def _reserve(self, n):
        need = self.num_matches + n
        if need <= self._w.size:
            return
        cap = self._w.size
        while cap < need:
            cap *= 2
        for name in ("_w", "_l"):
            grown = np.empty(cap, np.int32)
            grown[: self.num_matches] = getattr(self, name)[: self.num_matches]
            setattr(self, name, grown)

    def winners(self):
        return self._w[: self.num_matches]

    def losers(self):
        return self._l[: self.num_matches]

    @property
    def tail_entries(self):
        """Entries (2 per match) waiting in the unmerged delta tail."""
        return self._tail_entries

    def _compact_limit(self):
        """LSM-style size-ratio bound on the delta tail: compact when
        the tail outgrows main/size_ratio entries, floored at
        compact_threshold so tiny early sets do not pay a merge per
        add. Amortized merge cost per entry is O(size_ratio) at ANY
        base size — the point of the policy."""
        return max(self.compact_threshold, self._keys.size // self.size_ratio)

    def add(self, winners, losers):
        """Merge one batch: O(d log d) sort of the delta, deferred
        linear galloping merge. Returns the number of matches added.
        The span covers lock wait + delta sort (+ any compaction the
        add triggers, which records its own nested span)."""
        with self._obs.span("ingest.csr_merge"), self._lock:
            return self._add_locked(winners, losers)

    def _add_locked(self, winners, losers):
        w = np.asarray(winners, np.int32)
        l = np.asarray(losers, np.int32)
        _validate_matches(self.num_players, w, l)
        d = w.shape[0]
        if d == 0:
            return 0
        self._reserve(d)
        base = self.num_matches
        self._w[base : base + d] = w
        self._l[base : base + d] = l
        wpos = (2 * base + 2 * np.arange(d)).astype(np.int32)
        keys = np.concatenate([w, l])
        pos = np.concatenate([wpos, wpos + 1])
        order = np.argsort(keys, kind="stable").astype(np.int64)
        self._tail_keys.append(keys[order].astype(np.int32))
        self._tail_pos.append(pos[order])
        self._tail_entries += 2 * d
        self.num_matches += d
        self._obs.counter("arena_ingest_matches_total").inc(d)
        if self._tail_entries > self._compact_limit():
            self._compact_locked()
        return d

    def compact(self):
        """Fold the delta tail into the main runs: one stable sort of
        the (small) tail, one linear galloping merge. No-op when the
        tail is empty."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self):
        if not self._tail_keys:
            return
        with self._obs.span("ingest.compaction"):
            tail_k = np.concatenate(self._tail_keys)
            tail_p = np.concatenate(self._tail_pos)
            order = np.argsort(tail_k, kind="stable").astype(np.int64)
            self._keys, self._pos = _gallop_merge(
                self._keys, self._pos, tail_k[order], tail_p[order]
            )
            self._tail_keys = []
            self._tail_pos = []
            self._tail_entries = 0
            self.compactions += 1
            self._obs.counter("arena_ingest_compactions_total").inc()

    def grouping(self):
        """Merged `(perm, bounds)` over all `2*num_matches` entries.

        `perm` holds interleaved entry positions in player-sorted
        order; `bounds[p]` is player p's start offset (length
        num_players+1). Compacts first, so the returned view IS the
        main runs — callers pay at most one tail merge, never a full
        re-sort. The returned arrays are a consistent snapshot: a later
        concurrent compaction builds NEW arrays, it never mutates
        these in place.
        """
        with self._lock:
            self._compact_locked()
            bounds = np.searchsorted(
                self._keys, np.arange(self.num_players + 1), side="left"
            ).astype(np.int32)
            return self._pos, bounds

    def export_state(self):
        """Run-level state for a durable snapshot: independent copies of
        the main sorted runs, the delta tail AS RUNS (per-run lengths
        preserved so a restore re-splits them without re-sorting — the
        point of the mergeable structure is that the O(N log N) sort
        never happens again, and that includes across a process
        restart), and the raw match log. Taken under the same lock the
        packer merges under, so a snapshot during concurrent ingest is
        a consistent structure. Every array is int32; the serving
        layer writes them raw."""
        with self._lock:
            tail_lengths = np.array(
                [run.size for run in self._tail_keys], np.int32
            )
            return {
                "num_matches": self.num_matches,
                "compactions": self.compactions,
                "compact_threshold": self.compact_threshold,
                "size_ratio": self.size_ratio,
                "keys": self._keys.copy(),
                "pos": self._pos.copy(),
                "tail_keys": (
                    np.concatenate(self._tail_keys)
                    if self._tail_keys
                    else np.empty(0, np.int32)
                ),
                "tail_pos": (
                    np.concatenate(self._tail_pos)
                    if self._tail_pos
                    else np.empty(0, np.int32)
                ),
                "tail_run_lengths": tail_lengths,
                "winners": self._w[: self.num_matches].copy(),
                "losers": self._l[: self.num_matches].copy(),
            }

    @classmethod
    def from_state(cls, num_players, state, obs=None):
        """Rebuild a store from `export_state` output WITHOUT re-sorting:
        the main runs and each tail run are installed as-is (they were
        sorted when exported; restore trusts the arrays only after the
        cross-checks below). Raises ValueError on any internal
        inconsistency — the serving loader converts that into its
        distinct SnapshotError, with the store never half-built."""
        csr = cls(
            num_players,
            compact_threshold=int(state["compact_threshold"]),
            size_ratio=int(state["size_ratio"]),
            obs=obs,
        )
        n = int(state["num_matches"])
        keys = np.asarray(state["keys"], np.int32)
        pos = np.asarray(state["pos"], np.int32)
        tail_keys = np.asarray(state["tail_keys"], np.int32)
        tail_pos = np.asarray(state["tail_pos"], np.int32)
        run_lengths = np.asarray(state["tail_run_lengths"], np.int64)
        w = np.asarray(state["winners"], np.int32)
        l = np.asarray(state["losers"], np.int32)
        if w.size != n or l.size != n:
            raise ValueError(
                f"match log length {w.size}/{l.size} != num_matches {n}"
            )
        if keys.size != pos.size or tail_keys.size != tail_pos.size:
            raise ValueError("grouping keys/pos arrays disagree in length")
        if int(run_lengths.sum()) != tail_keys.size:
            raise ValueError(
                f"tail run lengths sum to {int(run_lengths.sum())}, "
                f"tail holds {tail_keys.size} entries"
            )
        if keys.size + tail_keys.size != 2 * n:
            raise ValueError(
                f"grouping covers {keys.size + tail_keys.size} entries, "
                f"expected {2 * n} (2 per match)"
            )
        _validate_matches(num_players, w, l)
        if keys.size and (keys[:-1] > keys[1:]).any():
            raise ValueError("main run keys are not sorted")
        csr.num_matches = n
        csr.compactions = int(state["compactions"])
        csr._keys = keys
        csr._pos = pos
        if run_lengths.size:
            splits = np.cumsum(run_lengths)[:-1]
            csr._tail_keys = list(np.split(tail_keys, splits))
            csr._tail_pos = list(np.split(tail_pos, splits))
        csr._tail_entries = tail_keys.size
        cap = max(1024, n)
        csr._w = np.empty(cap, np.int32)
        csr._l = np.empty(cap, np.int32)
        csr._w[:n] = w
        csr._l[:n] = l
        return csr

    def clone(self, obs=None):
        """Independent copy (bench baseline-vs-delta runs; also the
        seed of the snapshot/restore the serving layer will need).
        Snapshots under the same lock the pipeline's packer merges
        under, so a clone taken while a compaction is in flight on
        another thread is still a consistent structure. `obs` rewires
        the copy's observability handle (the bench's overhead gate
        clones one base into a null-instrumented and a live-
        instrumented run); default inherits the source's."""
        with self._lock:
            other = MergeableCSR(
                self.num_players, self.compact_threshold, self.size_ratio,
                obs=obs if obs is not None else self._obs,
            )
            other.num_matches = self.num_matches
            other.compactions = self.compactions
            other._keys = self._keys.copy()
            other._pos = self._pos.copy()
            other._tail_keys = [run.copy() for run in self._tail_keys]
            other._tail_pos = [run.copy() for run in self._tail_pos]
            other._tail_entries = self._tail_entries
            other._w = self._w.copy()
            other._l = self._l.copy()
            return other


class _Slot:
    """One staging slot: preallocated bucket-shaped host arrays."""

    def __init__(self, bucket, num_players, dtype):
        self.bucket = bucket
        self.w = np.zeros(bucket, np.int32)
        self.l = np.zeros(bucket, np.int32)
        self.valid = np.zeros(bucket, dtype)
        self.combined = np.empty(2 * bucket, np.int32)
        self.sorted_keys = np.empty(2 * bucket, np.int32)
        self.perm = np.empty(2 * bucket, np.int32)
        self.bounds = np.empty(num_players + 1, np.int32)
        self.in_flight = False


class StagingBuffers:  # protocol: stage->release
    """Reusable, double-buffered host→device staging per pow2 bucket.

    `stage(winners, losers)` fills the NEXT slot of the batch's bucket
    in place (pad, group, bound — the same layout `engine.pack_batch`
    computes into fresh allocations) and returns a `PackedBatch` of
    device arrays. Slots rotate, so the host never overwrites the
    arrays a still-in-flight dispatch was staged from, and steady
    state allocates nothing: `slots_allocated` stops growing after
    warmup, and because slot shapes are exactly the pow2 buckets the
    jit cache stops growing too (the `RecompileSentinel` contract).

    Slot lifetime is EXPLICIT, not caller discipline: `stage` marks
    the filled slot in-flight and `release()` retires the oldest one
    (call it once the dispatch that consumed the slot has been issued
    — `ArenaEngine` pairs the two in `_dispatch_packed`). Rotating
    into a slot that is still in-flight raises by default instead of
    silently overwriting the arrays a live dispatch was staged from;
    `stage(..., block=True)` waits for the slot instead (what the
    pipeline's packer thread does while the main thread drains).
    """

    def __init__(self, num_players, min_bucket=MIN_BUCKET, dtype=np.float32,
                 depth=2, obs=None):
        if depth < 2:
            raise ValueError("double buffering needs at least two slots per bucket")
        self.num_players = num_players
        self.min_bucket = min_bucket
        self.depth = depth
        self._dtype = dtype
        self._obs = obs if obs is not None else NULL_OBS
        # The packer thread stages while the dispatching thread
        # releases: ring state and the in-flight queue share this
        # condition's lock (guarded_by = the jaxlint contract).
        self._cond = threading.Condition()
        self._rings = {}  # guarded_by: _cond  (bucket -> list of slots)
        self._next = {}  # guarded_by: _cond  (bucket -> rotation index)
        self._inflight = deque()  # guarded_by: _cond  (stage order, until release())
        self.slots_allocated = 0  # guarded_by: _cond
        self.stages = 0  # single-writer: only the staging thread bumps it

    def in_flight(self):
        """Slots staged but not yet release()d."""
        with self._cond:
            return len(self._inflight)

    def _acquire(self, bucket, block):
        with self._cond:
            ring = self._rings.get(bucket)
            if ring is None:
                ring = []
                self._rings[bucket] = ring
                self._next[bucket] = 0
            if len(ring) < self.depth:
                slot = _Slot(bucket, self.num_players, self._dtype)
                ring.append(slot)
                self.slots_allocated += 1
            else:
                slot = ring[self._next[bucket] % len(ring)]
                if slot.in_flight and not block:
                    raise RuntimeError(
                        f"all {self.depth} staging slots of bucket {bucket} "
                        "are in-flight; rotating now would overwrite arrays "
                        "a live dispatch was staged from — release() the "
                        "oldest dispatch first (or stage with block=True)"
                    )
                while slot.in_flight:
                    self._cond.wait()
            self._next[bucket] = (self._next[bucket] + 1) % self.depth
            slot.in_flight = True
            self._inflight.append(slot)
            return slot

    def release(self):
        """Retire the OLDEST in-flight slot (dispatches are FIFO)."""
        with self._cond:
            if not self._inflight:
                raise RuntimeError("no in-flight staging slot to release")
            slot = self._inflight.popleft()
            slot.in_flight = False
            self._cond.notify_all()

    def _abandon(self, slot):
        """Un-acquire THIS slot after a failed pack. release() retires
        the FIFO head, which mid-pack is some OTHER dispatch's slot —
        abandoning must target the exact slot or the in-flight queue
        loses sync with the dispatch order."""
        with self._cond:
            try:
                self._inflight.remove(slot)
            except ValueError:
                pass  # never enqueued / already released
            slot.in_flight = False
            # Point the rotation back at the freed slot: _acquire
            # already advanced past it, and without the rewind the next
            # stage() of this bucket lands on an older still-in-flight
            # slot and trips the rotation guard while this one sits
            # idle.
            ring = self._rings.get(slot.bucket, ())
            if slot in ring:
                self._next[slot.bucket] = ring.index(slot)
            self._cond.notify_all()

    def stage(self, winners, losers, block=False):
        """Pack one validated batch through a reusable slot."""
        with self._obs.span("ingest.staging"):
            return self._stage(winners, losers, block)

    def _stage(self, winners, losers, block):
        w = np.asarray(winners, np.int32)
        l = np.asarray(losers, np.int32)
        _validate_matches(self.num_players, w, l)
        n = w.shape[0]
        b = bucket_size(n, self.min_bucket)
        slot = self._acquire(b, block)
        # A failure past _acquire would otherwise leak the slot
        # permanently: it sits in _inflight with in_flight=True, no
        # PackedBatch ever reaches the dispatcher, so no release() ever
        # retires it — after `depth` such failures the bucket stalls
        # every stage() forever (the silent class v4's
        # resource-leaked-on-exception rule exists for).
        try:
            slot.w[:n] = w
            slot.w[n:] = 0
            slot.l[:n] = l
            slot.l[n:] = 0
            slot.valid[:n] = 1
            slot.valid[n:] = 0
            slot.combined[:b] = slot.w
            slot.combined[b:] = slot.l
            slot.perm[:] = np.argsort(slot.combined, kind="stable")
            slot.sorted_keys[:] = slot.combined[slot.perm]
            slot.bounds[:] = np.searchsorted(
                slot.sorted_keys, np.arange(self.num_players + 1), side="left"
            )
            self.stages += 1
            return PackedBatch(
                jnp.asarray(slot.w),
                jnp.asarray(slot.l),
                jnp.asarray(slot.valid),
                jnp.asarray(slot.perm),
                jnp.asarray(slot.bounds),
                n,
            )
        except BaseException:
            self._abandon(slot)
            raise


def chunk_layout(perm, bounds, chunk_entries=DEFAULT_CHUNK_ENTRIES):
    """Split a merged whole-set grouping into the chunked epoch layout.

    Returns `(perms, chunk_bounds)` for `ratings.bt_fit_chunked`:
    `perms` is (num_chunks, chunk_entries) int32 over the SORTED entry
    order, padded with the sentinel position `total` (the index of the
    one appended zero in the values array — padding lives in sorted
    space, so the match arrays stay exact-length and need no validity
    mask); `chunk_bounds` is (num_chunks, num_players+1), the global
    bounds clipped into each chunk. The largest allocated bucket is
    one chunk — strictly smaller than the single-pow2-bucket packing
    whenever `chunk_entries < 2*bucket_size(num_matches)`.
    """
    if chunk_entries < 1:
        raise ValueError("chunk_entries must be >= 1")
    total = int(perm.shape[0])
    if total == 0:
        raise ValueError("cannot lay out an empty grouping")
    num_chunks = -(-total // chunk_entries)
    padded = np.full(num_chunks * chunk_entries, total, np.int32)
    padded[:total] = perm
    perms = padded.reshape(num_chunks, chunk_entries)
    starts = (np.arange(num_chunks, dtype=np.int64) * chunk_entries)[:, None]
    chunk_bounds = np.clip(
        bounds[None, :].astype(np.int64) - starts, 0, chunk_entries
    ).astype(np.int32)
    return perms, chunk_bounds
