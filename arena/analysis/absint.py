"""Abstract interpretation: the value-flow half of jaxlint v3.

jaxlint v1/v2 matched *syntax* — a dotted name here, a decorator there
— so the invariants that are properties of VALUES stayed invisible: an
array whose shape was derived from a raw input length three
assignments ago, an int64 array born from a bare `np.arange` two
helpers away, untrusted wire bytes flowing into an engine mutation.
This module is the fix: a forward abstract interpretation over the
stdlib `ast` that propagates an abstract value lattice through
assignments and calls — intraprocedurally per scope, and
interprocedurally ONE HOP through the project symbol table's call
edges (the same resolution depth the lock-order analyzer uses).

The lattice (`AbsValue`) tracks, per value:

- **shape provenance** — `constant(k)` (a literal size), `padded(b)`
  (explicitly padded to a constant), `pow2-bucketed` (produced by a
  recognized bucketing op: `bucket_size`, `next_pow2`, `pack_batch`,
  `pack_epoch`, `chunk_layout`, a staging `stage`, `np.pad`), or
  `dynamic` (derived from a raw input length: `len(x)`, `x.shape[0]`,
  `.size` off an ingest array). Join is by rank; two different
  same-rank constants join to `bucketed` (still a finite shape set),
  anything joined with `dynamic` is `dynamic`.
- **dtype** — concrete (`int32`, `float32`, ...) when an explicit
  dtype was seen, the 64-bit defaults (`int64`/`float64`) for the bare
  NumPy constructors that produce them, `py64` for Python numbers out
  of `json.loads` (which `np.asarray` silently widens to 64-bit), or
  unknown (no claim).
- **kind** — scalar vs array, so the array-shape rule and v1's
  scalar `nonstatic-shape-arg` rule never double-report one hazard.
- **tainted** — set by wire-input sources (`self.rfile`,
  `self.headers`, a request handler's `self.path`, `parse_qs`),
  propagated through arithmetic/indexing/unknown calls, cleared ONLY
  by the recognized sanitizers (`protocol.parse_path`,
  `parse_submit_body`, `_query_int`, `_validate_matches`, and the
  `pack_batch`/`pack_epoch` bounds checks — which also clear the
  taint of the argument NAMES they validate in place).

The three rule families on top:

- `unbucketed-shape-at-jit-boundary` — a dynamic-shaped ARRAY reaches
  a `jax.jit`/`shard_map`-wrapped call site without passing through a
  bucketing op. This is the ROADMAP's standing "every new kernel must
  be born shape-bucketed" constraint as a statically checked contract.
- `dtype-drift-into-kernel` — a 64-bit-producing op (bare
  `np.arange`/`np.argsort`/`np.zeros`, `json.loads` numerics) flows
  into a jitted kernel argument; the snapshot wire format pins
  int32/float32, so 64-bit inputs either silently downcast (x32) or
  poison the cache with second dtypes (x64).
- `unvalidated-wire-input` — tainted request data reaches an
  engine/front-door mutation call (`submit`, `admit`, `update`,
  `ingest`, `ingest_async`, `add`, `adopt_state`, `resubmit_spilled`)
  with no sanitizer on SOME path (branch envs are joined, so a
  sanitizer on one arm of an `if` does not launder the other arm).

Like every jaxlint rule: heuristic, not sound — tuned so the clean
tree lints clean and each family fires on its badcorpus example.
Control flow is handled by joining branch environments (if/try arms)
and running loop bodies twice; unknown calls propagate taint but make
no shape/dtype claim, which keeps false positives down at the cost of
missing exotic flows. No jax imports anywhere.
"""

from __future__ import annotations

import ast
import dataclasses

from arena.analysis.jaxlint import rule
from arena.analysis.project import dotted

# --- the abstract value lattice --------------------------------------------

# Shape provenance tags, in join rank order. BOTTOM = no information;
# DYNAMIC = derived from a raw input length. Two distinct same-rank
# elements (constant(2) vs constant(4), constant vs padded) join UP to
# BUCKETED: "one of finitely many static shapes" — still compile-safe,
# no longer a single known size.
S_BOTTOM, S_STATIC, S_BUCKETED, S_DYNAMIC = 0, 1, 2, 3


@dataclasses.dataclass(frozen=True)
class Shape:
    """One shape-lattice element: (rank, tag, payload)."""

    rank: int
    tag: str  # "bottom" | "constant" | "padded" | "bucketed" | "dynamic"
    size: object = None  # int payload for constant/padded, else None


SHAPE_BOTTOM = Shape(S_BOTTOM, "bottom")
SHAPE_BUCKETED = Shape(S_BUCKETED, "bucketed")
SHAPE_DYNAMIC = Shape(S_DYNAMIC, "dynamic")


def shape_constant(k):
    return Shape(S_STATIC, "constant", k)


def shape_padded(b=None):
    return Shape(S_STATIC, "padded", b)


def join_shape(a: Shape, b: Shape) -> Shape:
    """Least upper bound. Commutative, idempotent, associative —
    property-tested over randomized elements (and mutation-audited:
    a join that collapses to bottom silently blinds every rule that
    rides the lattice)."""
    if a.rank < b.rank:
        return b
    if b.rank < a.rank:
        return a
    if a == b:
        return a
    # Same rank, different elements: the only multi-element rank is
    # S_STATIC (constant(k)/padded(b)); their lub is "finite shape
    # set" — bucketed.
    return SHAPE_BUCKETED


# 64-bit dtypes the kernel rule flags. "py64" marks Python numbers
# (json.loads output, float()/int() chains) that np.asarray widens to
# a 64-bit array when no explicit dtype pins them.
WIDE_DTYPES = frozenset({"int64", "float64", "py64"})

_DTYPE_TAILS = frozenset({
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bfloat16", "bool_",
})


# Dtype/kind are flat lattices: None is BOTTOM (no information — the
# identity, so a known dtype survives joining with an untracked
# value), "mixed" is TOP (two different concrete claims — no single
# claim survives, and "mixed" is never in WIDE_DTYPES so the boundary
# rule stays quiet on it).
MIXED = "mixed"


def join_dtype(a, b):
    if a is None or a == b:
        return b
    if b is None:
        return a
    return MIXED


def join_kind(a, b):
    if a is None or a == b:
        return b
    if b is None:
        return a
    return MIXED


@dataclasses.dataclass(frozen=True)
class AbsValue:
    """One abstract value: shape provenance x dtype x kind x taint."""

    shape: Shape = SHAPE_BOTTOM
    dtype: object = None  # str | None
    kind: object = None  # "scalar" | "array" | None
    tainted: bool = False


BOTTOM = AbsValue()
TAINTED = AbsValue(tainted=True)


def join(a: AbsValue, b: AbsValue) -> AbsValue:
    return AbsValue(
        shape=join_shape(a.shape, b.shape),
        dtype=join_dtype(a.dtype, b.dtype),
        kind=join_kind(a.kind, b.kind),
        tainted=a.tainted or b.tainted,
    )


def join_all(values):
    out = BOTTOM
    for v in values:
        out = join(out, v)
    return out


# --- recognized operation sets ---------------------------------------------

# Bucketing ops: calls whose RESULT is shape-safe by contract (the
# pow2 bucket contract, the chunked epoch layout, the reusable staging
# slots, an explicit pad-to-constant). The shape rule treats their
# results as `bucketed`; an emptied set here is the
# "bucketing-op-not-recognized" mutant — every real pack_batch /
# chunk_layout call site would read dynamic and the clean-tree gate
# goes red.
BUCKETING_TAILS = frozenset({
    "bucket_size", "next_pow2", "_pow2_ceil", "pack_batch", "pack_epoch",
    "chunk_layout", "stage", "pad",
})

# Taint sources: the HTTP handler request fields. `rfile`/`headers`
# attribute reads are sources anywhere (nothing else in the tree spells
# them); `path`/`requestline`/`command` only inside classes whose bases
# mention RequestHandler (a pathlib `.path` must not taint the world).
WIRE_TAINT_ATTRS = frozenset({"rfile", "headers"})
HANDLER_TAINT_ATTRS = frozenset({"path", "requestline", "command"})
TAINT_SOURCE_TAILS = frozenset({"parse_qs", "parse_qsl"})

# Sanitizers: the protocol validation helpers and the engine's ingest
# bounds checks. A call clears the taint of its RESULT and of the
# argument names it validated in place (`_validate_matches(n, w, l)`
# leaves w/l checked). The "taint-sanitizer-check-skipped" mutant
# empties this set: validated flows read tainted and the fixture
# pinning `parse_submit_body` as a sanitizer goes red.
TAINT_SANITIZER_TAILS = frozenset({
    "parse_submit_body", "parse_path", "_query_int", "_validate_matches",
    "_validate_tenant", "pack_batch", "pack_epoch",
})

# Sinks: engine/front-door mutation calls. Generic-looking tails
# (`update`, `add`) are safe here because a finding additionally
# requires a TAINTED argument — taint only exists on wire-input flows.
TAINT_SINK_TAILS = frozenset({
    "submit", "admit", "update", "ingest", "ingest_async", "add",
    "adopt_state", "resubmit_spilled",
})

# NumPy/jnp constructors and transforms the interpreter models.
_NUMPY_ROOTS = frozenset({"np", "numpy", "jnp"})
_INT64_PRODUCER_TAILS = frozenset({
    "arange", "argsort", "searchsorted", "bincount", "nonzero", "argwhere",
    "argmax", "argmin",
})
_FLOAT64_DEFAULT_TAILS = frozenset({"zeros", "ones", "empty"})
_PROPAGATE_TAILS = frozenset({
    "array", "asarray", "ascontiguousarray", "sort", "cumsum", "unique",
    "where", "concatenate", "stack", "hstack", "vstack", "repeat", "split",
})
_LIKE_TAILS = frozenset({"zeros_like", "ones_like", "empty_like", "full_like"})

RULE_UNBUCKETED = "unbucketed-shape-at-jit-boundary"
RULE_DTYPE = "dtype-drift-into-kernel"
RULE_TAINT = "unvalidated-wire-input"


def _is_numpy_call(fname):
    return fname is not None and "." in fname and fname.split(".")[0] in _NUMPY_ROOTS


def _resolve_dtype(node, default=None):
    """A dtype expression -> dtype name, or `default` when absent /
    unresolvable (unresolvable means NO claim, never a 64-bit claim)."""
    if node is None:
        return default
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
        return name if name in _DTYPE_TAILS else None
    name = dotted(node)
    if name is not None:
        tail = name.split(".")[-1]
        if tail in _DTYPE_TAILS:
            return "bool" if tail == "bool_" else tail
    return None


def _kwargs(call):
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


# --- one function scope, interpreted forward -------------------------------


class _ScopeAnalysis:
    """Forward pass over one scope (function body or module level).

    `interp` is the per-module _ModuleAnalysis (jit-boundary sets,
    one-hop resolution, the shared finding sink); `depth` > 0 means
    this scope is being evaluated as a ONE-HOP callee summary — no
    further call expansion, and findings go to the summary's
    collector instead of straight to the module's."""

    def __init__(self, interp, scope_node, cls_node, depth, seed_env=None):
        self.interp = interp
        self.scope = scope_node
        self.cls = cls_node
        self.depth = depth
        self.env = dict(seed_env or {})
        self.returns = BOTTOM
        self.findings = []  # (rule, node, message)

    # -- statement walk ----------------------------------------------------

    def run(self):
        body = getattr(self.scope, "body", [])
        self.exec_stmts(body, self.env)
        return self

    def exec_stmts(self, stmts, env):
        for stmt in stmts:
            self.exec_stmt(stmt, env)

    def exec_stmt(self, stmt, env):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested scopes are analyzed on their own
        if isinstance(stmt, ast.Assign):
            val = self.eval(stmt.value, env)
            for tgt in stmt.targets:
                self.assign(tgt, val, stmt.value, env)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.assign(stmt.target, self.eval(stmt.value, env), stmt.value, env)
        elif isinstance(stmt, ast.AugAssign):
            val = self.eval(stmt.value, env)
            name = dotted(stmt.target)
            if name is not None:
                env[name] = join(env.get(name, BOTTOM), val)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.returns = join(self.returns, self.eval(stmt.value, env))
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value, env)
        elif isinstance(stmt, ast.If):
            self.eval(stmt.test, env)
            then_env = dict(env)
            self.exec_stmts(stmt.body, then_env)
            else_env = dict(env)
            self.exec_stmts(stmt.orelse, else_env)
            self._merge(env, then_env, else_env)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            iter_val = self.eval(stmt.iter, env)
            # The loop variable inherits the iterable's taint/dtype
            # (an element of attacker data is attacker data).
            elem = AbsValue(dtype=iter_val.dtype, tainted=iter_val.tainted)
            for _pass in (0, 1):  # twice: loop-carried flows settle
                self.assign(stmt.target, elem, stmt.iter, env)
                body_env = dict(env)
                self.exec_stmts(stmt.body, body_env)
                self._merge(env, body_env, env)
            self.exec_stmts(stmt.orelse, env)
        elif isinstance(stmt, ast.While):
            for _pass in (0, 1):
                self.eval(stmt.test, env)
                body_env = dict(env)
                self.exec_stmts(stmt.body, body_env)
                self._merge(env, body_env, env)
            self.exec_stmts(stmt.orelse, env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                ctx_val = self.eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, ctx_val, item.context_expr, env)
            self.exec_stmts(stmt.body, env)
        elif isinstance(stmt, ast.Try):
            body_env = dict(env)
            self.exec_stmts(stmt.body, body_env)
            arm_envs = [body_env]
            for handler in stmt.handlers:
                h_env = dict(env)
                self.exec_stmts(handler.body, h_env)
                arm_envs.append(h_env)
            self._merge(env, *arm_envs)
            self.exec_stmts(stmt.orelse, env)
            self.exec_stmts(stmt.finalbody, env)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                name = dotted(tgt)
                if name is not None:
                    env.pop(name, None)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for field in ("exc", "cause", "test", "msg"):
                sub = getattr(stmt, field, None)
                if sub is not None:
                    self.eval(sub, env)

    def _merge(self, env, *arm_envs):
        """Join arm environments back into `env` in place: a name is
        as bad as its worst arm — which is what makes "sanitizer on
        every path" a real check rather than a first-path accident."""
        keys = set(env)
        for arm in arm_envs:
            keys |= set(arm)
        for key in keys:
            vals = [arm.get(key, env.get(key, BOTTOM)) for arm in arm_envs]
            env[key] = join_all(vals)

    def assign(self, target, value, value_node, env):
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(value_node, (ast.Tuple, ast.List)) and len(
                value_node.elts
            ) == len(elts):
                for tgt, sub in zip(elts, value_node.elts):
                    self.assign(tgt, self.eval(sub, env), sub, env)
            else:
                for tgt in elts:
                    self.assign(tgt, value, value_node, env)
            return
        if isinstance(target, ast.Starred):
            target = target.value
        name = dotted(target)
        if name is not None:
            env[name] = value

    # -- expression evaluation --------------------------------------------

    def eval(self, node, env) -> AbsValue:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool) or node.value is None:
                return BOTTOM
            if isinstance(node.value, int):
                return AbsValue(shape=shape_constant(node.value), kind="scalar")
            return AbsValue(kind="scalar")
        if isinstance(node, ast.Name):
            return env.get(node.id, BOTTOM)
        if isinstance(node, ast.Attribute):
            return self.eval_attribute(node, env)
        if isinstance(node, ast.Subscript):
            return self.eval_subscript(node, env)
        if isinstance(node, ast.Call):
            return self.eval_call(node, env)
        if isinstance(node, ast.BinOp):
            return join(self.eval(node.left, env), self.eval(node.right, env))
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.BoolOp):
            return join_all(self.eval(v, env) for v in node.values)
        if isinstance(node, ast.Compare):
            vals = [self.eval(node.left, env)]
            vals += [self.eval(c, env) for c in node.comparators]
            # A comparison result is a bool; only taint survives.
            return AbsValue(kind="scalar", tainted=any(v.tainted for v in vals))
        if isinstance(node, ast.IfExp):
            self.eval(node.test, env)
            return join(self.eval(node.body, env), self.eval(node.orelse, env))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return join_all(self.eval(e, env) for e in node.elts)
        if isinstance(node, ast.Dict):
            return join_all(
                self.eval(v, env) for v in node.values if v is not None
            )
        if isinstance(node, ast.JoinedStr):
            parts = [
                self.eval(v.value, env)
                for v in node.values
                if isinstance(v, ast.FormattedValue)
            ]
            return AbsValue(kind="scalar", tainted=any(p.tainted for p in parts))
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            tainted = False
            for gen in node.generators:
                tainted = tainted or self.eval(gen.iter, env).tainted
            return AbsValue(tainted=tainted)
        if isinstance(node, ast.Slice):
            return join_all(
                self.eval(s, env)
                for s in (node.lower, node.upper, node.step)
                if s is not None
            )
        return BOTTOM

    def _shape_provenance(self, base_val: AbsValue) -> Shape:
        """The provenance of a size READ off a value: a known shape is
        its own provenance; reading the length of an UNTRACKED value
        is the rule's dynamic source (`len(matches)` off raw ingest)."""
        if base_val.shape.rank > S_BOTTOM:
            return base_val.shape
        return SHAPE_DYNAMIC

    def eval_attribute(self, node, env) -> AbsValue:
        name = dotted(node)
        if name is not None and name in env:
            return env[name]
        base = self.eval(node.value, env)
        attr = node.attr
        if attr in WIRE_TAINT_ATTRS:
            return TAINTED
        if attr in HANDLER_TAINT_ATTRS and self._in_handler_class(node):
            return TAINTED
        if attr in ("shape", "size", "nbytes"):
            return AbsValue(
                shape=self._shape_provenance(base),
                kind="scalar",
                tainted=base.tainted,
            )
        # A field of a tracked value (packed.winners off a bucketed
        # PackedBatch) carries the container's provenance.
        return AbsValue(
            shape=base.shape, dtype=base.dtype, tainted=base.tainted
        )

    def _in_handler_class(self, node):
        if self.cls is None:
            return False
        if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
            return False
        for base in self.cls.bases:
            base_name = dotted(base) or ""
            if "RequestHandler" in base_name:
                return True
        return False

    def eval_subscript(self, node, env) -> AbsValue:
        base = self.eval(node.value, env)
        idx = node.slice
        if isinstance(idx, ast.Slice):
            bound = join_all(
                self.eval(s, env)
                for s in (idx.lower, idx.upper, idx.step)
                if s is not None
            )
            shape = base.shape
            if bound.shape == SHAPE_DYNAMIC:
                shape = SHAPE_DYNAMIC  # x[:n] with a raw-length n
            return AbsValue(
                shape=shape, dtype=base.dtype, kind="array",
                tainted=base.tainted or bound.tainted,
            )
        self.eval(idx, env)
        # Single-element access: provenance and taint ride along
        # (doc["winners"] of a tainted doc is tainted; x.shape[0]
        # keeps the shape provenance the attribute read established,
        # and stays a SCALAR — the v1 nonstatic-shape-arg rule owns
        # scalar shape args, the v3 rule owns arrays).
        return AbsValue(
            shape=base.shape, dtype=base.dtype,
            kind="scalar" if base.kind == "scalar" else None,
            tainted=base.tainted,
        )

    # -- calls --------------------------------------------------------------

    def eval_call(self, call, env) -> AbsValue:
        arg_vals = [self.eval(a, env) for a in call.args]
        kw_nodes = _kwargs(call)
        kw_vals = {k: self.eval(v, env) for k, v in kw_nodes.items()}
        all_vals = arg_vals + list(kw_vals.values())
        fname = dotted(call.func)
        tail = fname.split(".")[-1] if fname else None
        receiver = (
            self.eval(call.func.value, env)
            if isinstance(call.func, ast.Attribute)
            else BOTTOM
        )

        # Sinks first: a tainted argument reaching a mutation call is
        # the unvalidated-wire-input finding, whatever else the call is.
        if tail in TAINT_SINK_TAILS:
            for arg_node, arg_val in zip(call.args, arg_vals):
                if arg_val.tainted:
                    self.report(
                        RULE_TAINT,
                        arg_node,
                        f"untrusted wire input reaches mutation call "
                        f"`{fname}` without passing a protocol validator "
                        "(parse_submit_body / parse_path / the pack_batch "
                        "bounds checks) on every path — validate before "
                        "mutating engine state",
                    )
            for k, v in kw_vals.items():
                if v.tainted:
                    self.report(
                        RULE_TAINT,
                        kw_nodes[k],
                        f"untrusted wire input reaches mutation call "
                        f"`{fname}` (kwarg `{k}`) without a sanitizer on "
                        "every path — validate before mutating engine state",
                    )

        # Jit boundaries: the shape and dtype contracts are checked on
        # every argument crossing into compiled code.
        if fname is not None and self.interp.is_jit_boundary(fname):
            self._check_boundary(call, fname, arg_vals, kw_nodes, kw_vals)
            return AbsValue(
                kind="array", tainted=any(v.tainted for v in all_vals)
            )

        if tail in TAINT_SANITIZER_TAILS:
            # Validation-in-place: the argument NAMES the sanitizer saw
            # are clean from here on (engine.ingest validates w/l then
            # hands the same arrays to the store).
            for arg_node in list(call.args) + list(kw_nodes.values()):
                arg_name = dotted(arg_node)
                if arg_name is not None and arg_name in env:
                    prev = env[arg_name]
                    if prev.tainted:
                        env[arg_name] = dataclasses.replace(prev, tainted=False)
            shape = SHAPE_BUCKETED if tail in BUCKETING_TAILS else SHAPE_BOTTOM
            return AbsValue(shape=shape, kind="array" if shape.rank else None)

        if tail in BUCKETING_TAILS:
            return AbsValue(
                shape=SHAPE_BUCKETED,
                kind="scalar" if tail in ("bucket_size", "next_pow2", "_pow2_ceil")
                else "array",
            )

        if tail in TAINT_SOURCE_TAILS:
            return TAINTED

        if tail == "loads" and fname in ("json.loads", "loads"):
            # Wire JSON: numbers decode as Python int/float — 64-bit
            # the moment an unpinned np.asarray touches them. Taint is
            # the INPUT's: json.loads of a trusted file stays clean.
            return AbsValue(
                dtype="py64", tainted=any(v.tainted for v in all_vals)
            )

        if fname == "len" and len(arg_vals) == 1:
            return AbsValue(
                shape=self._shape_provenance(arg_vals[0]),
                kind="scalar",
                tainted=arg_vals[0].tainted,
            )

        if fname in ("int", "float", "bool", "str", "abs", "min", "max", "sum"):
            joined = join_all(arg_vals)
            return AbsValue(
                shape=joined.shape, kind="scalar", tainted=joined.tainted
            )

        if _is_numpy_call(fname):
            out = self._eval_numpy(
                fname.split(".")[0], tail, call, arg_vals, kw_nodes, kw_vals
            )
            if out is not None:
                return out

        if isinstance(call.func, ast.Attribute):
            out = self._eval_method(call, receiver, arg_vals, kw_nodes)
            if out is not None:
                return out

        # One-hop interprocedural: a callee the project table resolves
        # is summarized with the call site's abstract arguments.
        if self.depth == 0 and fname is not None:
            out = self.interp.expand_call(self, call, fname, arg_vals, kw_vals)
            if out is not None:
                return out

        # Unknown call: taint flows through, and so does SHAPE
        # provenance (join of the arguments') — a helper the table
        # cannot resolve is assumed to hand back what it was fed.
        # This is what makes the recognized bucketing ops load-
        # bearing: they are the only calls that launder a dynamic
        # size back to a safe shape, so dropping one from the
        # recognized set turns its real call sites into findings
        # (the "bucketing-op-not-recognized" mutant's kill path).
        joined = join_all(all_vals)
        return AbsValue(
            shape=joined.shape,
            tainted=receiver.tainted or joined.tainted,
        )

    def _eval_numpy(self, root, tail, call, arg_vals, kw_nodes, kw_vals):
        # The 64-bit DEFAULT claims apply to host NumPy only: under the
        # repo's x32 JAX config the jnp constructors default to 32-bit,
        # so a bare `jnp.zeros(n)` is not a drift producer.
        host_np = root in ("np", "numpy")
        args = call.args
        if tail in _FLOAT64_DEFAULT_TAILS:
            dt_node = kw_nodes.get("dtype") or (args[1] if len(args) > 1 else None)
            dtype = _resolve_dtype(dt_node, default="float64" if host_np else None)
            shape = arg_vals[0].shape if arg_vals else SHAPE_BOTTOM
            return AbsValue(shape=shape, dtype=dtype, kind="array")
        if tail == "full":
            dt_node = kw_nodes.get("dtype") or (args[2] if len(args) > 2 else None)
            default = None
            if (
                host_np
                and dt_node is None
                and len(args) > 1
                and isinstance(args[1], ast.Constant)
            ):
                if isinstance(args[1].value, float):
                    default = "float64"
                elif isinstance(args[1].value, int):
                    default = "int64"
            dtype = _resolve_dtype(dt_node, default=default)
            shape = arg_vals[0].shape if arg_vals else SHAPE_BOTTOM
            return AbsValue(shape=shape, dtype=dtype, kind="array")
        if tail in _INT64_PRODUCER_TAILS:
            dt_node = kw_nodes.get("dtype")
            if dt_node is None and tail == "arange":
                has_float = any(
                    isinstance(a, ast.Constant) and isinstance(a.value, float)
                    for a in args
                )
                dtype = (
                    "float64" if has_float else "int64"
                ) if host_np else None
            else:
                dtype = _resolve_dtype(
                    dt_node, default="int64" if host_np else None
                )
            if tail == "arange":
                shape = SHAPE_BOTTOM
                for v in arg_vals:
                    shape = join_shape(shape, v.shape)
            else:
                shape = arg_vals[0].shape if arg_vals else SHAPE_BOTTOM
            return AbsValue(
                shape=shape, dtype=dtype, kind="array",
                tainted=any(v.tainted for v in arg_vals),
            )
        if tail in _LIKE_TAILS:
            base = arg_vals[0] if arg_vals else BOTTOM
            dt_node = kw_nodes.get("dtype")
            dtype = _resolve_dtype(dt_node, default=base.dtype)
            return AbsValue(
                shape=base.shape, dtype=dtype, kind="array", tainted=base.tainted
            )
        if tail in _PROPAGATE_TAILS:
            base = join_all(arg_vals) if arg_vals else BOTTOM
            dt_node = kw_nodes.get("dtype") or (
                args[1] if tail in ("array", "asarray") and len(args) > 1 else None
            )
            dtype = _resolve_dtype(dt_node, default=base.dtype)
            return AbsValue(
                shape=base.shape, dtype=dtype, kind="array", tainted=base.tainted
            )
        return None

    def _eval_method(self, call, receiver, arg_vals, kw_nodes):
        meth = call.func.attr
        if meth == "astype":
            dt_node = kw_nodes.get("dtype") or (call.args[0] if call.args else None)
            dtype = _resolve_dtype(dt_node)
            return AbsValue(
                shape=receiver.shape, dtype=dtype, kind="array",
                tainted=receiver.tainted,
            )
        if meth in ("copy", "ravel", "flatten", "tolist", "view"):
            return dataclasses.replace(receiver)
        if meth == "reshape":
            shape = receiver.shape
            for v in arg_vals:
                shape = join_shape(shape, v.shape)
            return AbsValue(
                shape=shape, dtype=receiver.dtype, kind="array",
                tainted=receiver.tainted,
            )
        if meth in ("get", "pop", "item", "read", "decode", "encode", "strip",
                    "split", "lower", "upper", "json"):
            tainted = receiver.tainted or any(v.tainted for v in arg_vals)
            return AbsValue(
                dtype=receiver.dtype if meth in ("get", "pop") else None,
                tainted=tainted,
            )
        return None

    def _check_boundary(self, call, fname, arg_vals, kw_nodes, kw_vals):
        items = list(zip(call.args, arg_vals)) + [
            (kw_nodes[k], v) for k, v in kw_vals.items()
        ]
        # `kind != "scalar"`: a KNOWN scalar shape arg is v1's
        # nonstatic-shape-arg territory; everything else (arrays, and
        # values a branch join blurred) belongs to the v3 contracts.
        for node, val in items:
            if val.shape == SHAPE_DYNAMIC and val.kind != "scalar":
                self.report(
                    RULE_UNBUCKETED,
                    node,
                    f"array shaped by a raw input length reaches jitted "
                    f"`{fname}` without a bucketing op (bucket_size / "
                    "pack_batch / pack_epoch / chunk_layout / pad-to-"
                    "constant) — every distinct size compiles a new "
                    "executable, breaking the recompile_events == 0 gate",
                )
            if val.dtype in WIDE_DTYPES and val.kind != "scalar":
                origin = (
                    "json-decoded Python numbers"
                    if val.dtype == "py64"
                    else f"a {val.dtype}-producing op"
                )
                self.report(
                    RULE_DTYPE,
                    node,
                    f"{origin} flow into jitted `{fname}` — the kernel "
                    "contract pins int32/float32 (the snapshot wire "
                    "format); pass an explicit 32-bit dtype at the "
                    "producer or .astype(...) before the boundary",
                )

    def report(self, rule_name, node, message):
        self.findings.append((rule_name, node, message))


# --- per-module driver ------------------------------------------------------


class _ModuleAnalysis:
    """One abstract-interpretation pass per module, shared by the
    three v3 rules (computed once, cached on the ModuleContext)."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.findings = {RULE_UNBUCKETED: [], RULE_DTYPE: [], RULE_TAINT: []}
        self._boundary_names = self._collect_boundaries(ctx)
        self._summary_cache = {}
        self._baseline_cache = {}
        self._stack = []

    @staticmethod
    def _collect_boundaries(ctx):
        names = set(ctx.jitted_callables)
        for fn in ctx.traced_defs:
            names.add(fn.name)
        return names

    def is_jit_boundary(self, fname):
        if fname in self._boundary_names:
            return True
        tail = fname.split(".")[-1]
        return tail in self._boundary_names and fname.startswith("self.")

    # -- scope enumeration --------------------------------------------------

    def run(self):
        ctx = self.ctx
        module_scope = _ScopeAnalysis(self, ctx.tree, None, depth=0)
        module_scope.run()
        self._drain(module_scope, ctx)
        for fn_node, cls_node in self._iter_functions(ctx.tree):
            if ctx.is_traced_def(fn_node):
                continue  # inside compiled code the contracts differ
            scope = _ScopeAnalysis(self, fn_node, cls_node, depth=0)
            scope.run()
            self._drain(scope, ctx)
        return self

    @staticmethod
    def _iter_functions(tree):
        def walk(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield child, cls
                    yield from walk(child, cls)
                elif isinstance(child, ast.ClassDef):
                    yield from walk(child, child)
                else:
                    yield from walk(child, cls)

        yield from walk(tree, None)

    def _drain(self, scope, ctx):
        seen = set()
        for rule_name, node, message in scope.findings:
            key = (rule_name, node.lineno, node.col_offset, message)
            if key in seen:
                continue
            seen.add(key)
            self.findings[rule_name].append(ctx.finding(node, rule_name, message))

    # -- one-hop call expansion --------------------------------------------

    def _resolve_callee(self, caller_scope, fname):
        """(def node, class node, home ModuleContext, qualname) for a
        callee the table resolves, else None. Same one-hop surface as
        the lock analyzer: same-module functions, same-class methods,
        `from x import f` imports."""
        ctx = self.ctx
        parts = fname.split(".")
        if parts[0] == "self" and len(parts) == 2 and caller_scope.cls is not None:
            for item in caller_scope.cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if item.name == parts[1]:
                        return item, caller_scope.cls, ctx, (
                            f"{caller_scope.cls.name}.{parts[1]}"
                        )
            return None
        if len(parts) == 1 and fname in ctx.symbols.functions:
            return ctx.symbols.functions[fname], None, ctx, fname
        # Imported: longest dotted prefix bound by an import.
        siblings = getattr(ctx, "siblings", None)
        if not siblings or ctx.project is None:
            return None
        for i in range(len(parts), 0, -1):
            head = ".".join(parts[:i])
            if head not in ctx.symbols.imports:
                continue
            src_name, symbol = ctx.symbols.imports[head]
            rest = parts[i:]
            if symbol is not None:
                rest = [symbol] + rest
            src = ctx.project.module(src_name)
            if src is None and rest:
                src = ctx.project.module(f"{src_name}.{rest[0]}")
                rest = rest[1:]
            if src is None:
                continue
            home = siblings.get(src.name)
            if home is None:
                continue
            if len(rest) == 1 and rest[0] in src.functions:
                return src.functions[rest[0]], None, home, rest[0]
        return None

    def expand_call(self, caller_scope, call, fname, arg_vals, kw_vals):
        resolved = self._resolve_callee(caller_scope, fname)
        if resolved is None:
            return None
        fn_node, cls_node, home, qualname = resolved
        key = (home.path, qualname)
        if key in self._stack:
            return None  # recursion: no claim
        interesting = any(
            v.tainted or v.shape == SHAPE_DYNAMIC or v.dtype in WIDE_DTYPES
            for v in list(arg_vals) + list(kw_vals.values())
        )
        if not interesting:
            base = self._baseline(fn_node, cls_node, home, key)
            return base.returns
        seed = self._seed_env(fn_node, call, arg_vals, kw_vals)
        self._stack.append(key)
        try:
            home_interp = self if home is self.ctx else _ModuleAnalysis(home)
            scope = _ScopeAnalysis(
                home_interp, fn_node, cls_node, depth=1, seed_env=seed
            )
            scope.run()
        finally:
            self._stack.pop()
        baseline = self._baseline(fn_node, cls_node, home, key)
        base_keys = {
            (r, n.lineno, n.col_offset) for r, n, _m in baseline.findings
        }
        for rule_name, node, message in scope.findings:
            if (rule_name, node.lineno, node.col_offset) in base_keys:
                continue  # the callee's own problem, reported at home
            caller_scope.report(
                rule_name,
                call,
                f"{message} (flows one call deep into `{qualname}`, "
                f"line {node.lineno})",
            )
        return scope.returns

    def _baseline(self, fn_node, cls_node, home, key):
        cached = self._baseline_cache.get(key)
        if cached is None:
            home_interp = self if home is self.ctx else _ModuleAnalysis(home)
            cached = _ScopeAnalysis(home_interp, fn_node, cls_node, depth=1).run()
            self._baseline_cache[key] = cached
        return cached

    @staticmethod
    def _seed_env(fn_node, call, arg_vals, kw_vals):
        args = fn_node.args
        params = [a.arg for a in args.posonlyargs + args.args]
        seed = {}
        offset = 1 if params and params[0] == "self" else 0
        for name, val in zip(params[offset:], arg_vals):
            seed[name] = val
        for name, val in kw_vals.items():
            if name in params or any(a.arg == name for a in args.kwonlyargs):
                seed[name] = val
        return seed


def _analysis(ctx):
    cached = getattr(ctx, "_absint_findings", None)
    if cached is None:
        cached = _ModuleAnalysis(ctx).run().findings
        ctx._absint_findings = cached
    return cached


# --- the three v3 rules -----------------------------------------------------


@rule(
    RULE_UNBUCKETED,
    "an array shaped by a raw input length (len(x), x.shape[0]) reaches a "
    "jit/shard_map boundary without a recognized bucketing op — the "
    "compile-free steady state as a statically checked contract",
    severity="error",
)
def _check_unbucketed_shape(ctx):
    yield from _analysis(ctx)[RULE_UNBUCKETED]


@rule(
    RULE_DTYPE,
    "a 64-bit-producing op (bare np.arange/np.zeros, json.loads numerics) "
    "flows into a jitted kernel argument pinned int32/float32 by the "
    "snapshot wire format",
    severity="warning",
)
def _check_dtype_drift(ctx):
    yield from _analysis(ctx)[RULE_DTYPE]


@rule(
    RULE_TAINT,
    "untrusted wire input (request body/headers/query) reaches an engine "
    "or front-door mutation call with no protocol validator on every path",
    severity="error",
)
def _check_wire_taint(ctx):
    yield from _analysis(ctx)[RULE_TAINT]
