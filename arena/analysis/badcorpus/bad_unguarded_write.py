"""jaxlint corpus: writing declared-guarded state without the lock.

`applied` carries the `# guarded_by: _lock` contract and the class
hands itself to a worker thread — but `bump()` mutates the counter
with no lock held, so the worker's increment and the caller's can
interleave as a lost update. Rule: unguarded-shared-write."""

import threading


class StatsSink:
    """Shared between the spawning caller and its worker thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self.applied = 0  # guarded_by: _lock
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        with self._lock:
            self.applied += 1

    def bump(self, n):
        self.applied += n  # races the worker: read-modify-write, no lock
