"""Metrics registry: counters, gauges, and log2 latency histograms.

The measurement half of `arena/obs/` (the other half is
`arena/obs/tracing.py`). Design constraints, in order:

1. **Hot-path cheap.** A histogram record is one vectorized
   `searchsorted` into a preallocated bounds array plus two in-place
   adds into preallocated numpy buffers — no allocation after
   construction. Locks are PER METRIC, never registry-wide, so two
   threads recording into different metrics never contend; the
   registry's own lock is taken only at get-or-create time (cold
   path). The per-metric lock is what makes concurrent increments sum
   EXACTLY (a bare `arr[0] += 1` is a read-modify-write that loses
   updates under threads; the tier-1 concurrency test pins exactness).

2. **Fixed memory.** Histograms are fixed-bucket log2: upper bounds
   `base * 2**i` for `num_buckets` buckets plus one overflow slot.
   Bucket semantics are Prometheus-style `le` (a value lands in the
   FIRST bucket whose upper bound is >= it, so a value exactly on a
   boundary belongs to that boundary's bucket — pinned by a boundary
   test and policed by a mutation-audit mutant). Percentiles are read
   from the cumulative counts and reported as the containing bucket's
   upper bound — a conservative (never under-reporting) estimate with
   log2 resolution, which is what a latency SLO check wants.

3. **A no-op twin.** `NullRegistry` serves the identical interface
   from singletons whose every method is a constant-time no-op — the
   uninstrumented baseline the bench overhead gate compares against
   (`arena/bench_arena.py` hard-gates live-vs-null regression < 3%),
   and the default for `ArenaEngine` so library users who never asked
   for metrics pay a method call, not a measurement.

No jax anywhere in this package: metrics must be importable (and
testable) on boxes with no accelerator stack, same discipline as the
linter half of `arena/analysis`.
"""

import json
import threading

import numpy as np

# Default histogram shape: 32 log2 buckets from 1us up (~4295s at the
# top) covers any host-stage latency this system can produce; value
# histograms (queue depth, staleness) pass base=1.
DEFAULT_LATENCY_BASE = 1e-6
DEFAULT_NUM_BUCKETS = 32


def _escape_label_value(value):
    """Prometheus exposition escaping for label VALUES: backslash,
    double quote, and newline — the three characters the text format
    names. Anything else passes through verbatim. Without this, one
    hostile label (a producer name with a quote in it) corrupts the
    whole /stats scrape; the round-trip is pinned by a tier-1 test."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text):
    """HELP-line escaping: backslash and newline (quotes are legal in
    help text per the exposition format)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _label_suffix(labels):
    """Stable `{k="v",...}` rendering (sorted keys, values escaped),
    "" when unlabeled."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(labels[k])}"' for k in sorted(labels)
    )
    return "{" + inner + "}"


# `# HELP` text per metric name (exposition-format hardening: scrapers
# and humans both read these). Names not listed render the default —
# an honest "no help registered", never a missing HELP line.
DEFAULT_HELP = "arena metric (no help text registered)"
HELP_TEXTS = {
    "arena_queries_total": "serving-tier queries answered",
    "arena_view_refreshes_total": "leaderboard view rebuilds",
    "arena_stale_serves_total": "queries answered from a stale view",
    "arena_snapshots_total": "engine snapshots taken",
    "arena_restores_total": "engine snapshot restores",
    "arena_recompile_events_total": "XLA recompilations observed",
    "arena_query_latency_seconds": "serving-tier query latency",
    "arena_query_staleness_matches": "matches behind at query time",
    "arena_ingest_matches_total": "matches ingested into the CSR store",
    "arena_ingest_compactions_total": "CSR store compactions",
    "arena_pipeline_submitted_batches_total":
        "batches submitted to the ingest pipeline",
    "arena_pipeline_dropped_batches_total":
        "batches shed by backpressure policy",
    "arena_pipeline_dropped_matches_total":
        "matches shed by backpressure policy",
    "arena_pipeline_spilled_batches_total": "batches spilled to disk",
    "arena_pipeline_spilled_matches_total": "matches spilled to disk",
    "arena_pipeline_enqueue_wait_seconds": "producer wait at enqueue",
    "arena_pipeline_queue_depth": "pipeline queue depth",
    "arena_frontdoor_staleness_matches":
        "front-door staleness behind the engine",
    "arena_shed_batch_matches":
        "shed batch sizes (exemplar: the dropped trace)",
    "arena_http_requests_total": "wire requests by endpoint and status",
    "arena_http_request_latency_seconds": "wire request latency",
    "arena_wire_cache_hits_total": "wire responses served from cached bytes",
    "arena_wire_cache_misses_total": "wire cache lookups that rendered fresh",
    "arena_wire_cache_evictions_total":
        "wire cache entries evicted (dead generation or capacity)",
    "arena_wire_cache_prerenders_total":
        "hot pages prerendered into the wire cache at view refresh",
    "arena_wire_cache_age_seconds":
        "age of the wire cache's current view generation",
    "arena_view_listener_errors_total":
        "view-refresh listener exceptions absorbed",
}


class Counter:
    """Monotone integer counter; `inc` is exact under concurrency."""

    __slots__ = ("name", "labels", "_buf", "_lock")

    def __init__(self, name, labels):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        # Preallocated, never resized; the per-metric lock is what
        # makes concurrent inc() exact (guarded_by = jaxlint contract).
        self._buf = np.zeros(1, np.int64)  # guarded_by: _lock

    def inc(self, n=1):
        with self._lock:
            self._buf[0] += n

    @property
    def value(self):
        return int(self._buf[0])


class Gauge:
    """Last-write-wins float value (queue depth, staleness, ...)."""

    __slots__ = ("name", "labels", "_buf", "_lock")

    def __init__(self, name, labels):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._buf = np.zeros(1, np.float64)  # guarded_by: _lock

    def set(self, v):
        with self._lock:
            self._buf[0] = v

    @property
    def value(self):
        return float(self._buf[0])


class Histogram:
    """Fixed-bucket log2 histogram over preallocated numpy arrays.

    Bucket i (0-based) has upper bound `base * 2**i`; a recorded value
    lands in the first bucket whose bound is >= it (`le` semantics —
    boundary values belong to their boundary's bucket). Values above
    the last bound land in the overflow slot (rendered `le="+Inf"`).
    Zero/negative values land in bucket 0 (latencies and depths are
    non-negative; a clock hiccup must not throw).

    **Exemplars**: `record(value, trace_id=...)` additionally stores a
    latest-wins `(trace_id, value)` exemplar IN THE VALUE'S BUCKET,
    under the same per-metric lock (two extra scalar stores into
    preallocated arrays — no allocation, no second lock). `exemplar(q)`
    returns the exemplar of the bucket containing quantile q, which is
    how "show me the trace behind the p99" resolves: the trace id keys
    into `Tracer.trace()`. A zero/None trace id records no exemplar, so
    uninstrumented callers pay nothing.
    """

    __slots__ = ("name", "labels", "base", "bounds", "_counts", "_sum",
                 "_count", "_lock", "_ex_trace", "_ex_value")

    def __init__(self, name, labels, base=DEFAULT_LATENCY_BASE,
                 num_buckets=DEFAULT_NUM_BUCKETS):
        if base <= 0 or num_buckets < 1:
            raise ValueError(
                f"histogram needs base > 0 and num_buckets >= 1, got "
                f"({base}, {num_buckets})"
            )
        self.name = name
        self.labels = labels
        self.base = base
        self.bounds = base * np.exp2(np.arange(num_buckets, dtype=np.float64))
        self._lock = threading.Lock()
        self._counts = np.zeros(num_buckets + 1, np.int64)  # guarded_by: _lock ([+Inf] last)
        self._sum = np.zeros(1, np.float64)  # guarded_by: _lock
        self._count = np.zeros(1, np.int64)  # guarded_by: _lock
        # Latest-wins exemplar per bucket: trace id 0 = no exemplar.
        self._ex_trace = np.zeros(num_buckets + 1, np.int64)  # guarded_by: _lock
        self._ex_value = np.zeros(num_buckets + 1, np.float64)  # guarded_by: _lock

    def bucket_index(self, value):
        """First bucket whose upper bound is >= value (le semantics);
        len(bounds) for overflow."""
        return int(np.searchsorted(self.bounds, value, side="left"))

    def record(self, value, trace_id=None):
        idx = self.bucket_index(value)
        with self._lock:
            self._counts[idx] += 1
            self._sum[0] += value
            self._count[0] += 1
            if trace_id:
                self._ex_trace[idx] = trace_id
                self._ex_value[idx] = value

    @property
    def count(self):
        return int(self._count[0])

    @property
    def sum(self):
        return float(self._sum[0])

    @staticmethod
    def _quantile_bucket(counts, total, q):
        """Index of the bucket containing quantile q (counts cumulated
        in place here; callers pass a consistent copy)."""
        target = q * total
        cum = np.cumsum(counts)
        return int(np.searchsorted(cum, target, side="left"))

    def percentile(self, q):
        """Upper bound of the bucket containing quantile q in [0, 1].

        Conservative by construction: the true quantile is <= the
        returned bound (within the overflow bucket it returns +inf —
        an honest "past the histogram's range", never a fabricated
        finite number). None when the histogram is empty.
        """
        with self._lock:
            total = int(self._count[0])
            if total == 0:
                return None
            idx = self._quantile_bucket(self._counts, total, q)
        if idx >= self.bounds.size:
            return float("inf")
        return float(self.bounds[idx])

    def exemplar(self, q):
        """The exemplar stored in quantile q's bucket: a dict with
        `trace_id` (keys into `Tracer.trace()`), the recorded `value`,
        and the `bucket_index` — the "show me the trace behind the p99"
        read. None when the histogram is empty or that bucket never
        recorded a traced value."""
        with self._lock:
            total = int(self._count[0])
            if total == 0:
                return None
            idx = self._quantile_bucket(self._counts, total, q)
            tid = int(self._ex_trace[idx])
            if tid == 0:
                return None
            return {
                "trace_id": tid,
                "value": float(self._ex_value[idx]),
                "bucket_index": idx,
            }

    def exemplars(self):
        """Every stored exemplar as `(bucket_index, trace_id, value)`,
        bucket order (a consistent snapshot under the metric lock)."""
        with self._lock:
            return [
                (i, int(t), float(v))
                for i, (t, v) in enumerate(
                    zip(self._ex_trace, self._ex_value)
                )
                if t
            ]

    def counts_snapshot(self):
        """Consistent `(counts copy, total, sum)` under the metric
        lock — the raw cumulative form the sliding-window ring
        (`arena/obs/windows.py`) diffs between boundaries."""
        with self._lock:
            return self._counts.copy(), int(self._count[0]), float(self._sum[0])

    def snapshot(self):
        """JSON-able summary: count, sum, p50/p99, per-bucket counts,
        per-bucket exemplars (keyed like `buckets`, overflow as
        "overflow")."""
        with self._lock:
            counts = self._counts.copy()
            total = int(self._count[0])
            s = float(self._sum[0])
            ex_trace = self._ex_trace.copy()
            ex_value = self._ex_value.copy()
        bucket_keys = [f"{float(b):g}" for b in self.bounds] + ["overflow"]
        out = {
            "count": total,
            "sum": round(s, 9),
            "buckets": {
                key: int(c)
                for key, c in zip(bucket_keys[:-1], counts[:-1])
                if c
            },
            "overflow": int(counts[-1]),
            "exemplars": {
                key: {"trace_id": int(t), "value": float(v)}
                for key, t, v in zip(bucket_keys, ex_trace, ex_value)
                if t
            },
        }
        for name, q in (("p50", 0.5), ("p99", 0.99)):
            p = self.percentile(q)
            out[name] = None if p is None else (
                p if p != float("inf") else "inf"
            )
        return out


class Registry:
    """Thread-safe get-or-create home for all metrics of one system.

    Metric identity is `(name, sorted label items)`; getting an
    existing metric is one dict lookup under the registry lock (cold
    path only — callers hold onto the returned metric for the hot
    path, or accept the lookup cost for occasional records).
    """

    enabled = True

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}  # guarded_by: _lock  (get-or-create only)

    def _get(self, cls, name, labels, **kwargs):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                metric = cls(name, labels, **kwargs)
                self._metrics[key] = metric
            elif not isinstance(metric, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(metric).__name__}, not {cls.__name__}"
                )
            return metric

    def counter(self, name, **labels):
        return self._get(Counter, name, labels)

    def gauge(self, name, **labels):
        return self._get(Gauge, name, labels)

    def histogram(self, name, base=DEFAULT_LATENCY_BASE,
                  num_buckets=DEFAULT_NUM_BUCKETS, **labels):
        return self._get(Histogram, name, labels, base=base,
                         num_buckets=num_buckets)

    def _sorted_metrics(self):
        with self._lock:
            items = list(self._metrics.items())
        return sorted(items, key=lambda kv: (kv[0][0], kv[0][1]))

    def counter_sum(self, name):
        """Sum of one counter name's value across every label set (0
        when it never fired) — how `stats()` folds policy-labeled
        counters into a single headline number."""
        total = 0
        for (n, _labels), metric in self._sorted_metrics():
            if n == name and isinstance(metric, Counter):
                total += metric.value
        return total

    def counter_by_label(self, name, key):
        """One counter name's values GROUPED by one label key — e.g.
        requests by `endpoint` or sheds by `policy` — summed across
        the other labels; rows missing the key fold under ``""``. The
        middle ground between `counter_sum`'s single number and
        `dump()`'s full label split — what `ArenaServer.stats()`
        reports the wire tier's per-endpoint/per-policy counts from
        (one schema, one registry)."""
        out = {}
        for (n, _labels), metric in self._sorted_metrics():
            if n == name and isinstance(metric, Counter):
                value = metric.labels.get(key, "")
                out[value] = out.get(value, 0) + metric.value
        return out

    def render(self):
        """Prometheus text exposition (the endpoint-ready form):
        `# HELP` + `# TYPE` per metric name, label values escaped."""
        lines = []
        typed = set()
        for (name, _labels), metric in self._sorted_metrics():
            kind = {"Counter": "counter", "Gauge": "gauge",
                    "Histogram": "histogram"}[type(metric).__name__]
            if name not in typed:
                help_text = HELP_TEXTS.get(name, DEFAULT_HELP)
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
                lines.append(f"# TYPE {name} {kind}")
                typed.add(name)
            suffix = _label_suffix(metric.labels)
            if isinstance(metric, Histogram):
                with metric._lock:
                    counts = metric._counts.copy()
                    total = int(metric._count[0])
                    s = float(metric._sum[0])
                    ex_trace = metric._ex_trace.copy()
                    ex_value = metric._ex_value.copy()
                cum = 0
                for i, (bound, c) in enumerate(zip(metric.bounds, counts[:-1])):
                    cum += int(c)
                    le = _label_suffix({**metric.labels, "le": f"{float(bound):g}"})
                    # OpenMetrics-style exemplar suffix: the trace id
                    # behind this bucket's latest traced observation.
                    ex = (
                        f' # {{trace_id="{int(ex_trace[i])}"}} '
                        f"{float(ex_value[i]):g}"
                        if ex_trace[i]
                        else ""
                    )
                    lines.append(f"{name}_bucket{le} {cum}{ex}")
                le = _label_suffix({**metric.labels, "le": "+Inf"})
                lines.append(f"{name}_bucket{le} {total}")
                lines.append(f"{name}_sum{suffix} {s:g}")
                lines.append(f"{name}_count{suffix} {total}")
            else:
                lines.append(f"{name}{suffix} {metric.value:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self):
        """One JSON-able dict of everything (the stats()/bench form)."""
        counters, gauges, histograms = {}, {}, {}
        for (name, _labels), metric in self._sorted_metrics():
            key = name + _label_suffix(metric.labels)
            if isinstance(metric, Counter):
                counters[key] = metric.value
            elif isinstance(metric, Gauge):
                gauges[key] = metric.value
            else:
                histograms[key] = metric.snapshot()
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def dump_json(self):
        return json.dumps(self.dump())


class _NullCounter:
    name = "null"
    labels = {}
    value = 0

    def inc(self, n=1):
        return None


class _NullGauge:
    name = "null"
    labels = {}
    value = 0.0

    def set(self, v):
        return None


_NULL_COUNTS = np.zeros(1, np.int64)


class _NullHistogram:
    name = "null"
    labels = {}
    count = 0
    sum = 0.0
    bounds = np.zeros(0, np.float64)

    def record(self, value, trace_id=None):
        return None

    def bucket_index(self, value):
        return 0

    def percentile(self, q):
        return None

    def exemplar(self, q):
        return None

    def exemplars(self):
        return []

    def counts_snapshot(self):
        return _NULL_COUNTS.copy(), 0, 0.0

    def snapshot(self):
        return {"count": 0, "sum": 0.0, "buckets": {}, "overflow": 0,
                "exemplars": {}, "p50": None, "p99": None}


class NullRegistry:
    """No-op twin of `Registry`: identical interface, singleton no-op
    metrics, constant-time everywhere. The uninstrumented baseline —
    `ArenaEngine`'s default, and the comparator the bench overhead
    gate measures the live registry against."""

    enabled = False
    _COUNTER = _NullCounter()
    _GAUGE = _NullGauge()
    _HISTOGRAM = _NullHistogram()

    def counter(self, name, **labels):
        return self._COUNTER

    def gauge(self, name, **labels):
        return self._GAUGE

    def histogram(self, name, base=DEFAULT_LATENCY_BASE,
                  num_buckets=DEFAULT_NUM_BUCKETS, **labels):
        return self._HISTOGRAM

    def counter_sum(self, name):
        return 0

    def counter_by_label(self, name, key):
        return {}

    def render(self):
        return ""

    def dump(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def dump_json(self):
        return "{}"
