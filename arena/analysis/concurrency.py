"""Lock-discipline rules: the concurrency half of jaxlint v2.

PRs 4-9 made arena genuinely concurrent — a packer thread, per-metric
registry locks, an RLock'd `MergeableCSR`, a reorder-buffered front
door, a threading HTTP server — but until now the only static gate
knew nothing about threads, so the class of bug MOST likely to ship
(an unguarded touch of lock-guarded state, a blocking call made while
holding a lock) was invisible. These four rules run on the pass-1
symbol table (`arena/analysis/project.py`) the two-pass driver builds:

- `unguarded-shared-write` — the `# guarded_by: <lockname>` annotation
  on a class attribute is a contract: every assignment to it outside
  `__init__` must happen while holding `self.<lockname>` (lexically
  inside `with self.<lockname>:`, or in a `*_locked` method — the
  repo's called-with-lock-held naming convention). Annotations opt a
  class in; the four production modules that share state across
  threads (`ingest.py`, `pipeline.py`, `obs/metrics.py`,
  `net/frontdoor.py`) carry them, so the clean-tree-lints-clean
  invariant is a real concurrency contract, not a vacuous pass.
- `blocking-while-locked` — `time.sleep`, `.join()` (zero positional
  args, so `str.join(iterable)` never matches), blocking queue
  `.get/.put(block=True)`, and `block_until_ready` inside a held-lock
  region: every other thread needing that lock stalls for the full
  wait, and joining a thread that needs the lock is a deadlock.
  `Condition.wait()` is deliberately NOT in the set — it releases the
  lock, which is the sanctioned wait shape.
- `lock-order-inversion` — two locks acquired in opposite nesting
  orders anywhere across the PROJECT (the cross-module lock-order
  graph: lexical nesting plus one-hop call-through edges resolved
  through the symbol table). Reported once per inverted pair per
  module, at a site that acquires in one of the two orders.
- `thread-no-liveness-recheck` — in a class that spawns a worker
  thread, a wait loop (`while ...: cond.wait(...)`) that never
  re-checks worker liveness (`.is_alive`, directly or one call deep
  into same-class helpers): if the worker died, the loop hangs
  forever — the exact hang class PR 4 fixed by hand with
  `_check_packer_locked()`. Thread-target methods themselves are
  exempt (the worker waiting for work needs no liveness check on
  itself).
"""

from __future__ import annotations

import ast

from arena.analysis.jaxlint import Finding, rule
from arena.analysis.project import (
    LOCKED_SUFFIX,
    dotted,
    make_lock_resolver,
    scan_function,
    _self_attr_writes,
    _stmt_exprs,
)

_SLEEP_CALLS = frozenset({"time.sleep", "sleep"})
_BLOCKING_QUEUE_METHODS = frozenset({"get", "put"})


def _short_lock(lock_id: str) -> str:
    """Human form of a project-global lock id: Class.attr or name."""
    return ".".join(lock_id.split(".")[-2:])


def _iter_scopes(symbols):
    """(fn_node, cls, held0) for every function and method: `_locked`
    methods start with every class lock held (the convention)."""
    for fn_node in symbols.functions.values():
        yield fn_node, None, ()
    for cls in symbols.classes.values():
        for mname, mnode in cls.methods.items():
            held0 = ()
            if mname.endswith(LOCKED_SUFFIX):
                held0 = tuple(sorted(cls.lock_ids()))
            yield mnode, cls, held0


@rule(
    "unguarded-shared-write",
    "assignment to a `# guarded_by: <lock>`-annotated attribute outside a "
    "`with self.<lock>:` block (or a *_locked method) in a thread-shared "
    "class — a data race on declared-guarded state",
    severity="error",
)
def _check_unguarded_shared_write(ctx):
    for cls in ctx.symbols.classes.values():
        if not cls.guarded:
            continue
        # The annotation is the opt-in: a class declaring guarded state
        # either spawns threads or is handed to them (why else guard?).
        if not (cls.spawns_thread or cls.lock_attrs):
            continue
        for mname, mnode in cls.methods.items():
            if mname == "__init__":
                continue  # pre-publication writes need no lock
            held_names = set(cls.lock_attrs) if mname.endswith(LOCKED_SUFFIX) else set()

            def resolve_attr(expr, _cls=cls):
                name = dotted(expr)
                if name and name.startswith("self."):
                    attr = name.split(".", 1)[1]
                    if "." not in attr and attr in _cls.lock_attrs:
                        return attr
                return None

            _acq, _edges, stmts = scan_function(
                mnode, resolve_attr, tuple(sorted(held_names))
            )
            for stmt, held in stmts:
                for attr, tgt in _self_attr_writes(stmt):
                    guard = cls.guarded.get(attr)
                    if guard and guard not in held:
                        yield ctx.finding(
                            tgt,
                            "unguarded-shared-write",
                            f"`self.{attr}` is declared `guarded_by: {guard}` "
                            f"but `{cls.name}.{mname}` writes it without "
                            f"holding `self.{guard}` — a racing thread can "
                            "observe or lose this update",
                        )


def _blocking_reason(call: ast.Call):
    """Why a call blocks while a lock is held, or None."""
    fname = dotted(call.func) or ""
    if fname in _SLEEP_CALLS:
        return f"`{fname}(...)` sleeps"
    if fname.split(".")[-1] == "block_until_ready":
        return f"`{fname}(...)` waits for the device"
    if isinstance(call.func, ast.Attribute):
        meth = call.func.attr
        if meth == "join" and not call.args:
            return "`.join()` waits for another thread"
        if meth in _BLOCKING_QUEUE_METHODS:
            for kw in call.keywords:
                if (
                    kw.arg == "block"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                ):
                    return f"`.{meth}(block=True)` blocks on the queue"
    return None


@rule(
    "blocking-while-locked",
    "a blocking call (time.sleep / .join() / queue get-put with block=True "
    "/ block_until_ready) inside a held-lock region — every thread needing "
    "the lock stalls for the full wait",
    severity="warning",
)
def _check_blocking_while_locked(ctx):
    symbols = ctx.symbols
    for fn_node, cls, held0 in _iter_scopes(symbols):
        resolver = make_lock_resolver(symbols, cls)
        _acq, _edges, stmts = scan_function(fn_node, resolver, held0)
        for stmt, held in stmts:
            if not held:
                continue
            for expr in _stmt_exprs(stmt):
                if not isinstance(expr, ast.Call):
                    continue
                reason = _blocking_reason(expr)
                if reason is not None:
                    yield ctx.finding(
                        expr,
                        "blocking-while-locked",
                        f"{reason} while `{_short_lock(held[-1])}` is held "
                        f"in `{fn_node.name}` — release the lock first, or "
                        "bound the wait outside the critical section",
                    )


@rule(
    "lock-order-inversion",
    "two locks are acquired in opposite nesting orders somewhere across "
    "the project (lexical nesting + one-hop call-through edges from the "
    "cross-module lock-order graph) — a deadlock waiting for load",
    severity="error",
)
def _check_lock_order_inversion(ctx):
    table = ctx.project
    if table is None:
        return
    pairs = {}
    for outer, inner, mod_name, line, col in table.all_lock_edges():
        if outer == inner:
            continue  # RLock re-entry is legal
        pairs.setdefault((outer, inner), []).append((mod_name, line, col))
    reported = set()
    for (a, b) in sorted(pairs):
        if (b, a) not in pairs:
            continue
        key = frozenset((a, b))
        if key in reported:
            continue
        for mod_name, line, col in sorted(pairs[(a, b)]):
            if mod_name != ctx.symbols.name:
                continue
            reported.add(key)
            other = sorted(pairs[(b, a)])[0]
            yield Finding(
                ctx.path,
                line,
                col,
                "lock-order-inversion",
                f"`{_short_lock(b)}` is acquired while holding "
                f"`{_short_lock(a)}` here, but `{other[0]}` (line "
                f"{other[1]}) nests them the other way around — "
                "inconsistent lock order deadlocks under contention",
            )
            break


def _walk_confined(node):
    """ast.walk that does not descend into nested function/class defs."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                stack.append(child)


def _rechecks_liveness(while_node, cls):
    """True if the loop re-checks worker liveness: an `.is_alive`
    reference in the loop, or one call deep into a same-class helper
    whose body references it (`_check_packer_locked` shape)."""
    for node in _walk_confined(while_node):
        if isinstance(node, ast.Attribute) and node.attr == "is_alive":
            return True
    for node in _walk_confined(while_node):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func)
        if not fname or not fname.startswith("self."):
            continue
        helper = cls.methods.get(fname.split(".", 1)[1])
        if helper is None:
            continue
        for sub in ast.walk(helper):
            if isinstance(sub, ast.Attribute) and sub.attr == "is_alive":
                return True
    return False


@rule(
    "thread-no-liveness-recheck",
    "a blocking wait loop in a thread-spawning class never re-checks "
    "worker liveness (.is_alive) — if the worker died, the caller hangs "
    "forever instead of raising",
    severity="error",
)
def _check_thread_no_liveness_recheck(ctx):
    for cls in ctx.symbols.classes.values():
        if not cls.spawns_thread:
            continue
        for mname, mnode in cls.methods.items():
            if mname == "__init__" or mname in cls.thread_targets:
                continue  # the worker itself waits for work, not for itself
            for node in _walk_confined(mnode):
                if not isinstance(node, ast.While):
                    continue
                waits = any(
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "wait"
                    for sub in _walk_confined(node)
                )
                if waits and not _rechecks_liveness(node, cls):
                    yield ctx.finding(
                        node,
                        "thread-no-liveness-recheck",
                        f"`{cls.name}.{mname}` waits in a loop for progress "
                        "a worker thread must make, but never re-checks "
                        "worker liveness — a dead worker hangs this caller "
                        "forever (re-check `.is_alive()` each wakeup and "
                        "raise instead)",
                    )
