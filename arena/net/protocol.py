"""Wire protocol: routes, the response envelope, and a tiny client.

The HTTP/JSON layer is deliberately thin: `ArenaServer.query()` already
returns JSON-shaped dicts, so the wire tier's whole protocol job is
(1) mapping paths/queries onto the batched query API, (2) validating
the submit body into the int32 arrays the front door admits, and
(3) the ENVELOPE — every JSON response carries the staleness
``watermark`` and the request's ``trace_id`` side by side (ROADMAP
item 1: the trace id goes in the response next to the watermark, so a
slow or stale response is one `tracer.trace(id)` away from its story).

Errors follow the repo's verdict discipline: a malformed request is a
structured JSON error with the right status (400/404/405), never a
stack trace; the status codes land in the same
`arena_http_requests_total{endpoint=,status=}` counters as successes.

`WireClient` is the stdlib consumer half (persistent
`http.client.HTTPConnection`, one reconnect on a dropped keep-alive) —
what the frontend bench's producer/reader threads and the wire tests
drive the real server with. No jax imports anywhere in this module.
"""

import json
import http.client
import urllib.parse

import numpy as np

ENDPOINTS = (
    "leaderboard", "player", "h2h", "submit", "stats", "healthz",
    # The live ops plane (PR 13): windowed metrics, SLO burn rates,
    # profiler stacks, and trace resolution — all GET, all wearing the
    # standard envelope.
    "debug_window", "debug_slo", "debug_profile", "debug_trace",
    # The batched read endpoint (PR 16): one POST carrying many
    # leaderboard/player/h2h lookups, every one answered from ONE view.
    "query",
    # The replication log (PR 18): replicas page the writer's applied
    # log by sequence number (or align a restored snapshot by
    # watermark) and replay it strictly in order.
    "log",
    # The matchmaking plane (PR 20): policy-ranked pairing proposals
    # off one immutable view (503 when no Matchmaker is attached).
    "match",
)

# Default leaderboard page when the query string omits one.
DEFAULT_PAGE_LIMIT = 50

# Default /match proposal count when the query string omits n=.
# (Kept here, not imported from arena.match: this module stays free of
# jax imports so clients parse without touching the kernel stack.)
DEFAULT_MATCH_PROPOSALS = 16

# Batched /query bound: a request is one view read, not a denial-of-
# service vector — more lookups than this is a 400, not a slow answer.
MAX_BATCH_QUERIES = 1024


class ProtocolError(ValueError):
    """A malformed request: carries the HTTP status it must map to."""

    def __init__(self, status, message):
        super().__init__(message)
        self.status = status


def _query_int(params, key, default=None):
    raw = params.get(key, [None])[0]
    if raw is None:
        if default is None:
            raise ProtocolError(400, f"missing required query param {key!r}")
        return default
    try:
        return int(raw)
    except ValueError:
        raise ProtocolError(
            400, f"query param {key!r} must be an integer, got {raw!r}"
        ) from None


def _query_opt_int(params, key):
    """An OPTIONAL integer query param: None when absent (unlike
    `_query_int`, whose None default means required)."""
    if params.get(key, [None])[0] is None:
        return None
    return _query_int(params, key)


def _parse_tenant(params, parsed):  # schema: wire-read-params@v1
    """Fold an optional `?tenant=` into a read endpoint's parsed params
    — included ONLY when present, so single-tenant requests parse (and
    byte-cache-key) exactly as before the tenant axis existed."""
    tenant = _query_opt_int(params, "tenant")
    if tenant is not None:
        parsed["tenant"] = tenant


def parse_path(method, path):
    """Map (method, raw path) onto (endpoint, params) or raise
    `ProtocolError` with the status an unmatched request deserves:
    404 for an unknown path, 405 for a known path with the wrong
    method, 400 for malformed params."""
    split = urllib.parse.urlsplit(path)
    parts = [p for p in split.path.split("/") if p]
    params = urllib.parse.parse_qs(split.query)
    route = parts[0] if parts else ""
    if route == "healthz" and len(parts) == 1:
        endpoint, want = "healthz", "GET"
        parsed = {}
    elif route == "stats" and len(parts) == 1:
        endpoint, want = "stats", "GET"
        parsed = {}
    elif route == "leaderboard" and len(parts) == 1:
        endpoint, want = "leaderboard", "GET"
        parsed = {
            "offset": _query_int(params, "offset", 0),
            "limit": _query_int(params, "limit", DEFAULT_PAGE_LIMIT),
        }
        as_of = _query_opt_int(params, "as_of")
        if as_of is not None:
            parsed["as_of"] = as_of
        _parse_tenant(params, parsed)
    elif route == "player" and len(parts) == 2:
        endpoint, want = "player", "GET"
        try:
            parsed = {"player": int(parts[1])}
        except ValueError:
            raise ProtocolError(
                400, f"player id must be an integer, got {parts[1]!r}"
            ) from None
        as_of = _query_opt_int(params, "as_of")
        if as_of is not None:
            parsed["as_of"] = as_of
        _parse_tenant(params, parsed)
    elif route == "h2h" and len(parts) == 1:
        endpoint, want = "h2h", "GET"
        parsed = {"a": _query_int(params, "a"), "b": _query_int(params, "b")}
        _parse_tenant(params, parsed)
    elif route == "match" and len(parts) == 1:
        endpoint, want = "match", "GET"
        parsed = {"n": _query_int(params, "n", DEFAULT_MATCH_PROPOSALS)}
        # The policy is a string knob, not an int: pass it through
        # verbatim and let the matchmaker's closed vocabulary 400 it.
        policy = params.get("policy", [None])[0]
        if policy is not None:
            parsed["policy"] = policy
        _parse_tenant(params, parsed)
    elif route == "submit" and len(parts) == 1:
        endpoint, want = "submit", "POST"
        parsed = {}
    elif route == "query" and len(parts) == 1:
        endpoint, want = "query", "POST"
        parsed = {}
    elif route == "log" and len(parts) == 1:
        endpoint, want = "log", "GET"
        parsed = {
            "after_seq": _query_int(params, "after_seq", -1),
            "after_watermark": _query_opt_int(params, "after_watermark"),
            "limit": _query_int(params, "limit", 0),
        }
        if parsed["after_seq"] < -1:
            raise ProtocolError(
                400, f"after_seq must be >= -1, got {parsed['after_seq']}"
            )
    elif (
        route == "debug"
        and len(parts) == 2
        and parts[1] in ("window", "slo", "profile")
    ):
        endpoint, want = "debug_" + parts[1], "GET"
        parsed = {}
    elif route == "debug" and len(parts) == 3 and parts[1] == "trace":
        endpoint, want = "debug_trace", "GET"
        try:
            parsed = {"trace_id": int(parts[2])}
        except ValueError:
            raise ProtocolError(
                400, f"trace id must be an integer, got {parts[2]!r}"
            ) from None
    else:
        raise ProtocolError(404, f"no such endpoint: {split.path!r}")
    if method != want:
        raise ProtocolError(
            405, f"/{endpoint} requires {want}, got {method}"
        )
    return endpoint, parsed


def parse_submit_body(raw):  # schema: wire-submit-request@v1
    """Validate a submit body into (winners, losers, producer, tenant,
    category).

    The body is ``{"winners": [ints], "losers": [ints],
    "producer": "name"?, "tenant": int?, "category": "name"?}``;
    `tenant` addresses a tenant slot directly, `category` names one
    through the server's category registry — one or the other, never
    both. Array-shape/range validation beyond this (equal length, ids
    in range, tenant known) happens at admission in the front door,
    where the engine's own reject posture applies."""
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise ProtocolError(400, f"submit body is not JSON: {exc}") from None
    if not isinstance(doc, dict):
        raise ProtocolError(400, "submit body must be a JSON object")
    producer = doc.get("producer", "local")
    if not isinstance(producer, str) or not producer:
        raise ProtocolError(
            400, f"producer must be a non-empty string, got {producer!r}"
        )
    tenant = doc.get("tenant")
    if tenant is not None and not _plain_int(tenant):
        raise ProtocolError(
            400, f"submit field 'tenant' must be an integer, got {tenant!r}"
        )
    category = doc.get("category")
    if category is not None and (
        not isinstance(category, str) or not category
    ):
        raise ProtocolError(
            400,
            f"submit field 'category' must be a non-empty string, "
            f"got {category!r}",
        )
    if tenant is not None and category is not None:
        raise ProtocolError(
            400, "submit takes 'tenant' OR 'category', not both"
        )
    out = []
    for key in ("winners", "losers"):
        ids = doc.get(key)
        if not isinstance(ids, list) or not all(
            isinstance(i, int) and not isinstance(i, bool) for i in ids
        ):
            raise ProtocolError(
                400, f"submit field {key!r} must be a list of integers"
            )
        out.append(np.asarray(ids, np.int32))
    return out[0], out[1], producer, tenant, category


def _plain_int(value):
    return isinstance(value, int) and not isinstance(value, bool)


def parse_query_body(raw):  # schema: wire-query-request@v1
    """Validate a batched read body into a list of query specs.

    The body is ``{"queries": [{"leaderboard": [offset, limit]?,
    "players": [ids]?, "pairs": [[a, b]...]?}, ...]}`` — each spec
    must name at least one lookup, and the list is bounded by
    `MAX_BATCH_QUERIES`. Range validation (ids within the roster,
    non-negative pages) happens in `ArenaServer.query_batch`, where
    the serving tier's own reject posture applies."""
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise ProtocolError(400, f"query body is not JSON: {exc}") from None
    if not isinstance(doc, dict):
        raise ProtocolError(400, "query body must be a JSON object")
    queries = doc.get("queries")
    if not isinstance(queries, list) or not queries:
        raise ProtocolError(
            400, "query field 'queries' must be a non-empty list"
        )
    if len(queries) > MAX_BATCH_QUERIES:
        raise ProtocolError(
            400,
            f"query batch carries {len(queries)} lookups, "
            f"max is {MAX_BATCH_QUERIES}",
        )
    specs = []
    for i, q in enumerate(queries):
        if not isinstance(q, dict):
            raise ProtocolError(400, f"queries[{i}] must be a JSON object")
        unknown = sorted(set(q) - {"leaderboard", "players", "pairs",
                                   "tenant"})
        if unknown:
            raise ProtocolError(
                400, f"queries[{i}] has unknown fields: {unknown}"
            )
        spec = {}
        if "tenant" in q:
            if not _plain_int(q["tenant"]):
                raise ProtocolError(
                    400, f"queries[{i}].tenant must be an integer"
                )
            spec["tenant"] = q["tenant"]
        if "leaderboard" in q:
            page = q["leaderboard"]
            if (
                not isinstance(page, list)
                or len(page) != 2
                or not all(_plain_int(v) for v in page)
            ):
                raise ProtocolError(
                    400,
                    f"queries[{i}].leaderboard must be [offset, limit]",
                )
            spec["leaderboard"] = (page[0], page[1])
        if "players" in q:
            ids = q["players"]
            if not isinstance(ids, list) or not all(
                _plain_int(v) for v in ids
            ):
                raise ProtocolError(
                    400, f"queries[{i}].players must be a list of integers"
                )
            spec["players"] = list(ids)
        if "pairs" in q:
            pairs = q["pairs"]
            if not isinstance(pairs, list) or not all(
                isinstance(p, list)
                and len(p) == 2
                and all(_plain_int(v) for v in p)
                for p in pairs
            ):
                raise ProtocolError(
                    400, f"queries[{i}].pairs must be a list of [a, b] pairs"
                )
            spec["pairs"] = [(p[0], p[1]) for p in pairs]
        if not set(spec) & {"leaderboard", "players", "pairs"}:
            raise ProtocolError(400, f"queries[{i}] names no lookups")
        specs.append(spec)
    return specs


def make_response(payload, *, watermark, trace_id):  # schema: wire-envelope@v1
    """The response envelope: the payload dict plus the staleness
    watermark and the request's trace id, side by side in EVERY JSON
    response (the wire contract the tier-1 wire tests pin; a payload's
    own watermark/trace_id fields are replaced by the authoritative
    pair so no endpoint can drift)."""
    out = {
        k: v for k, v in payload.items() if k not in ("watermark", "trace_id")
    }
    out["watermark"] = watermark
    out["trace_id"] = trace_id
    return out


class WireClient:
    """Minimal persistent-connection JSON client for the wire tier.

    One `http.client.HTTPConnection` reused across requests (keep-
    alive); a dropped connection is rebuilt once per request. Returns
    `(status, payload)` — payload is the decoded JSON body, or the
    raw text for non-JSON responses (`/stats`)."""

    def __init__(self, host, port, timeout=30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._conn = None
        # How many TCP connections this client has opened: a reuse
        # regression (e.g. an endpoint that closes after every POST)
        # shows up as this number tracking the request count instead
        # of staying at 1.
        self.connections_opened = 0

    def _connect(self):
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
            self.connections_opened += 1
        return self._conn

    def _request(self, method, path, body=None):
        headers = {}
        data = None
        if body is not None:
            data = json.dumps(body).encode("utf-8")
            headers["Content-Type"] = "application/json"
        for attempt in (0, 1):
            conn = self._connect()
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                content_type = resp.getheader("Content-Type", "")
                if content_type.startswith("application/json"):
                    payload = json.loads(raw.decode("utf-8"))
                else:
                    payload = raw.decode("utf-8")
                return resp.status, payload, dict(resp.getheaders())
            except (http.client.HTTPException, ConnectionError, OSError):
                self.close()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def get(self, path):
        status, payload, _headers = self._request("GET", path)
        return status, payload

    def get_with_headers(self, path):
        return self._request("GET", path)

    def post(self, path, doc):
        status, payload, _headers = self._request("POST", path, body=doc)
        return status, payload

    def batch_query(self, queries):  # schema: wire-query-request@v1
        """POST many lookups as ONE /query request on the persistent
        connection. `queries` is a list of spec dicts (the
        `parse_query_body` schema); the response's "results" list is
        index-aligned with it, every entry answered from one view."""
        return self.post("/query", {"queries": list(queries)})

    def propose_matches(self, n, policy=None, tenant=None):  # schema: wire-match@v1
        """GET /match on the persistent connection (mirrors
        `batch_query`): up to `n` policy-ranked pairing proposals from
        the server's matchmaker. `policy=` picks from the matchmaker's
        vocabulary (server 400s unknown names); `tenant=` scopes the
        candidate set to one tenant's arena. 503 when the server has
        no matchmaker attached."""
        query = [f"n={int(n)}"]
        if policy is not None:
            query.append(f"policy={policy}")
        if tenant is not None:
            query.append(f"tenant={int(tenant)}")
        return self.get("/match?" + "&".join(query))

    def submit(self, winners, losers, producer="local", tenant=None,
               category=None):  # schema: wire-submit-request@v1
        """POST one batch to /submit (ids coerced to plain ints).
        `tenant=` submits tenant-local ids to one tenant's arena;
        `category=` names the tenant through the server's category
        registry instead (one or the other)."""
        doc = {
            "winners": [int(i) for i in np.asarray(winners).tolist()],
            "losers": [int(i) for i in np.asarray(losers).tolist()],
            "producer": producer,
        }
        if tenant is not None:
            doc["tenant"] = int(tenant)
        if category is not None:
            doc["category"] = category
        return self.post("/submit", doc)

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None
