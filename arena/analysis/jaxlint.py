"""jaxlint v2 — cross-module static analysis guarding the arena hot path.

PR 1's measured speedup rests on invariants no runtime check enforces
by default: zero recompiles across variable batch sizes (the pow2
shape-bucket contract), safe use of donated buffers, no host round
trips inside jitted bodies, honest timing of asynchronous dispatch,
and NumPy — not jnp — on host-side ingest paths. Each rule here is one
of those invariants expressed over the stdlib `ast`, so a regression
is caught at lint time instead of as a silently-lost speedup in a
bench run weeks later.

v2 adds the TWO-PASS driver: `lint_paths` first builds a project-wide
symbol table over every file being linted (`arena/analysis/project.py`
— module -> classes/functions/meshes/locks/assigned attributes, with
`from x import y` and attribute chains resolved), then runs the rules
with that table in scope (`ModuleContext.project`). That closes the
gap ROADMAP item 3 names (`sharding-spec-arity` now resolves meshes
DEFINED IN OTHER MODULES) and carries the concurrency lock-discipline
analyzer (`arena/analysis/concurrency.py`): `unguarded-shared-write`,
`blocking-while-locked`, `lock-order-inversion`,
`thread-no-liveness-recheck`, built on the `# guarded_by: <lockname>`
annotation convention the production modules now use.

Design:

- **No new dependencies.** Parsing is `ast`, comment handling is
  `tokenize`, the CLI is `argparse`. This module never imports jax —
  lint runs and lint TESTS need no accelerator stack (the `-m
  arena.analysis` entrypoint does import the arena package, whose
  __init__ pulls jax; import `arena.analysis.jaxlint` directly to
  stay jax-free).
- **Rule registry.** Every rule is a function registered via `@rule`
  with a kebab-case name and a one-line summary; `RULES` is the
  registry the CLI, the tests, and the bad-example corpus all iterate.
  A rule receives a `ModuleContext` (one shared analysis pass: jitted
  callables + their static/donate info, traced function bodies, the
  module's symbols, the project table, suppression table) and yields
  `Finding`s.
- **Heuristic, not sound.** This is a linter: dotted-name matching and
  straight-line dataflow, not type inference. Rules are tuned so the
  CLEAN TREE LINTS CLEAN (a tier-1 test pins zero findings over
  `arena/`, `bench.py`, `tests/`) and every rule fires on the embedded
  corpus (`arena/analysis/badcorpus/`, excluded from default walks).
- **Suppressible.** `# jaxlint: disable=<rule>[,<rule>...]` on the
  offending line suppresses named rules there; `disable=all` mutes the
  line. The directive is honored across the whole ENCLOSING STATEMENT
  for multi-line expressions (a decorated def, a wrapped `with`
  header), so the comment can sit on any line of the statement the
  finding points into. Deliberate violations (e.g. the sanitizer tests
  proving reuse-after-donate fails loudly) carry the comment as
  documentation.
- **Machine-readable output.** `--format=json` emits one JSON object
  per line (rule/path/line/col/message/suppressed — suppressed
  findings included, flagged) with rc semantics unchanged, so CI and
  the perf watchdog consume lint output mechanically; the human
  format stays the default.

What "jitted" means to the linter (tracked per module):

- a `def` decorated with `jax.jit` / `jit` / `jax.jit(...)` /
  `partial(jax.jit, ...)` / `shard_map` / `partial(shard_map, ...)`;
- a `def` whose name is later passed to `jax.jit(f, ...)` (including
  through `partial(f, ...)` inside the jit call);
- an assignment `name = jax.jit(...)` — `name` becomes a known-jitted
  callable, with `static_argnums`/`static_argnames` and
  `donate_argnums`/`donate_argnames` read off the call;
- the repo's own factories: `jit_elo_epoch(...)` (donates argnum 0
  unless `donate=False`), `jit_bt_fit(...)`, `jit_bt_fit_chunked(...)`,
  and `sanitize.donation_guard(fn, donate_argnums=...)`.
"""

from __future__ import annotations

import argparse
import ast
import concurrent.futures
import dataclasses
import io
import json
import pathlib
import sys
import threading
import tokenize

from arena.analysis import project as project_mod
from arena.analysis.project import dotted

# --- findings and the rule registry ---------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    check: object  # ModuleContext -> iterable of Finding
    severity: str  # "error" | "warning" — every rule must declare one


RULES: dict[str, Rule] = {}

# The severity vocabulary is closed: a rule must declare one of these
# at registration (no default — the selfcheck pins that every rule in
# the registry declares one, so severity can never silently drift as
# the registry grows) and --format=json carries it per finding.
SEVERITIES = ("error", "warning")

# Findings synthesized outside the registry (unparseable file) are
# errors by definition.
_SYNTHETIC_SEVERITY = "error"


def rule(name, summary, *, severity):
    if severity not in SEVERITIES:
        raise ValueError(
            f"rule {name!r} declares severity {severity!r}; must be one of "
            f"{SEVERITIES}"
        )

    def register(fn):
        if name in RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name, summary, fn, severity)
        return fn

    return register


def finding_severity(finding) -> str:
    """The declared severity of a finding's rule (synthetic rules like
    syntax-error report as errors)."""
    r = RULES.get(finding.rule)
    return r.severity if r is not None else _SYNTHETIC_SEVERITY


# --- shared AST helpers ----------------------------------------------------
# (`dotted` lives in arena.analysis.project — the symbol table and the
# rules share one spelling of name resolution.)


def scope_walk(scope):
    """ast.walk confined to one scope: yields nodes under `scope`
    without descending into nested function/class definitions, so a
    call is attributed to exactly one scope."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


_JIT_NAMES = {"jax.jit", "jit"}
_TRACER_DECORATORS = _JIT_NAMES | {"shard_map", "jax.experimental.shard_map.shard_map"}
# Repo factories returning jitted callables: tail name -> (static, donate).
# `static=True` means "shape handling is the factory's contract" — the
# nonstatic-shape-arg rule stays quiet on calls to these.
_FACTORY_TAILS = {
    "jit_elo_epoch": (True, (0,)),
    "jit_bt_fit": (True, ()),
    "jit_bt_fit_chunked": (True, ()),
    "jit_elo_bootstrap": (True, ()),
}
_DONATION_GUARD_TAIL = "donation_guard"


@dataclasses.dataclass
class JitInfo:
    """What the linter knows about one jitted callable."""

    has_static: bool = False
    donate_argnums: tuple = ()


def _literal_argnums(keyword_value) -> tuple:
    """donate_argnums=(0,) / 0 / [0, 1] -> a tuple of ints; unknown -> (0,)."""
    try:
        val = ast.literal_eval(keyword_value)
    except (ValueError, TypeError, SyntaxError):
        return (0,)
    if isinstance(val, int):
        return (val,)
    if isinstance(val, (tuple, list)) and all(isinstance(v, int) for v in val):
        return tuple(val)
    return (0,)


def _jit_call_info(call: ast.Call) -> JitInfo | None:
    """JitInfo if `call` constructs a jitted callable, else None."""
    fname = dotted(call.func)
    if fname is None:
        return None
    tail = fname.split(".")[-1]
    if fname in _JIT_NAMES:
        info = JitInfo()
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                info.has_static = True
            elif kw.arg in ("donate_argnums", "donate_argnames"):
                info.donate_argnums = _literal_argnums(kw.value)
        return info
    if tail in _FACTORY_TAILS:
        static, donate = _FACTORY_TAILS[tail]
        for kw in call.keywords:
            if kw.arg == "donate" and isinstance(kw.value, ast.Constant):
                donate = (0,) if kw.value.value else ()
        return JitInfo(has_static=static, donate_argnums=donate)
    if tail == _DONATION_GUARD_TAIL:
        donate = (0,)
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                donate = _literal_argnums(kw.value)
        return JitInfo(has_static=True, donate_argnums=donate)
    return None


def _decorator_is_tracing(dec) -> bool:
    name = dotted(dec)
    if name in _TRACER_DECORATORS:
        return True
    if isinstance(dec, ast.Call):
        cname = dotted(dec.func)
        if cname in _TRACER_DECORATORS:
            return True
        # functools.partial(jax.jit, ...) / partial(shard_map, ...)
        if cname and cname.split(".")[-1] == "partial" and dec.args:
            return dotted(dec.args[0]) in _TRACER_DECORATORS
    return False


# --- per-module shared analysis -------------------------------------------


class ModuleContext:
    """One parse + one discovery pass, shared by every rule.

    `symbols` is this module's slice of the pass-1 symbol table;
    `project` is the whole `ProjectTable` (set by the two-pass driver —
    `lint_source` wraps a single-module table so rules never branch on
    its absence beyond cross-module lookups failing softly).
    """

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree, raw_suppressions, comments = _parsed(path, source)
        self.suppressions = _expand_suppressions(self.tree, raw_suppressions)
        self.symbols = project_mod.module_symbols(path, self.tree, comments)
        self.project = None
        # dotted target name -> JitInfo, collected from every assignment
        # anywhere in the module (covers `self._update = jax.jit(...)`
        # in __init__ being called from another method).
        self.jitted_callables: dict[str, JitInfo] = {}
        # FunctionDef nodes whose bodies are traced by jit/shard_map.
        self.traced_defs: list[ast.FunctionDef] = []
        self._discover()

    def _discover(self):
        defs_by_name = {}
        traced_names = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, node)
                if any(_decorator_is_tracing(d) for d in node.decorator_list):
                    traced_names.add(node.name)
            elif isinstance(node, ast.Call):
                info = _jit_call_info(node)
                if info is None:
                    continue
                # jax.jit(f, ...) / jax.jit(partial(f, ...)): the wrapped
                # def (if visible in this module) is traced.
                for arg in node.args[:1]:
                    target = arg
                    if isinstance(target, ast.Call):
                        tname = dotted(target.func)
                        if tname and tname.split(".")[-1] == "partial" and target.args:
                            target = target.args[0]
                    tname = dotted(target)
                    if tname and "." not in tname:
                        traced_names.add(tname)
            if isinstance(node, ast.Assign):
                value = node.value
                if isinstance(value, ast.Call):
                    info = _jit_call_info(value)
                    if info is not None:
                        for tgt in node.targets:
                            tname = dotted(tgt)
                            if tname:
                                self.jitted_callables[tname] = info
        self.traced_defs = [
            d for name, d in defs_by_name.items() if name in traced_names
        ]
        self._traced_def_ids = {id(d) for d in self.traced_defs}

    def is_traced_def(self, node) -> bool:
        return id(node) in self._traced_def_ids

    def finding(self, node, rule_name, message) -> Finding:
        return Finding(self.path, node.lineno, node.col_offset, rule_name, message)


# Content-keyed parse memo. The selfcheck suite, the corpus tests,
# and `--gate` all call `lint_paths`/`lint_source` repeatedly in one
# process, and every call re-parsed and re-tokenized the same
# unchanged sources. One entry caches the (tree, raw suppression
# table, comment table) triple per (path, source); no pass mutates a
# parsed tree or either table, so sharing them across ModuleContext
# instances is safe. Keyed by source HASH with the full source kept in
# the entry for an equality check (a hash collision must miss, never
# serve the wrong tree). Bounded by wholesale reset — the working set
# is one repo's files; an eviction policy would be ceremony.
_PARSE_CACHE: dict = {}
_PARSE_CACHE_LOCK = threading.Lock()
_PARSE_CACHE_MAX = 1024


def _parsed(path: str, source: str):
    key = (path, hash(source))
    with _PARSE_CACHE_LOCK:
        hit = _PARSE_CACHE.get(key)
        if hit is not None and hit[0] == source:
            return hit[1]
    tree = ast.parse(source, filename=path)
    raw_suppressions, comments = _comment_tables(source)
    entry = (tree, raw_suppressions, comments)
    with _PARSE_CACHE_LOCK:
        if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            _PARSE_CACHE.clear()
        _PARSE_CACHE[key] = (source, entry)
    return entry


def clear_parse_cache():
    """Drop every memoized parse (tests use this to compare a cold run
    against a warm one bit-for-bit)."""
    with _PARSE_CACHE_LOCK:
        _PARSE_CACHE.clear()


def _comment_tables(source: str):
    """Two line-keyed comment tables from ONE tokenize pass:
    suppression directives (lineno -> rule names disabled; {'all'}
    mutes) and raw comment text (lineno -> text — the symbol table
    reads `guarded_by:` annotations from it)."""
    table: dict[int, set[str]] = {}
    comments: dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            text = tok.string.lstrip("#").strip()
            comments[tok.start[0]] = text
            if not text.startswith("jaxlint:"):
                continue
            directive = text[len("jaxlint:"):].strip()
            if directive.startswith("disable="):
                names = {n.strip() for n in directive[len("disable="):].split(",")}
                table.setdefault(tok.start[0], set()).update(n for n in names if n)
    except tokenize.TokenError:
        pass  # unterminated source: lint what parsed, suppress nothing
    return table, comments


def _stmt_header_span(stmt) -> tuple[int, int]:
    """The line span a suppression directive on any of its lines covers:
    for compound statements, first decorator line through the header's
    last line (the body is NOT included — a comment inside a with/if
    body must not mute findings on the header, and vice versa); for
    simple statements, the whole (possibly wrapped) expression."""
    start = stmt.lineno
    if isinstance(
        stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ) and stmt.decorator_list:
        start = min(start, min(d.lineno for d in stmt.decorator_list))
    body = getattr(stmt, "body", None)
    if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
        end = body[0].lineno - 1
    else:
        end = stmt.end_lineno or stmt.lineno
    return start, end


def _expand_suppressions(tree, table: dict[int, set[str]]) -> dict[int, set[str]]:
    """Widen line-keyed directives to their enclosing statement: a
    finding inside a multi-line expression (a decorated def, a wrapped
    `with` header, a parenthesized assignment) is suppressed by a
    directive on ANY line of that statement's header span — the
    comment naturally sits at the end of the wrapped construct, while
    the finding points at the line the offending node started on."""
    if not table:
        return table
    out = {line: set(rules) for line, rules in table.items()}
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        start, end = _stmt_header_span(node)
        if end <= start:
            continue
        merged: set[str] = set()
        for line in range(start, end + 1):
            merged |= table.get(line, set())
        if merged:
            for line in range(start, end + 1):
                out.setdefault(line, set()).update(merged)
    return out


# --- rules ----------------------------------------------------------------

_MUTABLE_CONSTRUCTORS = {"list", "dict", "set", "collections.defaultdict", "defaultdict"}


def _mutable_bindings(scope_node) -> dict[str, ast.AST]:
    """Names bound DIRECTLY in `scope_node` to mutable literals/ctors."""
    out = {}
    body = getattr(scope_node, "body", [])
    for stmt in body:
        if isinstance(stmt, ast.Assign):
            value = stmt.value
            mutable = isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                         ast.DictComp, ast.SetComp))
            if isinstance(value, ast.Call) and dotted(value.func) in _MUTABLE_CONSTRUCTORS:
                mutable = True
            if mutable:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = stmt
    return out


def _local_names(fn_node) -> set[str]:
    """Parameters plus names stored anywhere inside the function."""
    args = fn_node.args
    names = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
    return names


@rule(
    "mutable-closure",
    "jit-traced function closes over mutable host state (list/dict/set); "
    "tracing captures it once — later mutations are invisible or unsound",
    severity="error",
)
def _check_mutable_closure(ctx: ModuleContext):
    if not ctx.traced_defs:
        return
    module_mutables = _mutable_bindings(ctx.tree)
    # Enclosing-function locals: map each traced def to mutable bindings
    # of every ancestor function scope.
    enclosing: dict[int, dict[str, ast.AST]] = {}

    def walk(node, inherited):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if ctx.is_traced_def(child):
                    enclosing[id(child)] = dict(inherited)
                walk(child, {**inherited, **_mutable_bindings(child)})
            else:
                walk(child, inherited)

    walk(ctx.tree, {})
    for fn in ctx.traced_defs:
        candidates = {**module_mutables, **enclosing.get(id(fn), {})}
        if not candidates:
            continue
        locals_ = _local_names(fn)
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in candidates
                and node.id not in locals_
            ):
                yield ctx.finding(
                    node,
                    "mutable-closure",
                    f"jitted `{fn.name}` reads enclosing mutable `{node.id}`; "
                    "tracing freezes its current value — pass it as an "
                    "argument or make it immutable",
                )


_HOST_SYNC_CALLS = frozenset({"float", "int", "bool", "print", "np.asarray", "np.array", "numpy.asarray", "numpy.array"})
_HOST_SYNC_METHOD_TAILS = ("item", "tolist")


@rule(
    "host-sync-in-jit",
    "host-synchronizing call (float()/.item()/np.asarray/print) inside a "
    "jit-traced body — forces a device round-trip or fails under tracing",
    severity="error",
)
def _check_host_sync(ctx: ModuleContext):
    for fn in ctx.traced_defs:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            if fname in _HOST_SYNC_CALLS:
                yield ctx.finding(
                    node,
                    "host-sync-in-jit",
                    f"`{fname}(...)` inside jitted `{fn.name}` forces a host "
                    "sync (or breaks under tracing); compute on-device and "
                    "convert outside the jitted region",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHOD_TAILS
                and not node.args
            ):
                yield ctx.finding(
                    node,
                    "host-sync-in-jit",
                    f"`.{node.func.attr}()` inside jitted `{fn.name}` is a "
                    "blocking device-to-host transfer",
                )


def _is_shapeish(expr, shape_locals) -> bool:
    """x.shape / x.shape[0] / len(x) / a name bound to one of those."""
    if isinstance(expr, ast.Attribute) and expr.attr == "shape":
        return True
    if isinstance(expr, ast.Subscript):
        return _is_shapeish(expr.value, shape_locals)
    if isinstance(expr, ast.Call) and dotted(expr.func) == "len":
        return True
    if isinstance(expr, ast.Name):
        return expr.id in shape_locals
    return False


@rule(
    "nonstatic-shape-arg",
    "shape-derived Python scalar flows into a jitted call that declares no "
    "static_argnums — a per-size recompile hazard (pow2 bucket contract)",
    severity="warning",
)
def _check_nonstatic_shape_arg(ctx: ModuleContext):
    if not ctx.jitted_callables:
        return
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        # One linear pass: track names bound to shape-derived scalars,
        # flag them (or direct .shape/len expressions) as jit args.
        shape_locals: set[str] = set()
        for node in scope_walk(scope):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.AST):
                if _is_shapeish(node.value, shape_locals):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            shape_locals.add(tgt.id)
        for node in scope_walk(scope):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func)
            info = ctx.jitted_callables.get(fname) if fname else None
            if info is None or info.has_static:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if _is_shapeish(arg, shape_locals):
                    yield ctx.finding(
                        arg,
                        "nonstatic-shape-arg",
                        f"shape-derived scalar passed to jitted `{fname}` "
                        "without static_argnums; batch sizes vary — route "
                        "through the pow2 bucket contract or declare it "
                        "static deliberately",
                    )


@rule(
    "use-after-donate",
    "a buffer passed in a donated position is used after the donating "
    "call — on device it may alias freed or reused memory",
    severity="error",
)
def _check_use_after_donate(ctx: ModuleContext):
    donating = {
        name: info
        for name, info in ctx.jitted_callables.items()
        if info.donate_argnums
    }
    if not donating:
        return
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        yield from _donate_scan(ctx, scope, donating)


def _stmt_children(stmt):
    """Nested statement lists of a compound statement, in source order."""
    for field in ("body", "orelse", "finalbody"):
        yield from getattr(stmt, field, [])
    for handler in getattr(stmt, "handlers", []):
        yield from handler.body


_STMT_LIST_FIELDS = ("body", "orelse", "finalbody", "handlers")


def _stmt_expr_walk(stmt):
    """Walk a statement's OWN expressions (test/items/iter/targets/value
    ...), leaving nested statement lists to the recursive scan — so a
    load inside a `with`/`if`/`for` body is seen exactly once, in
    source order relative to the poisoning calls around it."""
    roots = []
    for field, value in ast.iter_fields(stmt):
        if field in _STMT_LIST_FIELDS:
            continue
        if isinstance(value, ast.AST):
            roots.append(value)
        elif isinstance(value, list):
            roots.extend(v for v in value if isinstance(v, ast.AST))
    for root in roots:
        yield root
        yield from ast.walk(root)


def _donate_scan(ctx, scope, donating):
    poisoned: dict[str, str] = {}  # dotted name -> donating callee

    def process(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested scopes get their own scan
            # 1. loads of already-poisoned names (poison from earlier stmts)
            if poisoned:
                for node in _stmt_expr_walk(stmt):
                    if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                        getattr(node, "ctx", None), ast.Load
                    ):
                        name = dotted(node)
                        if name in poisoned:
                            yield ctx.finding(
                                node,
                                "use-after-donate",
                                f"`{name}` was donated to `{poisoned[name]}` "
                                "and may alias freed device memory; rebind "
                                "it to the call's result or stop donating",
                            )
            # 2. donating calls poison their donated args
            for node in _stmt_expr_walk(stmt):
                if isinstance(node, ast.Call):
                    fname = dotted(node.func)
                    info = donating.get(fname) if fname else None
                    if info is None:
                        continue
                    for i in info.donate_argnums:
                        if i < len(node.args):
                            target_name = dotted(node.args[i])
                            if target_name:
                                poisoned[target_name] = fname
            # 3. rebinding clears poison
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            elif isinstance(stmt, ast.Delete):
                targets = stmt.targets
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                targets = [stmt.target]
            for tgt in targets:
                for node in ast.walk(tgt):
                    name = dotted(node)
                    if name:
                        poisoned.pop(name, None)
            yield from process(_stmt_children(stmt))

    yield from process(getattr(scope, "body", []))


_TIMING_CALLS = frozenset(
    {"time.perf_counter", "time.time", "time.monotonic", "perf_counter", "monotonic"}
)


@rule(
    "timing-without-block",
    "wall-clock measured across asynchronous JAX dispatch without "
    "block_until_ready — the timer stops before the device finishes",
    severity="warning",
)
def _check_timing_without_block(ctx: ModuleContext):
    for scope in [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        calls = [n for n in scope_walk(scope) if isinstance(n, ast.Call)]
        timing = sorted(
            (c for c in calls if dotted(c.func) in _TIMING_CALLS),
            key=lambda c: (c.lineno, c.col_offset),
        )
        for first, second in zip(timing, timing[1:]):
            region = [
                c for c in calls if first.lineno < c.lineno < second.lineno
            ]
            has_block = any(
                (dotted(c.func) or "").endswith("block_until_ready") for c in region
            )
            if has_block:
                continue
            for c in region:
                fname = dotted(c.func) or ""
                root = fname.split(".")[0]
                if root in ("jax", "jnp") or fname in ctx.jitted_callables:
                    yield ctx.finding(
                        second,
                        "timing-without-block",
                        f"timed region dispatches `{fname}` asynchronously "
                        "but never calls block_until_ready before reading "
                        "the clock — the measurement excludes device time",
                    )
                    break


_HOST_COMPUTE_OPS = frozenset(
    {"argsort", "sort", "searchsorted", "bincount", "cumsum",
     "concatenate", "unique", "nonzero", "where", "stack"}
)


@rule(
    "jnp-on-host-path",
    "device jnp compute op in a host-side NumPy ingest path — pays "
    "dispatch overhead and device round-trips where np is correct",
    severity="warning",
)
def _check_jnp_on_host_path(ctx: ModuleContext):
    for scope in [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and not ctx.is_traced_def(n)
    ]:
        calls = [n for n in scope_walk(scope) if isinstance(n, ast.Call)]
        uses_numpy = any(
            (dotted(c.func) or "").split(".")[0] in ("np", "numpy") for c in calls
        )
        if not uses_numpy:
            continue
        for c in calls:
            fname = dotted(c.func) or ""
            parts = fname.split(".")
            if (
                len(parts) == 2
                and parts[0] in ("jnp", "jax.numpy")
                and parts[1] in _HOST_COMPUTE_OPS
            ):
                yield ctx.finding(
                    c,
                    "jnp-on-host-path",
                    f"`{fname}` in host-side `{scope.name}` runs on device; "
                    "this is a NumPy ingest path — use "
                    f"`np.{parts[1]}` (see engine.pack_batch)",
                )


def _pspec_aliases(tree) -> set:
    """Names PartitionSpec is bound to ('PartitionSpec' plus any
    `from jax.sharding import PartitionSpec as P` alias)."""
    names = {"PartitionSpec"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return names


def _shard_map_site(call):
    """(kwargs, wrapped-fn node or None) if `call` applies shard_map —
    either `shard_map(f, mesh=..., in_specs=..., out_specs=...)` or
    `partial(shard_map, mesh=..., ...)` (the decorator idiom)."""
    fname = dotted(call.func)
    if fname is None:
        return None
    tail = fname.split(".")[-1]
    if tail == "shard_map":
        fn = call.args[0] if call.args else None
        return {kw.arg: kw.value for kw in call.keywords}, fn
    if tail == "partial" and call.args:
        inner = dotted(call.args[0])
        if inner and inner.split(".")[-1] == "shard_map":
            return {kw.arg: kw.value for kw in call.keywords}, None
    return None


@rule(
    "sharding-spec-arity",
    "shard_map in_specs arity disagrees with the wrapped function, or a "
    "PartitionSpec names a mesh axis the site's mesh does not define — "
    "resolved CROSS-MODULE through the project symbol table, the silent "
    "class of mistake match_partition_rules only catches at runtime",
    severity="error",
)
def _check_sharding_spec_arity(ctx: ModuleContext):
    tree = ctx.tree
    sym = ctx.symbols
    str_consts = sym.str_consts
    local_axes, local_known = sym.mesh_union
    pspec_names = _pspec_aliases(tree)
    defs_by_name = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }

    def resolve_axis(arg):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.Name) and arg.id in str_consts:
            return str_consts[arg.id]
        return None  # None / unresolvable: no claim

    def site_mesh(kws):
        """(axes, known, where) for THIS site's mesh: the `mesh=` kwarg
        resolved by name — locally, then through the project table
        (the v2 cross-module upgrade: a mesh imported from another
        module resolves to its defining module's axis names). Falls
        back to the module union (v1 semantics) when the site's mesh
        expression is not a resolvable name."""
        mesh_expr = kws.get("mesh")
        if mesh_expr is not None:
            name = dotted(mesh_expr)
            if name:
                if name in sym.meshes:
                    axes, known = sym.meshes[name]
                    return axes, known, "this module"
                if ctx.project is not None:
                    resolved = ctx.project.resolve_mesh(sym, name)
                    if resolved is not None:
                        axes, known = resolved
                        return axes, known, f"`{name}`'s defining module"
        return local_axes, local_known, "this module"

    def check_site(kws, fn_def):
        in_specs = kws.get("in_specs")
        if (
            in_specs is not None
            and isinstance(in_specs, ast.Tuple)
            and fn_def is not None
            and not fn_def.args.vararg
        ):
            nparams = len(fn_def.args.posonlyargs) + len(fn_def.args.args)
            nspecs = len(in_specs.elts)
            if nspecs != nparams:
                yield ctx.finding(
                    in_specs,
                    "sharding-spec-arity",
                    f"in_specs carries {nspecs} specs but the shard_mapped "
                    f"`{fn_def.name}` takes {nparams} arguments — every "
                    "operand needs exactly one PartitionSpec",
                )
        axes, axes_known, where = site_mesh(kws)
        for spec_expr in (in_specs, kws.get("out_specs")):
            if spec_expr is None or not axes_known:
                continue
            for node in ast.walk(spec_expr):
                if not isinstance(node, ast.Call):
                    continue
                cname = dotted(node.func)
                if cname is None or cname.split(".")[-1] not in pspec_names:
                    continue
                for arg in node.args:
                    name = resolve_axis(arg)
                    if name is not None and name not in axes:
                        yield ctx.finding(
                            node,
                            "sharding-spec-arity",
                            f"PartitionSpec axis {name!r} is not defined by "
                            f"the mesh at this site ({where} defines axes "
                            f"{sorted(axes)}) — sharding over it fails at "
                            "runtime or silently replicates",
                        )

    seen_decorators = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    site = _shard_map_site(dec)
                    if site is not None:
                        seen_decorators.add(id(dec))
                        kws, fn = site
                        fn_def = defs_by_name.get(fn.id) if isinstance(fn, ast.Name) else node
                        yield from check_site(kws, fn_def)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and id(node) not in seen_decorators:
            site = _shard_map_site(node)
            if site is not None:
                kws, fn = site
                fn_def = defs_by_name.get(fn.id) if isinstance(fn, ast.Name) else None
                yield from check_site(kws, fn_def)


# --- driver ---------------------------------------------------------------

BADCORPUS_DIR = "badcorpus"


class PathError(Exception):
    """One or more lint targets were unusable (missing path, unreadable
    file). Carries EVERY bad path seen in the run — the CLI reports
    each on its own line and exits 2, instead of stopping at the first
    (a CI run over a long target list should name every problem at
    once)."""

    def __init__(self, errors):
        self.errors = list(errors)  # [(path, detail), ...]
        super().__init__("; ".join(f"{p}: {d}" for p, d in self.errors))


def _select_rules(rules):
    """The registry slice a run executes: `rules=None` means all.
    Unknown names raise ValueError (the CLI maps it to rc 2)."""
    if rules is None:
        return list(RULES.values())
    unknown = sorted(set(rules) - set(RULES))
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
    return [RULES[name] for name in rules]


def _apply_rules(ctx: ModuleContext, keep_suppressed: bool, selected=None) -> list[Finding]:
    """Pass 2 for one module: run every selected rule, then apply the
    suppression table. keep_suppressed=True returns muted findings too,
    marked `suppressed=True` (the JSON format's contract); they never
    affect exit codes."""
    findings = []
    for r in (selected if selected is not None else RULES.values()):
        findings.extend(r.check(ctx))
    kept = []
    for f in findings:
        disabled = ctx.suppressions.get(f.line, set())
        if "all" in disabled or f.rule in disabled:
            if keep_suppressed:
                kept.append(dataclasses.replace(f, suppressed=True))
            continue
        kept.append(f)
    return kept


def _sorted_findings(findings):
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def lint_source(
    source: str, path: str = "<string>", keep_suppressed: bool = False,
    rules=None,
) -> list[Finding]:
    """Lint one module's source; returns findings after suppression.
    Single-module form: the project table holds just this module, so
    cross-module lookups fail softly (imported meshes stay unknown —
    exactly the v1 behavior `lint_paths` upgrades on). `rules` selects
    a registry subset by name (None = all)."""
    selected = _select_rules(rules)
    try:
        ctx = ModuleContext(path, source)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, exc.offset or 0, "syntax-error", str(exc))]
    ctx.project = project_mod.ProjectTable([ctx.symbols])
    ctx.siblings = {ctx.symbols.name: ctx}
    return _sorted_findings(_apply_rules(ctx, keep_suppressed, selected))


def collect_python_files(paths):
    """Expand files/dirs into `(files, errors)` — every bad path in the
    target list is collected (with its reason), never just the first.
    Directory walks skip the embedded bad-example corpus (and
    __pycache__) unless the given root itself points into the corpus —
    so `jaxlint arena/` is clean while `jaxlint arena/analysis/badcorpus`
    lints the corpus."""
    files, errors = [], []
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_file():
            files.append(p)
        elif p.is_dir():
            inside_corpus = BADCORPUS_DIR in p.resolve().parts
            for f in sorted(p.rglob("*.py")):
                rel_parts = f.resolve().parts
                if "__pycache__" in rel_parts:
                    continue
                if not inside_corpus and BADCORPUS_DIR in rel_parts:
                    continue
                files.append(f)
        else:
            errors.append((raw, "no such file or directory"))
    return files, errors


def iter_python_files(paths):
    """`collect_python_files` with the historical contract: raises
    `PathError` (an all-bad-paths report) if anything was unusable."""
    files, errors = collect_python_files(paths)
    if errors:
        raise PathError(errors)
    return iter(files)


def lint_paths(paths, keep_suppressed: bool = False, rules=None,
               jobs: int = 1) -> list[Finding]:
    """The two-pass driver: pass 1 parses EVERY file and builds the
    project-wide symbol table; pass 2 runs the rules per module with
    that table in scope — so a rule looking at module B can resolve a
    mesh or a lock defined in module A. `rules` selects a registry
    subset by name (None = all). `jobs` fans pass 2 over a thread
    pool (pass 1 stays serial — the symbol table is shared state);
    results are collected in submission order and sorted identically,
    so parallel findings are bit-identical to serial. Raises
    `PathError` carrying EVERY missing/unreadable target after the
    whole walk."""
    selected = _select_rules(rules)
    findings = []
    contexts = []
    files, errors = collect_python_files(paths)
    for f in files:
        try:
            source = f.read_text()
        except OSError as exc:
            errors.append((str(f), f"unreadable: {exc.strerror or exc}"))
            continue
        try:
            contexts.append(ModuleContext(str(f), source))
        except SyntaxError as exc:
            findings.append(
                Finding(str(f), exc.lineno or 0, exc.offset or 0,
                        "syntax-error", str(exc))
            )
    if errors:
        raise PathError(errors)
    table = project_mod.ProjectTable([ctx.symbols for ctx in contexts])
    siblings = {ctx.symbols.name: ctx for ctx in contexts}
    for ctx in contexts:
        ctx.project = table
        ctx.siblings = siblings
    if jobs > 1 and len(contexts) > 1:
        with concurrent.futures.ThreadPoolExecutor(max_workers=jobs) as pool:
            per_ctx = pool.map(
                lambda ctx: _apply_rules(ctx, keep_suppressed, selected),
                contexts,
            )
            for batch in per_ctx:
                findings.extend(batch)
    else:
        for ctx in contexts:
            findings.extend(_apply_rules(ctx, keep_suppressed, selected))
    return _sorted_findings(findings)


def default_targets() -> list[str]:
    """The repo surfaces the tier-1 gate lints: arena/, bench.py, tests/."""
    repo = pathlib.Path(__file__).resolve().parent.parent.parent
    return [str(repo / "arena"), str(repo / "bench.py"), str(repo / "tests")]


def _json_line(finding: Finding) -> str:
    """One finding as one JSON object on one line — the mechanical
    consumption contract (CI, the perf watchdog): stable keys, no
    nesting, suppressed findings included but flagged, and the rule's
    declared `severity` carried per finding so a consumer can gate on
    errors while only reporting warnings."""
    return json.dumps({
        "rule": finding.rule,
        "path": finding.path,
        "line": finding.line,
        "col": finding.col,
        "message": finding.message,
        "suppressed": finding.suppressed,
        "severity": finding_severity(finding),
    }, sort_keys=True)


def _sarif_report(findings) -> str:
    """Minimal SARIF 2.1.0 document: rule id + severity level + one
    physical location + message text per finding, rule descriptors for
    every rule referenced. Enough for standard CI tooling to annotate
    PRs; nothing speculative beyond that. Suppressed findings carry an
    inSource suppression object (the SARIF spelling of the JSON
    format's `suppressed: true`)."""
    rule_ids = sorted({f.rule for f in findings})
    descriptors = []
    for rid in rule_ids:
        r = RULES.get(rid)
        descriptors.append({
            "id": rid,
            "shortDescription": {
                "text": r.summary if r is not None else "synthetic finding",
            },
        })
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": finding_severity(f),  # SEVERITIES ⊂ SARIF levels
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": f.col + 1,  # SARIF is 1-based
                    },
                },
            }],
        }
        if f.suppressed:
            result["suppressions"] = [{"kind": "inSource"}]
        results.append(result)
    return json.dumps({
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "jaxlint", "rules": descriptors}},
            "results": results,
        }],
    }, sort_keys=True)


def baseline_key(finding: Finding) -> str:
    """The identity a baseline entry pins: rule + path + message —
    deliberately NOT the line, so unrelated edits that drift a known
    finding up or down the file don't resurrect it."""
    return f"{finding.rule}::{finding.path}::{finding.message}"


def _parse_rule_list(raw):
    return [name.strip() for name in raw.split(",") if name.strip()]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m arena.analysis",
        description="JAX-aware lint rules guarding the arena hot path",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the repo's arena/, "
        "bench.py, tests/)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule registry (name, severity, one-line "
        "semantics) and exit",
    )
    parser.add_argument(
        "--rules", metavar="A,B",
        help="run ONLY the named rules (comma-separated registry names) "
        "— e.g. the expensive abstract-interp families in isolation. "
        "Exit-code semantics unchanged.",
    )
    parser.add_argument(
        "--disable", metavar="A,B",
        help="skip the named rules (applied after --rules when both are "
        "given). Exit-code semantics unchanged.",
    )
    parser.add_argument(
        "--format", choices=("human", "json", "sarif"), default="human",
        help="human (default): path:line:col: rule: message; json: one "
        "JSON object per finding per line (suppressed findings included, "
        "flagged; severity carried); sarif: one SARIF 2.1.0 document on "
        "stdout for CI annotation tooling. Exit codes are identical in "
        "all formats.",
    )
    parser.add_argument(
        "--baseline", metavar="FILE",
        help="if FILE exists: report only findings NOT recorded in it "
        "(keyed rule+path+message — tolerant of line drift; a finding "
        "from a rule the baseline never ran is always reported). If "
        "FILE does not exist: write the current findings to it and "
        "exit 0, so a new rule can land on a dirty tree without "
        "flag-day fixes.",
    )
    parser.add_argument(
        "--gate", action="store_true",
        help="one-shot CI mode: the FULL registry over the default "
        "targets, findings printed in the human format AND a SARIF "
        "2.1.0 document written to jaxlint.sarif in the current "
        "directory (next to the exit code, for annotation tooling). "
        "Exit-code semantics unchanged. Combining --gate with explicit "
        "paths, --rules/--disable, or --baseline is an error (rc 2) — "
        "the gate IS the fixed configuration.",
    )
    parser.add_argument(
        "--jobs", type=int, default=1, metavar="N",
        help="fan the per-file rule pass over N threads after the "
        "serial symbol-table pass; findings are bit-identical to the "
        "serial run (collected in submission order, same final sort)",
    )
    args = parser.parse_args(argv)
    if args.list_rules:
        for r in RULES.values():
            print(f"{r.name} [{r.severity}]: {r.summary}")
        return 0
    if args.gate and (
        args.paths or args.rules is not None or args.disable is not None
        or args.baseline is not None
    ):
        print(
            "jaxlint: --gate fixes the configuration (full registry, "
            "default targets); drop the extra paths/--rules/--disable/"
            "--baseline",
            file=sys.stderr,
        )
        return 2
    selected = None
    if args.rules is not None or args.disable is not None:
        selected = (
            _parse_rule_list(args.rules) if args.rules is not None
            else list(RULES)
        )
        disabled = set(_parse_rule_list(args.disable or ""))
        try:
            _select_rules(selected)  # validate --rules names
            _select_rules(sorted(disabled))  # validate --disable names
        except ValueError as exc:
            print(f"jaxlint: {exc}", file=sys.stderr)
            return 2
        selected = [name for name in selected if name not in disabled]
    if args.jobs < 1:
        print(f"jaxlint: --jobs must be >= 1, got {args.jobs}",
              file=sys.stderr)
        return 2
    targets = args.paths or default_targets()
    try:
        findings = lint_paths(
            targets,
            keep_suppressed=(args.format in ("json", "sarif") or args.gate),
            rules=selected,
            jobs=args.jobs,
        )
    except PathError as exc:
        # EVERY bad path gets its own line (rc 2 covers them all): a
        # long CI target list should not reveal its problems one
        # rerun at a time. (sarif has no per-error result shape worth
        # inventing here — bad paths fall back to the human lines.)
        for path, detail in exc.errors:
            if args.format == "json":
                print(json.dumps(
                    {"error": "bad-path", "path": path, "message": detail},
                    sort_keys=True,
                ))
            else:
                print(f"jaxlint: {path}: {detail}", file=sys.stderr)
        return 2
    except FileNotFoundError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2
    if args.baseline is not None:
        bl_path = pathlib.Path(args.baseline)
        if bl_path.exists():
            try:
                data = json.loads(bl_path.read_text(encoding="utf-8"))
                known = set(data["findings"])
                covered = data.get("rules", "all")
            except (OSError, ValueError, KeyError, TypeError) as exc:
                print(
                    f"jaxlint: --baseline {args.baseline}: not a baseline "
                    f"file ({exc})",
                    file=sys.stderr,
                )
                return 2
            # Filtering composes AFTER registry subsetting, and only
            # rules the baseline actually RAN can suppress: a finding
            # from a rule outside the baseline's recorded coverage was
            # never assessed at write time, so its absence from the
            # key set means nothing (legacy baselines without a
            # "rules" key were written by full-registry runs).
            covered_set = None if covered == "all" else set(covered)
            findings = [
                f for f in findings
                if (covered_set is not None and f.rule not in covered_set)
                or baseline_key(f) not in known
            ]
        else:
            # First run: record the dirty tree and succeed. Suppressed
            # findings are already acknowledged in-source — recording
            # them too would mask the suppression comment ever being
            # removed. The registry subset in effect is recorded as
            # the baseline's coverage, so a later wider run knows
            # which rules' findings this file can legitimately mute.
            keys = sorted(
                {baseline_key(f) for f in findings if not f.suppressed}
            )
            bl_path.write_text(
                json.dumps({
                    "findings": keys,
                    "rules": "all" if selected is None else sorted(selected),
                }, indent=2) + "\n",
                encoding="utf-8",
            )
            print(
                f"jaxlint: baseline written: {len(keys)} finding key(s) "
                f"-> {args.baseline}",
                file=sys.stderr,
            )
            findings = [f for f in findings if f.suppressed]
    live = [f for f in findings if not f.suppressed]
    if args.gate:
        gate_path = pathlib.Path("jaxlint.sarif")
        gate_path.write_text(_sarif_report(findings) + "\n", encoding="utf-8")
        print(f"jaxlint: SARIF written -> {gate_path}", file=sys.stderr)
    if args.format == "json":
        for f in findings:
            print(_json_line(f))
    elif args.format == "sarif":
        print(_sarif_report(findings))
    else:
        for f in live:
            print(f.format())
    n_rules = len(RULES) if selected is None else len(selected)
    print(
        f"jaxlint: {len(live)} finding(s) over {n_rules} rule(s)",
        file=sys.stderr,
    )
    return 1 if live else 0


# Register the concurrency lock-discipline rules and the v3 abstract-
# interpretation rules (they import this module's registry, so the
# imports sit at the bottom — by now every name they need is defined;
# either import order ends with all rules registered exactly once).
from arena.analysis import concurrency as _concurrency  # noqa: E402,F401
from arena.analysis import absint as _absint  # noqa: E402,F401
from arena.analysis import lifecycle as _lifecycle  # noqa: E402,F401
from arena.analysis import effects as _effects  # noqa: E402,F401
from arena.analysis import schema as _schema  # noqa: E402,F401


if __name__ == "__main__":
    sys.exit(main())
