"""jaxlint corpus: 64-bit producers leaking into a pinned kernel.

The snapshot wire format pins every kernel input to int32/float32
(`arrays.bin` stores raw int32/float32; `pack_batch` coerces at
ingest). A bare `np.arange` mints int64, and numbers out of
`json.loads` are Python ints/floats that `np.asarray` widens to
64-bit — either silently downcast at the jit boundary (x32) or
poison the compile cache with second-dtype executables (x64).
Rule: dtype-drift-into-kernel."""

import json

import jax
import numpy as np

kernel = jax.jit(lambda idx, w: w[idx].sum())


def refit(num_players, weights):
    """Bare np.arange defaults to int64 — the wire format says int32."""
    idx = np.arange(num_players)
    return kernel(idx, weights)


def load_scores(text):
    """json numerics -> np.asarray with no dtype: a float64 array
    reaches the kernel argument the snapshot pins float32."""
    doc = json.loads(text)
    scores = np.asarray(doc["scores"])
    return kernel(np.arange(4, dtype=np.int32), scores)
