"""jaxlint corpus: an unversioned wire format grows an undeclared key.

`render_rows` is contracted to `corpus-wire@v1`, whose sidecar
(`schemas/corpus-wire.json`) declares fields {status, rows}. The
render also writes `debug_hint` — additive wire evolution is fine,
but only THROUGH the sidecar, so readers learn the field exists from
a reviewed diff instead of from production traffic.
Rule: undeclared-serialized-field.
"""


def render_rows(rows):  # schema: corpus-wire@v1
    return {
        "status": "ok",
        "rows": list(rows),
        "debug_hint": "drop me before shipping",
    }
