"""jaxlint v6 contracts: serialized-schema analysis + the replication
boundary.

Three layers, mirroring the analyzer:

- grammar + fact extraction unit tests (`parse_schema`,
  `_extract_facts`) — the shared front end every schema rule consumes;
- seeded-drift demos against MUTATED COPIES of the real writers: add
  a manifest field / reorder the array table in `arena/serving.py`
  without bumping `SNAPSHOT_VERSION` and the linter objects; bump the
  constant and it stands down. Same shape for the replication
  boundary: graft a ratings-writing helper onto `ArenaEngine` outside
  the apply closure and the linter objects. The real tree stays byte
  and finding identical — mutations live in strings here, never on
  disk;
- sidecar registry hygiene: every checked-in schema JSON is
  well-formed and self-consistent.

Several tests here are the named kill-tests for the v6 mutation-audit
entries (see tools/mutation_audit.py): the fact-extraction test kills
`schema-facts-extractor-returns-empty`, the seeded field-add test
kills `version-bump-check-inverted`, and the two-hop closure test
kills `replication-boundary-uses-one-hop-not-fixpoint`.
"""

import ast
import json
import pathlib

from arena.analysis import jaxlint, project, schema

REPO = pathlib.Path(__file__).resolve().parent.parent
SERVING = REPO / "arena" / "serving.py"
ENGINE = REPO / "arena" / "engine.py"

SCHEMA_RULES = set(schema._RULE_NAMES)


def _schema_findings(findings):
    return sorted(
        (f.rule, f.message) for f in findings if f.rule in SCHEMA_RULES
    )


# --- grammar ---------------------------------------------------------------


def test_parse_schema_grammar():
    assert project.parse_schema("schema: arena-snapshot@v1") == (
        "arena-snapshot", 1
    )
    # The clause coexists with the v5 effect-contract clauses on one
    # comment — the real annotation style in serving.py/frontdoor.py.
    assert project.parse_schema(
        "deterministic; mutates: a, b; schema: wire-envelope@v12"
    ) == ("wire-envelope", 12)
    assert project.parse_schema(
        "pure-render(view); schema: wire-player-row@v1"
    ) == ("wire-player-row", 1)
    # Malformed clauses are no contract at all, never a guess.
    for bad in (
        "schema: missing-version",
        "schema: bad@vX",
        "schema: @v1",
        "schemas: name@v1",
        "deterministic; mutates: a",
    ):
        assert project.parse_schema(bad) is None


def test_schema_clause_does_not_disturb_the_mutates_clause():
    """`mutates:` parsing stops at the `;` so appending a schema clause
    to an existing effect contract leaves the declared write set
    unchanged."""
    src = (
        "class C:\n"
        "    def apply(self, b):  # deterministic; mutates: ratings, log; schema: applied-log-record@v1\n"
        "        self.ratings = b\n"
        "        self.log = [b]\n"
    )
    ctx = jaxlint.ModuleContext("t.py", src)
    contract = ctx.symbols.contracts["C.apply"]
    assert contract["deterministic"] is True
    assert set(contract["mutates"]) == {"ratings", "log"}
    assert ctx.symbols.schemas["C.apply"] == ("applied-log-record", 1)


def test_schema_contract_attaches_to_def_class_and_method():
    src = (
        "def writer(x):  # schema: fmt-a@v1\n"
        "    return {'k': x}\n"
        "class Codec:  # schema: fmt-b@v2\n"
        "    def parse(self, raw):  # schema: fmt-c@v3\n"
        "        return raw\n"
    )
    schemas = jaxlint.ModuleContext("t.py", src).symbols.schemas
    assert schemas == {
        "writer": ("fmt-a", 1),
        "Codec": ("fmt-b", 2),
        "Codec.parse": ("fmt-c", 3),
    }


# --- fact extraction -------------------------------------------------------


def test_extract_facts_collects_produced_consumed_arrays_dtypes():
    """The front end every schema rule consumes: dict keys and tagged
    tuples are produced (with resolvable dtypes), `.get`/subscript
    loads/membership tuples/iteration tuples are consumed, and the
    `[("name", arr), ...]` table yields the array order. An extractor
    returning empty facts makes every downstream rule vacuous — this
    is the named kill for the `schema-facts-extractor-returns-empty`
    mutant."""
    src = (
        "import numpy as np\n"
        "def roundtrip(state, payload, arrs):\n"
        "    table = [\n"
        "        ('keys', arrs['keys']),\n"
        "        ('ratings', np.asarray(arrs['r'], np.float32)),\n"
        "    ]\n"
        "    out = {\n"
        "        'magic': 'X',\n"
        "        'count': np.zeros(3, dtype='int32'),\n"
        "        'arrays': table,\n"
        "    }\n"
        "    out['checksum'] = 'abc'\n"
        "    want = payload.get('version')\n"
        "    for key in ('num_rows', 'num_cols'):\n"
        "        state[key] = payload[key]\n"
        "    if 'stale' in ('stale', 'fresh'):\n"
        "        pass\n"
        "    required = {'queue_batches'}\n"
        "    tag = ('ratings', np.asarray(arrs['r'], np.float32))\n"
        "    return out, want, required, tag\n"
    )
    fn = ast.parse(src).body[1]
    facts = schema._extract_facts(fn)
    assert {"magic", "count", "arrays", "checksum", "ratings"} <= facts.produced
    # Iteration/membership tuples are reader collections, not tags...
    assert {"version", "num_rows", "num_cols", "stale", "fresh",
            "queue_batches"} <= facts.consumed
    # ...and never leak into produced.
    assert "num_rows" not in facts.produced
    assert facts.arrays == ("keys", "ratings")
    assert facts.dtypes["count"] == "int32"
    assert facts.dtypes["ratings"] == "float32"
    # Consumed subscripts: state[key] has a Name slice — no claim. But
    # payload[key] under the same loop reads the iterated keys via the
    # loop tuple, which is the claim the rule needs.


def test_extract_facts_no_claim_on_dynamic_shapes():
    src = (
        "def opaque(d, k, v):\n"
        "    d[k] = v\n"
        "    return {k: v for k in d}\n"
    )
    fn = ast.parse(src).body[0]
    facts = schema._extract_facts(fn)
    assert facts.produced == frozenset()
    assert facts.consumed == frozenset()
    assert facts.arrays == ()


# --- sidecar plumbing ------------------------------------------------------


def test_missing_sidecar_is_a_drift_finding(tmp_path):
    src = (
        "def writer(x):  # schema: nobody-recorded-this@v1\n"
        "    return {'k': x}\n"
    )
    findings = jaxlint.lint_source(src, str(tmp_path / "mod.py"))
    assert [(f.rule,) for f in findings] == [(schema.RULE_DRIFT,)]
    assert "no recorded shape" in findings[0].message


def test_local_sidecar_shadows_the_global_registry(tmp_path):
    """A `schemas/` directory next to the module wins over the global
    registry — corpus fixtures carry their own shapes."""
    (tmp_path / "schemas").mkdir()
    (tmp_path / "schemas" / "wire-envelope.json").write_text(
        json.dumps({"schema": "wire-envelope", "fields": ["totally_local"]})
    )
    src = (
        "def render(w):  # schema: wire-envelope@v1\n"
        "    return {'totally_local': w}\n"
    )
    assert jaxlint.lint_source(src, str(tmp_path / "mod.py")) == []
    # The same source against the REAL wire-envelope sidecar fires.
    real = jaxlint.lint_source(src, str(REPO / "arena" / "net" / "x.py"))
    assert [(f.rule,) for f in real] == [(schema.RULE_UNDECLARED,)]


def test_real_sidecars_are_well_formed():
    """Registry hygiene: every checked-in sidecar parses, names itself
    after its file, declares unique string fields, and versioned ones
    carry an int version plus the module constant to bump."""
    paths = sorted(schema.SCHEMAS_DIR.glob("*.json"))
    assert len(paths) >= 18
    for path in paths:
        record = json.loads(path.read_text())
        if path.stem == "replication-boundary":
            for cls, entry in record["exempt"].items():
                assert entry["attrs"] and entry["why"], cls
            continue
        assert record["schema"] == path.stem
        fields = record["fields"]
        assert isinstance(fields, list)
        assert all(isinstance(f, str) for f in fields)
        assert len(set(fields)) == len(fields)
        if "version_constant" in record:
            assert isinstance(record["version"], int)
            assert isinstance(record["version_constant"], str)
        for key in record.get("dtypes", {}):
            assert key in fields or key in record.get("arrays", ())


# --- seeded drift against the real snapshot writer -------------------------


def _lint_serving(src):
    return _schema_findings(jaxlint.lint_source(src, str(SERVING)))


def test_pristine_serving_has_no_schema_findings():
    assert _lint_serving(SERVING.read_text()) == []


def test_seeded_manifest_field_add_without_bump_is_flagged():
    """Add one field to the snapshot manifest without touching
    SNAPSHOT_VERSION: the drift rule objects and names the field. This
    is the named kill for the `version-bump-check-inverted` mutant —
    under `>=`, v1 == v1 would count as bumped and this seeded drift
    would sail through."""
    src = SERVING.read_text().replace(
        '"bin_bytes": len(blob),',
        '"bin_bytes": len(blob),\n        "spare_field": 0,',
    )
    assert src != SERVING.read_text()
    found = _lint_serving(src)
    assert [rule for rule, _msg in found] == [schema.RULE_DRIFT]
    assert "spare_field" in found[0][1]
    assert "SNAPSHOT_VERSION" in found[0][1]


def test_seeded_array_reorder_without_bump_is_flagged():
    """Swap two entries of the arrays.bin table: offsets shift, every
    deployed reader slices garbage — flagged without a bump."""
    src = SERVING.read_text().replace(
        '        ("winners", winners_arr),\n'
        '        ("losers", losers_arr),',
        '        ("losers", losers_arr),\n'
        '        ("winners", winners_arr),',
    )
    assert src != SERVING.read_text()
    found = _lint_serving(src)
    assert [rule for rule, _msg in found] == [schema.RULE_DRIFT]
    assert "array order" in found[0][1]


def test_version_bump_suppresses_schema_drift():
    """The sanctioned evolution path: the same seeded field-add WITH
    `SNAPSHOT_VERSION` bumped past the recorded version lints clean —
    the rule polices silent drift, not evolution."""
    src = SERVING.read_text().replace(
        '"bin_bytes": len(blob),',
        '"bin_bytes": len(blob),\n        "spare_field": 0,',
    ).replace("SNAPSHOT_VERSION = 3", "SNAPSHOT_VERSION = 4")
    assert _lint_serving(src) == []


def test_seeded_dtype_change_without_bump_is_flagged():
    """Serialize ratings as float64 while the sidecar records float32:
    readers allocate and slice the wrong width — flagged."""
    src = SERVING.read_text().replace(
        '("ratings", np.asarray(ratings, np.float32)),',
        '("ratings", np.asarray(ratings, np.float64)),',
    )
    assert src != SERVING.read_text()
    found = _lint_serving(src)
    assert [rule for rule, _msg in found] == [schema.RULE_DRIFT]
    assert "float32 -> float64" in found[0][1]


# --- the replication boundary ----------------------------------------------


def test_pristine_engine_has_no_schema_findings():
    assert _schema_findings(
        jaxlint.lint_source(ENGINE.read_text(), str(ENGINE))
    ) == []


def test_seeded_out_of_closure_ratings_write_is_flagged():
    """Graft a helper onto ArenaEngine that rescales `self.ratings` in
    place, reachable from no `# deterministic` apply root: a replica
    replaying the match log never runs it — flagged, naming the
    attribute."""
    src = ENGINE.read_text() + (
        "\n"
        "    def sneaky_refit(self, scale):\n"
        "        self.ratings = self.ratings * scale\n"
    )
    found = _schema_findings(jaxlint.lint_source(src, str(ENGINE)))
    assert [rule for rule, _msg in found] == [schema.RULE_BOUNDARY]
    assert "sneaky_refit" in found[0][1]
    assert "ratings" in found[0][1]


def test_two_hop_closure_is_inside_the_boundary(tmp_path):
    """The closure is computed to a FIXPOINT over resolved call edges:
    apply -> _stage -> _commit, where only the two-hop callee writes
    the declared state. Clean — the write replays. This is the named
    kill for the `replication-boundary-uses-one-hop-not-fixpoint`
    mutant, which stops after the roots' direct callees and would flag
    `_commit` as outside the boundary."""
    src = (
        "class Replica:\n"
        "    def __init__(self):\n"
        "        self.ratings = {}\n"
        "        self.applied = 0\n"
        "    def apply(self, batch):  # deterministic; mutates: ratings, applied\n"
        "        for rec in batch:\n"
        "            self._stage(rec)\n"
        "    def _stage(self, rec):\n"
        "        self._commit(rec[0], rec[1])\n"
        "    def _commit(self, player, delta):\n"
        "        self.ratings[player] = self.ratings.get(player, 0.0) + delta\n"
        "        self.applied += 1\n"
    )
    assert jaxlint.lint_source(src, str(tmp_path / "mod.py")) == []


def test_replication_exemption_sidecar_is_honored(tmp_path):
    """An admission-path attribute exempted (with a reason) in the
    class's replication-boundary sidecar stops protecting — the
    FrontDoor intake-buffer pattern. Without the exemption the same
    source is flagged."""
    src = (
        "class Door:\n"
        "    def __init__(self):\n"
        "        self.buffer = []\n"
        "        self.applied = 0\n"
        "    def apply(self, batch):  # deterministic; mutates: applied, buffer\n"
        "        self.applied += 1\n"
        "        self.buffer = self.buffer[1:]\n"
        "    def admit(self, rec):\n"
        "        self.buffer.append(rec)\n"
    )
    flagged = jaxlint.lint_source(src, str(tmp_path / "mod.py"))
    assert [f.rule for f in flagged] == [schema.RULE_BOUNDARY]
    (tmp_path / "schemas").mkdir()
    (tmp_path / "schemas" / "replication-boundary.json").write_text(
        json.dumps({"exempt": {"Door": {
            "attrs": ["buffer"],
            "why": "intake staging; drained by the apply path",
        }}})
    )
    assert jaxlint.lint_source(src, str(tmp_path / "mod.py")) == []


def test_lifecycle_and_protocol_methods_are_exempt(tmp_path):
    """__init__ seeds replicated state (replay lands ON it) and v4
    `# protocol:` teardown methods run outside replay by design —
    neither is a boundary violation."""
    src = (
        "class Replica:  # protocol: close\n"
        "    def __init__(self):\n"
        "        self.ratings = {}\n"
        "    def apply(self, batch):  # deterministic; mutates: ratings\n"
        "        for player, delta in batch:\n"
        "            self.ratings[player] = delta\n"
        "    def close(self):\n"
        "        self.ratings = {}\n"
    )
    assert jaxlint.lint_source(src, str(tmp_path / "mod.py")) == []


# --- reader/writer + unversioned wire fixtures -----------------------------


def test_undeclared_field_and_mismatch_fixtures(tmp_path):
    (tmp_path / "schemas").mkdir()
    (tmp_path / "schemas" / "tiny-wire.json").write_text(
        json.dumps({"schema": "tiny-wire", "fields": ["status", "rows"]})
    )
    writer = (
        "def render(rows):  # schema: tiny-wire@v1\n"
        "    return {'status': 'ok', 'rows': rows, 'extra': 1}\n"
    )
    found = jaxlint.lint_source(writer, str(tmp_path / "w.py"))
    assert [f.rule for f in found] == [schema.RULE_UNDECLARED]
    assert "extra" in found[0].message
    reader = (
        "def parse(payload):  # schema: tiny-wire@v1\n"
        "    return payload['rows'], payload.get('row_count')\n"
    )
    found = jaxlint.lint_source(reader, str(tmp_path / "r.py"))
    assert [f.rule for f in found] == [schema.RULE_MISMATCH]
    assert "row_count" in found[0].message
    # Touching a strict subset of declared fields is fine: facts are
    # one-sided, a reader is never required to consume everything.
    subset = (
        "def peek(payload):  # schema: tiny-wire@v1\n"
        "    return payload.get('status')\n"
    )
    assert jaxlint.lint_source(subset, str(tmp_path / "s.py")) == []
