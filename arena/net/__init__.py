"""arena.net — the network serving tier (ROADMAP item 1).

Three parts, layered over the existing serving and pipeline stack:

- `arena.net.protocol`  — the wire protocol: route parsing, the
  response envelope (staleness watermark + request trace id in every
  JSON response), submit-body validation, and `WireClient`, the
  stdlib persistent-connection consumer half.
- `arena.net.frontdoor` — the multi-producer front door: global
  sequence numbers assigned at admission, a reorder-buffer merge that
  applies strictly in sequence order (async==sync bit-exact under N
  writers), and bounded-degradation load shedding (oldest batches
  coalesce into a summary update; the summary's backlog is staleness-
  bounded, trimming beyond it is counted, never silent).
- `arena.net.server`    — the HTTP/JSON server (`ThreadingHTTPServer`,
  stdlib only): /leaderboard, /player/{id}, /h2h, /submit, /stats
  (Prometheus render()), /healthz.

What this tier deliberately defers (ROADMAP item 2): replica catch-up
— a read-only `ArenaHTTPServer(frontdoor=None)` already serves 503 on
/submit, but keeping it fresh needs incremental snapshots + log
shipping, not a wire-layer feature.
"""

from arena.net.frontdoor import (
    DEFAULT_CAPACITY,
    DEFAULT_MAX_STALENESS_MATCHES,
    POLICY_COALESCE,
    POLICY_STALENESS,
    SUMMARY_PRODUCER,
    FrontDoor,
    FrontDoorError,
)
from arena.net.protocol import (
    ENDPOINTS,
    ProtocolError,
    WireClient,
    make_response,
    parse_path,
    parse_submit_body,
)
from arena.net.server import ArenaHTTPServer

__all__ = [
    "ArenaHTTPServer",
    "DEFAULT_CAPACITY",
    "DEFAULT_MAX_STALENESS_MATCHES",
    "ENDPOINTS",
    "FrontDoor",
    "FrontDoorError",
    "POLICY_COALESCE",
    "POLICY_STALENESS",
    "ProtocolError",
    "SUMMARY_PRODUCER",
    "WireClient",
    "make_response",
    "parse_path",
    "parse_submit_body",
]
