"""Serving layer: durable snapshot/restore, batched queries, bounded staleness.

PR 3/4 made ingest unbounded, but ratings were queryable only
in-process (`ArenaEngine.leaderboard`) and a process restart lost
everything: the mergeable CSR runs, the match log, any queued
batches. This module is the serving surface the ROADMAP's north star
needs — the engine behind arena traffic:

1. **Durable snapshot/restore.** `ArenaServer.snapshot(path)` spills
   the whole engine — the `MergeableCSR` main runs AND delta tail
   (run boundaries preserved, so restore never re-sorts), the raw
   match log, the ratings vector, and (with `spill=True`) the
   still-raw pipeline queue — to a versioned on-disk format: one
   `arrays.bin` (8-byte magic + little-endian uint32 version header,
   then each array written raw) plus a `manifest.json` carrying the
   counts, the array table (name/dtype/offset/length), and a sha256
   checksum of the binary. `restore(path)` validates EVERYTHING
   before touching live state — magic, version, checksum, byte
   length, array table bounds, count cross-checks — and raises the
   distinct `SnapshotError` naming expected vs found on any mismatch,
   with the serving engine untouched (the same reject posture as
   `engine.pack_batch` validation). A valid snapshot is rebuilt into
   a FRESH engine (`MergeableCSR.from_state`, `ArenaEngine.adopt_state`)
   and swapped in whole, then any spilled queue batches are
   resubmitted in FIFO order — the restarted server resumes
   mid-stream, bit-exact to the uninterrupted one (property-tested).

2. **Batched queries from immutable views.** `ArenaServer.query()`
   answers leaderboard pages, per-player ratings (with bootstrap
   (lo, hi) intervals when computed), and head-to-head win
   probabilities — every part of one call from ONE `ServingView`, an
   immutable host-side snapshot built from the engine's atomic
   `(ratings copy, watermark)` pair plus `MergeableCSR.clone()` under
   its existing lock. Reads never block the ingest path: queries hit
   the prebuilt view; only a refresh takes the short locks.

3. **Staleness-bounded reads.** Each view carries the applied-match
   watermark it was built at. A query whose staleness (matches
   ingested since the view's watermark) exceeds
   `max_staleness_matches` triggers a view refresh first; the
   response reports `watermark`, `staleness`, and `stale` (True only
   when the bound could not be met — e.g. an async pipeline deeper
   than the bound, or a restore in progress, during which queries are
   served from the last complete view rather than blocking).

Production-mode sanitizers ride along by default: a count-mode
`RecompileSentinel` over the engine's update cache and a sampled
count-mode `donation_guard` around the donating update — violations
land in `stats()` as counters, never as a crashed request (test
posture elsewhere is unchanged; see `arena.analysis.sanitize`).

Everything here is host-side NumPy + stdlib IO; jnp appears only at
the `adopt_state` device boundary (the jaxlint host-path discipline).
"""

import hashlib
import json
import math
import os
import pathlib
import threading
import time

import numpy as np

from arena import ratings as R
from arena.analysis import sanitize
from arena.engine import ArenaEngine
from arena.ingest import MergeableCSR
from arena.obs import Observability

SNAPSHOT_MAGIC = b"ARENASNP"
# v2 (PR 18): incremental snapshots. A snapshot is now either
# kind="full" (the v1 shape, every array materialized) or
# kind="incremental" (cut against a named base): the match log ships
# only the rows past the base's watermark (`delta_winners` /
# `delta_losers`), the immutable compacted runs (`keys`/`pos`) are
# SKIPPED entirely when the store's compaction count is unchanged
# since the base (the LSM unlock: main runs are rewritten only by a
# compaction), and the manifest carries a chain link — the base's
# checksum, watermark, and compaction count — that restore validates
# hop by hop back to a full snapshot.
# v3 (PR 19): multi-tenant arenas. The manifest carries the tenant
# geometry (`num_tenants`, `players_per_tenant` — `num_players` stays
# the COMPOSITE bound, so every v2 size/count invariant reads
# unchanged) and the arrays gain a per-tenant match-count column
# (`tenant_counts`); restore rebuilds a `MultiTenantEngine` whenever
# the manifest says more than one tenant rode the stream.
SNAPSHOT_VERSION = 3
MANIFEST_NAME = "manifest.json"
ARRAYS_NAME = "arrays.bin"
_HEADER_BYTES = len(SNAPSHOT_MAGIC) + 4  # magic + uint32 version

# Longest base chain restore will walk before declaring a cycle/runaway
# (each hop is one manifest+arrays read; operational bound, not RAM).
MAX_CHAIN_DEPTH = 1024

# Raw-array dtypes a snapshot may carry. int32 everywhere except the
# ratings vector; anything else in a manifest is a corrupt/foreign file.
_DTYPES = {"int32": np.int32, "float32": np.float32}

# Default staleness bound: refresh the view once this many matches have
# been ingested past its watermark. A view rebuild clones the match
# store (O(history)), so serving wants it per-batch-of-traffic, not
# per-query; 0 means "always serve fresh" (rebuild whenever anything
# new applied), which tests use.
DEFAULT_MAX_STALENESS_MATCHES = 10_000


class SnapshotError(RuntimeError):
    """A snapshot failed validation: wrong magic/version, truncated or
    corrupt data, or internally inconsistent counts. Restore raises
    this BEFORE touching any live engine state — a reject never
    leaves a half-restored server."""


def _array_entry(name, arr, offset):  # schema: arena-snapshot@v3
    return {
        "name": name,
        "dtype": arr.dtype.name,
        "length": int(arr.size),
        "offset": offset,
    }


def _check_base_compatible(base_manifest, *, num_players, k, scale, base,
                           min_bucket, store_state, num_tenants=1,
                           players_per_tenant=None):  # schema: incremental-manifest@v2
    """An increment may only be cut against a base describing the SAME
    arena (players, rating hyperparameters, store tuning) at an
    earlier-or-equal point of the SAME stream. Raises SnapshotError —
    the write-side reject — before any bytes hit disk."""
    pairs = (
        ("num_players", num_players),
        ("k", k),
        ("scale", scale),
        ("base", base),
        ("min_bucket", min_bucket),
        ("compact_threshold", int(store_state["compact_threshold"])),
        ("size_ratio", int(store_state["size_ratio"])),
        # Tenant geometry (v3): a base with a different per-tenant
        # roster size would silently re-slice every composite id —
        # same-arena means same geometry. (`num_players` above already
        # pins the tenant BUCKET; the tenant COUNT may grow within it
        # between base and increment, checked below.)
        ("players_per_tenant",
         num_players if players_per_tenant is None else players_per_tenant),
    )
    for field, ours in pairs:
        theirs = base_manifest.get(field)
        if theirs != ours:
            raise SnapshotError(
                f"incremental base mismatch on {field!r}: base snapshot "
                f"has {theirs!r}, live state has {ours!r}"
            )
    if int(base_manifest.get("num_tenants", 1)) > num_tenants:
        raise SnapshotError(
            f"incremental base serves {base_manifest.get('num_tenants')} "
            f"tenants, live state only {num_tenants} — tenants never "
            "shrink on one stream"
        )
    base_n = int(base_manifest.get("num_matches"))
    if base_n > int(store_state["num_matches"]):
        raise SnapshotError(
            f"incremental base is AHEAD of the live state: base holds "
            f"{base_n} matches, live state {int(store_state['num_matches'])}"
        )
    if int(base_manifest.get("compactions")) > int(store_state["compactions"]):
        raise SnapshotError(
            f"incremental base counts {int(base_manifest.get('compactions'))} "
            f"compactions, live state only {int(store_state['compactions'])} "
            "— not the same stream"
        )


def write_snapshot(path, *, num_players, k, scale, base, min_bucket,
                   store_state, ratings, queue, base_manifest=None,
                   base_ref=None, num_tenants=1,
                   players_per_tenant=None):  # deterministic; schema: arena-snapshot@v3
    """Write one snapshot directory: arrays.bin + manifest.json.

    `store_state` is `MergeableCSR.export_state()` output; `ratings` a
    (num_players,) float32 copy consistent with it (every stored match
    applied); `queue` a list of raw `(winners, losers)` int32 batch
    pairs spilled from the pipeline (empty for a drained snapshot).
    The binary is written first and the manifest last (atomic rename),
    so a torn write leaves no manifest — and a manifest always
    describes complete bytes.

    With `base_manifest` (+ `base_ref`, the path of that base RELATIVE
    to this snapshot's directory, recorded verbatim in the manifest)
    the snapshot is cut INCREMENTALLY: the match log carries only the
    rows past the base's watermark, and the compacted main runs are
    skipped entirely when no compaction has happened since the base.
    The manifest's counts (`num_matches`, …) always describe the FULL
    assembled state, so an increment's manifest reads like the full
    snapshot it reconstructs to.
    """
    path = pathlib.Path(path)
    if players_per_tenant is None:
        players_per_tenant = num_players
    # Per-tenant match counts over the FULL stored log (full-state
    # semantics even in an increment, like every other manifest count):
    # the tenant column replicas and ops dashboards read without
    # re-deriving composite ids.
    tenant_counts = np.bincount(
        np.asarray(store_state["winners"], np.int64) // players_per_tenant,
        minlength=num_tenants,
    ).astype(np.int32)
    # A multi-tenant engine hands ratings in as (tenant_bucket, P); the
    # serialized layout is always the flat composite vector.
    ratings = np.ascontiguousarray(np.asarray(ratings).reshape(-1))
    queue_lengths = np.array([int(w.shape[0]) for w, _l in queue], np.int32)
    queue_w = (
        np.concatenate([w for w, _l in queue]).astype(np.int32)
        if queue else np.empty(0, np.int32)
    )
    queue_l = (
        np.concatenate([l for _w, l in queue]).astype(np.int32)
        if queue else np.empty(0, np.int32)
    )
    empty = np.empty(0, np.int32)
    if base_manifest is not None:
        if not base_ref or not isinstance(base_ref, str):
            raise SnapshotError(
                f"incremental snapshot needs a base_ref path, got {base_ref!r}"
            )
        _check_base_compatible(
            base_manifest, num_players=num_players, k=k, scale=scale,
            base=base, min_bucket=min_bucket, store_state=store_state,
            num_tenants=num_tenants, players_per_tenant=players_per_tenant,
        )
        base_n = int(base_manifest["num_matches"])
        reuses_base_runs = (
            int(store_state["compactions"]) == int(base_manifest["compactions"])
        )
        kind = "incremental"
        keys_arr = empty if reuses_base_runs else store_state["keys"]
        pos_arr = empty if reuses_base_runs else store_state["pos"]
        winners_arr, losers_arr = empty, empty
        delta_w = np.ascontiguousarray(store_state["winners"][base_n:])
        delta_l = np.ascontiguousarray(store_state["losers"][base_n:])
        chain_depth = int(base_manifest.get("chain_depth", 0)) + 1
        base_checksum = base_manifest["checksum_sha256"]
        base_compactions = int(base_manifest["compactions"])
    else:
        kind = "full"
        base_n = 0
        reuses_base_runs = False
        keys_arr, pos_arr = store_state["keys"], store_state["pos"]
        winners_arr, losers_arr = store_state["winners"], store_state["losers"]
        delta_w, delta_l = empty, empty
        chain_depth = 0
        base_ref = None
        base_checksum = None
        base_compactions = 0
    # Directory creation waits until the base checks above pass: a
    # rejected increment leaves NOTHING on disk, not even an empty dir.
    path.mkdir(parents=True, exist_ok=True)
    arrays = [
        ("keys", keys_arr),
        ("pos", pos_arr),
        ("tail_keys", store_state["tail_keys"]),
        ("tail_pos", store_state["tail_pos"]),
        ("tail_run_lengths", store_state["tail_run_lengths"]),
        ("winners", winners_arr),
        ("losers", losers_arr),
        ("delta_winners", delta_w),
        ("delta_losers", delta_l),
        ("ratings", np.asarray(ratings, np.float32)),
        ("tenant_counts", tenant_counts),
        ("queue_lengths", queue_lengths),
        ("queue_winners", queue_w),
        ("queue_losers", queue_l),
    ]
    table = []
    blob = bytearray(SNAPSHOT_MAGIC)
    blob += int(SNAPSHOT_VERSION).to_bytes(4, "little")
    for name, arr in arrays:
        table.append(_array_entry(name, arr, len(blob)))
        blob += arr.tobytes()
    blob = bytes(blob)
    bin_tmp = path / (ARRAYS_NAME + ".tmp")
    bin_tmp.write_bytes(blob)
    bin_tmp.rename(path / ARRAYS_NAME)
    manifest = {
        "magic": SNAPSHOT_MAGIC.decode("ascii"),
        "version": SNAPSHOT_VERSION,
        "kind": kind,
        "num_players": num_players,
        "num_tenants": int(num_tenants),
        "players_per_tenant": int(players_per_tenant),
        "num_matches": int(store_state["num_matches"]),
        "compactions": int(store_state["compactions"]),
        "compact_threshold": int(store_state["compact_threshold"]),
        "size_ratio": int(store_state["size_ratio"]),
        "k": k,
        "scale": scale,
        "base": base,
        "min_bucket": min_bucket,
        "queue_batches": int(queue_lengths.size),
        "queue_matches": int(queue_lengths.sum()),
        "base_snapshot": base_ref,
        "base_checksum_sha256": base_checksum,
        "base_num_matches": base_n,
        "base_compactions": base_compactions,
        "delta_matches": int(delta_w.size),
        "reuses_base_runs": reuses_base_runs,
        "chain_depth": chain_depth,
        "bin_bytes": len(blob),
        "checksum_sha256": hashlib.sha256(blob).hexdigest(),
        "arrays": table,
    }
    man_tmp = path / (MANIFEST_NAME + ".tmp")
    man_tmp.write_text(json.dumps(manifest, indent=1))
    man_tmp.rename(path / MANIFEST_NAME)
    return manifest


def _read_manifest(path):  # deterministic; schema: arena-snapshot@v3
    """Load and gate one snapshot manifest (magic + version only —
    the cheap checks that do not need the array bytes). Cutting an
    increment reads its base through here without paying for the
    base's arrays; `read_snapshot` layers the full validation on
    top."""
    path = pathlib.Path(path)
    man_path = path / MANIFEST_NAME
    try:
        manifest = json.loads(man_path.read_text())
    except FileNotFoundError:
        raise SnapshotError(f"no snapshot manifest at {man_path}") from None
    except (OSError, ValueError) as exc:
        raise SnapshotError(f"unreadable snapshot manifest {man_path}: {exc}") from exc
    if manifest.get("magic") != SNAPSHOT_MAGIC.decode("ascii"):
        raise SnapshotError(
            f"bad snapshot magic: expected {SNAPSHOT_MAGIC.decode('ascii')!r}, "
            f"found {manifest.get('magic')!r}"
        )
    found_version = manifest.get("version")
    if found_version != SNAPSHOT_VERSION:
        raise SnapshotError(
            f"unsupported snapshot version: expected {SNAPSHOT_VERSION}, "
            f"found {found_version}"
        )
    return manifest


def read_snapshot(path):  # deterministic; schema: arena-snapshot@v3
    """Validate and load one snapshot directory.

    Returns `(manifest, arrays)` with every array materialized as an
    independent ndarray. Raises `SnapshotError` — naming expected vs
    found — on a missing piece, a foreign magic, a version this loader
    does not speak, a checksum/byte-length mismatch (truncation or
    corruption), an array table pointing outside the bytes, or counts
    that disagree with the arrays. Loading mutates nothing: callers
    install the result only after this returns.

    An incremental snapshot validates as ONE LINK: its own bytes,
    checksum, and delta counts. Use `read_snapshot_chain` to resolve
    it against its base chain into full assembled state.
    """
    path = pathlib.Path(path)
    bin_path = path / ARRAYS_NAME
    manifest = _read_manifest(path)
    try:
        blob = bin_path.read_bytes()
    except FileNotFoundError:
        raise SnapshotError(f"no snapshot arrays at {bin_path}") from None
    except OSError as exc:
        raise SnapshotError(f"unreadable snapshot arrays {bin_path}: {exc}") from exc
    if blob[: len(SNAPSHOT_MAGIC)] != SNAPSHOT_MAGIC:
        raise SnapshotError(
            f"bad arrays header magic: expected {SNAPSHOT_MAGIC!r}, "
            f"found {blob[:len(SNAPSHOT_MAGIC)]!r}"
        )
    bin_version = int.from_bytes(
        blob[len(SNAPSHOT_MAGIC): _HEADER_BYTES], "little"
    )
    if bin_version != SNAPSHOT_VERSION:
        raise SnapshotError(
            f"unsupported arrays header version: expected {SNAPSHOT_VERSION}, "
            f"found {bin_version}"
        )
    if len(blob) != manifest.get("bin_bytes"):
        raise SnapshotError(
            f"truncated snapshot arrays: manifest promises "
            f"{manifest.get('bin_bytes')} bytes, found {len(blob)}"
        )
    digest = hashlib.sha256(blob).hexdigest()
    if digest != manifest.get("checksum_sha256"):
        raise SnapshotError(
            f"snapshot checksum mismatch: manifest expects "
            f"{manifest.get('checksum_sha256')}, arrays hash to {digest}"
        )
    for field in (
        "num_players", "num_tenants", "players_per_tenant", "num_matches",
        "compactions", "compact_threshold",
        "size_ratio", "queue_batches", "queue_matches", "base_num_matches",
        "base_compactions", "delta_matches", "chain_depth",
    ):
        value = manifest.get(field)
        if not isinstance(value, int) or isinstance(value, bool) or value < 0:
            raise SnapshotError(
                f"manifest field {field!r} must be a non-negative int, "
                f"found {value!r}"
            )
    for field in ("k", "scale", "base", "min_bucket"):
        value = manifest.get(field)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise SnapshotError(
                f"manifest field {field!r} must be numeric, found {value!r}"
            )
    kind = manifest.get("kind")
    if kind not in ("full", "incremental"):
        raise SnapshotError(
            f"manifest field 'kind' must be 'full' or 'incremental', "
            f"found {kind!r}"
        )
    if kind == "incremental":
        if not isinstance(manifest.get("base_snapshot"), str) or not manifest.get("base_snapshot"):
            raise SnapshotError(
                f"incremental manifest needs a 'base_snapshot' path, "
                f"found {manifest.get('base_snapshot')!r}"
            )
        if not isinstance(manifest.get("base_checksum_sha256"), str):
            raise SnapshotError(
                f"incremental manifest needs a 'base_checksum_sha256', "
                f"found {manifest.get('base_checksum_sha256')!r}"
            )
        if manifest.get("chain_depth") < 1:
            raise SnapshotError(
                "incremental manifest must sit at chain_depth >= 1, "
                f"found {manifest.get('chain_depth')!r}"
            )
    elif manifest.get("base_snapshot") is not None:
        raise SnapshotError(
            f"full snapshot must not name a base, found "
            f"{manifest.get('base_snapshot')!r}"
        )
    arrays = {}
    for entry in manifest.get("arrays", []):
        try:
            name = entry["name"]
            dtype = _DTYPES.get(entry["dtype"])
            start = int(entry["offset"])
            length = int(entry["length"])
        except (KeyError, TypeError, ValueError) as exc:
            raise SnapshotError(
                f"malformed snapshot array table entry {entry!r}: {exc}"
            ) from exc
        if dtype is None:
            raise SnapshotError(
                f"array {name!r} has unsupported dtype "
                f"{entry['dtype']!r} (expected one of {sorted(_DTYPES)})"
            )
        nbytes = length * np.dtype(dtype).itemsize
        if start < _HEADER_BYTES or length < 0 or start + nbytes > len(blob):
            raise SnapshotError(
                f"array {name!r} spans bytes "
                f"[{start}, {start + nbytes}) outside the {len(blob)}-byte blob"
            )
        arrays[name] = np.frombuffer(
            blob, dtype, count=length, offset=start
        ).copy()
    required = {
        "keys", "pos", "tail_keys", "tail_pos", "tail_run_lengths",
        "winners", "losers", "delta_winners", "delta_losers", "ratings",
        "tenant_counts", "queue_lengths", "queue_winners", "queue_losers",
    }
    missing = required - set(arrays)
    if missing:
        raise SnapshotError(f"snapshot is missing arrays: {sorted(missing)}")
    n = manifest.get("num_matches")
    if kind == "incremental":
        d = manifest.get("delta_matches")
        if arrays["delta_winners"].size != d or arrays["delta_losers"].size != d:
            raise SnapshotError(
                f"incremental match-log delta holds "
                f"{arrays['delta_winners'].size}/"
                f"{arrays['delta_losers'].size} matches, manifest promises {d}"
            )
        if manifest.get("base_num_matches") + d != n:
            raise SnapshotError(
                f"incremental counts disagree: base {manifest.get('base_num_matches')} "
                f"+ delta {d} != total {n}"
            )
        if arrays["winners"].size or arrays["losers"].size:
            raise SnapshotError(
                "incremental snapshot must ship the match log as deltas "
                f"only, found {arrays['winners'].size} full rows"
            )
        if manifest.get("reuses_base_runs") and (
            arrays["keys"].size or arrays["pos"].size
        ):
            raise SnapshotError(
                "increment claims to reuse the base's compacted runs but "
                f"ships {arrays['keys'].size} run entries of its own"
            )
    elif arrays["winners"].size != n or arrays["losers"].size != n:
        raise SnapshotError(
            f"match log holds {arrays['winners'].size}/"
            f"{arrays['losers'].size} matches, manifest promises {n}"
        )
    if arrays["ratings"].size != manifest.get("num_players"):
        raise SnapshotError(
            f"ratings vector holds {arrays['ratings'].size} players, "
            f"manifest promises {manifest.get('num_players')}"
        )
    nt = manifest.get("num_tenants")
    ppt = manifest.get("players_per_tenant")
    if (nt < 1 or ppt < 1 or nt * ppt > manifest.get("num_players")
            or manifest.get("num_players") % ppt):
        raise SnapshotError(
            f"tenant geometry {nt} tenants x {ppt} players does not fit "
            f"the {manifest.get('num_players')}-player composite space"
        )
    if arrays["tenant_counts"].size != nt:
        raise SnapshotError(
            f"tenant_counts holds {arrays['tenant_counts'].size} tenants, "
            f"manifest promises {nt}"
        )
    if int(arrays["tenant_counts"].sum()) != n:
        raise SnapshotError(
            f"tenant_counts sums to {int(arrays['tenant_counts'].sum())} "
            f"matches, manifest promises {n}"
        )
    qm = manifest.get("queue_matches")
    if (
        int(arrays["queue_lengths"].sum()) != qm
        or arrays["queue_winners"].size != qm
        or arrays["queue_losers"].size != qm
    ):
        raise SnapshotError(
            f"spilled queue arrays hold {arrays['queue_winners'].size}/"
            f"{arrays['queue_losers'].size} matches in "
            f"{arrays['queue_lengths'].size} batches summing "
            f"{int(arrays['queue_lengths'].sum())}, manifest promises {qm}"
        )
    return manifest, arrays


def _validate_chain_link(child, base_manifest, base_dir):  # deterministic; schema: incremental-manifest@v2
    """Chain integrity: an increment must resolve against EXACTLY the
    base it was cut from. The link is pinned three ways — the base's
    arrays checksum (content identity), its watermark, and its
    compaction count — so swapping a self-consistent but different
    snapshot into the base slot is a reject, not a silently forked
    replica."""
    if base_manifest.get("checksum_sha256") != child.get("base_checksum_sha256"):
        raise SnapshotError(
            f"snapshot chain broken at {base_dir}: increment was cut "
            f"against base arrays {child.get('base_checksum_sha256')}, "
            f"base holds {base_manifest.get('checksum_sha256')}"
        )
    if int(base_manifest.get("num_matches")) != int(child.get("base_num_matches")):
        raise SnapshotError(
            f"snapshot chain broken at {base_dir}: increment expects the "
            f"base at watermark {child.get('base_num_matches')}, base "
            f"holds {base_manifest.get('num_matches')} matches"
        )
    if int(base_manifest.get("compactions")) != int(child.get("base_compactions")):
        raise SnapshotError(
            f"snapshot chain broken at {base_dir}: increment expects "
            f"{child.get('base_compactions')} compactions at the base, "
            f"base counts {base_manifest.get('compactions')}"
        )
    if int(child.get("chain_depth")) != int(base_manifest.get("chain_depth")) + 1:
        raise SnapshotError(
            f"snapshot chain broken at {base_dir}: increment sits at "
            f"chain_depth {child.get('chain_depth')} over a base at "
            f"depth {base_manifest.get('chain_depth')}"
        )


def read_snapshot_chain(path):  # deterministic; schema: arena-snapshot@v3
    """Resolve a snapshot — full or the head of an incremental chain —
    into fully materialized state.

    Walks `base_snapshot` links (each relative to the directory that
    names it) back to a full snapshot, validating every directory with
    `read_snapshot` and every LINK with `_validate_chain_link`, then
    assembles oldest-first: the match log is the base's rows plus each
    increment's delta rows in chain order; the compacted runs come
    from the NEWEST link that shipped them; the delta tail, ratings,
    and spilled queue come from the head (they describe final state).
    Returns `(head_manifest, arrays)` in exactly `read_snapshot`'s
    full-snapshot shape — restore cannot tell the difference, which is
    the crash-restart property test's bit-exactness claim.
    """
    head_dir = pathlib.Path(path)
    head_manifest, head_arrays = read_snapshot(head_dir)
    links = [(head_manifest, head_arrays, head_dir)]
    seen = {head_dir.resolve()}
    manifest, cur = head_manifest, head_dir
    while manifest.get("kind") == "incremental":
        if len(links) > MAX_CHAIN_DEPTH:
            raise SnapshotError(
                f"snapshot chain exceeds {MAX_CHAIN_DEPTH} links at {cur}"
            )
        base_dir = cur / manifest["base_snapshot"]
        resolved = base_dir.resolve()
        if resolved in seen:
            raise SnapshotError(f"snapshot chain cycles through {base_dir}")
        seen.add(resolved)
        base_manifest, base_arrays = read_snapshot(base_dir)
        _validate_chain_link(manifest, base_manifest, base_dir)
        links.append((base_manifest, base_arrays, base_dir))
        manifest, cur = base_manifest, base_dir
    links.reverse()  # oldest (the full base) first
    merged = dict(links[0][1])
    for link_manifest, link_arrays, _dir in links[1:]:
        merged["winners"] = np.concatenate(
            [merged["winners"], link_arrays["delta_winners"]]
        )
        merged["losers"] = np.concatenate(
            [merged["losers"], link_arrays["delta_losers"]]
        )
        if not link_manifest.get("reuses_base_runs"):
            merged["keys"] = link_arrays["keys"]
            merged["pos"] = link_arrays["pos"]
        merged["tail_keys"] = link_arrays["tail_keys"]
        merged["tail_pos"] = link_arrays["tail_pos"]
        merged["tail_run_lengths"] = link_arrays["tail_run_lengths"]
        merged["ratings"] = link_arrays["ratings"]
        merged["tenant_counts"] = link_arrays["tenant_counts"]
        merged["queue_lengths"] = link_arrays["queue_lengths"]
        merged["queue_winners"] = link_arrays["queue_winners"]
        merged["queue_losers"] = link_arrays["queue_losers"]
    merged["delta_winners"] = np.empty(0, np.int32)
    merged["delta_losers"] = np.empty(0, np.int32)
    n = head_manifest.get("num_matches")
    if merged["winners"].size != n or merged["losers"].size != n:
        raise SnapshotError(
            f"assembled chain holds {merged['winners'].size}/"
            f"{merged['losers'].size} matches, head manifest promises {n}"
        )
    return head_manifest, merged


class ServingView:
    """One immutable, internally consistent read snapshot.

    `ratings` is a host copy taken atomically with `watermark` (the
    number of matches those ratings reflect); `store` is a
    `MergeableCSR.clone()` — by convention never mutated once inside a
    view. `order` is the precomputed descending-rating permutation
    leaderboard pages slice; `wins`/`losses` are per-player counts
    from the cloned log. `lo`/`hi` are the bootstrap interval arrays
    current at build time (None until `refresh_intervals` runs).

    Multi-tenant arenas serve per-tenant slices of this SAME view:
    `ratings` is always the flat composite vector (a 2-D engine
    snapshot is flattened on the way in), and `tenant_order(t)` is the
    per-tenant leaderboard permutation over tenant t's local-id slice
    — computed on first use and memoized for the view's lifetime, so
    a view refresh never pays an argsort for a tenant nobody queried.
    """

    __slots__ = (
        "ratings", "watermark", "matches_ingested", "store", "order",
        "wins", "losses", "lo", "hi", "seq", "ratings_sum",
        "num_tenants", "players_per_tenant", "_tenant_orders",
    )

    def __init__(self, ratings, watermark, store, lo, hi, seq,
                 num_tenants=1, players_per_tenant=None):
        ratings = np.asarray(ratings).reshape(-1)
        self.ratings = ratings
        self.num_tenants = num_tenants
        self.players_per_tenant = (
            ratings.size if players_per_tenant is None else players_per_tenant
        )
        self._tenant_orders = {}
        self.watermark = watermark
        self.store = store
        self.matches_ingested = store.num_matches
        # Total rating mass — Elo is zero-sum, so any complete view
        # conserves it (up to float accumulation); the serve bench's
        # torn-view check reads it per response.
        self.ratings_sum = float(ratings.sum())
        self.order = np.argsort(-ratings, kind="stable").astype(np.int32)
        self.wins = np.bincount(store.winners(), minlength=ratings.size)
        self.losses = np.bincount(store.losers(), minlength=ratings.size)
        self.lo = lo
        self.hi = hi
        self.seq = seq

    def tenant_order(self, tenant):
        """Descending-rating permutation of tenant `tenant`'s LOCAL id
        slice (memoized per view; dict assignment is atomic under the
        GIL, so concurrent first readers at worst both compute it)."""
        order = self._tenant_orders.get(tenant)
        if order is None:
            off = tenant * self.players_per_tenant
            row = self.ratings[off: off + self.players_per_tenant]
            order = np.argsort(-row, kind="stable").astype(np.int32)
            self._tenant_orders[tenant] = order
        return order


class ArenaServer:  # protocol: close
    """The serving surface over one `ArenaEngine`.

    Construction wires the production-mode sanitizers (count-mode
    recompile sentinel + sampled count-mode donation guard — metrics
    via `stats()`, never raises) and builds the first view lazily on
    the first query. All public methods are thread-safe; queries on
    the prebuilt view take no engine locks at all.
    """

    def __init__(
        self,
        num_players=None,
        engine=None,
        max_staleness_matches=DEFAULT_MAX_STALENESS_MATCHES,
        bootstrap_rounds=32,
        bootstrap_seed=0,
        donation_sample_every=16,
        obs=None,
        **engine_kwargs,
    ):
        if (engine is None) == (num_players is None):
            raise ValueError("pass exactly one of num_players / engine")
        if max_staleness_matches < 0:
            raise ValueError(
                f"max_staleness_matches must be >= 0, got {max_staleness_matches}"
            )
        # A serving surface defaults to a LIVE observability instance —
        # latency percentiles and drop counters are what a front door's
        # load-shedding policy stands behind (ROADMAP item 1's
        # telemetry prerequisite). An explicit `obs` wins everywhere; a
        # handed-in engine keeps its own live obs; a handed-in
        # null-instrumented engine is upgraded to the server's.
        if obs is not None:
            self.obs = obs
        elif engine is not None and engine.obs.enabled:
            self.obs = engine.obs
        else:
            self.obs = Observability()
        if engine is not None:
            if engine.obs is not self.obs:
                engine.set_obs(self.obs)
            self.engine = engine
        else:
            self.engine = ArenaEngine(num_players, obs=self.obs, **engine_kwargs)
        self.max_staleness_matches = max_staleness_matches
        self.bootstrap_rounds = bootstrap_rounds
        self.bootstrap_seed = bootstrap_seed
        self._donation_sample_every = donation_sample_every
        # One lock serializes view refresh + engine swap (restore);
        # the stale-serving read path deliberately does NOT take it.
        self._lock = threading.RLock()
        self._view = None
        self._seq = 0
        self._restoring = False
        self._intervals = None  # (lo, hi) ndarrays from the last bootstrap
        # View-refresh listeners (the wire tier's prerender hook): fired
        # under the lock right after a fresh view is published, so hot
        # leaderboard bytes exist in the wire cache before any reader
        # can miss on the new view.
        self._refresh_listeners = []  # guarded_by: _lock
        # Serving counters live in the registry — ONE schema shared by
        # stats(), the Prometheus render(), and the soak bench line.
        reg = self.obs
        self._c_queries = reg.counter("arena_queries_total")
        self._c_view_refreshes = reg.counter("arena_view_refreshes_total")
        self._c_stale_serves = reg.counter("arena_stale_serves_total")
        self._c_snapshots = reg.counter("arena_snapshots_total")
        self._c_restores = reg.counter("arena_restores_total")
        self._c_recompiles = reg.counter("arena_recompile_events_total")
        self._c_donation_calls = reg.counter("arena_donation_calls_total")
        self._c_donation_sampled = reg.counter("arena_donation_sampled_total")
        self._c_donation_skipped = reg.counter("arena_donation_skipped_total")
        self._h_query_latency = reg.histogram("arena_query_latency_seconds")
        self._h_staleness = reg.histogram(
            "arena_query_staleness_matches", base=1.0
        )
        self._c_listener_errors = reg.counter(
            "arena_view_listener_errors_total"
        )
        # The live ops plane (PR 13): windows + SLO engine + profiler
        # over the same registry. Construction only — no threads until
        # a wire server's start() (or the bench) calls start_ops().
        # First-call-wins: a caller that pre-configured intervals on
        # its obs keeps them.
        self.obs.enable_ops()
        self._wire_sanitizers()

    # --- production-mode sanitizers ----------------------------------

    def _wire_sanitizers(self):
        """Count-mode sentinel over the engine's update AND bootstrap
        caches + sampled count-mode donation guard around the donating
        update. Serving default posture: violations become `stats()`
        counters. Re-wired on restore (fresh engine), so the delta
        baselines reset alongside."""
        self._sentinel = sanitize.RecompileSentinel(
            mode="count",
            update=self.engine.num_compiles,
            bootstrap=self.engine.num_bootstrap_compiles,
        )
        self.engine._update = self._donation_guard = sanitize.donation_guard(
            self.engine._update,
            donate_argnums=(0,),
            mode="count",
            sample_every=self._donation_sample_every,
        )
        # Deltas already absorbed into the registry counters from the
        # PREVIOUS sentinel/guard (zero on first wire).
        self._absorbed = {"recompile": 0, "calls": 0, "sampled": 0,
                          "skipped": 0}

    def _observe_sanitizers(self):
        """Absorb the sentinel/guard counters into the registry — the
        single schema every exposition path (stats(), render(), the
        soak line) reads. Delta-based so re-reads never double-count,
        and a re-wire (restore) restarts cleanly at zero."""
        with self._lock:
            self._sentinel.observe()
            for key, counter, now in (
                ("recompile", self._c_recompiles,
                 self._sentinel.recompile_events),
                ("calls", self._c_donation_calls, self._donation_guard.calls),
                ("sampled", self._c_donation_sampled,
                 self._donation_guard.sampled),
                ("skipped", self._c_donation_skipped,
                 self._donation_guard.donation_skipped),
            ):
                delta = now - self._absorbed[key]
                if delta:
                    counter.inc(delta)
                    self._absorbed[key] = now

    def stats(self):
        """Serving + sanitizer + pipeline counters (all monotone), plus
        the full one-JSON-line observability dump under "obs". Every
        number is read from the metrics registry — the same schema
        `render()` exposes and the soak bench reports."""
        self._observe_sanitizers()
        reg = self.obs.registry
        pipe = self.engine._pipeline
        return {
            "queries": self._c_queries.value,
            "view_refreshes": self._c_view_refreshes.value,
            "stale_serves": self._c_stale_serves.value,
            "snapshots": self._c_snapshots.value,
            "restores": self._c_restores.value,
            "matches_ingested": self.engine.matches_ingested,
            "matches_applied": self.engine.matches_applied,
            "recompile_events": self._c_recompiles.value,
            "donation_calls": self._c_donation_calls.value,
            "donation_sampled": self._c_donation_sampled.value,
            "donation_skipped": self._c_donation_skipped.value,
            # Per-stage drop accounting (policy-labeled counters summed
            # here; the labeled split is in the "obs" dump). Registry
            # counters survive pipeline restarts, so these are stream
            # totals, not last-pipeline totals.
            "pipeline": {
                "pending": pipe.pending() if pipe is not None else 0,
                "dropped_batches": reg.counter_sum(
                    "arena_pipeline_dropped_batches_total"
                ),
                "dropped_matches": reg.counter_sum(
                    "arena_pipeline_dropped_matches_total"
                ),
                "spilled_batches": reg.counter_sum(
                    "arena_pipeline_spilled_batches_total"
                ),
                "spilled_matches": reg.counter_sum(
                    "arena_pipeline_spilled_matches_total"
                ),
            },
            # Wire-tier counters (PR 9), through the SAME registry the
            # HTTP handlers write and /stats renders — requests by
            # endpoint/status plus the shed split by policy. Zeros
            # until a wire server runs; one schema either way.
            "net": {
                "requests": reg.counter_sum("arena_http_requests_total"),
                "requests_by_endpoint": reg.counter_by_label(
                    "arena_http_requests_total", "endpoint"
                ),
                "requests_by_status": reg.counter_by_label(
                    "arena_http_requests_total", "status"
                ),
                "shed_batches_by_policy": reg.counter_by_label(
                    "arena_pipeline_dropped_batches_total", "policy"
                ),
                # The wire byte cache (PR 16): effectiveness counters +
                # the age of the current cache generation (seconds since
                # the view it renders for was published). Zeros until a
                # wire server with a cache runs; same registry either
                # way, so render() and /debug/window see them too.
                "cache": {
                    "hits": reg.counter_sum("arena_wire_cache_hits_total"),
                    "misses": reg.counter_sum(
                        "arena_wire_cache_misses_total"
                    ),
                    "evictions": reg.counter_sum(
                        "arena_wire_cache_evictions_total"
                    ),
                    "prerenders": reg.counter_sum(
                        "arena_wire_cache_prerenders_total"
                    ),
                    "age_seconds": reg.gauge(
                        "arena_wire_cache_age_seconds"
                    ).value,
                },
                # The matchmaking plane (PR 20): presence bit (the
                # `arena_matchmaker_present` gauge a `Matchmaker` sets
                # on attach and zeroes on close) plus proposal
                # counters. Zeros until a matchmaker attaches; same
                # one registry.
                "matchmaker": {
                    "present": bool(
                        reg.gauge("arena_matchmaker_present").value
                    ),
                    "requests": reg.counter_sum(
                        "arena_match_requests_total"
                    ),
                    "proposals": reg.counter_sum(
                        "arena_match_proposals_total"
                    ),
                },
            },
            # The live ops plane (PR 13): burn-rate evaluation over
            # the sliding windows, plus window/profiler thread health.
            # A dead sampler or rotator surfaces HERE as an explicit
            # error — never a silently frozen window.
            "slo": self._slo_block(),
            "obs": self.obs.dump(),
        }

    def _slo_block(self):
        """One SLO evaluation + ops-thread health. `None` when the ops
        plane is off (a NULL-obs server reports the null engine's
        empty block instead)."""
        if self.obs.slo is None:
            return None
        out = self.obs.slo.evaluate()
        window_health = (
            self.obs.windows.health() if self.obs.windows is not None
            else None
        )
        profiler_health = (
            self.obs.profiler.health() if self.obs.profiler is not None
            else None
        )
        errors = [
            h["error"]
            for h in (window_health, profiler_health)
            if h is not None and h.get("error")
        ]
        out["window_health"] = window_health
        out["profiler_health"] = profiler_health
        out["errors"] = errors
        out["healthy"] = not errors
        return out

    # --- views and staleness -----------------------------------------

    def refresh_view(self):
        """Build a fresh immutable view from the live engine."""
        with self.obs.span("serve.view_build"), self._lock:
            ratings, watermark = self.engine.ratings_snapshot()
            store = self.engine._store.clone()
            lo, hi = self._intervals if self._intervals is not None else (None, None)
            self._seq += 1
            self._view = ServingView(
                ratings, watermark, store, lo, hi, self._seq,
                num_tenants=self.engine.num_tenants,
                players_per_tenant=self.engine.players_per_tenant,
            )
            self._c_view_refreshes.inc()
            self._observe_sanitizers()
            for listener in list(self._refresh_listeners):
                try:
                    listener(self._view)
                except Exception:
                    # A broken listener (e.g. a wire prerenderer) must
                    # never take down view refresh — queries depend on
                    # it. Counted, not raised.
                    self._c_listener_errors.inc()
            return self._view

    def add_refresh_listener(self, fn):
        """Register `fn(view)` to run (under the serving lock) each
        time a fresh view is published. The wire tier uses this to
        prerender hot leaderboard pages into its byte cache at refresh
        time; listener exceptions are absorbed into
        `arena_view_listener_errors_total`."""
        with self._lock:
            self._refresh_listeners.append(fn)

    def remove_refresh_listener(self, fn):
        """Unregister a refresh listener (a no-op if absent)."""
        with self._lock:
            if fn in self._refresh_listeners:
                self._refresh_listeners.remove(fn)

    def refresh_intervals(self, num_rounds=None, seed=None, alpha=0.05,
                          batch_size=8192, min_epoch_batches=None):
        """Recompute bootstrap (lo, hi) rating intervals and refresh
        the view so queries serve them. Deterministic under a fixed
        seed (defaults to the server's `bootstrap_seed`). The epoch
        batch count is pow2-padded and the resampler jit is cached per
        engine (`ArenaEngine.bootstrap_ratings`), so refreshing at a
        fixed cadence as history grows compiles O(log N) times total —
        `min_epoch_batches` pins the padding to a planned horizon for
        a strictly compile-free window (the soak bench's posture)."""
        rounds = self.bootstrap_rounds if num_rounds is None else num_rounds
        samples = self.engine.bootstrap_ratings(
            num_rounds=rounds,
            seed=self.bootstrap_seed if seed is None else seed,
            batch_size=batch_size,
            min_batches=min_epoch_batches,
        )
        lo, hi = R.bootstrap_intervals(samples, alpha=alpha)
        with self._lock:
            self._intervals = (np.asarray(lo), np.asarray(hi))
            return self.refresh_view()

    def _staleness(self, view):
        return self.engine.matches_ingested - view.watermark

    def _serve_view(self):
        """The staleness policy: serve the current view if it is within
        `max_staleness_matches` of the ingested stream, else refresh
        first. During a restore, serve the last complete view as-is
        with the explicit stale marker. Returns (view, stale)."""
        view = self._view
        if self._restoring and view is not None:
            self._c_stale_serves.inc()
            return view, True
        if view is None or self._staleness(view) > self.max_staleness_matches:
            view = self.refresh_view()
        stale = self._staleness(view) > self.max_staleness_matches
        if stale:
            # Refresh could not catch up (async pipeline deeper than
            # the bound): served honestly, marked explicitly.
            self._c_stale_serves.inc()
        return view, stale

    # --- the batched query API ---------------------------------------

    def query(self, leaderboard=None, players=None, pairs=None, tenant=None):
        """One batched query, every part answered from ONE view.

        leaderboard: (offset, limit) page of the descending-rating
        order. players: iterable of player ids. pairs: iterable of
        (a, b) id pairs — answered with the Elo-model P(a beats b)
        from the view's ratings. Ids out of range raise ValueError
        (nothing is served). The response carries the view's
        watermark, its staleness at serve time, and the stale flag.

        `tenant=` scopes EVERY part to that tenant's slice of the same
        view: ids become tenant-local, the leaderboard pages the
        per-tenant order. An unknown tenant is a reject (ValueError —
        the wire's 400), same posture as an out-of-range player id.
        """
        t0 = time.perf_counter()
        # Root span: this query's trace id — the view build (when this
        # query triggers one) nests under it, the latency/staleness
        # histograms record it as the bucket exemplar, and
        # `obs.tracer.trace(id)` replays the whole request afterwards.
        with self.obs.span("serve.query") as qspan:
            out = self._query_into(
                qspan, t0, leaderboard, players, pairs, tenant
            )
        return out

    def _query_into(self, qspan, t0, leaderboard, players, pairs, tenant):
        view, stale = self._serve_view()
        self._c_queries.inc()
        out = self._query_parts(
            view, stale, leaderboard, players, pairs, qspan.trace_id,
            tenant=tenant,
        )
        # Latency + staleness distributions: the p50/p99 substrate the
        # soak bench (and the network tier) reports. Host-side work
        # only between the clock reads — every value served came from
        # the prebuilt host view, nothing here awaits a device. The
        # trace id rides into each bucket as its exemplar: "show me
        # the trace behind the p99 bucket" resolves via tracer.trace().
        latency = time.perf_counter() - t0
        self._h_query_latency.record(latency, trace_id=qspan.trace_id)
        self._h_staleness.record(out["staleness"], trace_id=qspan.trace_id)
        return out

    def query_batch(self, specs):  # schema: wire-query-batch@v1
        """Many lookups answered from ONE view.

        Each spec is a dict with any of the `query()` keyword shapes —
        "leaderboard": (offset, limit), "players": [ids...], "pairs":
        [(a, b)...] — and the whole batch is rendered against a single
        `_serve_view()` call, so every result shares one watermark, one
        view_seq and one staleness number. This is the in-process
        engine behind the wire's POST /query endpoint: N lookups cost
        one staleness decision and one HTTP round trip instead of N.
        An id out of range raises ValueError and nothing is served,
        same as `query()`.
        """
        t0 = time.perf_counter()
        with self.obs.span("serve.query_batch") as qspan:
            view, stale = self._serve_view()
            staleness = view.matches_ingested - view.watermark
            results = []
            for spec in specs:
                results.append(self._query_parts(
                    view, stale,
                    spec.get("leaderboard"), spec.get("players"),
                    spec.get("pairs"), qspan.trace_id,
                    staleness=staleness, tenant=spec.get("tenant"),
                ))
            self._c_queries.inc(len(results))
            latency = time.perf_counter() - t0
            self._h_query_latency.record(latency, trace_id=qspan.trace_id)
            self._h_staleness.record(staleness, trace_id=qspan.trace_id)
            return {
                "watermark": view.watermark,
                "trace_id": qspan.trace_id,
                "view_seq": view.seq,
                "stale": stale,
                "queries": len(results),
                "results": results,
            }

    def _query_parts(self, view, stale, leaderboard, players, pairs,
                     trace_id, staleness=None, tenant=None):  # schema: wire-query-response@v1
        """Render one lookup's response parts against an already-chosen
        view. Deterministic in (view, arguments) apart from the
        engine's immutable Elo scale — the property the wire byte
        cache stands on: same view + same arguments => same payload,
        byte for byte. `staleness` defaults to the live ingest
        distance (the `query()` contract); the wire fast path passes
        the view-stable distance so cached bytes never embed a number
        that drifts between identical renders.

        `tenant=` selects one tenant's slice of the view: every id in
        the arguments AND in the rendered rows is tenant-local, and the
        leaderboard pages `view.tenant_order(tenant)`. None keeps the
        composite-space behavior — on a single-tenant arena that IS the
        arena; on a multi-tenant one it is the cross-tenant admin view."""
        if tenant is None:
            num_players = view.ratings.size
            off = 0
            order = view.order
        else:
            tenant = int(tenant)
            if not 0 <= tenant < view.num_tenants:
                raise ValueError(
                    f"unknown tenant {tenant}: this arena serves tenants "
                    f"[0, {view.num_tenants})"
                )
            num_players = view.players_per_tenant
            off = tenant * num_players
            order = view.tenant_order(tenant)
        out = {
            "watermark": view.watermark,
            # The request's trace id rides NEXT TO the watermark in
            # every response (ROADMAP item 1): a stale or slow answer
            # is one tracer.trace(id) away from its causal story. The
            # wire tier's envelope re-stamps the same pair (the net
            # root span shares this trace).
            "trace_id": trace_id,
            "matches_ingested": view.matches_ingested,
            "staleness": (
                self._staleness(view) if staleness is None else staleness
            ),
            "stale": stale,
            "view_seq": view.seq,
            "view_ratings_sum": view.ratings_sum,
        }
        if tenant is not None:
            out["tenant"] = tenant
        if leaderboard is not None:
            offset, limit = leaderboard
            if offset < 0 or limit < 0:
                raise ValueError(
                    f"leaderboard page must be non-negative, got "
                    f"({offset}, {limit})"
                )
            page = order[offset: offset + limit]
            out["leaderboard"] = [
                self._player_row(view, int(p), rank=offset + i + 1, off=off)
                for i, p in enumerate(page)
            ]
        if players is not None:
            ids = np.asarray(list(players), np.int64)
            if ids.size and (
                ids.min() < 0 or ids.max() >= num_players
            ):
                raise ValueError(
                    f"player ids must be in [0, {num_players})"
                )
            out["players"] = [
                self._player_row(view, int(p), off=off) for p in ids
            ]
        if pairs is not None:
            rows = []
            for a, b in pairs:
                if not (0 <= a < num_players and 0 <= b < num_players):
                    raise ValueError(
                        f"pair ({a}, {b}) outside [0, {num_players})"
                    )
                rows.append({
                    "a": int(a),
                    "b": int(b),
                    "p_a_beats_b": _elo_win_prob(
                        float(view.ratings[off + a]),
                        float(view.ratings[off + b]),
                        self.engine.scale,
                    ),
                })
            out["pairs"] = rows
        return out

    def _player_row(self, view, p, rank=None, off=0):  # pure-render(view); schema: wire-player-row@v1
        """`off` is the tenant's composite-space offset: rows report the
        TENANT-LOCAL id, reads index the composite arrays."""
        row = {
            "player": p,
            "rating": float(view.ratings[off + p]),
            "lo": None if view.lo is None else float(view.lo[off + p]),
            "hi": None if view.hi is None else float(view.hi[off + p]),
            "wins": int(view.wins[off + p]),
            "losses": int(view.losses[off + p]),
        }
        if rank is not None:
            row["rank"] = rank
        return row

    # --- snapshot / restore ------------------------------------------

    def snapshot(self, path, spill=False, base=None):  # schema: arena-snapshot@v3
        """Spill the engine to a durable snapshot directory.

        Default: the async pipeline (if any) is DRAINED first
        (`engine.flush()`), so the snapshot is the fully-applied
        state and the queue section is empty. spill=True instead
        shuts the pipeline down spilling its still-raw queue into the
        snapshot (the restart-mid-stream form; the pipeline restarts
        lazily on the next ingest_async). Either way ratings and
        match store agree exactly at write time.

        `base=<path of an existing snapshot>` cuts an INCREMENTAL
        snapshot against it: only the match rows past the base's
        watermark, the delta tail, ratings, and (only if a compaction
        rewrote them) the main runs are spilled, with a validated
        manifest chain back to the base. The base may itself be an
        increment — chains restore transitively.
        """
        base_manifest = None
        base_ref = None
        if base is not None:
            base_manifest = _read_manifest(base)
            base_ref = os.path.relpath(
                pathlib.Path(base).resolve(), start=pathlib.Path(path).resolve()
            )
        with self.obs.span("serve.snapshot"), self._lock:
            eng = self.engine
            if spill:
                queue = eng.shutdown(spill=True)
            else:
                queue = []
                eng.flush()
            # flush()/shutdown() drained everything merged, so the
            # watermark and the store must agree; a concurrent ingest
            # on another thread can land BETWEEN its store merge and
            # its rating dispatch, so wait briefly for the pair to
            # line up rather than persisting a torn snapshot.
            deadline = time.monotonic() + 10.0
            while True:
                # Deliberate post-shutdown read: shutdown(spill=True) is
                # the restart-mid-stream form — the engine stays readable
                # and restarts its pipeline lazily on the next
                # ingest_async, so this is the contract, not a zombie.
                ratings, watermark = eng.ratings_snapshot()  # jaxlint: disable=use-after-close
                state = eng._store.export_state()
                if watermark == state["num_matches"]:
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"snapshot raced an ingest for 10s: {watermark} "
                        f"matches applied vs {state['num_matches']} stored"
                    )
                # Deliberate: the serving lock must stay held while the
                # watermark settles (a view refresh mid-snapshot would
                # serve half-written state); reads never take this lock,
                # so only writers wait, bounded by the deadline above.
                time.sleep(0.001)  # jaxlint: disable=blocking-while-locked
            manifest = write_snapshot(
                path,
                num_players=eng.num_players,
                k=eng.k,
                scale=eng.scale,
                base=eng.base,
                min_bucket=eng.min_bucket,
                store_state=state,
                ratings=ratings,
                queue=queue,
                base_manifest=base_manifest,
                base_ref=base_ref,
                num_tenants=eng.num_tenants,
                players_per_tenant=eng.players_per_tenant,
            )
            self._c_snapshots.inc()
            return manifest

    def restore(self, path):  # schema: arena-snapshot@v3
        """Reload a snapshot — full or incremental head — and resume
        mid-stream.

        Validation and assembly happen on fresh objects FIRST; the
        live engine is swapped only after everything checked out, so
        a corrupt snapshot (or a broken base chain) leaves the server
        exactly as it was (`SnapshotError` names expected vs found).
        While the restore is in progress, concurrent queries serve the
        last complete view with `stale=True`. Spilled queue batches
        from the snapshot are resubmitted synchronously, FIFO — after
        restore the ratings equal an uninterrupted run over the same
        stream.
        """
        self._restoring = True
        try:
            with self.obs.span("serve.restore"):
                manifest, arrays = read_snapshot_chain(path)
                store = self._assemble_store(manifest, arrays)
                if manifest.get("num_tenants", 1) > 1:
                    # Imported lazily: arena.tenancy imports this
                    # module's engine primitives at its own top level.
                    from arena.tenancy import MultiTenantEngine

                    # Pin the tenant bucket to exactly the written
                    # geometry (num_players is tenant_bucket * ppt), so
                    # a restored engine's composite space — and every
                    # stored composite id — lines up bit-for-bit.
                    eng = MultiTenantEngine(
                        manifest["players_per_tenant"],
                        num_tenants=manifest["num_tenants"],
                        k=manifest["k"],
                        scale=manifest["scale"],
                        base=manifest["base"],
                        min_bucket=manifest["min_bucket"],
                        obs=self.obs,
                        min_tenant_bucket=(
                            manifest["num_players"]
                            // manifest["players_per_tenant"]
                        ),
                    )
                else:
                    eng = ArenaEngine(
                        manifest["num_players"],
                        k=manifest["k"],
                        scale=manifest["scale"],
                        base=manifest["base"],
                        min_bucket=manifest["min_bucket"],
                        obs=self.obs,
                    )
                eng.adopt_state(arrays["ratings"], store)
                queue = _split_queue(arrays)
                with self._lock:
                    old = self.engine
                    old.shutdown()
                    self.engine = eng
                    self._wire_sanitizers()
                    # Resume mid-stream: the spilled queue replays
                    # through the normal ingest path, in submission
                    # order.
                    for w, l in queue:
                        eng.ingest(w, l)
                    self._c_restores.inc()
        finally:
            self._restoring = False
        self.refresh_view()
        return manifest

    def _assemble_store(self, manifest, arrays):  # schema: arena-snapshot@v3
        """`MergeableCSR.from_state` with its ValueErrors upgraded to
        the snapshot-reject contract (distinct error, nothing
        installed). The delta tail is restored AS RUNS — dropping it
        here would silently lose every not-yet-compacted entry's
        grouping, which the crash-restart property test pins."""
        state = {
            "num_matches": manifest["num_matches"],
            "compactions": manifest["compactions"],
            "compact_threshold": manifest["compact_threshold"],
            "size_ratio": manifest["size_ratio"],
            "keys": arrays["keys"],
            "pos": arrays["pos"],
            "tail_keys": arrays["tail_keys"],
            "tail_pos": arrays["tail_pos"],
            "tail_run_lengths": arrays["tail_run_lengths"],
            "winners": arrays["winners"],
            "losers": arrays["losers"],
        }
        try:
            return MergeableCSR.from_state(
                manifest["num_players"], state, obs=self.obs
            )
        except ValueError as exc:
            raise SnapshotError(
                f"snapshot arrays are internally inconsistent: {exc}"
            ) from exc

    def close(self):
        """Shut the engine's pipeline down (drained). The server stays
        queryable from its last view."""
        self.engine.shutdown()


def _split_queue(arrays):  # schema: arena-snapshot@v3
    lengths = arrays["queue_lengths"]
    if not lengths.size:
        return []
    splits = np.cumsum(lengths[:-1])
    return list(
        zip(np.split(arrays["queue_winners"], splits),
            np.split(arrays["queue_losers"], splits))
    )


def _elo_win_prob(r_a, r_b, scale):  # deterministic
    """Host-side Elo win probability (see `ratings.elo_expected` for
    the device form): 1 / (1 + 10^((r_b - r_a)/scale))."""
    return 1.0 / (1.0 + math.pow(10.0, (r_b - r_a) / scale))


def restore_server(path, **server_kwargs):
    """Cold start: a fresh `ArenaServer` restored from a snapshot
    (or the head of an incremental chain)."""
    manifest = _read_manifest(path)
    srv = ArenaServer(num_players=manifest["num_players"], **server_kwargs)
    srv.restore(path)
    return srv
