"""Replica catch-up and time travel over the shipped applied log.

ROADMAP item 2's read-fleet piece. A writer with a `FrontDoor`
(`record_applied=True`) exposes its applied log — the deterministic
total order — over `GET /log` (see `protocol.parse_path` and
`server._log_payload`). This module is the consumer side:

1. **`ReplicaReader`** — restores a base snapshot (full or the head of
   an incremental chain) into its own `ArenaServer`, then tails the
   writer's log over the existing HTTP tier and replays records
   STRICTLY in log-sequence order through the synchronous
   `ArenaEngine.ingest` path. jaxlint v5's `# deterministic` contracts
   on the apply path are the static statement of why this works: the
   writer applied the same records in the same order through the same
   kernels, so the replica's ratings are bit-exact vs the writer at
   equal watermark (every record's post-apply watermark is
   cross-checked during replay — a divergence is a raised
   `ReplicaError`, not a silently forked replica). The replica serves
   reads through `ArenaHTTPServer(frontdoor=None)` — the read-only
   skeleton that 503s on /submit — with PR 16's fastpath cache
   unchanged.

2. **Tail/replay split.** The network fetch (`arena-replica-tail`
   thread) and the deterministic apply (`arena-replica-replay` thread)
   are separate so a slow writer round-trip never stalls the replay of
   already-fetched segments, and the profiler folds the two costs
   under distinct roles.

3. **Per-replica staleness as an SLO objective.** Every poll records
   how many matches the replica trails the writer into
   `arena_replica_staleness_matches` and evaluates the burn-rate
   engine; `ReplicaReader.start()` registers the `replica-staleness`
   objective (`slo.replica_staleness_slo`) on the replica's own
   engine, so `/debug/slo` on the replica is the health surface a
   fleet controller polls for placement/eviction.

4. **`TimeTravelIndex`** — answers `?as_of=<watermark>` reads by
   replaying the shipped log to the requested watermark against the
   nearest retained snapshot (historical views are immutable, so a
   small bounded cache makes repeats cheap). Works on the writer
   (log source = the front door) and on replicas (log source = the
   reader's retained records) alike.

Everything here is host-side stdlib + NumPy; jitted work stays behind
`ArenaEngine`.
"""

import bisect
import threading
import time
from collections import deque

import numpy as np

from arena import serving as serving_mod
from arena.engine import ArenaEngine
from arena.net import protocol
from arena.net.frontdoor import MAX_LOG_SEGMENT_RECORDS
from arena.obs import slo as slo_mod
from arena.serving import ServingView

# Part of the observability contract: the sampling profiler
# (arena/obs/profile.py) maps these names to the "replica-tail" /
# "replica-replay" roles. Rename here and the role table moves along.
TAIL_THREAD_NAME = "arena-replica-tail"
REPLAY_THREAD_NAME = "arena-replica-replay"

# How long the tail sleeps after an empty poll (the writer had nothing
# new). Small: catch-up lag under live ingest is poll-bounded.
DEFAULT_POLL_INTERVAL_S = 0.02

# Fetched-but-not-yet-applied segments the tail may buffer ahead of
# the replay thread before it stops fetching (bounds memory, not
# correctness — replay order is carried by the records themselves).
DEFAULT_PENDING_SEGMENTS = 64

# Historical views a TimeTravelIndex retains; one view is a full
# store clone, so this bounds memory like the serving view does.
DEFAULT_CACHED_VIEWS = 8


class ReplicaError(RuntimeError):
    """The replica cannot make progress or has DIVERGED from the
    writer: an out-of-sequence record, a watermark cross-check
    mismatch, a failed /log fetch, or a dead worker thread."""


class SegmentCursor:  # protocol: close
    """One replica's read position in a writer's applied log, plus the
    persistent wire connection it pages over.

    The first fetch aligns by watermark (`after_watermark=` — how a
    reader restored from a snapshot at watermark W seats its cursor
    without re-shipping history); every later fetch pages by the
    sequence cursor the previous response returned. The cursor also
    verifies each page CONTINUES the sequence — a gap at the transport
    layer is an error here, before any record reaches an engine."""

    def __init__(self, host, port, *, start_watermark=None, timeout=10.0):
        self._client = protocol.WireClient(host, port, timeout=timeout)
        self._start_watermark = start_watermark
        self._aligned = start_watermark is None
        self.next_seq = 0
        self.log_len = 0
        self.base_watermark = None
        self.writer_watermark = 0
        self.fetches = 0

    def fetch(self, limit=MAX_LOG_SEGMENT_RECORDS):  # schema: wire-log-segment@v1
        """One /log page: a list of record dicts in sequence order
        (possibly empty). Raises ReplicaError on any non-200 answer or
        a page that does not continue this cursor's sequence."""
        if not self._aligned:
            path = (
                f"/log?after_watermark={int(self._start_watermark)}"
                f"&limit={int(limit)}"
            )
        else:
            path = f"/log?after_seq={self.next_seq - 1}&limit={int(limit)}"
        status, doc = self._client.get(path)
        if status != 200:
            err = doc.get("error") if isinstance(doc, dict) else doc
            raise ReplicaError(f"writer /log answered {status}: {err}")
        records = doc["records"]
        expect = self.next_seq
        for rec in records:
            if not self._aligned:
                # The aligned page may start anywhere the watermark
                # mapped to; later pages must continue exactly.
                expect = rec["seq"]
                self._aligned = True
            if rec["seq"] != expect:
                raise ReplicaError(
                    f"log page breaks the sequence: expected seq {expect}, "
                    f"got {rec['seq']}"
                )
            expect += 1
        self._aligned = True
        if records:
            self.next_seq = records[-1]["seq"] + 1
        else:
            # An empty page still positions the cursor: the writer's
            # next_seq is where the watermark (or after_seq) mapped to.
            # Without this, a replica restored exactly at the writer's
            # head would fall back to seq 0 on its next poll and
            # re-ship history into the divergence check.
            self.next_seq = doc["next_seq"]
        self.log_len = doc["log_len"]
        self.base_watermark = doc["base_watermark"]
        self.writer_watermark = doc["watermark"]
        self.fetches += 1
        return records

    def close(self):
        self._client.close()


class ReplicaReader:  # protocol: start->close
    """Catch a read replica up to a writer and keep it caught up.

    Construction optionally restores `snapshot` (full or incremental
    head) into the replica's `ArenaServer`; `start()` spawns the tail
    and replay threads; `close()` stops and joins them and closes the
    wire connection. Replay is strict: records apply in log-sequence
    order through the deterministic sync ingest path, each record's
    post-apply watermark is cross-checked against the writer's, and
    any violation kills the reader with a `ReplicaError` surfaced on
    the next call — a stopped replica, never a forked one.
    """

    def __init__(self, server, writer_host, writer_port, *, snapshot=None,
                 poll_interval_s=DEFAULT_POLL_INTERVAL_S,
                 segment_limit=MAX_LOG_SEGMENT_RECORDS,
                 pending_segments=DEFAULT_PENDING_SEGMENTS,
                 staleness_slo_matches=slo_mod.DEFAULT_REPLICA_STALENESS_MATCHES):
        self._srv = server
        self._obs = server.obs
        if snapshot is not None:
            server.restore(snapshot)
        self._eng = server.engine
        self._poll_interval_s = poll_interval_s
        self._segment_limit = segment_limit
        self._pending_segments = pending_segments
        self._staleness_slo_matches = staleness_slo_matches
        self._base_watermark = int(self._eng.matches_applied)
        self._cursor = SegmentCursor(
            writer_host, writer_port, start_watermark=self._base_watermark
        )
        self._cv = threading.Condition()
        self._pending = deque()  # guarded_by: _cv  (fetched segments)
        self._closed = False  # guarded_by: _cv
        self._error = None  # guarded_by: _cv
        self._applied_seq = -1  # log seq of the last applied record
        # The first record after watermark alignment anchors the seq
        # (the writer owns the watermark->seq mapping); its OWN
        # correctness is still pinned by the record-watermark
        # cross-check. Every later record must continue exactly.
        self._anchored = False
        self._watermark = self._base_watermark
        self._writer_log_len = None  # guarded_by: _cv  (None until a fetch)
        # The locally retained shipped log — (seq, kind, winners,
        # losers, watermark) tuples in apply order. Feeds this
        # replica's own TimeTravelIndex and the bit-exactness tests.
        self.records = []
        self.segments_fetched = 0
        self.records_applied = 0
        self._tail = None
        self._replay = None

    # --- lifecycle ----------------------------------------------------

    def start(self):
        """Register the staleness SLO objective and spawn the tail and
        replay threads. Idempotence is not a goal: one reader, one
        start."""
        if self._tail is not None:
            raise ReplicaError("replica reader already started")
        try:
            self._obs.slo.add(
                slo_mod.replica_staleness_slo(self._staleness_slo_matches)
            )
        except slo_mod.SLOError:
            pass  # already registered on this obs (restarted reader)
        self._tail = threading.Thread(
            target=self._tail_loop, name=TAIL_THREAD_NAME, daemon=True
        )
        self._replay = threading.Thread(
            target=self._replay_loop, name=REPLAY_THREAD_NAME, daemon=True
        )
        self._tail.start()
        self._replay.start()
        return self

    def close(self):
        """Stop both threads, join them, close the wire connection.
        Safe to call more than once; never raises on a dead worker
        (the error already surfaced or will via `raise_if_failed`)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for thread in (self._tail, self._replay):
            if thread is not None and thread.is_alive():
                thread.join(timeout=10.0)
        self._cursor.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()

    # --- status -------------------------------------------------------

    def watermark(self):
        """Matches applied on the replica engine (== the writer's
        watermark at the last applied record boundary)."""
        return self._watermark

    def applied_seq(self):
        return self._applied_seq

    def staleness_matches(self):
        """How many matches the replica trails the writer's last
        observed watermark (0 until the first fetch lands)."""
        return max(0, self._cursor.writer_watermark - self._watermark)

    def raise_if_failed(self):
        """Surface a dead worker as an explicit error (the PR 10
        liveness discipline): a recorded failure re-raises, and a
        worker that died WITHOUT recording one is still a raise, never
        a silent hang for whoever is waiting on replica progress."""
        with self._cv:
            if self._error is not None:
                raise ReplicaError(
                    f"replica reader failed: {self._error!r}"
                ) from self._error
            if self._closed:
                return
            for thread in (self._tail, self._replay):
                if thread is not None and not thread.is_alive():
                    raise ReplicaError(
                        f"replica worker {thread.name!r} died without "
                        "recording a failure"
                    )

    def wait_for_watermark(self, watermark, timeout=30.0):
        """Block until the replica has applied up to `watermark`.
        Raises ReplicaError on a reader failure or timeout — catch-up
        lag is BOUNDED, not best-effort."""
        deadline = time.monotonic() + timeout
        while True:
            self.raise_if_failed()
            if self._watermark >= watermark:
                return self._watermark
            if time.monotonic() > deadline:
                raise ReplicaError(
                    f"replica did not reach watermark {watermark} within "
                    f"{timeout}s (at {self._watermark})"
                )
            with self._cv:
                self._cv.wait(0.01)

    def log_segment(self, after_seq=-1, after_watermark=None,
                    limit=MAX_LOG_SEGMENT_RECORDS):
        """The replica's retained shipped log, in `FrontDoor
        .log_segment` shape — so a `TimeTravelIndex` (and anything
        else that pages a log) works against writer and replica
        alike."""
        with self._cv:
            log_len = len(self.records)
            if after_watermark is not None:
                wm = int(after_watermark)
                if wm == self._base_watermark:
                    start = 0
                else:
                    marks = [r[4] for r in self.records]
                    idx = bisect.bisect_left(marks, wm)
                    if idx >= log_len or marks[idx] != wm:
                        raise ValueError(
                            f"watermark {wm} is not a replayed record "
                            f"boundary on this replica"
                        )
                    start = idx + 1
            else:
                start = int(after_seq) + 1
            stop = min(log_len, start + int(limit))
            # Replayed records keep their WRITER log seqs; index
            # locally by offset from the first retained record.
            return (
                list(self.records[start:stop]),
                stop,
                log_len,
                self._base_watermark,
            )

    # --- the tail thread (network) ------------------------------------

    def _tail_loop(self):
        try:
            while True:
                with self._cv:
                    if self._closed:
                        return
                    while (
                        len(self._pending) >= self._pending_segments
                        and not self._closed
                    ):
                        self._cv.wait(0.05)
                    if self._closed:
                        return
                records = self._cursor.fetch(limit=self._segment_limit)
                self.segments_fetched += 1
                with self._cv:
                    self._writer_log_len = self._cursor.log_len
                    if records:
                        self._pending.append(records)
                        self._cv.notify_all()
                self._observe_staleness()
                if not records:
                    time.sleep(self._poll_interval_s)
        except BaseException as exc:  # noqa: BLE001 — surface on callers
            with self._cv:
                self._error = exc
                self._cv.notify_all()

    def _observe_staleness(self):
        """One staleness observation per poll + one burn-rate pull:
        the replica-staleness objective only means something if it is
        actually EVALUATED on the live engine (the mutation audit
        pins this — see staleness-slo-never-evaluated)."""
        lag = float(self.staleness_matches())
        self._obs.histogram(
            "arena_replica_staleness_matches", base=1.0
        ).record(lag)
        self._obs.gauge("arena_replica_staleness_matches_now").set(lag)
        self._obs.slo.evaluate()

    # --- the replay thread (deterministic apply) ----------------------

    def _replay_loop(self):
        try:
            while True:
                with self._cv:
                    while not self._pending and not self._closed:
                        self._cv.wait(0.05)
                    if not self._pending and self._closed:
                        return
                    segment = self._pending.popleft()
                    self._cv.notify_all()
                with self._obs.span("replica.replay"):
                    self._apply_records(segment)
                # Publish: reads on this replica see the new state at
                # the next view refresh (the serving staleness policy),
                # and the fastpath cache invalidates by view seq.
                self._srv.refresh_view()
                with self._cv:
                    self._cv.notify_all()
        except BaseException as exc:  # noqa: BLE001 — surface on callers
            with self._cv:
                self._error = exc
                self._cv.notify_all()

    def _apply_records(self, segment):  # deterministic; mutates: _applied_seq, _anchored, _watermark, records, records_applied
        """Replay one fetched segment STRICTLY in sequence order
        through the synchronous ingest path. Three checks stand
        between a bad segment and the engine: the seq must continue
        the applied sequence exactly (arrival order is NOT apply
        order), the kind must be known, and the post-apply watermark
        must equal the writer's recorded one (the bit-exactness
        cross-check: same records, same order, same kernels)."""
        for rec in segment:
            seq = rec["seq"]
            if self._anchored and seq != self._applied_seq + 1:
                raise ReplicaError(
                    f"record out of sequence: expected {self._applied_seq + 1}, "
                    f"got {seq} — refusing to apply out of order"
                )
            self._anchored = True
            kind = rec["kind"]
            if kind not in ("batch", "summary"):
                raise ReplicaError(f"unknown log record kind {kind!r}")
            w = np.asarray(rec["winners"], np.int32)
            l = np.asarray(rec["losers"], np.int32)
            self._eng.ingest(w, l)
            applied = int(self._eng.matches_applied)
            if applied != rec["record_watermark"]:
                raise ReplicaError(
                    f"watermark diverged at seq {seq}: replica at {applied}, "
                    f"writer recorded {rec['record_watermark']}"
                )
            self._applied_seq = seq
            self._watermark = applied
            self.records.append((seq, kind, w, l, applied))
            self.records_applied += 1


class TimeTravelIndex:
    """`?as_of=<watermark>` reads: the leaderboard as it stood at an
    earlier point of the stream, answered by replaying the shipped log
    to the requested watermark against the nearest retained snapshot.

    `log_source` is anything with the `log_segment` shape —
    `FrontDoor` on a writer, `ReplicaReader` on a replica. Snapshots
    are registered by path (`add_snapshot`, typically right after
    `ArenaServer.snapshot()` cuts one); the index reads only manifests
    until a query actually needs a restore. Answers carry the
    HISTORICAL watermark (the greatest record boundary <= `as_of`)
    plus `as_of`/`as_of_watermark` markers; historical state is
    immutable, so built views are cached (bounded)."""

    def __init__(self, server, log_source, snapshots=(),
                 cached_views=DEFAULT_CACHED_VIEWS):
        self._srv = server
        self._log = log_source
        self._lock = threading.Lock()
        self._snapshots = []  # guarded_by: _lock  ((watermark, path) sorted)
        self._views = {}  # guarded_by: _lock  (as_of -> ServingView)
        self._cached_views = cached_views
        for path in snapshots:
            self.add_snapshot(path)

    def add_snapshot(self, path):  # schema: arena-snapshot@v2
        """Register one retained snapshot (validating its manifest);
        returns the watermark it pins."""
        manifest = serving_mod._read_manifest(path)
        watermark = int(manifest["num_matches"])
        with self._lock:
            bisect.insort(self._snapshots, (watermark, str(path)))
        return watermark

    def snapshots(self):
        with self._lock:
            return list(self._snapshots)

    def leaderboard(self, offset, limit, as_of):  # schema: wire-query-response@v1
        view = self._view_for(as_of)
        payload = self._srv._query_parts(
            view, False, (offset, limit), None, None, 0, staleness=0
        )
        payload["as_of"] = as_of
        payload["as_of_watermark"] = view.watermark
        return payload

    def player(self, player, as_of):  # schema: wire-query-response@v1
        view = self._view_for(as_of)
        payload = self._srv._query_parts(
            view, False, None, [player], None, 0, staleness=0
        )
        payload["as_of"] = as_of
        payload["as_of_watermark"] = view.watermark
        return payload

    def _view_for(self, as_of):
        """The historical view answering `as_of`: nearest retained
        snapshot at watermark <= as_of, plus a strict-order replay of
        the shipped log records whose post-apply watermark is <= as_of.
        404 when no retained snapshot can seed the replay."""
        as_of = int(as_of)
        if as_of < 0:
            raise protocol.ProtocolError(
                400, f"as_of must be a non-negative watermark, got {as_of}"
            )
        with self._lock:
            view = self._views.get(as_of)
            if view is not None:
                return view
            idx = bisect.bisect_right(self._snapshots, (as_of, chr(0x10FFFF)))
            if idx == 0:
                raise protocol.ProtocolError(
                    404, f"no retained snapshot at or below watermark "
                    f"{as_of} (oldest: "
                    f"{self._snapshots[0][0] if self._snapshots else None})"
                )
            snap_watermark, snap_path = self._snapshots[idx - 1]
            view = self._build_view(snap_path, snap_watermark, as_of)
            self._views[as_of] = view
            while len(self._views) > self._cached_views:
                self._views.pop(next(iter(self._views)))
            return view

    def _build_view(self, snap_path, snap_watermark, as_of):
        """Restore the snapshot chain into a throwaway engine, replay
        shipped records up to `as_of`, freeze a `ServingView`."""
        manifest, arrays = serving_mod.read_snapshot_chain(snap_path)
        store = self._srv._assemble_store(manifest, arrays)
        eng = ArenaEngine(
            manifest["num_players"],
            k=manifest["k"],
            scale=manifest["scale"],
            base=manifest["base"],
            min_bucket=manifest["min_bucket"],
            obs=self._srv.obs,
        )
        eng.adopt_state(arrays["ratings"], store)
        cursor_watermark = snap_watermark
        done = False
        while not done:
            try:
                records, _next, log_len, _base = self._log.log_segment(
                    after_watermark=cursor_watermark
                )
            except ValueError as exc:
                raise protocol.ProtocolError(
                    409, f"snapshot watermark {cursor_watermark} does not "
                    f"align with the shipped log: {exc}"
                ) from None
            if not records:
                break
            for rec in records:
                watermark = rec[4] if isinstance(rec, tuple) else rec["record_watermark"]
                if watermark > as_of:
                    done = True
                    break
                if isinstance(rec, tuple):
                    _seq, _kind, w, l, _wm = rec
                else:
                    w = np.asarray(rec["winners"], np.int32)
                    l = np.asarray(rec["losers"], np.int32)
                eng.ingest(w, l)
                cursor_watermark = watermark
        ratings, watermark = eng.ratings_snapshot()
        view = ServingView(
            ratings, watermark, eng._store.clone(), None, None, seq=0
        )
        eng.shutdown()
        return view
