"""Replica catch-up and time travel (arena/net/replica.py, GET /log).

ROADMAP item 2's read-fleet contracts, pinned over a REAL wire:

- `GET /log` pages the writer's applied log in strict sequence order,
  seats a restored replica's cursor by watermark, and answers 503/409
  (no log / non-boundary watermark) instead of shipping garbage;
- a `ReplicaReader` restored from an incremental-chain snapshot tails
  the writer and is BIT-EXACT at equal watermark — including across
  forced overload sheds, whose coalesced summary records replay like
  any other record;
- replay is strict: an out-of-sequence record, an unknown kind, or a
  record whose post-apply watermark disagrees with the writer's is a
  raised `ReplicaError`, never a silently forked replica (the audit's
  replica-applies-arrival-order mutant dies here);
- `?as_of=` time-travel reads equal a synchronous replay of the same
  log prefix (the audit's staleness-slo-never-evaluated mutant dies on
  the SLO assertions, and the profiler maps the tail/replay threads to
  their roles).
"""

import numpy as np
import pytest

from arena.engine import ArenaEngine
from arena.net import ArenaHTTPServer, FrontDoor, WireClient
from arena.net.replica import (
    ReplicaError,
    ReplicaReader,
    SegmentCursor,
    TimeTravelIndex,
)
from arena.obs import Observability
from arena.obs.profile import thread_role
from arena.serving import ArenaServer

PLAYERS = 32


def make_batch(rng, n=40):
    a = rng.integers(0, PLAYERS, n).astype(np.int32)
    b = ((a + 1 + rng.integers(0, PLAYERS - 1, n)) % PLAYERS).astype(np.int32)
    return a, b


class WriterStack:
    """One writer: ArenaServer + recording FrontDoor + wire tier."""

    def __init__(self):
        self.obs = Observability()
        self.srv = ArenaServer(
            num_players=PLAYERS, max_staleness_matches=0, obs=self.obs
        )
        self.frontdoor = FrontDoor(
            self.srv.engine, capacity=64, record_applied=True
        )
        self.wire = ArenaHTTPServer(self.srv, frontdoor=self.frontdoor).start()
        self.client = WireClient(self.wire.host, self.wire.port)
        self.rng = np.random.default_rng(17)

    def feed(self, batches, n=40):
        for _ in range(batches):
            w, l = make_batch(self.rng, n)
            self.frontdoor.submit(w, l, producer="writer")
        self.frontdoor.flush()
        return self.srv.engine.matches_applied

    def close(self):
        self.client.close()
        self.wire.close()
        self.frontdoor.close()
        self.srv.close()


@pytest.fixture()
def writer():
    stack = WriterStack()
    yield stack
    stack.close()


def make_replica(snapshot, host, port, **kwargs):
    obs = Observability()
    rsrv = ArenaServer(num_players=PLAYERS, max_staleness_matches=0, obs=obs)
    reader = ReplicaReader(rsrv, host, port, snapshot=snapshot, **kwargs)
    return rsrv, reader


def replay_sync(frontdoor, up_to_watermark):
    """The oracle: replay the writer's applied log SYNCHRONOUSLY to a
    watermark on a fresh engine."""
    eng = ArenaEngine(PLAYERS)
    for (kind, w, l), mark in zip(
        frontdoor.applied_log, frontdoor.applied_watermarks
    ):
        if mark > up_to_watermark:
            break
        assert kind in ("summary", "batch")
        eng.ingest(w, l)
    ratings = np.asarray(eng.ratings).copy()
    eng.shutdown()
    return ratings


# --- GET /log ---------------------------------------------------------------


def test_log_endpoint_pages_in_sequence_order(writer):
    writer.feed(6)
    status, doc = writer.client.get("/log?after_seq=-1&limit=4")
    assert status == 200
    assert [r["seq"] for r in doc["records"]] == [0, 1, 2, 3]
    assert doc["next_seq"] == 4 and doc["log_len"] == 6
    assert doc["base_watermark"] == 0
    assert doc["watermark"] == writer.srv.engine.matches_applied
    # Record watermarks are cumulative post-apply marks.
    assert [r["record_watermark"] for r in doc["records"]] == [
        40, 80, 120, 160
    ]
    status, doc = writer.client.get("/log?after_seq=3")
    assert status == 200
    assert [r["seq"] for r in doc["records"]] == [4, 5]
    assert doc["next_seq"] == 6
    # Watermark alignment: a restored replica seats its cursor at a
    # record boundary without re-shipping history.
    status, doc = writer.client.get("/log?after_watermark=120")
    assert status == 200
    assert doc["records"][0]["seq"] == 3
    status, doc = writer.client.get("/log?after_watermark=0")
    assert status == 200
    assert doc["records"][0]["seq"] == 0
    # A watermark BETWEEN record boundaries is a 409 conflict — the
    # replica must re-seat from a boundary snapshot, not guess.
    status, doc = writer.client.get("/log?after_watermark=130")
    assert status == 409
    assert "boundary" in doc["error"]
    # Malformed cursors are 400s.
    status, _doc = writer.client.get("/log?after_seq=-2")
    assert status == 400
    status, _doc = writer.client.get("/log?after_seq=nope")
    assert status == 400


def test_log_endpoint_503_without_a_recording_frontdoor(writer):
    # A read-only replica (no front door) ships no log...
    rsrv = ArenaServer(num_players=PLAYERS, max_staleness_matches=0)
    rwire = ArenaHTTPServer(rsrv, frontdoor=None).start()
    rclient = WireClient(rwire.host, rwire.port)
    try:
        status, doc = rclient.get("/log?after_seq=-1")
        assert status == 503
        assert "read-only" in doc["error"]
        # ...and neither does a front door that is not recording.
        eng = ArenaEngine(PLAYERS)
        fd = FrontDoor(eng, capacity=8, record_applied=False)
        try:
            with pytest.raises(Exception, match="record_applied"):
                fd.log_segment()
        finally:
            fd.close()
            eng.shutdown()
    finally:
        rclient.close()
        rwire.close()
        rsrv.close()


# --- replica catch-up -------------------------------------------------------


def test_replica_catches_up_bit_exact_across_overload_sheds(writer, tmp_path):
    """The tentpole property over the wire: snapshot -> restore ->
    tail -> strict replay == writer, bit for bit, at equal watermark —
    with the log containing coalesced SUMMARY records from forced
    overload sheds on both sides of the snapshot cut."""
    fd = writer.frontdoor
    # Shed BEFORE the snapshot: pause the apply path, overflow the
    # 64-slot buffer, resume — the oldest batches coalesce into one
    # summary record that lands in the log.
    fd.pause()
    for _ in range(70):
        w, l = make_batch(writer.rng)
        fd.submit(w, l, producer="burst")
    fd.resume()
    fd.flush()
    assert fd.shed_batches > 0
    writer.feed(5)
    snap = tmp_path / "base"
    writer.srv.snapshot(snap)
    snap_watermark = writer.srv.engine.matches_applied

    wm_mid = writer.feed(5)
    rsrv, reader = make_replica(snap, writer.wire.host, writer.wire.port)
    assert reader.watermark() == snap_watermark  # restored, not replayed
    reader.start()
    try:
        reader.wait_for_watermark(wm_mid)
        # Shed AFTER the replica is already tailing.
        fd.pause()
        for _ in range(70):
            w, l = make_batch(writer.rng)
            fd.submit(w, l, producer="burst2")
        fd.resume()
        fd.flush()
        wm_end = writer.feed(3)
        reader.wait_for_watermark(wm_end)

        w_ratings, w_mark = writer.srv.engine.ratings_snapshot()
        r_ratings, r_mark = rsrv.engine.ratings_snapshot()
        assert w_mark == r_mark == wm_end
        np.testing.assert_array_equal(
            np.asarray(w_ratings), np.asarray(r_ratings)
        )
        # The replayed records include summary kinds, and the replica's
        # retained log replays to the same ratings synchronously.
        kinds = {kind for _seq, kind, _w, _l, _wm in reader.records}
        assert "summary" in kinds and "batch" in kinds
        np.testing.assert_array_equal(
            np.asarray(w_ratings), replay_sync(fd, wm_end)
        )
        # The replica SERVES what it replayed, read-only.
        rwire = ArenaHTTPServer(rsrv, frontdoor=None).start()
        rclient = WireClient(rwire.host, rwire.port)
        try:
            _s, board = rclient.get("/leaderboard?offset=0&limit=10")
            _s, wboard = writer.client.get("/leaderboard?offset=0&limit=10")
            assert board["leaderboard"] == wboard["leaderboard"]
            status, _doc = rclient.post(
                "/submit", {"winners": [1], "losers": [2], "producer": "x"}
            )
            assert status == 503  # replicas take no writes
        finally:
            rclient.close()
            rwire.close()
    finally:
        reader.close()
        rsrv.close()


def test_replica_refuses_out_of_sequence_and_diverged_records(writer):
    """Strict replay: arrival order is NOT apply order. A record that
    skips ahead, an unknown kind, and a record whose post-apply
    watermark disagrees with the writer's are each a distinct
    `ReplicaError` raised BEFORE the bad record can fork the replica.
    Named kill for the replica-applies-arrival-order-not-sequence-order
    mutant."""
    rsrv = ArenaServer(num_players=PLAYERS, max_staleness_matches=0)
    reader = ReplicaReader(rsrv, writer.wire.host, writer.wire.port)
    try:
        rec = {
            "seq": 0, "kind": "batch", "winners": [0, 1], "losers": [2, 3],
            "record_watermark": 2,
        }
        reader._apply_records([rec])
        assert reader.watermark() == 2 and reader.applied_seq() == 0
        # seq 2 after seq 0: a gap — refused, nothing applied.
        bad = dict(rec, seq=2, record_watermark=4)
        with pytest.raises(ReplicaError, match="out of sequence"):
            reader._apply_records([bad])
        assert reader.watermark() == 2
        with pytest.raises(ReplicaError, match="unknown log record kind"):
            reader._apply_records([dict(rec, seq=1, kind="mystery")])
        # A watermark cross-check failure is DIVERGENCE, not progress.
        with pytest.raises(ReplicaError, match="watermark diverged"):
            reader._apply_records([dict(rec, seq=1, record_watermark=99)])
    finally:
        reader.close()
        rsrv.close()


def test_segment_cursor_rejects_a_gapped_page(writer, monkeypatch):
    """The transport-level guard: a /log page whose records do not
    continue the cursor's sequence is an error at the CURSOR, before
    any record reaches an engine."""
    cursor = SegmentCursor(writer.wire.host, writer.wire.port)
    try:
        writer.feed(2)
        page = cursor.fetch()
        assert [r["seq"] for r in page] == [0, 1]
        gapped = {
            "records": [
                {"seq": 3, "kind": "batch", "winners": [0], "losers": [1],
                 "record_watermark": 120}
            ],
            "next_seq": 4, "log_len": 4, "base_watermark": 0, "watermark": 120,
        }
        monkeypatch.setattr(cursor._client, "get", lambda path: (200, gapped))
        with pytest.raises(ReplicaError, match="breaks the sequence"):
            cursor.fetch()
        # A non-200 answer is a named error too, not a None-deref.
        monkeypatch.setattr(
            cursor._client, "get", lambda path: (503, {"error": "nope"})
        )
        with pytest.raises(ReplicaError, match="answered 503"):
            cursor.fetch()
    finally:
        cursor.close()


def test_cursor_aligned_at_the_head_does_not_reship_history(writer):
    """A replica restored exactly at the writer's head gets an EMPTY
    alignment page — the cursor must adopt the writer's next_seq from
    it, not fall back to seq 0 on the next poll and re-ship history
    into the divergence check (found live by the replica bench)."""
    wm = writer.feed(3)
    cursor = SegmentCursor(
        writer.wire.host, writer.wire.port, start_watermark=wm
    )
    try:
        assert cursor.fetch() == []
        assert cursor.next_seq == 3
        writer.feed(1)
        page = cursor.fetch()
        assert [r["seq"] for r in page] == [3]
        assert page[0]["record_watermark"] == wm + 40
    finally:
        cursor.close()


def test_replica_staleness_slo_and_profiler_roles(writer, tmp_path):
    """start() registers the replica-staleness objective on the
    replica's own burn-rate engine and every tail poll EVALUATES it —
    the engine-side `evaluations` counter is the evidence (named kill
    for the staleness-slo-never-evaluated mutant). The tail/replay
    threads carry the profiler's replica roles."""
    writer.feed(4)
    snap = tmp_path / "snap"
    writer.srv.snapshot(snap)
    wm = writer.feed(2)
    rsrv, reader = make_replica(snap, writer.wire.host, writer.wire.port)
    robs = rsrv.obs
    assert "replica-staleness" not in [s.name for s in robs.slo.slos]
    reader.start()
    try:
        reader.wait_for_watermark(wm)
        assert "replica-staleness" in [s.name for s in robs.slo.slos]
        assert robs.slo.evaluations > 0, (
            "the staleness objective was registered but never evaluated"
        )
        # The staleness histogram took real observations.
        hist = robs.histogram("arena_replica_staleness_matches", base=1.0)
        assert hist.snapshot()["count"] > 0
        # /debug/slo on the REPLICA's wire surfaces the objective.
        rwire = ArenaHTTPServer(rsrv, frontdoor=None).start()
        rclient = WireClient(rwire.host, rwire.port)
        try:
            _s, doc = rclient.get("/debug/slo")
            assert "replica-staleness" in doc["objectives"]
        finally:
            rclient.close()
            rwire.close()
    finally:
        reader.close()
        rsrv.close()
    assert thread_role("arena-replica-tail") == "replica-tail"
    assert thread_role("arena-replica-replay-1") == "replica-replay"


# --- time travel ------------------------------------------------------------


def test_time_travel_reads_match_sync_replay(writer, tmp_path):
    """`?as_of=W` == a synchronous replay of the same log prefix: for
    every record boundary covered by a retained snapshot, the
    time-travel ratings equal the oracle's, the payload carries
    as_of/as_of_watermark, and the envelope watermark is the
    HISTORICAL one. Non-boundary as_of answers at the greatest
    boundary <= as_of; below-oldest-snapshot is a 404; the fastpath
    byte cache is bypassed in both directions."""
    writer.feed(4)
    snap1 = tmp_path / "s1"
    writer.srv.snapshot(snap1)
    wm1 = writer.srv.engine.matches_applied
    writer.feed(4)
    snap2 = tmp_path / "s2"
    writer.srv.snapshot(snap2, base=snap1)
    wm_end = writer.feed(3)

    index = TimeTravelIndex(
        writer.srv, writer.frontdoor, snapshots=[snap1, snap2]
    )
    writer.wire.time_travel = index
    hits = writer.obs.counter("arena_wire_cache_hits_total")
    misses = writer.obs.counter("arena_wire_cache_misses_total")
    cache_before = (hits.value, misses.value)

    for as_of in (wm1, wm1 + 40, wm_end):
        status, doc = writer.client.get(
            f"/leaderboard?offset=0&limit={PLAYERS}&as_of={as_of}"
        )
        assert status == 200
        assert doc["as_of"] == as_of
        assert doc["as_of_watermark"] <= as_of
        assert doc["watermark"] == doc["as_of_watermark"]
        oracle = replay_sync(writer.frontdoor, as_of)
        assert len(doc["leaderboard"]) == PLAYERS
        for row in doc["leaderboard"]:
            assert row["rating"] == float(oracle[row["player"]])
        # /player as-of agrees with the oracle row for that player.
        status, pdoc = writer.client.get(f"/player/3?as_of={as_of}")
        assert status == 200
        assert pdoc["players"][0]["rating"] == float(oracle[3])
    # A non-boundary as_of answers at the previous record boundary.
    status, doc = writer.client.get(
        f"/leaderboard?offset=0&limit=5&as_of={wm1 + 13}"
    )
    assert status == 200
    assert doc["as_of_watermark"] == wm1
    # Below the oldest retained snapshot: 404, with the envelope intact.
    status, doc = writer.client.get("/leaderboard?offset=0&limit=5&as_of=1")
    assert status == 404
    assert "watermark" in doc and "trace_id" in doc
    # as_of never fills or reads the byte cache — historical answers
    # must not evict (or masquerade as) live fastpath entries.
    assert (hits.value, misses.value) == cache_before
    # Without a configured index, as_of is a 503 (contract, not a 500).
    writer.wire.time_travel = None
    status, doc = writer.client.get("/leaderboard?offset=0&limit=5&as_of=40")
    assert status == 503


def test_time_travel_on_a_replica_uses_its_retained_log(writer, tmp_path):
    """The same index works on a REPLICA with the reader's retained
    records as the log source — historical reads answered entirely
    from shipped state."""
    writer.feed(3)
    snap = tmp_path / "snap"
    writer.srv.snapshot(snap)
    wm_snap = writer.srv.engine.matches_applied
    wm_end = writer.feed(3)

    rsrv, reader = make_replica(snap, writer.wire.host, writer.wire.port)
    reader.start()
    try:
        reader.wait_for_watermark(wm_end)
        index = TimeTravelIndex(rsrv, reader, snapshots=[snap])
        mid = wm_snap + 40  # one record past the snapshot boundary
        payload = index.leaderboard(0, PLAYERS, mid)
        assert payload["as_of_watermark"] == mid
        oracle = replay_sync(writer.frontdoor, mid)
        for row in payload["leaderboard"]:
            assert row["rating"] == float(oracle[row["player"]])
        # The replica's log_segment mirrors the front door's shape.
        records, next_seq, log_len, base = reader.log_segment(
            after_watermark=wm_snap
        )
        assert base == wm_snap
        assert log_len == len(reader.records)
        assert records[0][4] == wm_snap + 40
        with pytest.raises(ValueError, match="boundary"):
            reader.log_segment(after_watermark=wm_snap + 1)
    finally:
        reader.close()
        rsrv.close()
