"""Interprocedural determinism & effect-contract analyzer (jaxlint v5).

The `# deterministic` / `# pure-render(view)` comments on a def header
(see `arena.analysis.project.parse_contract`) declare the function's
effect contract. This module builds a PROJECT-WIDE effect-summary
table — per function: the `self` attributes it reads and writes, the
module globals it writes, and the nondeterministic sources whose
values flow into its results, branches, or state writes — then
propagates the summaries to a fixpoint over the call graph the symbol
table can resolve (same-class `self.m()` calls, same-module and
imported module functions). That closure is the upgrade over the
v3/v4 analyzers' one-hop resolution: a wall-clock read three helpers
deep still breaks a `# deterministic` promise at the annotated
function. Four rules run on the result:

- ``nondeterminism-in-deterministic-fn``: a `# deterministic`
  function's closure consumes wall-clock time, unseeded RNG,
  set/`popitem` iteration order, `id()`, `os.environ`, or thread
  identity — and the value flows into a return, a branch, a call
  argument, or a state write (a source whose value is discarded is
  not a finding).
- ``hidden-state-read-in-pure-render``: a `# pure-render(view)`
  function reads `self` state (or consumes a nondeterministic source)
  — its result must depend only on its parameters and the named
  immutable view, the exact precondition a `(page, watermark)`-keyed
  byte cache needs.
- ``check-then-act-race``: a `# guarded_by:` field is read into a
  local under its lock, the lock is released, and a later write (or a
  branch that drives writes) consumes the stale local without
  re-acquiring the lock and re-reading — path-sensitive over the
  PR 14 exception-edge CFG. This extends PR 10's lock discipline from
  "hold the lock" to "hold it atomically": every write in the racy
  shape can be individually lock-held and the interleaving still
  loses updates. Rebinding the local (the re-read-under-the-lock
  idiom) clears the stale fact — that IS the fix shape.
- ``undeclared-mutation-in-contract`` (warning): a contract-annotated
  function's closure writes state not listed in its optional
  `# mutates:` allowance — the contract documents the write set, so
  an undeclared write is either a bug or a stale annotation.

No-claim semantics, like everything in jaxlint: calls the table
cannot resolve (attribute receivers like `self._eng.ingest_async`,
dynamic dispatch) contribute nothing to the closure; a read reached
through a local alias is not a guarded-field read. Seeded randomness
(`jax.random` key-passing, `Random(seed)`, `default_rng(seed)`) is
deterministic and never flagged.
"""

from __future__ import annotations

import ast
import threading

from arena.analysis.cfg import K_STMT, build_cfg
from arena.analysis.jaxlint import rule
from arena.analysis.project import (
    LOCKED_SUFFIX,
    _self_attr_writes,
    dotted,
    make_lock_resolver,
    scan_function,
)

RULE_NONDET = "nondeterminism-in-deterministic-fn"
RULE_HIDDEN = "hidden-state-read-in-pure-render"
RULE_RACE = "check-then-act-race"
RULE_UNDECLARED = "undeclared-mutation-in-contract"

_RULE_NAMES = (RULE_NONDET, RULE_HIDDEN, RULE_RACE, RULE_UNDECLARED)

# Method tails whose call on `self.X` mutates the attribute in place —
# the write-effect spelling of `self.X.append(...)`. Deliberately NOT
# `release`/`stage`/`flush`: those are protocol verbs on owned
# objects, not container mutations of this object's state.
_MUTATOR_TAILS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "clear",
    "update", "extend", "insert", "remove", "discard", "setdefault",
    "sort", "reverse",
})

# --- nondeterministic sources ----------------------------------------------

_WALLCLOCK = frozenset({
    "time.time", "time.perf_counter", "time.monotonic", "time.time_ns",
    "time.perf_counter_ns", "time.monotonic_ns",
})
_THREAD_IDENT = frozenset({
    "threading.get_ident", "threading.current_thread",
    "threading.active_count",
})


def _nondet_call_label(fname: str, call: ast.Call) -> str | None:
    """Label when this resolved call name is a nondeterministic source,
    else None. Seeded constructions (`Random(7)`, `default_rng(0)`,
    `jax.random.*` key-passing) are deterministic by design."""
    if fname in _WALLCLOCK:
        return f"wall-clock `{fname}()`"
    parts = fname.split(".")
    tail = parts[-1]
    if tail in ("now", "utcnow") and "datetime" in parts:
        return f"wall-clock `{fname}()`"
    if fname in _THREAD_IDENT:
        return f"thread-identity `{fname}()`"
    if fname == "id":
        return "`id()` (address-dependent ordering)"
    if fname in ("os.getenv", "os.environ.get"):
        return f"`{fname}()` (environment-dependent)"
    if tail == "default_rng" and not call.args and not call.keywords:
        return f"unseeded `{fname}()`"
    if tail == "popitem":
        return "`.popitem()` iteration order"
    root = parts[0]
    if root == "random" and len(parts) > 1 and not tail[0].isupper():
        return f"unseeded RNG `{fname}()`"
    if (root in ("np", "numpy") and len(parts) > 2 and parts[1] == "random"
            and not tail[0].isupper() and tail != "default_rng"):
        return f"unseeded RNG `{fname}()`"
    return None


def _is_set_expr(node) -> bool:
    if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        return fname in ("set", "frozenset")
    return False


class _Summary:
    """One function's effect summary; also the closure record (the
    fixpoint merges summaries with `|`)."""

    __slots__ = ("self_reads", "self_writes", "global_writes", "nondet")

    def __init__(self, self_reads=frozenset(), self_writes=frozenset(),
                 global_writes=frozenset(), nondet=frozenset()):
        self.self_reads = self_reads
        self.self_writes = self_writes
        self.global_writes = global_writes
        self.nondet = nondet  # frozenset of (label, origin_key, lineno)

    def __or__(self, other):
        return _Summary(
            self.self_reads | other.self_reads,
            self.self_writes | other.self_writes,
            self.global_writes | other.global_writes,
            self.nondet | other.nondet,
        )

    def __eq__(self, other):
        return (self.self_reads == other.self_reads
                and self.self_writes == other.self_writes
                and self.global_writes == other.global_writes
                and self.nondet == other.nondet)


def _bound_names(tgt):
    """Plain local names a binding target (re)binds — tuples unpacked,
    attribute/subscript targets skipped (they mutate, not rebind)."""
    if isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _bound_names(elt)
    elif isinstance(tgt, ast.Starred):
        yield from _bound_names(tgt.value)
    elif isinstance(tgt, ast.Name):
        yield tgt.id


def _assign_targets(stmt):
    if isinstance(stmt, ast.Assign):
        return stmt.targets
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return [stmt.target]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target]
    if isinstance(stmt, ast.Delete):
        return stmt.targets
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.optional_vars for i in stmt.items
                if i.optional_vars is not None]
    return []


# --- per-function raw summaries --------------------------------------------


def _raw_summary(fn_node, origin_key, method_names):
    """(summary, callee keys) from one walk over the function INCLUDING
    nested def bodies (an inner `step` runs as part of the enclosing
    kernel — its effects are the enclosing function's effects).
    `method_names` filters method references out of self reads so
    `self.helper()` is a call edge, not a state read."""
    self_reads, self_writes, global_writes = set(), set(), set()
    callees = []
    sources = []  # (label, node)
    src_index = {}
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if (node.value.id == "self" and isinstance(node.ctx, ast.Load)
                    and node.attr not in method_names):
                self_reads.add(node.attr)
            if dotted(node) == "os.environ":
                src_index[id(node)] = len(sources)
                sources.append(("`os.environ` (environment-dependent)", node))
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            for attr, _tgt in _self_attr_writes(node):
                self_writes.add(attr)
        if isinstance(node, ast.Global):
            global_writes.update(node.names)
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname is not None:
                callees.append(fname)
                label = _nondet_call_label(fname, node)
                if label is not None:
                    src_index[id(node)] = len(sources)
                    sources.append((label, node))
                parts = fname.split(".")
                if (parts[0] == "self" and len(parts) == 3
                        and parts[2] in _MUTATOR_TAILS):
                    self_writes.add(parts[1])
    direct = set()  # source indices consumed regardless of data flow
    for node in ast.walk(fn_node):
        it = None
        if isinstance(node, (ast.For, ast.AsyncFor)):
            it = node.iter
        elif isinstance(node, ast.comprehension):
            it = node.iter
        if it is not None and _is_set_expr(it):
            src_index[id(it)] = len(sources)
            sources.append(("set iteration order", it))
            direct.add(len(sources) - 1)
    consumed = direct | _consumed_sources(fn_node, src_index, global_writes)
    nondet = frozenset(
        (label, origin_key, node.lineno)
        for i, (label, node) in enumerate(sources) if i in consumed
    )
    summary = _Summary(frozenset(self_reads), frozenset(self_writes),
                       frozenset(global_writes), nondet)
    return summary, callees


def _consumed_sources(fn_node, src_index, global_names):
    """Source indices whose VALUE flows into a sink: a return/yield, an
    if/while test, a call argument, or the RHS of a self-attribute or
    global write — via a small tainted-locals fixpoint. A source read
    and discarded is noise, not nondeterminism."""
    if not src_index:
        return set()
    taint = {}  # local name -> set of source indices

    def expr_sources(expr):
        out = set()
        for n in ast.walk(expr):
            idx = src_index.get(id(n))
            if idx is not None:
                out.add(idx)
            if isinstance(n, ast.Name) and n.id in taint:
                out |= taint[n.id]
        return out

    assigns = [
        n for n in ast.walk(fn_node)
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign))
        and n.value is not None
    ]
    changed = True
    while changed:
        changed = False
        for stmt in assigns:
            flowing = expr_sources(stmt.value)
            if not flowing:
                continue
            for tgt in _assign_targets(stmt):
                for name in _bound_names(tgt):
                    cur = taint.get(name, set())
                    if not flowing <= cur:
                        taint[name] = cur | flowing
                        changed = True
    consumed = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Return) and n.value is not None:
            consumed |= expr_sources(n.value)
        elif isinstance(n, (ast.Yield, ast.YieldFrom)) and n.value is not None:
            consumed |= expr_sources(n.value)
        elif isinstance(n, (ast.If, ast.While)):
            consumed |= expr_sources(n.test)
        elif isinstance(n, ast.Call):
            for a in n.args:
                consumed |= expr_sources(a)
            for kw in n.keywords:
                consumed |= expr_sources(kw.value)
        elif isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if n.value is None:
                continue
            keys = set()
            for tgt in _assign_targets(n):
                key = dotted(tgt)
                if key is not None:
                    keys.add(key)
            if any(k.startswith("self.") for k in keys) or any(
                k in global_names for k in keys
            ):
                consumed |= expr_sources(n.value)
    return consumed


# --- call-graph resolution + fixpoint --------------------------------------


def _resolve_callee(mod, cls_name, fname, project):
    """Global summary key (`module::qualname`) for a call spelled
    `fname` inside `mod` (method of `cls_name` when not None), or None
    when the table cannot resolve it — no claim, no edge."""
    parts = fname.split(".")
    if parts[0] == "self":
        if cls_name is not None and len(parts) == 2:
            cls = mod.classes.get(cls_name)
            if cls is not None and parts[1] in cls.methods:
                return f"{mod.name}::{cls_name}.{parts[1]}"
        return None
    if fname in mod.functions:
        return f"{mod.name}::{fname}"
    if project is None:
        return None
    for i in range(len(parts), 0, -1):
        head = ".".join(parts[:i])
        if head not in mod.imports:
            continue
        src_name, symbol = mod.imports[head]
        rest = parts[i:]
        if symbol is not None:
            rest = [symbol] + rest
        src = project.module(src_name)
        if src is None and rest:
            src = project.module(f"{src_name}.{rest[0]}")
            rest = rest[1:]
        if src is not None and len(rest) == 1 and rest[0] in src.functions:
            return f"{src.name}::{rest[0]}"
    return None


def _iter_module_functions(mod):
    """(qualname, fn_node, cls_name) over a module's registered
    functions and methods — the summary table's key space."""
    for fname, fn_node in mod.functions.items():
        yield fname, fn_node, None
    for cls in mod.classes.values():
        for mname, mnode in cls.methods.items():
            yield f"{cls.name}.{mname}", mnode, cls.name


def _build_summaries(mods, project):
    """key -> closure summary over every function the table registers,
    propagated to a fixpoint over the resolvable call edges."""
    raw, calls = {}, {}
    for mod in mods:
        for qualname, fn_node, cls_name in _iter_module_functions(mod):
            key = f"{mod.name}::{qualname}"
            methods = (set(mod.classes[cls_name].methods)
                       if cls_name is not None else frozenset())
            summary, callee_names = _raw_summary(fn_node, key, methods)
            raw[key] = summary
            edges = set()
            for fname in callee_names:
                target = _resolve_callee(mod, cls_name, fname, project)
                if target is not None and target != key:
                    edges.add(target)
            calls[key] = frozenset(edges)
    closure = dict(raw)
    changed = True
    while changed:  # to fixpoint: one call-graph hop per pass
        changed = False
        prev = dict(closure)
        for key in closure:
            merged = raw[key]
            for callee in calls[key]:
                if callee in prev:
                    merged = merged | prev[callee]
            if merged != closure[key]:
                closure[key] = merged
                changed = True
    return closure


_SUMMARY_CACHE_LOCK = threading.Lock()


def _project_summaries(ctx):
    """The project-wide closure table, computed once per ProjectTable
    and cached on it (lock-guarded: `--jobs` runs the per-module rule
    pass on a thread pool and every module shares this table)."""
    project = ctx.project
    if project is None:
        return _build_summaries([ctx.symbols], None)
    with _SUMMARY_CACHE_LOCK:
        cached = getattr(project, "_effects_summaries", None)
        if cached is None:
            cached = _build_summaries(list(project.modules.values()), project)
            project._effects_summaries = cached
        return cached


# --- the module pass -------------------------------------------------------


class _ModuleEffects:
    """One module's effect pass: contract checks against the project
    closure table + the path-sensitive check-then-act analysis,
    findings bucketed per rule."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.findings = {name: [] for name in _RULE_NAMES}
        self._seen = set()

    def run(self):
        summaries = _project_summaries(self.ctx)
        self._check_contracts(summaries)
        self._check_then_act()
        return self

    def _emit(self, rule_name, node, message):
        key = (rule_name, node.lineno, node.col_offset)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings[rule_name].append(
            self.ctx.finding(node, rule_name, message)
        )

    # -- contract checks ----------------------------------------------------

    def _contract_node(self, qualname):
        sym = self.ctx.symbols
        if "." in qualname:
            cls_name, mname = qualname.split(".", 1)
            cls = sym.classes.get(cls_name)
            if cls is not None and mname in cls.methods:
                return cls.methods[mname], cls
            return None, None
        return sym.functions.get(qualname), None

    def _check_contracts(self, summaries):
        sym = self.ctx.symbols
        for qualname in sorted(sym.contracts):
            contract = sym.contracts[qualname]
            fn_node, cls_sym = self._contract_node(qualname)
            if fn_node is None:
                continue
            closure = summaries.get(f"{sym.name}::{qualname}")
            if closure is None:
                continue
            if contract["deterministic"]:
                for label, origin, line in sorted(closure.nondet):
                    self._emit(
                        RULE_NONDET, fn_node,
                        f"`{qualname}` is declared `# deterministic` but its "
                        f"call-graph closure consumes {label} in `{origin}` "
                        f"(line {line}) — same inputs can produce different "
                        "outputs or state writes",
                    )
            view = contract["pure_render"]
            if view is not None:
                self._check_pure_render(qualname, fn_node, cls_sym, view,
                                        closure)
            undeclared = sorted(
                (closure.self_writes | closure.global_writes)
                - contract["mutates"]
            )
            if undeclared:
                names = ", ".join(f"`{n}`" for n in undeclared)
                self._emit(
                    RULE_UNDECLARED, fn_node,
                    f"`{qualname}`'s call-graph closure writes {names} not "
                    "listed in its `# mutates:` allowance — declare the "
                    "write set or stop writing it",
                )

    def _check_pure_render(self, qualname, fn_node, cls_sym, view, closure):
        args = fn_node.args
        params = {a.arg for a in (args.posonlyargs + args.args
                                  + args.kwonlyargs)}
        if args.vararg is not None:
            params.add(args.vararg.arg)
        if args.kwarg is not None:
            params.add(args.kwarg.arg)
        methods = set(cls_sym.methods) if cls_sym is not None else set()
        hidden_attrs = set()
        for node in ast.walk(fn_node):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            root = node.value.id
            if root == view or (root != "self" and root in params):
                # Reads through the named view or any other parameter
                # ARE the contract's declared inputs — never hidden.
                continue
            if root == "self" and node.attr not in methods:
                if node.attr not in hidden_attrs:
                    hidden_attrs.add(node.attr)
                    self._emit(
                        RULE_HIDDEN, node,
                        f"`{qualname}` is `# pure-render({view})` but reads "
                        f"hidden state `self.{node.attr}` — the render must "
                        f"depend only on its parameters and `{view}`, or a "
                        "byte cache keyed on the view serves stale pages",
                    )
        if view != "self":
            for attr in sorted(closure.self_reads - hidden_attrs):
                self._emit(
                    RULE_HIDDEN, fn_node,
                    f"`{qualname}` is `# pure-render({view})` but its "
                    f"call-graph closure reads hidden state `self.{attr}` — "
                    f"the render must depend only on its parameters and "
                    f"`{view}`",
                )
        for label, origin, line in sorted(closure.nondet):
            self._emit(
                RULE_HIDDEN, fn_node,
                f"`{qualname}` is `# pure-render({view})` but its closure "
                f"consumes {label} in `{origin}` (line {line}) — a "
                "nondeterministic render cannot be cached by view",
            )

    # -- check-then-act -----------------------------------------------------

    def _check_then_act(self):
        sym = self.ctx.symbols
        for cls in sym.classes.values():
            if not cls.guarded or not cls.lock_attrs:
                continue
            for mname, mnode in cls.methods.items():
                if mname == "__init__" or self.ctx.is_traced_def(mnode):
                    continue
                self._cta_function(cls, mnode)

    def _cta_function(self, cls, fn_node):
        sym = self.ctx.symbols
        resolver = make_lock_resolver(sym, cls)
        held0 = ()
        if fn_node.name.endswith(LOCKED_SUFFIX):
            held0 = tuple(sorted(cls.lock_ids()))
        _acquired, _edges, stmts = scan_function(fn_node, resolver, held0)
        held_by_stmt = {id(stmt): frozenset(held) for stmt, held in stmts}
        cfg = build_cfg(fn_node)

        def node_state(node, state):
            """Transfer: escalate escaped facts by this statement's
            held set, kill rebound locals, gen fresh guarded reads."""
            stmt = node.stmt
            if (node.kind != K_STMT or stmt is None
                    or not isinstance(stmt, ast.stmt)
                    or isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))):
                return state
            held = held_by_stmt.get(id(stmt), frozenset())
            facts = {
                (name, attr, lock, escaped or lock not in held)
                for name, attr, lock, escaped in state
            }
            rebound = set()
            for tgt in _assign_targets(stmt):
                rebound.update(_bound_names(tgt))
            if rebound:
                # Rebinding is the re-check credit: a fresh read under
                # a re-acquired lock replaces the stale fact entirely.
                facts = {f for f in facts if f[0] not in rebound}
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                value = dotted(stmt.value)
                if (value is not None and value.startswith("self.")
                        and value.count(".") == 1):
                    attr = value.split(".", 1)[1]
                    lockname = cls.guarded.get(attr)
                    if lockname is not None:
                        lock_id = f"{sym.name}.{cls.name}.{lockname}"
                        if lock_id in held:
                            facts.add((stmt.targets[0].id, attr, lock_id,
                                       False))
            return frozenset(facts)

        in_states = [None] * len(cfg.nodes)
        in_states[cfg.entry_idx] = frozenset()
        work = [cfg.entry_idx]
        while work:
            idx = work.pop()
            out = node_state(cfg.nodes[idx], in_states[idx])
            for succ, _kind in cfg.nodes[idx].succs:
                prev = in_states[succ]
                merged = out if prev is None else prev | out
                if merged != prev:
                    in_states[succ] = merged
                    work.append(succ)
        self._cta_report(cls, fn_node, cfg, in_states, held_by_stmt)

    def _cta_report(self, cls, fn_node, cfg, in_states, held_by_stmt):
        reported = set()
        for node in cfg.nodes:
            stmt = node.stmt
            state = in_states[node.idx]
            if (state is None or node.kind != K_STMT or stmt is None
                    or not isinstance(stmt, ast.stmt)
                    or id(stmt) in reported):
                continue
            held = held_by_stmt.get(id(stmt), frozenset())
            stale = {
                name: (attr, lock)
                for name, attr, lock, escaped in state
                if escaped or lock not in held
            }
            if not stale:
                continue
            consumed = self._cta_consumption(cls, stmt, stale)
            if consumed is None:
                continue
            name, attr, verb = consumed
            reported.add(id(stmt))
            lockname = cls.guarded[attr]
            self._emit(
                RULE_RACE, stmt,
                f"`{name}` was read from `self.{attr}` under "
                f"`self.{lockname}` but the lock was released before this "
                f"{verb} consumes it — re-acquire `self.{lockname}` and "
                f"re-read `self.{attr}` (the check and the act must share "
                "one critical section)",
            )

    def _cta_consumption(self, cls, stmt, stale):
        """(local, attr, verb) when this statement acts on a stale
        guarded read: a self-state write whose RHS reads it, or an
        if/while whose test reads it and whose body writes self state
        or calls a same-class method."""
        if (isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign))
                and stmt.value is not None and _self_attr_writes(stmt)):
            for n in ast.walk(stmt.value):
                if isinstance(n, ast.Name) and n.id in stale:
                    return n.id, stale[n.id][0], "write"
        if isinstance(stmt, (ast.If, ast.While)):
            hit = None
            for n in ast.walk(stmt.test):
                if isinstance(n, ast.Name) and n.id in stale:
                    hit = n.id
                    break
            if hit is None:
                return None
            for body_stmt in stmt.body + stmt.orelse:
                for sub in ast.walk(body_stmt):
                    if (isinstance(sub, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign))
                            and _self_attr_writes(sub)):
                        return hit, stale[hit][0], "branch"
                    if isinstance(sub, ast.Call):
                        fname = dotted(sub.func)
                        if fname is None or not fname.startswith("self."):
                            continue
                        parts = fname.split(".")
                        if (len(parts) == 2 and parts[1] in cls.methods) or (
                            len(parts) == 3 and parts[2] in _MUTATOR_TAILS
                        ):
                            return hit, stale[hit][0], "branch"
        return None


def _analysis(ctx):
    cached = getattr(ctx, "_effects_findings", None)
    if cached is None:
        cached = _ModuleEffects(ctx).run().findings
        ctx._effects_findings = cached
    return cached


# --- the four v5 rules -------------------------------------------------------


@rule(
    RULE_NONDET,
    "a `# deterministic` function's call-graph closure consumes wall-clock, "
    "unseeded RNG, set/popitem ordering, id(), os.environ, or thread "
    "identity and lets the value flow into results or state writes",
    severity="error",
)
def _check_nondet_contract(ctx):
    yield from _analysis(ctx)[RULE_NONDET]


@rule(
    RULE_HIDDEN,
    "a `# pure-render(view)` function reads self state (or consumes a "
    "nondeterministic source) — the render must be a pure function of its "
    "parameters and the named immutable view, or view-keyed caching breaks",
    severity="error",
)
def _check_hidden_state_read(ctx):
    yield from _analysis(ctx)[RULE_HIDDEN]


@rule(
    RULE_RACE,
    "a `# guarded_by:` field read under its lock, released, then consumed "
    "by a write or write-driving branch without re-acquiring and re-reading "
    "— the check and the act must share one critical section",
    severity="error",
)
def _check_check_then_act(ctx):
    yield from _analysis(ctx)[RULE_RACE]


@rule(
    RULE_UNDECLARED,
    "a contract-annotated function's closure writes state not listed in its "
    "`# mutates:` allowance — declare the write set or stop writing it",
    severity="warning",
)
def _check_undeclared_mutation(ctx):
    yield from _analysis(ctx)[RULE_UNDECLARED]
