"""The matchmaking plane (arena/match/): policy math against a numpy
oracle, watermark-seeded determinism, tenant scoping, the wire-match
envelope, and the degenerate rosters.

Two mutation-audit kills are named here:
`test_pair_components_matches_numpy_oracle` (proposal-ignores-CI-width
— drop the bootstrap widths from the effective-uncertainty blend and
the overlap surface detaches from the oracle) and
`test_match_envelope_watermark_is_the_views`
(match-envelope-omits-watermark — rename the payload's watermark and
the envelope silently falls back to the LIVE counter, claiming
freshness the proposing view does not have).
"""

import numpy as np
import pytest

from arena import match as match_mod
from arena.match import (
    EXPLORATION_FLOOR,
    MAX_PROPOSALS,
    POLICIES,
    Matchmaker,
    pair_components,
    propose_pairs,
)
from arena.net import ArenaHTTPServer, FrontDoor, WireClient
from arena.obs import Observability
from arena.serving import ArenaServer
from arena.tenancy import MultiTenantEngine

P = 40


@pytest.fixture(scope="module")
def stack():
    obs = Observability()
    srv = ArenaServer(num_players=P, max_staleness_matches=0, obs=obs)
    rng = np.random.default_rng(7)
    a = rng.integers(0, P, 600).astype(np.int32)
    b = ((a + 1 + rng.integers(0, P - 1, 600)) % P).astype(np.int32)
    srv.engine.ingest(a, b)
    srv.refresh_intervals(num_rounds=8, seed=0)
    frontdoor = FrontDoor(srv.engine, capacity=16)
    matchmaker = Matchmaker(srv)
    server = ArenaHTTPServer(
        srv, frontdoor=frontdoor, matchmaker=matchmaker
    ).start()
    client = WireClient(server.host, server.port)
    yield server, client, matchmaker
    client.close()
    server.close()
    matchmaker.close()
    frontdoor.close()
    srv.close()


# --- the scoring kernel vs a numpy oracle ----------------------------------


def test_pair_components_matches_numpy_oracle():
    """Every (B, B) ingredient the policies rank by, recomputed in
    plain numpy. The named kill for proposal-ignores-CI-width: the
    effective uncertainty MUST blend the bootstrap widths with the
    count-decaying prior — `widths + scale/(1+counts)` — or wide-CI
    players stop outranking settled ones and the overlap surface
    drifts off this oracle."""
    rng = np.random.default_rng(3)
    n, scale = 24, 400.0
    r = rng.normal(1500.0, 120.0, n).astype(np.float32)
    w = rng.uniform(0.0, 80.0, n).astype(np.float32)
    c = rng.integers(0, 30, n).astype(np.float32)
    p, info, width, overlap, bonus = (
        np.asarray(m) for m in pair_components(r, w, c, scale=scale)
    )
    r64 = r.astype(np.float64)
    want_p = 1.0 / (1.0 + 10.0 ** ((r64[None, :] - r64[:, None]) / scale))
    assert np.allclose(p, want_p, atol=1e-5)
    assert np.allclose(info, 4.0 * p * (1.0 - p), atol=1e-6)
    eff = w + scale / (1.0 + c)
    want_width = eff[:, None] + eff[None, :]
    assert np.allclose(width, want_width, rtol=1e-5)
    gap = np.abs(r64[:, None] - r64[None, :])
    assert np.allclose(
        overlap, np.maximum(want_width / 2.0 - gap, 0.0), rtol=1e-4,
        atol=1e-3,
    )
    total = np.log1p(c.sum())
    assert np.allclose(
        bonus, np.sqrt(total / (c[:, None] + c[None, :] + 1.0)), rtol=1e-5
    )
    # The prior is the whole story for an unplayed player: zero
    # bootstrap width, zero matches -> it must carry the LARGEST
    # effective uncertainty on the board.
    w2 = w.copy()
    w2[0], c2 = 0.0, c.copy()
    c2[0] = 0.0
    _, _, width2, _, _ = (
        np.asarray(m) for m in pair_components(r, w2, c2, scale=scale)
    )
    eff2 = np.diag(width2) / 2.0
    assert eff2[0] == eff2.max()


def test_fair_policy_concentrates_on_even_matches(stack):
    """`fair` minimizes pairwise win-prob skew: its proposals' mean
    |p - 0.5| sits well under the all-pairs mean, and no player is
    proposed twice before every player has appeared once (the
    matching-round constraint)."""
    server, _client, matchmaker = stack
    view, _stale, _pol, rows = matchmaker.propose(8, policy="fair")
    skews = [abs(p - 0.5) for _a, _b, p, _s in rows]
    r = np.asarray(view.ratings, np.float64)
    scale = float(server.server.engine.scale)
    all_p = 1.0 / (1.0 + 10.0 ** ((r[None, :] - r[:, None]) / scale))
    iu, ju = np.triu_indices(P, k=1)
    assert np.mean(skews) < np.mean(np.abs(all_p[iu, ju] - 0.5))
    players = [x for a, b, _p, _s in rows for x in (a, b)]
    assert len(players) == len(set(players)), "a player proposed twice"


def test_policies_are_deterministic_at_a_fixed_watermark(stack):
    """The `# deterministic` contract over the full policy surface:
    at one view (one watermark) the same request replays bit-equal,
    for every policy — the RNG is derived, not ambient."""
    _server, client, matchmaker = stack
    for policy in POLICIES:
        _v, _s, _p, first = matchmaker.propose(6, policy=policy)
        _v, _s, _p, again = matchmaker.propose(6, policy=policy)
        assert first == again, policy
        status, r1 = client.propose_matches(6, policy=policy)
        assert status == 200
        status, r2 = client.propose_matches(6, policy=policy)
        assert r1["proposals"] == r2["proposals"], policy
    # ... and the watermark is the seed: advancing it reshuffles the
    # stochastic policies.
    _v, _s, _p, before = matchmaker.propose(10, policy="random")
    server = _server.server
    server.engine.ingest(
        np.arange(10, dtype=np.int32), np.arange(10, 20, dtype=np.int32)
    )
    _v, _s, _p, after = matchmaker.propose(10, policy="random")
    assert before != after


def test_active_scores_rank_overlapping_uncertain_pairs_first(stack):
    """The active policy's rows carry the CI-overlap score it ranked
    by (plus the Boltzmann floor's guarantee: scores are finite and
    non-negative), and proposals respect the matching-round bound."""
    _server, _client, matchmaker = stack
    _v, _s, _p, rows = matchmaker.propose(8, policy="active")
    assert rows
    for a, b, p, score in rows:
        assert 0 <= a < P and 0 <= b < P and a != b
        assert 0.0 < p < 1.0
        assert score >= 0.0
    assert EXPLORATION_FLOOR > 0.0


def test_tenant_scoping_proposes_tenant_local_pairs():
    """`?tenant=` scopes proposals to one tenant's roster: ids are
    tenant-local, win probs come from that tenant's ratings slice, and
    an out-of-range tenant is the standard 400 reject."""
    obs = Observability()
    eng = MultiTenantEngine(16, num_tenants=3, min_bucket=64, obs=obs)
    srv = ArenaServer(engine=eng, max_staleness_matches=0, obs=obs)
    matchmaker = Matchmaker(srv)
    server = ArenaHTTPServer(srv, matchmaker=matchmaker).start()
    client = WireClient(server.host, server.port)
    try:
        rng = np.random.default_rng(1)
        for t in range(3):
            a = rng.integers(0, 16, 80).astype(np.int32)
            b = ((a + 1 + rng.integers(0, 15, 80)) % 16).astype(np.int32)
            eng.ingest(a, b, tenant=t)
        status, resp = client.propose_matches(5, tenant=1)
        assert status == 200 and resp["tenant"] == 1
        assert resp["proposals"]
        view, _stale = srv._serve_view()
        scale = float(eng.scale)
        r = np.asarray(view.ratings, np.float64)
        for row in resp["proposals"]:
            a, b = row["a"], row["b"]
            assert 0 <= a < 16 and 0 <= b < 16
            ra, rb = r[16 + a], r[16 + b]
            want = 1.0 / (1.0 + 10.0 ** ((rb - ra) / scale))
            assert row["p_a_beats_b"] == pytest.approx(want, abs=1e-5)
        # Tenant streams are independent: same watermark, same n, but
        # tenant-salted RNG -> scoped proposals differ from global.
        status, global_resp = client.propose_matches(5)
        assert "tenant" not in global_resp
        status, resp2 = client.propose_matches(5, tenant=2)
        assert resp2["proposals"] != resp["proposals"]
        for bad in (3, -1):
            status, err = client.propose_matches(5, tenant=bad)
            assert status == 400 and "unknown tenant" in err["error"]
    finally:
        client.close()
        server.close()
        matchmaker.close()
        srv.close()


# --- degenerate rosters and request bounds ---------------------------------


def test_degenerate_rosters_and_bounds():
    # One player: no admissible pair, not an error. (The engine itself
    # refuses a 1-player arena, so this exercises the pure function on
    # a 1-player view — the tenant-scoped shape a 1-player tenant
    # would produce.)
    class _OnePlayerView:
        ratings = np.zeros(1, np.float32)

    assert propose_pairs(_OnePlayerView(), 4, "active", pair_fn=None) == []
    obs = Observability()
    srv = ArenaServer(num_players=2, max_staleness_matches=0, obs=obs)
    matchmaker = Matchmaker(srv)
    try:
        # n=0 is a valid no-op request.
        assert matchmaker.propose(0)[3] == []
        with pytest.raises(ValueError, match=">= 0"):
            matchmaker.propose(-1)
        with pytest.raises(ValueError, match=str(MAX_PROPOSALS)):
            matchmaker.propose(MAX_PROPOSALS + 1)
        with pytest.raises(ValueError, match="unknown match policy"):
            matchmaker.propose(2, policy="bogus")
    finally:
        matchmaker.close()
        srv.close()


def test_all_equal_cis_still_propose_distinct_pairs():
    """Before any interval refresh every CI is equally unknown (the
    uniform-width degenerate case): active must still produce n
    distinct, round-constrained pairs instead of collapsing onto one
    argmax pair."""
    obs = Observability()
    srv = ArenaServer(num_players=12, max_staleness_matches=0, obs=obs)
    matchmaker = Matchmaker(srv)
    try:
        srv.engine.ingest(
            np.arange(6, dtype=np.int32), np.arange(6, 12, dtype=np.int32)
        )
        view, _ = srv._serve_view()
        assert view.lo is None  # intervals never refreshed
        _v, _s, _p, rows = matchmaker.propose(6, policy="active")
        assert len(rows) == 6
        pairs = {(a, b) for a, b, _p2, _s2 in rows}
        assert len(pairs) == 6
        players = [x for a, b, _p2, _s2 in rows for x in (a, b)]
        assert len(players) == len(set(players))
    finally:
        matchmaker.close()
        srv.close()


# --- the wire surface ------------------------------------------------------


def test_match_envelope_watermark_is_the_views():
    """The named kill for match-envelope-omits-watermark: the /match
    envelope's watermark is the PROPOSING view's, not the live
    counter. With a staleness allowance the two diverge — rename the
    payload key and `make_response` silently falls back to
    `matches_applied`, stamping proposals with freshness they were
    never ranked at."""
    obs = Observability()
    srv = ArenaServer(num_players=16, max_staleness_matches=10_000, obs=obs)
    matchmaker = Matchmaker(srv)
    server = ArenaHTTPServer(srv, matchmaker=matchmaker).start()
    client = WireClient(server.host, server.port)
    try:
        srv.engine.ingest(
            np.arange(8, dtype=np.int32), np.arange(8, 16, dtype=np.int32)
        )
        view, _ = srv._serve_view()  # pin the view at watermark 8
        srv.engine.ingest(
            np.arange(8, dtype=np.int32), np.arange(8, 16, dtype=np.int32)
        )
        assert srv.engine.matches_applied == 16
        status, resp = client.propose_matches(3)
        assert status == 200
        # The envelope watermark is the VIEW's (8), not the live
        # counter (16) `make_response` would fall back to if the
        # payload dropped its watermark.
        assert resp["watermark"] == view.watermark == 8
        assert resp["watermark"] != srv.engine.matches_applied
        # Every other header field is view-stable too.
        assert resp["matches_ingested"] == 8
        assert resp["staleness"] == 0
    finally:
        client.close()
        server.close()
        matchmaker.close()
        srv.close()


def test_match_counters_slo_and_presence(stack):
    """The ops surface: request/proposal counters reconcile with the
    traffic, the `match-proposal-latency` SLO objective is registered
    on the server's burn-rate engine, /healthz and stats()["net"]
    carry the presence bit, and close() drops it."""
    server, client, matchmaker = stack
    srv = server.server
    net = srv.stats()["net"]["matchmaker"]
    req0, prop0 = net["requests"], net["proposals"]
    status, resp = client.propose_matches(4)
    assert status == 200 and len(resp["proposals"]) == 4
    net = srv.stats()["net"]["matchmaker"]
    assert net["present"] is True
    assert net["requests"] == req0 + 1
    assert net["proposals"] == prop0 + 4
    assert "match-proposal-latency" in srv.obs.slo.evaluate()["objectives"]
    _status, health = client.get("/healthz")
    assert health["matchmaker"] is True
    # A second matchmaker on the same server must not double-register
    # the SLO objective.
    extra = Matchmaker(srv)
    extra.close()
    # close() drops the presence gauge (stats), tested on `extra` so
    # the shared fixture keeps serving.
    assert srv.stats()["net"]["matchmaker"]["present"] is False
    matchmaker._g_present.set(1)  # restore the fixture's bit


def test_match_503_without_matchmaker_and_thread_front_end_parity():
    """A server with no matchmaker 503s /match but serves everything
    else; the legacy threaded front end serves /match through the same
    dispatch — same watermark, same proposals, bit-equal."""
    obs = Observability()
    srv = ArenaServer(num_players=12, max_staleness_matches=0, obs=obs)
    srv.engine.ingest(
        np.arange(6, dtype=np.int32), np.arange(6, 12, dtype=np.int32)
    )
    bare = ArenaHTTPServer(srv).start()
    bare_client = WireClient(bare.host, bare.port)
    try:
        status, resp = bare_client.propose_matches(2)
        assert status == 503 and "no matchmaker" in resp["error"]
        status, _health = bare_client.get("/healthz")
        assert _health["matchmaker"] is False
    finally:
        bare_client.close()
        bare.close()
    matchmaker = Matchmaker(srv)
    fast = ArenaHTTPServer(srv, matchmaker=matchmaker).start()
    threaded = ArenaHTTPServer(
        srv, matchmaker=matchmaker, fastpath_reads=False
    ).start()
    c_fast = WireClient(fast.host, fast.port)
    c_thread = WireClient(threaded.host, threaded.port)
    try:
        s1, r1 = c_fast.propose_matches(4, policy="ucb")
        s2, r2 = c_thread.propose_matches(4, policy="ucb")
        assert s1 == s2 == 200
        assert r1["proposals"] == r2["proposals"]
        assert r1["watermark"] == r2["watermark"]
        status, err = c_fast.propose_matches(4, policy="bogus")
        assert status == 400 and "unknown match policy" in err["error"]
    finally:
        c_fast.close()
        c_thread.close()
        fast.close()
        threaded.close()
        matchmaker.close()
        srv.close()


def test_epsilon_policy_mixes_but_replays(stack):
    """epsilon-greedy at epsilon=1.0 replaces every slot with its
    exploration draw — still watermark-seeded, still replayable."""
    server, _client, _matchmaker = stack
    view, _ = server.server._serve_view()
    mm_pair = _matchmaker._pair_fn
    rows1 = propose_pairs(view, 8, "epsilon", mm_pair, epsilon=1.0)
    rows2 = propose_pairs(view, 8, "epsilon", mm_pair, epsilon=1.0)
    assert rows1 == rows2
    assert len(rows1) == 8
    greedy = propose_pairs(view, 8, "epsilon", mm_pair, epsilon=0.0)
    assert greedy != rows1
