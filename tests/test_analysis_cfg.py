"""Properties of the jaxlint v4 exception-edge CFG (arena/analysis/cfg.py).

Two classes of pin:

- TOTALITY over the real tree: every raise-capable statement node in
  every function of arena/, tests/, and bench.py carries an exception
  successor, and every graph is well-formed (no dangling indices, no
  stuck non-terminal nodes). This is the property the
  exception-edge-dropped-from-cfg mutant breaks.
- SHAPE on synthetic functions: finally duplication dominating both
  edge kinds, with-unwind on the body's exceptional path, break/
  continue/return routed through enclosing finally copies, handler
  dispatch fanning with an unmatched path unless a catch-all exists.

Imports `arena.analysis.cfg` directly (stdlib-only, never touches jax).
"""

import ast
import pathlib

from arena.analysis.cfg import (
    EDGE_EXC,
    EDGE_NORMAL,
    K_STMT,
    K_WITH_UNWIND,
    build_cfg,
    stmt_can_raise,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _functions_of(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _iter_repo_functions():
    paths = [REPO / "bench.py"]
    for sub in ("arena", "tests"):
        paths.extend(sorted((REPO / sub).rglob("*.py")))
    for path in paths:
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:  # badcorpus keeps only parseable files today,
            continue  # but the CFG suite must not depend on that
        for fn in _functions_of(tree):
            yield path, fn


def _fn(src, name=None):
    for node in _functions_of(ast.parse(src)):
        if name is None or node.name == name:
            return node
    raise AssertionError(f"no function {name!r} in source")


def _reachable_avoiding(cfg, start, blocked):
    """Node set reachable from `start` along paths that never enter a
    node in `blocked` — the dominance probe: if the exits drop out of
    this set, every path runs one of the blocked nodes."""
    seen, stack = set(), [start]
    while stack:
        idx = stack.pop()
        if idx in seen or idx in blocked:
            continue
        seen.add(idx)
        stack.extend(succ for succ, _kind in cfg.nodes[idx].succs)
    return seen


# --- totality over the real tree ------------------------------------------


def test_every_raise_capable_statement_has_an_exception_successor():
    """THE property: no raise-capable statement is silently treated as
    safe anywhere in the repo. Counted, so the sweep cannot go vacuous
    if the walk breaks."""
    checked = 0
    for path, fn in _iter_repo_functions():
        cfg = build_cfg(fn)
        for node in cfg.nodes:
            if node.kind == K_STMT and node.raise_capable:
                kinds = {kind for _succ, kind in node.succs}
                assert EDGE_EXC in kinds, (
                    f"{path}:{getattr(node.stmt, 'lineno', '?')}: "
                    f"raise-capable statement with no exception successor"
                )
                checked += 1
    assert checked > 1000, f"sweep went vacuous ({checked} nodes checked)"


def test_cfgs_are_well_formed_over_the_real_tree():
    """Every edge lands on a real node with a known kind; every
    non-terminal node can go somewhere (no stuck states for the
    typestate worklist to lose obligations in)."""
    for path, fn in _iter_repo_functions():
        cfg = build_cfg(fn)
        terminal = {cfg.exit_idx, cfg.raise_idx}
        assert cfg.nodes[cfg.entry_idx].succs
        for node in cfg.nodes:
            for succ, kind in node.succs:
                assert 0 <= succ < len(cfg.nodes)
                assert kind in (EDGE_NORMAL, EDGE_EXC)
            if node.idx not in terminal:
                assert node.succs, (
                    f"{path}:{fn.name}: stuck node {node!r}"
                )


def test_raise_capability_is_syntactic_and_conservative():
    assert stmt_can_raise(ast.parse("x = f()").body[0])
    assert stmt_can_raise(ast.parse("x = d[k]").body[0])
    assert stmt_can_raise(ast.parse("x = a + b").body[0])
    assert stmt_can_raise(ast.parse("raise ValueError").body[0])
    assert stmt_can_raise(ast.parse("assert x").body[0])
    assert stmt_can_raise(ast.parse("for i in xs:\n    pass").body[0])
    assert stmt_can_raise(ast.parse("with cm:\n    pass").body[0])
    # Plain reads/binds are deemed safe — the heuristic the clean tree
    # relies on (headers only: the compound bodies are separate nodes).
    assert not stmt_can_raise(ast.parse("x = y").body[0])
    assert not stmt_can_raise(ast.parse("pass").body[0])
    assert not stmt_can_raise(ast.parse("if x:\n    y = f()").body[0])


# --- finally: duplication dominating both edge kinds ----------------------


def test_finally_dominates_both_normal_and_exceptional_exits():
    src = (
        "def f(res, wire):\n"
        "    try:\n"
        "        wire.send()\n"
        "    finally:\n"
        "        res.release()\n"
    )
    fn = _fn(src)
    cfg = build_cfg(fn)
    send = cfg.stmt_nodes(fn.body[0].body[0])[0]
    release_idxs = {n.idx for n in cfg.stmt_nodes(fn.body[0].finalbody[0])}
    assert len(release_idxs) >= 2  # one copy per continuation
    # The exceptional and the normal successor each reach a release copy...
    exc = {s for s, k in send.succs if k == EDGE_EXC}
    norm = {s for s, k in send.succs if k == EDGE_NORMAL}
    assert exc and norm
    assert all(release_idxs & cfg.reachable_from(s) for s in exc | norm)
    # ...and NO path from entry reaches either exit without running one:
    # the finally dominates both edge kinds.
    reach = _reachable_avoiding(cfg, cfg.entry_idx, release_idxs)
    assert cfg.exit_idx not in reach
    assert cfg.raise_idx not in reach


def test_return_routes_through_the_finally_copy():
    src = (
        "def f(res):\n"
        "    try:\n"
        "        return res.value()\n"
        "    finally:\n"
        "        res.release()\n"
    )
    fn = _fn(src)
    cfg = build_cfg(fn)
    ret = next(n for n in cfg.nodes if isinstance(n.stmt, ast.Return))
    release_idxs = {n.idx for n in cfg.stmt_nodes(fn.body[0].finalbody[0])}
    norm = {s for s, k in ret.succs if k == EDGE_NORMAL}
    assert norm and norm <= release_idxs  # return enters the finally first
    assert cfg.exit_idx in cfg.reachable_from(next(iter(norm)))


def test_break_and_continue_route_through_enclosing_finally():
    src = (
        "def f(items, res):\n"
        "    for it in items:\n"
        "        try:\n"
        "            if it:\n"
        "                break\n"
        "            continue\n"
        "        finally:\n"
        "            res.note()\n"
        "    return res\n"
    )
    fn = _fn(src)
    cfg = build_cfg(fn)
    for_stmt = fn.body[0]
    note_idxs = {
        n.idx for n in cfg.stmt_nodes(for_stmt.body[0].finalbody[0])
    }
    brk = next(n for n in cfg.nodes if isinstance(n.stmt, ast.Break))
    cont = next(n for n in cfg.nodes if isinstance(n.stmt, ast.Continue))
    brk_norm = {s for s, k in brk.succs if k == EDGE_NORMAL}
    cont_norm = {s for s, k in cont.succs if k == EDGE_NORMAL}
    assert brk_norm and brk_norm <= note_idxs
    assert cont_norm and cont_norm <= note_idxs
    # Distinct continuations get distinct finally copies, and continue's
    # copy flows back to the loop header while break's does not.
    assert brk_norm != cont_norm
    header_idx = cfg.stmt_nodes(for_stmt)[0].idx
    assert header_idx in cfg.reachable_from(next(iter(cont_norm)))


# --- with: unwind node on the exceptional path ----------------------------


def test_with_unwind_sits_on_the_body_exception_path():
    src = (
        "def f(lock, wire):\n"
        "    with lock:\n"
        "        wire.send()\n"
    )
    fn = _fn(src)
    cfg = build_cfg(fn)
    unwinds = [n for n in cfg.nodes if n.kind == K_WITH_UNWIND]
    assert len(unwinds) == 1  # __exit__-on-unwind is modeled exactly once
    send = cfg.stmt_nodes(fn.body[0].body[0])[0]
    assert (unwinds[0].idx, EDGE_EXC) in send.succs
    assert (cfg.raise_idx, EDGE_EXC) in unwinds[0].succs


# --- try/except dispatch --------------------------------------------------


def test_uncaught_raise_reaches_only_the_raise_exit():
    src = "def f():\n    raise ValueError('boom')\n"
    cfg = build_cfg(_fn(src))
    r = next(n for n in cfg.nodes if isinstance(n.stmt, ast.Raise))
    assert r.succs == [(cfg.raise_idx, EDGE_EXC)]


def test_handler_dispatch_fans_out_with_unmatched_path():
    src = (
        "def f(wire):\n"
        "    try:\n"
        "        wire.send()\n"
        "    except KeyError:\n"
        "        return 1\n"
        "    except ValueError:\n"
        "        return 2\n"
        "    return 0\n"
    )
    fn = _fn(src)
    cfg = build_cfg(fn)
    send = cfg.stmt_nodes(fn.body[0].body[0])[0]
    (dispatch_idx,) = {s for s, k in send.succs if k == EDGE_EXC}
    # Two handlers plus the unmatched propagation path: neither handler
    # is a catch-all, so a TypeError must still escape the function.
    assert len(cfg.nodes[dispatch_idx].succs) == 3
    assert cfg.raise_idx in cfg.reachable_from(dispatch_idx)
    # Swapping one handler for a catch-all removes the unmatched path.
    caught = src.replace("except ValueError:", "except Exception:")
    fn2 = _fn(caught)
    cfg2 = build_cfg(fn2)
    send2 = cfg2.stmt_nodes(fn2.body[0].body[0])[0]
    (d2,) = {s for s, k in send2.succs if k == EDGE_EXC}
    assert len(cfg2.nodes[d2].succs) == 2
    assert cfg2.raise_idx not in cfg2.reachable_from(d2)


def test_nested_try_except_else_finally_edge_routing():
    src = (
        "def f(res, wire):\n"
        "    try:\n"
        "        res.stage()\n"
        "    except KeyError:\n"
        "        wire.nack()\n"
        "    else:\n"
        "        wire.send()\n"
        "    finally:\n"
        "        res.release()\n"
    )
    fn = _fn(src)
    cfg = build_cfg(fn)
    try_stmt = fn.body[0]
    stage = cfg.stmt_nodes(try_stmt.body[0])[0]
    nack = cfg.stmt_nodes(try_stmt.handlers[0].body[0])[0]
    send = cfg.stmt_nodes(try_stmt.orelse[0])[0]
    release_idxs = {n.idx for n in cfg.stmt_nodes(try_stmt.finalbody[0])}
    # The body's exception goes to handler dispatch (the handler is
    # reachable from it)...
    (stage_exc,) = {s for s, k in stage.succs if k == EDGE_EXC}
    assert nack.idx in cfg.reachable_from(stage_exc)
    # ...while else-clause and handler-body exceptions propagate OUTWARD
    # — their exception successors are finally copies, not the dispatch.
    for node in (send, nack):
        exc = {s for s, k in node.succs if k == EDGE_EXC}
        assert exc and exc <= release_idxs
    # And the finally still dominates every exit of the whole statement.
    reach = _reachable_avoiding(cfg, cfg.entry_idx, release_idxs)
    assert cfg.exit_idx not in reach
    assert cfg.raise_idx not in reach
